#!/usr/bin/env python
"""Metrics smoke test (`make metrics-smoke`, ISSUE 1 satellite).

Boots the batch-resolution service on an ephemeral port, resolves the
golden e2e problem file (test/e2e/problem.json), scrapes ``/metrics``,
and asserts the scrape carries a nonzero ``deppy_resolutions_total``
plus the ISSUE 1 histogram families.  Fast on purpose: host backend, no
device compile — the full device pass is `make e2e`.
"""

from __future__ import annotations

import json
import os
import sys
from http.client import HTTPConnection

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

GOLDEN = os.path.join(REPO, "test", "e2e", "problem.json")

REQUIRED_FAMILIES = (
    "deppy_solve_seconds_bucket",
    "deppy_batch_fill_ratio_bucket",
    "deppy_escalation_stage_bucket",
)


def request(port: int, method: str, path: str, body=None):
    conn = HTTPConnection("127.0.0.1", port, timeout=30)
    headers = {"Content-Type": "application/json"} if body is not None else {}
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers=headers)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def main() -> int:
    from deppy_tpu.service import Server

    with open(GOLDEN, "r", encoding="utf-8") as fh:
        doc = json.load(fh)

    srv = Server(bind_address="127.0.0.1:0", probe_address="127.0.0.1:0",
                 backend="host")
    srv.start()
    try:
        status, _ = request(srv.probe_port, "GET", "/healthz")
        assert status == 200, f"/healthz returned {status}"
        status, data = request(srv.api_port, "POST", "/v1/resolve", doc)
        assert status == 200, f"/v1/resolve returned {status}: {data!r}"
        status, data = request(srv.api_port, "GET", "/metrics")
        assert status == 200, f"/metrics returned {status}"
        text = data.decode()

        resolved = 0
        for line in text.splitlines():
            if line.startswith("deppy_resolutions_total{"):
                resolved += int(float(line.rsplit(" ", 1)[1]))
        assert resolved > 0, (
            f"deppy_resolutions_total is zero after a resolve:\n{text}"
        )
        missing = [f for f in REQUIRED_FAMILIES if f not in text]
        assert not missing, f"histogram families missing: {missing}"
        print(f"metrics-smoke: PASS ({resolved} resolutions scraped; "
              f"{len(REQUIRED_FAMILIES)} histogram families present)")
        return 0
    finally:
        srv.shutdown()


if __name__ == "__main__":
    sys.exit(main())
