"""Problem-file codec: JSON ⇄ variables.

The reference ships no file format (its CLI is an empty cobra stub,
/root/reference/cmd/root/root.go:7-14); SURVEY.md §3.3 calls for making the
CLI real with a ``resolve`` subcommand that reads a problem file.  This
module defines that format — a direct JSON rendering of the constraint
model (README.md:28-107's "Entities and Constraints passed to Deppy"):

Single problem::

    {
      "variables": [
        {"id": "a", "constraints": [
          {"type": "mandatory"},
          {"type": "dependency", "ids": ["b", "c"]},
          {"type": "conflict", "id": "d"},
          {"type": "atMost", "n": 1, "ids": ["x", "y"]},
          {"type": "prohibited"}
        ]},
        {"id": "b"}
      ]
    }

Batch of independent problems (the TPU-native extension)::

    {"problems": [{"variables": [...]}, {"variables": [...]}]}

``dependency.ids`` order is preference order, exactly as in the in-memory
model (reference constraints.go:125-137).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Sequence, Tuple

from .sat.constraints import (
    AppliedConstraint,
    AtMost,
    Conflict,
    Constraint,
    Dependency,
    Mandatory,
    Prohibited,
    Variable,
)


class ProblemFormatError(ValueError):
    """Raised on a malformed problem document."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ProblemFormatError(msg)


def constraint_from_dict(d: Dict[str, Any]) -> Constraint:
    _require(isinstance(d, dict), f"constraint must be an object, got {type(d).__name__}")
    kind = d.get("type")
    if kind == "mandatory":
        return Mandatory()
    if kind == "prohibited":
        return Prohibited()
    if kind == "dependency":
        ids = d.get("ids")
        _require(isinstance(ids, list) and all(isinstance(i, str) for i in ids),
                 "dependency requires a list of string ids")
        return Dependency(tuple(ids))
    if kind == "conflict":
        _require(isinstance(d.get("id"), str), "conflict requires a string id")
        return Conflict(d["id"])
    if kind == "atMost":
        n, ids = d.get("n"), d.get("ids")
        _require(isinstance(n, int) and not isinstance(n, bool) and n >= 0,
                 "atMost requires a non-negative integer n")
        _require(isinstance(ids, list) and all(isinstance(i, str) for i in ids),
                 "atMost requires a list of string ids")
        return AtMost(n, tuple(ids))
    raise ProblemFormatError(f"unknown constraint type {kind!r}")


def constraint_to_dict(c: Constraint) -> Dict[str, Any]:
    if isinstance(c, Mandatory):
        return {"type": "mandatory"}
    if isinstance(c, Prohibited):
        return {"type": "prohibited"}
    if isinstance(c, Dependency):
        return {"type": "dependency", "ids": list(c.ids)}
    if isinstance(c, Conflict):
        return {"type": "conflict", "id": c.id}
    if isinstance(c, AtMost):
        return {"type": "atMost", "n": c.n, "ids": list(c.ids)}
    raise ProblemFormatError(f"unknown constraint {c!r}")


def variable_from_dict(d: Dict[str, Any]) -> Variable:
    _require(isinstance(d, dict), f"variable must be an object, got {type(d).__name__}")
    _require(isinstance(d.get("id"), str), "variable requires a string id")
    raw = d.get("constraints", [])
    _require(isinstance(raw, list), "variable constraints must be a list")
    return Variable(d["id"], tuple(constraint_from_dict(c) for c in raw))


def variable_to_dict(v: Variable) -> Dict[str, Any]:
    out: Dict[str, Any] = {"id": v.identifier}
    if v.constraints:
        out["constraints"] = [constraint_to_dict(c) for c in v.constraints]
    return out


def problem_from_dict(d: Dict[str, Any]) -> List[Variable]:
    _require(isinstance(d, dict), "problem must be an object")
    raw = d.get("variables")
    _require(isinstance(raw, list), 'problem requires a "variables" list')
    return [variable_from_dict(v) for v in raw]


def parse_document(doc: Any) -> Tuple[List[List[Variable]], bool]:
    """Accepts ``{"variables": [...]}`` (one problem) or
    ``{"problems": [...]}`` (a batch); returns (problems, is_batch).
    ``is_batch`` reflects the input form so callers can keep the output
    schema a function of the input shape."""
    _require(isinstance(doc, dict), "document must be a JSON object")
    if "problems" in doc:
        raw = doc["problems"]
        _require(isinstance(raw, list), '"problems" must be a list')
        return [problem_from_dict(p) for p in raw], True
    return [problem_from_dict(doc)], False


def problems_from_document(doc: Any) -> List[List[Variable]]:
    return parse_document(doc)[0]


def load_document(path: str) -> Tuple[List[List[Variable]], bool]:
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ProblemFormatError(f"{path}: invalid JSON: {e}") from e
    return parse_document(doc)


def solution_to_dict(solution: Dict[str, bool]) -> Dict[str, Any]:
    """Render a Solution (every input id → selected?) the way the reference
    facade reports it (solver.go:52-62), plus the selected subset for
    humans."""
    return {
        "status": "sat",
        "selected": sorted(k for k, v in solution.items() if v),
        "solution": dict(solution),
    }


def unsat_to_dict(constraints: Sequence[AppliedConstraint]) -> Dict[str, Any]:
    """Render a NotSatisfiable core: the same constraint strings the error
    message carries (reference solve.go:20-30)."""
    return {
        "status": "unsat",
        "conflicts": [str(c) for c in constraints],
    }


def incomplete_to_dict(error: Exception) -> Dict[str, Any]:
    """Render an Incomplete outcome (step budget exhausted before a
    definitive answer — the reference's ErrIncomplete, solve.go:14)."""
    return {
        "status": "incomplete",
        "error": str(error),
    }


def result_to_dict(result: Any) -> Dict[str, Any]:
    """Render one per-problem BatchResolver result — a Solution dict, a
    NotSatisfiable error, or an Incomplete marker — into its wire form.
    The single dispatch shared by the CLI and the service so their output
    schemas cannot drift."""
    from .sat.errors import Incomplete, NotSatisfiable

    if isinstance(result, NotSatisfiable):
        return unsat_to_dict(result.constraints)
    if isinstance(result, Incomplete):
        return incomplete_to_dict(result)
    return solution_to_dict(result)
