"""Speculative pre-resolution (ISSUE 14).

Production churn is push-shaped: one catalog publish fans out to
thousands of dependent clients who all re-ask within minutes, and today
the first asker per clause-set family eats the cold solve while the
device sits mostly idle.  This subsystem converts that slack into
pre-solved answers:

  * :mod:`.manager` — :class:`PublishDelta` (the parsed
    ``POST /v1/catalog/publish`` / ``deppy publish`` body: absolute
    per-bundle constraint updates and withdrawals) and
    :class:`SpeculationManager`, which retains recently served problem
    families, enumerates the cached fingerprints a publish touches via
    the :class:`deppy_tpu.incremental.ClauseSetIndex` per-row keys,
    applies the delta to each retained family, and pre-solves the
    results through the scheduler's **idle-priority speculative class**
    — drained only when no live lane is queued, preempted by live
    traffic at every flush boundary.  Results land in the exact result
    cache and delta index like ordinary solves, so under sustained
    publish+query load the churn p99 becomes pure cache lookup.
  * The same machinery exposed read-only is the **what-if tier**
    (``POST /v1/resolve/preview``): resolve a *proposed* catalog change
    against the live index without serving or caching it —
    upgrade-impact preview as an API.

``DEPPY_TPU_SPECULATE=off`` constructs none of this: the scheduler's
submit and dispatch paths are byte-identical to the pre-speculation
tree, and the publish/preview endpoints 404 like any unknown path.
See docs/serving.md (Speculative pre-resolution).
"""

from .manager import (  # noqa: F401
    PublishDelta,
    PublishFormatError,
    SpeculationManager,
)
