"""Publish deltas and the speculation manager (ISSUE 14 tentpole).

A catalog publish names the bundles whose constraint sets changed (an
ABSOLUTE replacement per bundle, so applying the same publish to any
retained state of a family is idempotent) and the bundles withdrawn
outright.  :class:`SpeculationManager` glues the publish feed to the
serving stack:

  * ``observe`` retains the most recent problem families the scheduler
    served (the original variable lists, keyed by canonical
    fingerprint) — the raw material a delta is applied to;
  * ``publish`` enumerates the affected cached fingerprints through the
    :meth:`ClauseSetIndex.affected_keys` per-row scan, evicts the now
    pre-publish entries from the exact result cache (publish-driven
    invalidation — they can never be re-asked and must not linger), and
    queues one speculative pre-solve per affected retained family
    through :meth:`Scheduler.submit_speculative`;
  * ``preview`` runs the same enumeration + application READ-ONLY: the
    proposed problems resolve on the host warm path (index plan → warm
    attempt → inline cold solve) without storing into the cache or the
    index — the "what-if" scenario class.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from .. import telemetry
from ..sat.constraints import Prohibited, Variable
from ..sat.errors import Incomplete, NotSatisfiable

# Families retained for delta application.  Bounded LRU like every other
# serving-side store; sized above the result cache's default so a family
# whose exact entry is still live always has its variables on hand.
DEFAULT_FAMILY_CAPACITY = 2048
# Preview solves run inline on the caller's thread; bound the fan-out so
# one what-if request over a huge index cannot monopolize a handler.
# MAX is a server-side ceiling the client's `limit` cannot exceed — the
# endpoint is unauthenticated, and one request asking for the whole
# retained store would be a trivially repeatable CPU drain.
DEFAULT_PREVIEW_LIMIT = 32
MAX_PREVIEW_LIMIT = 128


class PublishFormatError(ValueError):
    """Raised on a malformed publish/preview document."""


class PublishDelta:
    """One parsed catalog publish.

    ``updates`` maps bundle identifier → its NEW constraint tuple
    (absolute replacement, not a diff); ``removed`` lists withdrawn
    bundles — applied as :class:`Prohibited` so dependents re-resolve
    away from them without dangling references."""

    __slots__ = ("updates", "removed")

    def __init__(self, updates: Dict[str, tuple], removed: Sequence[str]):
        self.updates = dict(updates)
        self.removed = frozenset(removed)

    @classmethod
    def from_doc(cls, doc) -> "PublishDelta":
        from .. import io as problem_io

        if not isinstance(doc, dict):
            raise PublishFormatError(
                f"publish body must be an object, got {type(doc).__name__}")
        updates: Dict[str, tuple] = {}
        raw = doc.get("updates", [])
        if not isinstance(raw, list):
            raise PublishFormatError('"updates" must be a list')
        for entry in raw:
            if not isinstance(entry, dict) \
                    or not isinstance(entry.get("id"), str):
                raise PublishFormatError(
                    'each update requires a string "id"')
            cons = entry.get("constraints", [])
            if not isinstance(cons, list):
                raise PublishFormatError(
                    f'update {entry["id"]!r}: "constraints" must be a list')
            try:
                updates[entry["id"]] = tuple(
                    problem_io.constraint_from_dict(c) for c in cons)
            except problem_io.ProblemFormatError as e:
                raise PublishFormatError(
                    f"update {entry['id']!r}: {e}") from e
        removed = doc.get("removed", [])
        if not isinstance(removed, list) \
                or not all(isinstance(i, str) for i in removed):
            raise PublishFormatError('"removed" must be a list of ids')
        if not updates and not removed:
            raise PublishFormatError(
                'publish names no changes (empty "updates" and "removed")')
        return cls(updates, removed)

    def changed_identifiers(self) -> frozenset:
        return frozenset(self.updates) | self.removed

    def apply(self, variables: Sequence[Variable]) -> Optional[tuple]:
        """The post-publish variable list for one family, or None when
        the publish leaves it untouched (no named bundle present, or
        every named bundle already carries the published constraints)."""
        changed = False
        out: List[Variable] = []
        for v in variables:
            if v.identifier in self.removed:
                nc: tuple = (Prohibited(),)
            elif v.identifier in self.updates:
                nc = self.updates[v.identifier]
            else:
                out.append(v)
                continue
            if tuple(v.constraints) != nc:
                changed = True
            out.append(Variable(v.identifier, nc))
        return tuple(out) if changed else None


class _Family:
    __slots__ = ("variables", "ids")

    def __init__(self, variables: Tuple[Variable, ...]):
        self.variables = variables
        self.ids = frozenset(v.identifier for v in variables)


class SpeculationManager:
    """Publish subscription + speculative pre-solve orchestration.

    Owned by the :class:`deppy_tpu.sched.Scheduler` (constructed only
    when ``DEPPY_TPU_SPECULATE`` is on) so publishes reach the exact
    cache, the clause-set index, and the idle-priority queue the live
    traffic uses — pre-solved answers are indistinguishable from
    ordinary ones."""

    def __init__(self, scheduler,
                 registry: Optional[telemetry.Registry] = None,
                 family_capacity: int = DEFAULT_FAMILY_CAPACITY):
        from ..analysis import lockdep

        self._sched = scheduler
        self._lock = lockdep.make_lock("speculate.families")
        self._families: "OrderedDict[str, _Family]" = OrderedDict()
        self._family_capacity = max(int(family_capacity), 0)
        reg = registry if registry is not None \
            else telemetry.default_registry()
        self._registry = reg
        self._c_publishes = reg.counter(
            "deppy_speculate_publishes_total",
            "Catalog publishes accepted on the watch endpoint/CLI.")
        self._c_affected = reg.counter(
            "deppy_speculate_affected_total",
            "Cached fingerprints enumerated as affected by a publish.")
        self._c_presolves = reg.counter(
            "deppy_speculate_presolves_total",
            "Speculative pre-solve lanes queued at idle priority.")
        self._c_dropped = reg.counter(
            "deppy_speculate_dropped_total",
            "Speculative pre-solves dropped (backlog cap, malformed "
            "family, or shutdown discard).")
        self._c_previews = reg.counter(
            "deppy_speculate_previews_total",
            "What-if preview resolutions served (read-only).")

    # ---------------------------------------------------------- observe

    def observe(self, key: str, variables: Sequence[Variable]) -> None:
        """Retain one served family (called per problem on the submit
        path — a dict store under the lock, nothing heavier).  The
        retained variable list is what a later publish is applied to."""
        if self._family_capacity == 0:
            return
        fam = _Family(tuple(variables))
        with self._lock:
            self._families[key] = fam
            self._families.move_to_end(key)
            while len(self._families) > self._family_capacity:
                self._families.popitem(last=False)

    def backlog(self) -> int:
        """Speculative lanes queued at idle priority right now."""
        return self._sched.speculative_depth()

    def note_discarded(self, n: int) -> None:
        """Speculative lanes the scheduler discarded (shutdown drain —
        no submitter waits on a pre-solve, so a drain drops them)."""
        if n:
            self._c_dropped.inc(n)

    # ---------------------------------------------------------- publish

    def _affected(self, delta: PublishDelta) -> List[Tuple[str, _Family]]:
        """Affected retained families, most recently served first: the
        union of the clause-set index's per-row enumeration (the
        tentpole surface — a key is affected when some structural row
        touches a changed bundle) and a membership check over retained
        families the index never admitted (non-SAT or backtracking
        solves still have cached exact results worth pre-replacing)."""
        changed = delta.changed_identifiers()
        index = getattr(self._sched, "incremental", None)
        index_keys = (set(index.affected_keys(changed))
                      if index is not None else set())
        with self._lock:
            items = list(reversed(self._families.items()))
        return [(key, fam) for key, fam in items
                if key in index_keys or fam.ids & changed]

    def publish(self, delta: PublishDelta,
                max_steps: Optional[int] = None) -> dict:
        """Handle one catalog publish: invalidate pre-publish cache
        entries, queue speculative pre-solves for every affected
        retained family, and return the accounting the endpoint/CLI
        renders."""
        reg = self._registry
        with reg.span("speculate.publish",
                      changed=len(delta.changed_identifiers())) as sp:
            self._c_publishes.inc()
            affected = self._affected(delta)
            self._c_affected.inc(len(affected))
            jobs: List[tuple] = []
            stale: List[str] = []
            unchanged = 0
            for key, fam in affected:
                new_vars = delta.apply(fam.variables)
                if new_vars is None:
                    # The family ALREADY carries the published
                    # constraints (an idempotent re-publish, or a
                    # post-publish re-ask already retained): its cached
                    # answer is the post-publish answer — evicting it
                    # would throw away exactly the hot entries the tier
                    # exists to keep.
                    unchanged += 1
                else:
                    stale.append(key)
                    jobs.append(new_vars)
            # Publish-driven invalidation (ISSUE 14 satellite): the
            # entries the delta actually changes describe PRE-publish
            # catalog states — retracted/contradicted — and must be
            # evicted, not served stale, counted on the existing
            # deppy_cache_invalidations_total family.
            invalidated = self._sched.cache.invalidate_keys(stale)
            # Retire the superseded retained states too: a later
            # publish applied to a pre-publish family would pre-solve
            # states no publish-tracking client will ever ask.  The
            # POST-publish states re-enter retention through
            # submit_speculative's observe (and through the clients'
            # own re-asks), so back-to-back publishes compose.
            with self._lock:
                for key in stale:
                    self._families.pop(key, None)
            queued, dropped = self._sched.submit_speculative(
                jobs, max_steps=max_steps)
            self._c_presolves.inc(queued)
            self._c_dropped.inc(dropped)
            out = {
                "changed": len(delta.changed_identifiers()),
                "affected": len(affected),
                "invalidated": invalidated,
                "queued": queued,
                "dropped": dropped,
                "unchanged": unchanged,
            }
            sp.set(**{k: v for k, v in out.items() if k != "changed"})
        return out

    # ---------------------------------------------------------- preview

    def preview(self, delta: PublishDelta,
                max_steps: Optional[int] = None,
                limit: Optional[int] = None) -> List[dict]:
        """Resolve a PROPOSED catalog change against the live index
        without serving or caching it: per affected family, the
        post-publish resolution (warm-started off the index when the
        plan certifies, inline cold host solve otherwise).  Nothing is
        stored anywhere — re-asking the same preview re-solves."""
        from ..incremental import attempt as warm_attempt
        from ..sat.encode import encode
        from ..sat.host import HostEngine
        from ..sched.cache import fingerprint

        if limit is None:
            limit = DEFAULT_PREVIEW_LIMIT
        limit = min(max(int(limit), 0), MAX_PREVIEW_LIMIT)
        index = getattr(self._sched, "incremental", None)
        out: List[dict] = []
        t0 = time.perf_counter()
        with self._registry.span("speculate.preview") as sp:
            for key, fam in self._affected(delta):
                if len(out) >= limit:
                    break
                new_vars = delta.apply(fam.variables)
                if new_vars is None:
                    continue
                problem = encode(new_vars)
                if problem.errors:
                    out.append({"fingerprint": key,
                                "error": "; ".join(problem.errors)})
                    continue
                new_key = fingerprint(problem)
                # account=False: a what-if consultation must not deflate
                # the serving tier's hit ratio or delta counters (the
                # same rule ResultCache.peek applies to the exact tier).
                plan = (index.plan(problem, new_key, 1 << 24,
                                   account=False)
                        if index is not None else None)
                klass = plan.klass if plan is not None else None
                result = None
                if plan is not None:
                    lane = warm_attempt(plan, max_steps)
                    if lane is not None:
                        result = {v.identifier: False
                                  for v in problem.variables}
                        for i in lane.installed_idx:
                            result[problem.variables[i].identifier] = True
                if result is None:
                    eng = HostEngine(problem, max_steps=max_steps)
                    try:
                        _, installed_idx = eng.solve()
                        result = {v.identifier: False
                                  for v in problem.variables}
                        for i in installed_idx:
                            result[problem.variables[i].identifier] = True
                    except NotSatisfiable as e:
                        result = e
                    except Incomplete as e:
                        result = e
                self._c_previews.inc()
                out.append({"fingerprint": key, "delta_class": klass,
                            "result": result})
            sp.set(families=len(out),
                   wall_ms=round((time.perf_counter() - t0) * 1e3, 3))
        return out
