"""Batch-resolution service: the rebuild's long-running process.

The reference's deployable binary is a controller-runtime manager scaffold
with metrics on :8080, health probes on :8081, and no reconcilers
(/root/reference/main.go:46-86; SURVEY.md §3.4 directs the rebuild to make
this a real batch-resolution service with the same health/metrics
surface).  This module is that service, on the stdlib HTTP server so the
library stays dependency-free:

  * ``POST /v1/resolve`` on the main address — accepts a problem document
    (the :mod:`deppy_tpu.io` format: one problem or a batch), dispatches it
    to the solver backend, returns per-problem solutions / conflict sets;
  * ``GET /metrics`` on the main address — Prometheus text format
    (the analog of controller-runtime's metrics registry, main.go:63-64,
    scraped via config/prometheus/monitor.yaml);
  * ``GET /healthz`` and ``GET /readyz`` on the probe address — liveness
    and readiness pings (main.go:75-81's healthz.Ping).

Counters follow SURVEY.md §5's observability plan: problems resolved by
outcome, batches, solve seconds, engine steps (propagation/decision
iterations as counted by the engine's step budget).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from . import config, faults
from . import io as problem_io
from . import profile as profiling
from . import telemetry
from .sat.errors import (BackendCapabilityError, DuplicateIdentifier,
                         InternalSolverError)


class _V6HTTPServer(ThreadingHTTPServer):
    address_family = socket.AF_INET6


class _DualStackHTTPServer(_V6HTTPServer):
    """Wildcard '::' bind accepting both IPv6 and IPv4-mapped connections —
    what the reference's Go ':8080' listeners do.  Keeps the shipped
    Deployment's probes working on IPv6-only clusters."""

    def server_bind(self):
        try:
            self.socket.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_V6ONLY, 0
            )
        except OSError:  # pragma: no cover - platform without the option
            pass
        super().server_bind()


def _make_http_server(addr: Tuple[str, int], handler) -> ThreadingHTTPServer:
    """Bind a threading HTTP server: explicit IPv4/IPv6 hosts get their
    family; an empty host (the ':8080' form) binds dual-stack, falling back
    to IPv4 wildcard where IPv6 is unavailable."""
    host, port = addr
    if host == "":
        try:
            return _DualStackHTTPServer(("::", port), handler)
        except OSError:
            return ThreadingHTTPServer(("0.0.0.0", port), handler)
    if ":" in host:  # IPv6 literal (brackets already stripped by _parse_addr)
        return _V6HTTPServer(addr, handler)
    return ThreadingHTTPServer(addr, handler)


def _default_engine_probe() -> Optional[bool]:
    """Auto-routing verdict for the scrape-time gauge: 1 tensor engine,
    0 host fallback (accelerator unusable), None while no verdict exists
    yet.  Lives behind an injectable callback so ``Metrics.render`` is
    pure and testable without the solver module (ISSUE 1 satellite)."""
    from .sat import solver as _solver

    return _solver._ENGINE_USABLE


class Metrics:
    """The service's metric surface, rendered in Prometheus text
    exposition format.

    Rebuilt on :class:`deppy_tpu.telemetry.Registry` (ISSUE 1): the
    historical counters keep their exact names and rendering, and the
    registry adds histogram families — ``deppy_solve_seconds`` (per-batch
    wall clock), ``deppy_batch_fill_ratio`` (live problems per dispatched
    lane) and ``deppy_escalation_stage`` (budget-escalation stage
    reached), fed from each batch's :class:`telemetry.SolveReport`.

    Each ``Metrics`` owns a private registry, so concurrent servers (and
    tests) never share counts; the pipeline-global driver telemetry
    lives separately on ``telemetry.default_registry()``.
    """

    def __init__(self, registry: Optional[telemetry.Registry] = None,
                 engine_usable_probe=_default_engine_probe) -> None:
        self.registry = registry if registry is not None else telemetry.Registry()
        self._engine_probe = engine_usable_probe
        self.leader: Optional[bool] = None  # None = election disabled
        # Per-tenant SLO accountant (ISSUE 11): set by the owning
        # Server; its deppy_tenant_* families append to every scrape.
        self.slo: Optional[profiling.SLOAccountant] = None
        r = self.registry
        self._resolutions = r.counter(
            "deppy_resolutions_total", "Problems resolved by outcome.",
            labelname="outcome",
        ).preset("sat", "unsat", "incomplete")
        self._batches = r.counter(
            "deppy_batches_total", "Resolution batches dispatched.")
        self._errors = r.counter(
            "deppy_request_errors_total", "Malformed or failed requests.")
        self._solve_seconds = r.counter(
            "deppy_solve_seconds_total",
            "Wall-clock seconds spent solving.", initial=0.0)
        self._engine_steps = r.counter(
            "deppy_engine_steps_total",
            "Engine iterations (tests, decisions, backtracks).")
        self._solve_hist = r.histogram(
            "deppy_solve_seconds",
            "Resolution batch wall-clock seconds.",
            buckets=telemetry.SECONDS_BUCKETS)
        self._fill_hist = r.histogram(
            "deppy_batch_fill_ratio",
            "Live problems per dispatched batch lane (1.0 = no padding).",
            buckets=telemetry.RATIO_BUCKETS)
        self._esc_hist = r.histogram(
            "deppy_escalation_stage",
            "Budget-escalation stage reached per batch (0 = single "
            "stage, 1 = stage-1 budget sufficed, 2 = stage-2 redo).",
            buckets=telemetry.STAGE_BUCKETS)
        # Per-request latency attribution (ISSUE 4): end-to-end request
        # wall clock, and the slice of it spent queued in the scheduler
        # before a coalesced dispatch picked the request up.
        self._request_hist = r.histogram(
            "deppy_request_total_seconds",
            "End-to-end /v1/resolve wall clock, admission through "
            "response render.",
            buckets=telemetry.SECONDS_BUCKETS)
        self._queue_wait_hist = r.histogram(
            "deppy_request_queue_wait_seconds",
            "Seconds a request's problems waited in the scheduler "
            "queue before their coalesced dispatch started.",
            buckets=telemetry.SECONDS_BUCKETS)

    def observe_batch(self, outcomes: Dict[str, int], seconds: float,
                      steps: int = 0,
                      report: Optional[telemetry.SolveReport] = None) -> None:
        self._batches.inc()
        for k, v in outcomes.items():
            self._resolutions.inc(v, label=k)
        self._solve_seconds.inc(seconds)
        self._engine_steps.inc(steps)
        self._solve_hist.observe(seconds)
        if report is not None:
            self._fill_hist.observe(report.batch_fill_ratio)
            self._esc_hist.observe(report.escalation_stage)

    def observe_error(self) -> None:
        self._errors.inc()

    def observe_request(self, total_s: float,
                        queue_wait_s: Optional[float] = None) -> None:
        """One /v1/resolve request's latency breakdown (ISSUE 4)."""
        self._request_hist.observe(total_s)
        if queue_wait_s is not None:
            self._queue_wait_hist.observe(queue_wait_s)

    def render(self) -> str:
        # The probe runs OUTSIDE any metric lock (it may import the
        # solver module on first call); rendering itself is pure.
        usable = None
        if self._engine_probe is not None:
            try:
                usable = self._engine_probe()
            # deppy: lint-ok[exception-hygiene] a broken probe must not break scrapes; gauge goes absent
            except Exception:
                usable = None  # a broken probe must not break scrapes
        lines = self.registry.render_lines()
        if usable is not None:
            lines += [
                "# HELP deppy_auto_engine_usable Auto routing verdict:"
                " 1 = tensor engine, 0 = host fallback.",
                "# TYPE deppy_auto_engine_usable gauge",
                f"deppy_auto_engine_usable {int(usable)}",
            ]
        if self.leader is not None:
            lines += [
                "# HELP deppy_leader HA election verdict: 1 = holding"
                " the lease (serving), 0 = standby.",
                "# TYPE deppy_leader gauge",
                f"deppy_leader {int(self.leader)}",
            ]
        # Fault-domain families (ISSUE 2): breaker state + retry/deadline
        # counters are pipeline-global (one accelerator, one breaker),
        # appended here so every scrape sees them.
        lines += faults.render_metric_lines()
        # Hostpool families (ISSUE 5): the breaker-open worker pool is
        # process-global too (one host, one pool) — same injection
        # pattern, so queue depth / busy workers / crash-recycle
        # counters ride every scrape.
        from . import hostpool

        lines += hostpool.render_metric_lines()
        # Profiler families (ISSUE 11): the trip ledger records on the
        # pipeline-global default registry (where the driver runs);
        # mirror them into the scrape like the fault/hostpool families.
        # Absent until a sampled dispatch, so disarmed scrapes are
        # unchanged.
        lines += profiling.render_metric_lines()
        # Per-tenant SLO families (ISSUE 11): request / deadline-miss /
        # violation counters plus p99 and burn-rate gauges, one line
        # per observed tenant — absent until the first request lands,
        # so a tenant-free deployment's scrape is unchanged.
        if self.slo is not None:
            lines += self.slo.render_metric_lines()
        # Fleet observability families (ISSUE 16): telemetry-streamer
        # counters + cost-model drift gauges, process-global like the
        # profiler's.  Disarmed (no --obs-stream / --obs-baseline) this
        # appends nothing — scrapes stay byte-identical.
        from . import obs, routes

        lines += obs.render_metric_lines()
        lines += routes.render_metric_lines()
        return "\n".join(lines) + "\n"


class Server:
    """The service: one HTTP server for API+metrics, one for health probes
    (mirroring the reference's two bind addresses, main.go:48-50)."""

    def __init__(
        self,
        bind_address: str = ":8080",
        probe_address: str = ":8081",
        backend: str = "auto",
        max_steps: Optional[int] = None,
        max_body_bytes: int = 8 * 1024 * 1024,
        elector=None,
        request_deadline_s: Optional[float] = None,
        drain_s: Optional[float] = None,
        sched: Optional[str] = None,
        sched_max_wait_ms: Optional[float] = None,
        sched_max_fill: Optional[int] = None,
        cache_size: Optional[int] = None,
        mesh_devices: Optional[int] = None,
        incremental: Optional[str] = None,
        incremental_max_delta: Optional[float] = None,
        incremental_index_size: Optional[int] = None,
        slo: Optional[str] = None,
        portfolio: Optional[str] = None,
        speculate: Optional[str] = None,
        speculate_max_backlog: Optional[int] = None,
        replica: Optional[str] = None,
        fair: Optional[str] = None,
        tenant_weights: Optional[str] = None,
        obs_stream: Optional[str] = None,
        obs_flush_ms: Optional[float] = None,
        obs_baseline: Optional[str] = None,
        fleet_router: Optional[str] = None,
        fleet_advertise: Optional[str] = None,
        opt: Optional[str] = None,
        opt_max_iterations: Optional[int] = None,
        opt_iter_budget: Optional[int] = None,
        opt_max_weight: Optional[int] = None,
        route_learn: Optional[str] = None,
        route_shadow_rate: Optional[float] = None,
        route_registry: Optional[str] = None,
        sessions: Optional[str] = None,
        session_lease_s: Optional[float] = None,
        session_max: Optional[int] = None,
        session_max_per_tenant: Optional[int] = None,
    ):
        self.backend = backend
        self.max_steps = max_steps
        self.max_body_bytes = max_body_bytes
        self.metrics = Metrics()
        # Replica serving identity (ISSUE 15): --replica /
        # DEPPY_TPU_REPLICA / `replica` config key.  Fleet deployments
        # set one per process so the SLO families, /debug/slo, and
        # every request's root span attribute burn rate per tenant PER
        # REPLICA; unset (single-process) keeps every surface byte-
        # identical to pre-fleet.
        if replica is None:
            replica = config.env_str("DEPPY_TPU_REPLICA")
        self.replica = profiling.sanitize_replica(replica)
        # Per-tenant SLO accounting (ISSUE 11): tenant identity from
        # X-Deppy-Tenant, targets from the declarative SLO spec
        # (--slo / DEPPY_TPU_SLO: inline JSON, @FILE, or a path).
        # Profiler arming is NOT a Server concern: like the host worker
        # pool, the profiler is process-global state, owned by the
        # process entry point (`deppy serve --profile`, cli._cmd_serve)
        # — a Server installing it would leak arming across embedded
        # servers that come and go.
        self.slo = profiling.SLOAccountant(
            profiling.slo_config_from_env() if slo is None
            else profiling.SLOConfig.from_spec(slo),
            replica=self.replica)
        self.metrics.slo = self.slo
        # Fleet observability plane (ISSUE 16).  --obs-stream arms the
        # telemetry streamer (sink events batch-pushed to the router's
        # POST /fleet/telemetry); --obs-baseline arms the cost-model
        # drift watchdog.  Both install process-global forwarders on
        # the default registry — replica-scoped state like the
        # profiler's, except a fleet replica runs exactly one Server, so
        # this Server owns their lifecycle and detaches them on
        # shutdown().  Unset (the default) arms nothing: the event
        # pipeline and /metrics stay byte-identical to pre-obs.
        if obs_stream is None:
            obs_stream = config.env_str("DEPPY_TPU_OBS_STREAM")
        if obs_baseline is None:
            obs_baseline = config.env_str("DEPPY_TPU_OBS_BASELINE")
        self._obs_armed = False
        if obs_stream or obs_baseline:
            from . import obs

            if obs_stream:
                obs.start_streamer(obs_stream, replica=self.replica,
                                   flush_ms=obs_flush_ms)
                self._obs_armed = True
            if obs_baseline:
                if obs.start_watchdog(obs_baseline,
                                      replica=self.replica) is not None:
                    self._obs_armed = True
        # Elastic fleet membership (ISSUE 17): --fleet-router names the
        # affinity router this replica announces itself to (POST
        # /fleet/join — the router streams it the warm state its arcs
        # inherit, then flips the ring atomically) once its listeners
        # are up, and leaves (the router's drain handoff) on graceful
        # shutdown.  Unset keeps the standalone lifecycle byte for
        # byte.  --fleet-advertise overrides the advertised host:port
        # (default 127.0.0.1:<api-port> — single-host fleets only).
        if fleet_router is None:
            fleet_router = config.env_str("DEPPY_TPU_FLEET_ROUTER")
        if fleet_advertise is None:
            fleet_advertise = config.env_str("DEPPY_TPU_FLEET_ADVERTISE")
        self.fleet_router = fleet_router
        self.fleet_advertise = fleet_advertise
        self._fleet_joined = False
        self._fleet_advertised: Optional[str] = None
        self.ready = threading.Event()
        self._stop = threading.Event()
        # Cross-request continuous batching + result cache (ISSUE 3):
        # concurrent /v1/resolve requests coalesce into shared device
        # dispatches through one Scheduler instead of each paying a
        # private pad/pack + device_put + launch.  Default on;
        # DEPPY_TPU_SCHED=off (or sched="off") restores the historical
        # per-request dispatch path — responses are byte-identical
        # either way.  The scheduler registers its queue/cache metric
        # families on this server's registry, so they ride /metrics.
        if sched is None:
            sched = config.env_raw("DEPPY_TPU_SCHED", "on")
        self.scheduler = None
        if str(sched).strip().lower() not in ("off", "0", "false", "no"):
            from .sched import Scheduler

            self.scheduler = Scheduler(
                backend=backend, max_steps=max_steps,
                max_wait_ms=sched_max_wait_ms, max_fill=sched_max_fill,
                cache_size=cache_size,
                registry=self.metrics.registry,
                mesh_devices=mesh_devices,
                incremental=incremental,
                incremental_max_delta=incremental_max_delta,
                incremental_index_size=incremental_index_size,
                portfolio=portfolio,
                speculate=speculate,
                speculate_max_backlog=speculate_max_backlog,
                fair=fair,
                tenant_weights=tenant_weights)
        # Optimization tier (ISSUE 18): POST /v1/optimize serves
        # upgrade planning / soft constraints / explain-why-not through
        # the bound-tightening loop.  The tier rides the scheduler's
        # idle-priority queue, so it exists only when the scheduler
        # does; "off" (or sched off) constructs nothing — the endpoint
        # 404s like any unknown path and every other surface is
        # byte-identical to pre-tier.  The planner's counters register
        # on this server's registry so they ride /metrics.
        if opt is None:
            opt = config.env_raw("DEPPY_TPU_OPT", "on")
        self.optimizer = None
        if self.scheduler is not None and str(opt).strip().lower() \
                not in ("off", "0", "false", "no"):
            from .optimize import Planner

            self.optimizer = Planner(
                self.scheduler, metrics=self.metrics.registry,
                max_iterations=opt_max_iterations,
                iter_budget=opt_iter_budget,
                max_weight=opt_max_weight)
        # Route-health plane (ISSUE 19): regret ledger + staleness
        # watcher + shadow sampler (+ online route registry when
        # --route-learn=on).  Exists only when the scheduler does —
        # every event it folds comes off the scheduler's racer.  "off"
        # (the default) constructs nothing: no forwarder, no route_*
        # metric families, POST /v1/routes/learned 404s, and responses
        # stay byte-identical to pre-plane.
        self.route_plane = None
        if self.scheduler is not None:
            from . import routes

            self.route_plane = routes.start_plane(
                self.scheduler, mode=route_learn,
                shadow_rate=route_shadow_rate,
                registry_path=route_registry,
                replica=self.replica)
        # Stateful resolution sessions (ISSUE 20): POST /v1/session +
        # /v1/session/{id}/op serve interactive assume/test/untest
        # exploration against a retained catalog epoch, with every
        # incremental solve routed through the scheduler's dedicated
        # session class (warm-started from the session's last model,
        # raced across registry backends, deadline/breaker/fair
        # semantics unchanged).  The tier exists only when the
        # scheduler does; "off" constructs NONE of it — the endpoints
        # 404 byte-identically to unknown paths, no session metric
        # family registers, and /v1/resolve is untouched.
        if sessions is None:
            sessions = config.env_raw("DEPPY_TPU_SESSIONS", "on")
        self.sessions = None
        if self.scheduler is not None and str(sessions).strip().lower() \
                not in ("off", "0", "false", "no"):
            from .sessions import SessionStore

            self.sessions = SessionStore(
                self.scheduler, metrics=self.metrics.registry,
                lease_s=session_lease_s, max_sessions=session_max,
                max_per_tenant=session_max_per_tenant,
                replica=self.replica)
        # Fault-domain knobs (ISSUE 2).  request_deadline_s: default
        # wall-clock budget per /v1/resolve (clients override per request
        # via the X-Deppy-Deadline-S header; None = unbounded).  drain_s
        # bounds the graceful-shutdown wait for in-flight requests —
        # defaulting to the request deadline, since no request should
        # legitimately outlive one.
        if request_deadline_s is None:
            request_deadline_s = faults.env_float(
                "DEPPY_TPU_REQUEST_DEADLINE_S", None, warn=True)
        self.request_deadline_s = request_deadline_s
        if drain_s is None:
            drain_s = faults.env_float("DEPPY_TPU_DRAIN_S", None, warn=True)
        if drain_s is None:
            drain_s = request_deadline_s if request_deadline_s else 10.0
        self._drain_s = max(float(drain_s), 0.0)
        self._inflight = 0
        from .analysis import lockdep

        self._inflight_lock = lockdep.make_lock("service.inflight")
        self._idle = threading.Event()
        self._idle.set()
        # Optional active-passive HA (the reference manager's leader
        # election, main.go:51,62-69): when DEPPY_HA_LEASE names a Lease,
        # only the holder reports ready, so a hot-standby pair exposes
        # exactly one pod through the Service.  Default off — the
        # stateless resolve API scales active-active without election.
        if elector is None:
            from .utils.lease import elector_from_env

            elector = elector_from_env()
        self.elector = elector
        if self.elector is not None:
            self.metrics.leader = False
            self.elector.on_change = self._on_leader_change
        try:
            self._reprobe_s = float(
                config.env_raw("DEPPY_TPU_REPROBE", "600")
            )
        except ValueError:
            # A typo'd env var must degrade to the default, not kill the
            # server at startup (matches DEPPY_BENCH_SELF_DESTRUCT's
            # defensive parsing).
            print("[service] ignoring non-numeric DEPPY_TPU_REPROBE="
                  f"{config.env_raw('DEPPY_TPU_REPROBE')!r}; using 600",
                  file=sys.stderr, flush=True)
            self._reprobe_s = 600.0
        self._api = _make_http_server(
            _parse_addr(bind_address), _api_handler(self)
        )
        try:
            self._probe = _make_http_server(
                _parse_addr(probe_address), _probe_handler(self)
            )
        except OSError:
            self._api.server_close()  # don't leak the already-bound socket
            raise
        self._threads: list = []

    @property
    def api_port(self) -> int:
        return self._api.server_address[1]

    @property
    def probe_port(self) -> int:
        return self._probe.server_address[1]

    def admission_retry_after(
            self, deadline_s: Optional[float],
            tenant: str = "default",
    ) -> Optional[Tuple[float, str]]:
        """Degraded-mode gate for one request: (seconds the client
        should wait before retrying, error text), or None to admit.
        Three unmeetable cases: the request's deadline is already spent
        (a proxy-propagated budget of <= 0), the caller insists on the
        device backend while the accelerator breaker is open, or the
        scheduler queue is over its depth limit — per TENANT under the
        weighted-fair gate (ISSUE 15: the noisy tenant sheds at its
        share while victims under theirs keep admitting), globally with
        ``DEPPY_TPU_SCHED_FAIR=off``.  An open breaker alone does NOT
        shed auto/host traffic — the scheduler's queue drains on the
        host engine in that mode."""
        breaker = faults.default_breaker()
        if deadline_s is not None and deadline_s <= 0:
            faults.note_deadline_exceeded("service.resolve",
                                          tenant=tenant)
            return (max(breaker.remaining_s(), 1.0),
                    "degraded: request deadline cannot be met")
        if self.backend == "tpu" and breaker.blocks_device():
            return (max(breaker.remaining_s(), 1.0),
                    "degraded: accelerator breaker open")
        if self.scheduler is not None:
            retry = self.scheduler.admission_retry_after(tenant=tenant)
            if retry is not None:
                return retry, "overloaded: scheduler queue full"
        return None

    def resolve_document(self, doc,
                         deadline_s: Optional[float] = None,
                         timings: Optional[dict] = None,
                         tenant: str = "default",
                         request_stats: Optional[dict] = None,
                         ) -> Tuple[int, dict]:
        """Resolve one request body; returns (http_status, response_doc).
        A 503 response carries ``retry_after_s`` (the handler mirrors it
        into a ``Retry-After`` header).  ``timings``, when given,
        receives this request's stage breakdown (ISSUE 4):
        ``queue_wait_s`` / ``dispatch_s`` / ``solve_s`` / ``decode_s``
        from the scheduler (or ``solve_s`` alone on the unscheduled
        path) — the handler feeds it to the latency histograms and, on
        ``X-Deppy-Timings: 1``, into the response body.  ``tenant``
        (ISSUE 11) rides the scheduler's lanes for deadline-miss
        attribution; ``request_stats``, when given, receives
        ``{"deadline_misses": N}`` for the SLO accountant — kept apart
        from ``timings`` so the opt-in response body stays exactly the
        documented stage breakdown."""
        faults.inject("service.resolve")
        if deadline_s is None:
            deadline_s = self.request_deadline_s
        gate = self.admission_retry_after(deadline_s, tenant=tenant)
        if gate is not None:
            retry_after, msg = gate
            self.metrics.observe_error()
            return 503, {
                "error": msg,
                "retry_after_s": round(retry_after, 3),
            }
        try:
            problems = problem_io.problems_from_document(doc)
        except problem_io.ProblemFormatError as e:
            self.metrics.observe_error()
            return 400, {"error": str(e)}

        t0 = time.perf_counter()
        try:
            if self.scheduler is not None:
                # Scheduled path (ISSUE 3): this request's problems join
                # the shared queue (coalescing with concurrent requests)
                # or are served straight from the result cache.
                stats: dict = {}
                results = self.scheduler.submit(
                    problems, deadline_s=deadline_s, stats=stats,
                    tenant=tenant)
                steps = stats.get("steps", 0)
                report = stats.get("report")
                if timings is not None:
                    timings.update(stats.get("timings") or {})
                if request_stats is not None:
                    request_stats["deadline_misses"] = \
                        stats.get("deadline_misses", 0)
            else:
                from .resolution.facade import BatchResolver

                resolver = BatchResolver(backend=self.backend,
                                         max_steps=self.max_steps,
                                         deadline_s=deadline_s)
                results = resolver.solve(problems)
                steps = resolver.last_steps
                report = resolver.last_report
                if timings is not None:
                    timings["solve_s"] = time.perf_counter() - t0
        except (DuplicateIdentifier, InternalSolverError) as e:
            self.metrics.observe_error()
            return 400, {"error": str(e)}
        except BackendCapabilityError as e:
            # The selected backend/impl cannot serve this solve path
            # (ISSUE 6 satellite): a clean capability verdict, not an
            # internal 500 — the client (or operator) picks a different
            # impl.
            self.metrics.observe_error()
            return 400, {"error": str(e)}

        outcomes = {"sat": 0, "unsat": 0, "incomplete": 0}
        rendered = []
        for res in results:
            r = problem_io.result_to_dict(res)
            outcomes[r["status"]] += 1
            rendered.append(r)
        if (request_stats is not None
                and "deadline_misses" not in request_stats
                and deadline_s is not None):
            # Unscheduled path (no per-lane triage verdicts): a request
            # that ran past its configured deadline AND reports
            # incomplete lanes was deadline-degraded — degradation
            # implies wall >= deadline, and within-deadline budget
            # exhaustion must not count as a miss.
            elapsed = time.perf_counter() - t0
            request_stats["deadline_misses"] = (
                outcomes["incomplete"] if elapsed >= deadline_s else 0)
        self.metrics.observe_batch(outcomes, time.perf_counter() - t0,
                                   steps=steps, report=report)
        return 200, {"results": rendered}

    def optimize_document(self, doc,
                          deadline_s: Optional[float] = None,
                          tenant: str = "default") -> Tuple[int, dict]:
        """Serve one optimize request body (ISSUE 18); returns
        (http_status, response_doc) with :meth:`resolve_document`'s
        error contract: malformed documents and unresolvable references
        are 400s, admission pressure is a 503 with ``retry_after_s``,
        runtime failures surface as the handler's 500."""
        from .optimize import OptimizeFormatError

        if deadline_s is None:
            deadline_s = self.request_deadline_s
        gate = self.admission_retry_after(deadline_s, tenant=tenant)
        if gate is not None:
            retry_after, msg = gate
            self.metrics.observe_error()
            return 503, {
                "error": msg,
                "retry_after_s": round(retry_after, 3),
            }
        try:
            out = self.optimizer.handle(doc, deadline_s=deadline_s,
                                        tenant=tenant)
        except OptimizeFormatError as e:
            self.metrics.observe_error()
            return 400, {"error": str(e)}
        except (DuplicateIdentifier, InternalSolverError) as e:
            self.metrics.observe_error()
            return 400, {"error": str(e)}
        return 200, {"optimize": out}

    def session_document(self, path: str, doc,
                         deadline_s: Optional[float] = None,
                         tenant: str = "default") -> Tuple[int, dict]:
        """Serve one session-tier request (ISSUE 20); returns
        (http_status, response_doc) with :meth:`resolve_document`'s
        error contract.  ``POST /v1/session`` creates a session from a
        single-problem document; ``POST /v1/session/{id}/op`` drives
        one assume/test/untest/resolve/explain op against the retained
        state.  Solve-carrying ops pass the same fair-admission gate as
        ``/v1/resolve`` (they join the scheduler queue like any other
        request); creation sheds a counted 503 at the session caps."""
        from .sessions.store import SessionError, SessionLost, SessionShed

        if deadline_s is None:
            deadline_s = self.request_deadline_s
        if path == "/v1/session":
            try:
                out = self.sessions.create(doc, tenant=tenant)
            except problem_io.ProblemFormatError as e:
                self.metrics.observe_error()
                return 400, {"error": str(e)}
            except (DuplicateIdentifier, InternalSolverError) as e:
                self.metrics.observe_error()
                return 400, {"error": str(e)}
            except SessionShed as e:
                self.metrics.observe_error()
                return 503, {
                    "error": str(e),
                    "retry_after_s": round(
                        min(self.sessions.lease_s, 5.0), 3),
                }
            return 200, {"session": out}
        rest = path[len("/v1/session/"):]
        sid, _, tail = rest.partition("/")
        if not sid or tail != "op":
            return 404, {"error": "not found"}
        op = doc.get("op") if isinstance(doc, dict) else None
        if op in ("resolve", "explain"):
            gate = self.admission_retry_after(deadline_s, tenant=tenant)
            if gate is not None:
                retry_after, msg = gate
                self.metrics.observe_error()
                return 503, {
                    "error": msg,
                    "retry_after_s": round(retry_after, 3),
                }
        try:
            out = self.sessions.op(sid, doc, deadline_s=deadline_s)
        except SessionLost:
            # A clean miss, not an error burst: the router retries the
            # ring successor once and renders a retried miss as the
            # 409 "session lost" contract.
            self.metrics.observe_error()
            return 404, {"error": "unknown session"}
        except SessionError as e:
            self.metrics.observe_error()
            return 400, {"error": str(e)}
        except (DuplicateIdentifier, InternalSolverError) as e:
            self.metrics.observe_error()
            return 400, {"error": str(e)}
        return 200, out

    def _on_leader_change(self, leading: bool) -> None:
        self.metrics.leader = leading
        print(f"[service] HA election: "
              f"{'acquired lease, serving' if leading else 'standby'}",
              file=sys.stderr, flush=True)

    def serving(self) -> bool:
        """Readiness verdict for /readyz: started, and — under HA
        election — currently holding the lease."""
        if not self.ready.is_set():
            return False
        return self.elector is None or self.elector.is_leader

    def degraded(self) -> bool:
        """True while the accelerator breaker is open: the service still
        serves (host engine), but /readyz says so and operators should
        expect host-engine latency."""
        return faults.default_breaker().blocks_device()

    def _enter_request(self) -> None:
        with self._inflight_lock:
            self._inflight += 1
            self._idle.clear()

    def _exit_request(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._idle.set()

    def start(self) -> None:
        """Start both listeners on daemon threads (non-blocking)."""
        if self.scheduler is not None:
            self.scheduler.start()
        if self.elector is not None:
            self.elector.start()
        for srv in (self._api, self._probe):
            # Tight poll so shutdown() returns promptly instead of
            # waiting out BaseServer's default 0.5s select timeout.
            t = threading.Thread(target=srv.serve_forever,
                                 kwargs={"poll_interval": 0.05},
                                 daemon=True)
            t.start()
            self._threads.append(t)
        if self.backend == "auto":
            # Pre-warm the auto-backend usability verdict: against a
            # crashed TPU worker the probe takes its full timeout (75s)
            # before falling back to host.  The verdict is process-cached
            # and probing is serialized (solver._ENGINE_USABLE_LOCK), so a
            # request landing mid-probe waits on the SHARED probe — worst
            # case the remaining probe window, never a duplicate one —
            # and every request after the verdict routes instantly.
            #
            # If the verdict comes back negative, keep re-probing on an
            # interval (DEPPY_TPU_REPROBE seconds, 0 disables): a service
            # that boots during a worker outage upgrades auto routing to
            # the tensor engine when the worker recovers, instead of
            # serving from the host engine for the rest of its life.
            def _prewarm():
                from .sat import solver as sat_solver

                try:
                    if sat_solver.resolve_backend("auto") == "tpu":
                        return
                # deppy: lint-ok[exception-hygiene] request-path resolution surfaces the real error
                except Exception:
                    pass  # request-path resolution will surface errors
                while self._reprobe_s > 0 and not self._stop.wait(
                        self._reprobe_s):
                    try:
                        if sat_solver.reprobe_engine():
                            return
                    # deppy: lint-ok[exception-hygiene] transient reprobe failure; next tick retries
                    except Exception:
                        continue  # transient; keep trying next tick

            threading.Thread(target=_prewarm, daemon=True).start()
        if self.fleet_router:
            threading.Thread(target=self._fleet_announce,
                             name="deppy-fleet-join",
                             daemon=True).start()
        self.ready.set()

    # -------------------------------------------- fleet membership

    def _fleet_post(self, path: str, doc: dict,
                    timeout: float) -> Tuple[int, bytes]:
        from http.client import HTTPConnection

        host, _, port = str(self.fleet_router).rpartition(":")
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]
        conn = HTTPConnection(host or "127.0.0.1", int(port),
                              timeout=timeout)
        try:
            conn.request("POST", path, body=json.dumps(doc).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _fleet_announce(self, deadline_s: float = 15.0) -> None:
        """Announce this replica to its fleet router (ISSUE 17): POST
        /fleet/join until the router answers or the deadline passes.
        Best-effort by design — a replica that cannot join still
        serves standalone, and the join's warm-state stream + arc flip
        happen entirely router-side."""
        advertise = self.fleet_advertise \
            or f"127.0.0.1:{self.api_port}"
        self._fleet_advertised = advertise
        deadline = time.monotonic() + deadline_s
        while not self._stop.is_set():
            try:
                # Generous timeout: the router streams warm state to
                # this replica before answering.
                status, body = self._fleet_post(
                    "/fleet/join", {"replica": advertise}, timeout=60.0)
            except OSError:
                if time.monotonic() >= deadline:
                    print(f"[service] fleet join: router "
                          f"{self.fleet_router} unreachable; serving "
                          "standalone", file=sys.stderr, flush=True)
                    return
                self._stop.wait(0.5)
                continue
            if status == 200 or (status == 400
                                 and b"already a fleet member" in body):
                self._fleet_joined = True
            else:
                print(f"[service] fleet join rejected (HTTP {status}): "
                      f"{body[:200]!r}; serving standalone",
                      file=sys.stderr, flush=True)
            return

    def _fleet_leave(self) -> None:
        """Leave = drain (ISSUE 17): ask the router to run the
        warm-state drain handoff for this replica before the listeners
        close — the router calls back ``GET /debug/warmstate``, so
        this must run while the API listener still serves."""
        try:
            self._fleet_post("/fleet/drain",
                             {"replica": self._fleet_advertised},
                             timeout=60.0)
        except OSError:
            # Router gone (or never reachable): this death looks like
            # a crash to the fleet and the probe loop cleans up.
            pass
        self._fleet_joined = False

    def shutdown(self, drain_s: Optional[float] = None) -> None:
        """Graceful stop: flip /readyz, wait (bounded by the drain
        budget — itself derived from the request-deadline machinery) for
        in-flight /v1/resolve requests to finish, then close the
        listeners.  A request slower than the drain budget is abandoned
        — by construction it has also blown its deadline."""
        self.ready.clear()
        if self._fleet_joined:
            # Leave the fleet FIRST (ISSUE 17): the router's drain
            # handoff re-homes this replica's warm tier onto its arc
            # inheritors, and needs our /debug/warmstate answered —
            # so it must precede _stop and the listener close.
            self._fleet_leave()
        self._stop.set()
        if drain_s is None:
            drain_s = self._drain_s
        if drain_s > 0:
            self._idle.wait(drain_s)
        if self.sessions is not None:
            # Stop the lease sweeper before the scheduler: a sweep
            # racing scheduler teardown buys nothing, and embedded
            # servers in tests must not leak sweeper threads.
            self.sessions.stop()
        if self.scheduler is not None:
            # After the drain: in-flight requests are parked on their
            # queue groups, and stopping first would orphan them.  A
            # request that outlived the drain budget dispatches inline
            # on its own handler thread instead (the scheduler's
            # fallback), so nothing hangs.
            self.scheduler.stop()
        if self.elector is not None:
            # Release the lease BEFORE closing the listeners: the standby
            # flips to ready on its next tick, shrinking the failover
            # window from lease-expiry to renew-interval.
            self.elector.stop(release=True)
        if self.route_plane is not None:
            # Detach the route plane's forwarder and clear its learned
            # overlay so embedded servers in tests don't leak adopted
            # rows across instances.
            from . import routes

            routes.stop_plane()
            self.route_plane = None
        if self._obs_armed:
            # Detach the streamer/watchdog forwarders this Server armed
            # (final flush included) so embedded servers in tests don't
            # leak obs state across instances.
            from . import obs

            obs.stop_all()
            self._obs_armed = False
        for srv in (self._api, self._probe):
            if self._threads:
                # BaseServer.shutdown blocks forever unless serve_forever is
                # running — only call it on a started server.
                srv.shutdown()
            srv.server_close()
        self._threads = []


def _parse_addr(addr: str) -> Tuple[str, int]:
    """':8080', 'host:8080', '[::1]:8080', or a bare port → (host, port).
    Raises ``ValueError`` with a usable message on anything else (callers
    surface it as a usage error)."""
    host, sep, port = addr.rpartition(":")
    if not sep:
        host, port = "", addr
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]  # IPv6 literal
    elif ":" in host:
        # An unbracketed IPv6 literal would silently misparse at the last
        # colon ('::1' -> host '::', port 1) — require brackets instead.
        raise ValueError(
            f"invalid listen address {addr!r}: bracket IPv6 literals, "
            "e.g. '[::1]:8080'"
        )
    try:
        port_n = int(port)
    except ValueError:
        raise ValueError(
            f"invalid listen address {addr!r}: want ':PORT', 'HOST:PORT', "
            "or a bare port number"
        ) from None
    # Empty host stays empty: _make_http_server turns it into a dual-stack
    # wildcard bind (the Go ':8080' behavior).
    return host, port_n


def _api_handler(server: Server):
    class Handler(BaseHTTPRequestHandler):
        # Trace context of the in-flight /v1/resolve (ISSUE 4); echoed
        # into response headers by _send when the client sent a tracing
        # header (strict byte-identity for clients that sent none).
        _trace_ctx = None
        _echo_ids = False
        _echo_traceparent = False

        def log_message(self, fmt, *args):  # keep the library print-free
            pass

        def _send(self, status: int, body: str, ctype: str,
                  extra_headers: Optional[dict] = None) -> int:
            data = body.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            if self._trace_ctx is not None and self._echo_ids:
                # Echo the honored id back so the caller can quote it
                # against /debug/traces and `deppy trace`; the W3C
                # header is echoed only to callers speaking it.  Header-
                # free requests get byte-identical pre-trace responses
                # (their traces are still in the flight recorder).
                self.send_header("X-Deppy-Request-Id",
                                 self._trace_ctx.request_id)
                if self._echo_traceparent:
                    self.send_header(
                        "traceparent",
                        telemetry.trace.traceparent_of(self._trace_ctx))
            for k, v in (extra_headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)
            return status

        def _send_json(self, status: int, doc: dict) -> int:
            headers = None
            if status == 503 and "retry_after_s" in doc:
                # Degraded mode (ISSUE 2): tell well-behaved clients when
                # the breaker's half-open probe is due.
                headers = {"Retry-After":
                           str(max(int(doc["retry_after_s"] + 0.5), 1))}
            return self._send(status, json.dumps(doc), "application/json",
                              headers)

        def do_GET(self):
            if self.path == "/metrics":
                self._send(200, server.metrics.render(),
                           "text/plain; version=0.0.4")
            elif self.path.split("?", 1)[0] == "/debug/traces":
                self._debug_traces()
            elif self.path.split("?", 1)[0] == "/debug/slo":
                # Per-tenant SLO accounting (ISSUE 11): every observed
                # tenant's counters, window p99 vs target, and
                # error-budget burn rate.  Fleet deployments (ISSUE 15)
                # also see the replica's serving identity, so N
                # replicas' documents aggregate attributably; without
                # one the body is byte-identical to pre-fleet.
                doc = {"slo": server.slo.snapshot()}
                if server.replica is not None:
                    doc["replica"] = server.replica
                self._send(200, json.dumps(doc, sort_keys=True),
                           "application/json")
            elif self.path.split("?", 1)[0] == "/debug/warmstate":
                # Warm-state snapshot export (ISSUE 15): the drain
                # handoff's read side.  404 with the scheduler off —
                # there is no warm tier to export.
                if server.scheduler is None:
                    self._send_json(404, {"error": "not found"})
                    return
                from .fleet import export_warm_state

                self._send(200, json.dumps(
                    export_warm_state(server.scheduler,
                                      sessions=server.sessions)),
                    "application/json")
            else:
                self._send_json(404, {"error": "not found"})

        def _debug_traces(self):
            """Flight-recorder lookup (ISSUE 4): the full span tree of
            one request (``?id=`` trace or request id) or the index of
            every retained trace."""
            from urllib.parse import parse_qs, urlsplit

            recorder = telemetry.trace.default_recorder()
            query = parse_qs(urlsplit(self.path).query)
            wanted = (query.get("id") or [None])[0]
            if wanted:
                trace = recorder.get(wanted)
                if trace is None:
                    self._send_json(404,
                                    {"error": f"unknown trace id {wanted!r}"})
                else:
                    self._send(200, json.dumps({"trace": trace},
                                               default=str),
                               "application/json")
            else:
                self._send(200, json.dumps(
                    {"traces": recorder.summaries()}, default=str),
                    "application/json")

        def do_POST(self):
            if self.path == "/v1/resolve":
                server._enter_request()
                try:
                    self._resolve_request()
                finally:
                    server._exit_request()
                return
            if self.path == "/debug/warmstate":
                # Warm-state snapshot import (ISSUE 15): the drain
                # handoff's write side — a draining replica's shard,
                # delivered by the router, merges into this replica's
                # clause-set index and exact cache (live state wins).
                if server.scheduler is None:
                    self._send_json(404, {"error": "not found"})
                    return
                doc, err = self._read_json_body()
                if err is not None:
                    return
                from .fleet import SnapshotFormatError, import_warm_state

                server._enter_request()
                try:
                    out = import_warm_state(server.scheduler, doc,
                                            sessions=server.sessions)
                except SnapshotFormatError as e:
                    server.metrics.observe_error()
                    self._send_json(400, {"error": str(e)})
                    return
                finally:
                    server._exit_request()
                self._send_json(200, {"imported": out})
                return
            if self.path in ("/v1/catalog/publish", "/v1/resolve/preview"):
                # Speculative pre-resolution (ISSUE 14): the publish
                # watch endpoint and the read-only what-if preview.
                # With the tier off these paths 404 exactly like any
                # unknown path — pre-change behavior byte for byte.
                sched = server.scheduler
                spec = sched.speculate if sched is not None else None
                if spec is None:
                    self._send_json(404, {"error": "not found"})
                    return
                server._enter_request()
                try:
                    if self.path == "/v1/catalog/publish":
                        self._publish_request(spec)
                    else:
                        self._preview_request(spec)
                finally:
                    server._exit_request()
                return
            if self.path == "/v1/routes/learned":
                # Route-gossip ingress (ISSUE 19): a peer replica's
                # live-learned routing rows, fanned out by the router.
                # Adoption changes which backends race, never answers;
                # without an armed learning plane this 404s exactly
                # like any unknown path.
                plane = server.route_plane
                if plane is None or plane.learner is None:
                    self._send_json(404, {"error": "not found"})
                    return
                doc, err = self._read_json_body()
                if err is not None:
                    return
                rows = doc.get("rows") if isinstance(doc, dict) else None
                if not isinstance(rows, dict):
                    server.metrics.observe_error()
                    self._send_json(
                        400, {"error": "body must be "
                              '{"rows": {"portfolio.<class>": "a,b"}}'})
                    return
                origin = doc.get("origin")
                applied = plane.learner.adopt(
                    {str(k): v for k, v in rows.items()},
                    source="gossip",
                    origin=origin if isinstance(origin, str) else None)
                self._send_json(200, {"applied": applied})
                return
            if self.path == "/v1/optimize":
                # Optimization tier (ISSUE 18).  With the tier off this
                # path 404s exactly like any unknown path — pre-change
                # behavior byte for byte.
                if server.optimizer is None:
                    self._send_json(404, {"error": "not found"})
                    return
                server._enter_request()
                try:
                    self._optimize_request()
                finally:
                    server._exit_request()
                return
            if self.path == "/debug/dump":
                # Flight-recorder dump on demand (ISSUE 16): the HTTP
                # twin of SIGUSR2, so the router can fan one operator
                # signal out to every live replica.  The optional JSON
                # body names a reason for the dumped trace events.
                doc, err = self._read_json_body()
                if err is not None:
                    return
                reason = "http"
                if isinstance(doc, dict) and isinstance(
                        doc.get("reason"), str) and doc["reason"]:
                    reason = doc["reason"]
                n = telemetry.trace.default_recorder().dump(reason=reason)
                out = {"dumped": n}
                if server.replica is not None:
                    out["replica"] = server.replica
                self._send_json(200, out)
                return
            if self.path == "/v1/session" \
                    or self.path.startswith("/v1/session/"):
                # Stateful resolution sessions (ISSUE 20).  With the
                # tier off these paths 404 exactly like any unknown
                # path — pre-change behavior byte for byte.
                if server.sessions is None:
                    self._send_json(404, {"error": "not found"})
                    return
                server._enter_request()
                try:
                    self._session_request()
                finally:
                    server._exit_request()
                return
            self._send_json(404, {"error": "not found"})

        def _read_json_body(self):
            """``(doc, None)`` — the length-checked parsed JSON body —
            or ``(None, status)`` after the error response has been
            sent.  The /v1/resolve validation rules, shared so the
            publish/preview endpoints cannot drift (a parsed ``null``
            body is a valid doc, hence the explicit error channel)."""
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                server.metrics.observe_error()
                return None, self._send_json(
                    400, {"error": "invalid Content-Length"})
            if length < 0:
                server.metrics.observe_error()
                return None, self._send_json(
                    400, {"error": "invalid Content-Length"})
            if length > server.max_body_bytes:
                server.metrics.observe_error()
                return None, self._send_json(
                    413,
                    {"error": f"body exceeds {server.max_body_bytes} bytes"},
                )
            try:
                return json.loads(self.rfile.read(length) or b"null"), None
            except (ValueError, json.JSONDecodeError) as e:
                server.metrics.observe_error()
                return None, self._send_json(
                    400, {"error": f"invalid JSON body: {e}"})

        def _parse_delta(self, doc):
            from .speculate import PublishDelta, PublishFormatError

            try:
                return PublishDelta.from_doc(doc)
            except PublishFormatError as e:
                server.metrics.observe_error()
                self._send_json(400, {"error": str(e)})
                return None

        def _publish_request(self, spec):
            """POST /v1/catalog/publish — subscribe-side entry of the
            speculative tier: invalidates retracted cache entries and
            queues idle-priority pre-solves for every affected retained
            family.  Returns the enumeration/queueing accounting; the
            pre-solves themselves run in the background."""
            doc, err = self._read_json_body()
            if err is not None:
                return
            delta = self._parse_delta(doc)
            if delta is None:
                return
            try:
                out = spec.publish(delta, max_steps=server.max_steps)
            except Exception as e:  # same contract as /v1/resolve: a
                # runtime failure is a visible 500, not a dropped
                # connection.
                server.metrics.observe_error()
                self._send_json(500, {"error": f"internal error: {e}"})
                return
            self._send_json(200, {"publish": out})

        def _preview_request(self, spec):
            """POST /v1/resolve/preview — the what-if tier: resolve a
            PROPOSED catalog change against the live index without
            serving or caching it.  Body is a publish document plus an
            optional ``limit`` (affected families previewed, most
            recently served first)."""
            doc, err = self._read_json_body()
            if err is not None:
                return
            limit = None
            if isinstance(doc, dict) and "limit" in doc:
                if not isinstance(doc["limit"], int) \
                        or isinstance(doc["limit"], bool) \
                        or doc["limit"] < 0:
                    server.metrics.observe_error()
                    self._send_json(
                        400, {"error": '"limit" must be a non-negative '
                              'integer'})
                    return
                limit = doc["limit"]
            delta = self._parse_delta(doc)
            if delta is None:
                return
            try:
                entries = spec.preview(delta, max_steps=server.max_steps,
                                       limit=limit)
            except Exception as e:
                server.metrics.observe_error()
                self._send_json(500, {"error": f"internal error: {e}"})
                return
            rendered = []
            for entry in entries:
                out = dict(entry)
                if "result" in out:
                    out["result"] = problem_io.result_to_dict(
                        out["result"])
                rendered.append(out)
            self._send_json(200, {"preview": rendered})

        def _optimize_request(self):
            """POST /v1/optimize (ISSUE 18) — the /v1/resolve request
            envelope (trace context, tenant identity, deadline header,
            SLO accounting) around the planner's bound-tightening loop,
            so optimization cost is attributable per tenant exactly
            like resolution cost."""
            inbound_tp = self.headers.get("traceparent")
            inbound_rid = self.headers.get("X-Deppy-Request-Id")
            ctx = telemetry.trace.context_from_headers(inbound_tp,
                                                       inbound_rid)
            self._trace_ctx = ctx
            self._echo_ids = inbound_tp is not None \
                or inbound_rid is not None
            self._echo_traceparent = inbound_tp is not None
            tenant = profiling.sanitize_tenant(
                self.headers.get("X-Deppy-Tenant"))
            timings: dict = {}
            t0 = time.perf_counter()
            reg = telemetry.default_registry()
            status = None
            try:
                span_attrs = {"path": "/v1/optimize",
                              "request_id": ctx.request_id,
                              "tenant": tenant}
                if server.replica is not None:
                    span_attrs["replica"] = server.replica
                with telemetry.trace.activate(ctx), \
                        reg.span("service.request", **span_attrs) as sp:
                    status = self._optimize_request_inner(tenant)
                    sp["status"] = status
            finally:
                timings["total_s"] = time.perf_counter() - t0
                server.metrics.observe_request(timings["total_s"], None)
                server.slo.observe(
                    tenant, timings["total_s"],
                    deadline_miss=False,
                    error=status is None or status >= 500)
                telemetry.trace.default_recorder().record(
                    ctx, status=status, timings=timings)

        def _optimize_request_inner(self, tenant) -> int:
            deadline_s = None
            raw_deadline = self.headers.get("X-Deppy-Deadline-S")
            if raw_deadline is not None:
                import math

                try:
                    deadline_s = float(raw_deadline)
                except ValueError:
                    deadline_s = None
                if deadline_s is None or not math.isfinite(deadline_s):
                    server.metrics.observe_error()
                    return self._send_json(
                        400, {"error": "invalid X-Deppy-Deadline-S header"})
            doc, err = self._read_json_body()
            if err is not None:
                return err
            try:
                status, resp = server.optimize_document(
                    doc, deadline_s=deadline_s, tenant=tenant)
            except Exception as e:  # same contract as /v1/resolve: a
                # runtime failure is a visible 500, not a dropped
                # connection.
                server.metrics.observe_error()
                status, resp = 500, {"error": f"internal error: {e}"}
            return self._send_json(status, resp)

        def _session_request(self):
            """POST /v1/session and /v1/session/{id}/op (ISSUE 20) —
            the /v1/resolve request envelope (trace context, tenant
            identity, deadline header, SLO accounting) around the
            session store, so interactive exploration cost is
            attributable per tenant exactly like one-shot resolution
            cost."""
            inbound_tp = self.headers.get("traceparent")
            inbound_rid = self.headers.get("X-Deppy-Request-Id")
            ctx = telemetry.trace.context_from_headers(inbound_tp,
                                                       inbound_rid)
            self._trace_ctx = ctx
            self._echo_ids = inbound_tp is not None \
                or inbound_rid is not None
            self._echo_traceparent = inbound_tp is not None
            tenant = profiling.sanitize_tenant(
                self.headers.get("X-Deppy-Tenant"))
            timings: dict = {}
            t0 = time.perf_counter()
            reg = telemetry.default_registry()
            status = None
            try:
                span_attrs = {"path": self.path,
                              "request_id": ctx.request_id,
                              "tenant": tenant}
                if server.replica is not None:
                    span_attrs["replica"] = server.replica
                with telemetry.trace.activate(ctx), \
                        reg.span("service.request", **span_attrs) as sp:
                    status = self._session_request_inner(tenant)
                    sp["status"] = status
            finally:
                timings["total_s"] = time.perf_counter() - t0
                server.metrics.observe_request(timings["total_s"], None)
                server.slo.observe(
                    tenant, timings["total_s"],
                    deadline_miss=False,
                    error=status is None or status >= 500)
                telemetry.trace.default_recorder().record(
                    ctx, status=status, timings=timings)

        def _session_request_inner(self, tenant) -> int:
            deadline_s = None
            raw_deadline = self.headers.get("X-Deppy-Deadline-S")
            if raw_deadline is not None:
                import math

                try:
                    deadline_s = float(raw_deadline)
                except ValueError:
                    deadline_s = None
                if deadline_s is None or not math.isfinite(deadline_s):
                    server.metrics.observe_error()
                    return self._send_json(
                        400, {"error": "invalid X-Deppy-Deadline-S header"})
            doc, err = self._read_json_body()
            if err is not None:
                return err
            try:
                status, resp = server.session_document(
                    self.path, doc, deadline_s=deadline_s, tenant=tenant)
            except Exception as e:  # same contract as /v1/resolve: a
                # runtime failure (including an injected sessions.op
                # fault) is a visible 500, not a dropped connection.
                server.metrics.observe_error()
                status, resp = 500, {"error": f"internal error: {e}"}
            return self._send_json(status, resp)

        def _resolve_request(self):
            # Per-request trace context (ISSUE 4): honor an inbound W3C
            # traceparent or X-Deppy-Request-Id, mint ids otherwise.
            # Every request is traced into the flight recorder; header
            # echo and the timings body key are the only response
            # changes, and the body changes only on explicit opt-in
            # (X-Deppy-Timings) — tracing-header-free responses stay
            # byte-identical.
            inbound_tp = self.headers.get("traceparent")
            inbound_rid = self.headers.get("X-Deppy-Request-Id")
            ctx = telemetry.trace.context_from_headers(inbound_tp,
                                                       inbound_rid)
            self._trace_ctx = ctx
            self._echo_ids = inbound_tp is not None or inbound_rid is not None
            self._echo_traceparent = inbound_tp is not None
            want_timings = (self.headers.get("X-Deppy-Timings") or "") \
                .strip().lower() in ("1", "true", "yes")
            # Tenant identity (ISSUE 11): X-Deppy-Tenant, sanitized to
            # a metric-label-safe id; absent/empty = the default
            # tenant.  Rides the root span's attrs (so `deppy stats
            # --tenant` filters from sink lines alone), the scheduler's
            # lanes, and the SLO accountant below.
            tenant = profiling.sanitize_tenant(
                self.headers.get("X-Deppy-Tenant"))
            timings: dict = {}
            request_stats: dict = {}
            t0 = time.perf_counter()
            reg = telemetry.default_registry()
            status = None
            try:
                # request_id rides the root span's attrs so `deppy
                # trace CLIENT-ID` resolves from live sink lines alone
                # (no flight-recorder dump required).
                # Replica identity rides the root span only when set
                # (ISSUE 15): replica-free deployments keep their
                # pre-fleet span attrs byte for byte.
                span_attrs = {"path": "/v1/resolve",
                              "request_id": ctx.request_id,
                              "tenant": tenant}
                if server.replica is not None:
                    span_attrs["replica"] = server.replica
                with telemetry.trace.activate(ctx), \
                        reg.span("service.request", **span_attrs) as sp:
                    status = self._resolve_request_inner(
                        t0, timings, want_timings, tenant, request_stats)
                    sp["status"] = status
            finally:
                # Runs even when the handler dies mid-response (client
                # disconnect → BrokenPipeError): the errored trace is
                # exactly the one the flight recorder's error ring
                # promises to retain, and the latency histogram must
                # count the request either way.  total_s is OVERWRITTEN
                # here — the opt-in body carries its own pre-send
                # snapshot, but the histogram/recorder interval must
                # not depend on whether the client sent X-Deppy-Timings.
                timings["total_s"] = time.perf_counter() - t0
                server.metrics.observe_request(timings["total_s"],
                                               timings.get("queue_wait_s"))
                # SLO accounting (ISSUE 11): every request lands on its
                # tenant's window — deadline misses from the
                # scheduler's triage, errors from the final status.
                server.slo.observe(
                    tenant, timings["total_s"],
                    deadline_miss=bool(
                        request_stats.get("deadline_misses")),
                    error=status is None or status >= 500)
                telemetry.trace.default_recorder().record(
                    ctx, status=status, timings=timings)

        def _resolve_request_inner(self, t0, timings, want_timings,
                                   tenant="default",
                                   request_stats=None) -> int:
            # Per-request deadline override: seconds of wall-clock budget
            # the client grants this resolve (proxy chains decrement it).
            deadline_s = None
            raw_deadline = self.headers.get("X-Deppy-Deadline-S")
            if raw_deadline is not None:
                import math

                try:
                    deadline_s = float(raw_deadline)
                except ValueError:
                    deadline_s = None
                # NaN would sail past every <= comparison (no 503, no
                # deadline at all) and inf would silently mean
                # "unbounded": both violate the header's contract.
                if deadline_s is None or not math.isfinite(deadline_s):
                    server.metrics.observe_error()
                    return self._send_json(
                        400, {"error": "invalid X-Deppy-Deadline-S header"})
            # A client-controlled Content-Length must not be able to
            # buffer unbounded memory on the service (enforced inside
            # the shared body reader).
            doc, err = self._read_json_body()
            if err is not None:
                return err
            try:
                status, resp = server.resolve_document(
                    doc, deadline_s=deadline_s, timings=timings,
                    tenant=tenant, request_stats=request_stats)
            except Exception as e:  # solver/runtime failure → a real 500,
                # visible to the caller and the error counter, instead of a
                # dropped connection from the handler's default traceback.
                server.metrics.observe_error()
                status, resp = 500, {"error": f"internal error: {e}"}
            if want_timings:
                # Opt-in breakdown (X-Deppy-Timings: 1): queue-wait /
                # dispatch / solve / decode seconds in the body.  Without
                # the header the body is untouched (byte-identical).
                timings["total_s"] = time.perf_counter() - t0
                resp = dict(resp)
                resp["timings"] = {k: round(float(v), 6)
                                   for k, v in sorted(timings.items())}
            return self._send_json(status, resp)

    return Handler


def _probe_handler(server: Server):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                ok = self.path == "/healthz" or server.serving()
                body = b"ok" if ok else b"not ready"
                if ok and self.path == "/readyz" and server.degraded():
                    # Still ready — the host engine serves — but say so:
                    # operators watching the probe see the degradation
                    # without waiting for a metrics scrape.
                    body = b"ok (degraded: accelerator breaker open)"
                self.send_response(200 if ok else 503)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_response(404)
                self.end_headers()

    return Handler


def serve(
    bind_address: str = ":8080",
    probe_address: str = ":8081",
    backend: str = "auto",
    max_steps: Optional[int] = None,
    request_deadline_s: Optional[float] = None,
    sched: Optional[str] = None,
    sched_max_wait_ms: Optional[float] = None,
    sched_max_fill: Optional[int] = None,
    cache_size: Optional[int] = None,
    mesh_devices: Optional[int] = None,
    incremental: Optional[str] = None,
    incremental_max_delta: Optional[float] = None,
    incremental_index_size: Optional[int] = None,
    slo: Optional[str] = None,
    portfolio: Optional[str] = None,
    speculate: Optional[str] = None,
    speculate_max_backlog: Optional[int] = None,
    replica: Optional[str] = None,
    fair: Optional[str] = None,
    tenant_weights: Optional[str] = None,
    obs_stream: Optional[str] = None,
    obs_flush_ms: Optional[float] = None,
    obs_baseline: Optional[str] = None,
    fleet_router: Optional[str] = None,
    fleet_advertise: Optional[str] = None,
    opt: Optional[str] = None,
    opt_max_iterations: Optional[int] = None,
    opt_iter_budget: Optional[int] = None,
    opt_max_weight: Optional[int] = None,
    route_learn: Optional[str] = None,
    route_shadow_rate: Optional[float] = None,
    route_registry: Optional[str] = None,
    sessions: Optional[str] = None,
    session_lease_s: Optional[float] = None,
    session_max: Optional[int] = None,
    session_max_per_tenant: Optional[int] = None,
) -> None:
    """Blocking entry point used by ``deppy serve`` (the analog of
    mgr.Start, main.go:85).  Exits cleanly on SIGTERM (how Kubernetes
    stops the shipped Deployment's pods) as well as Ctrl-C: readiness is
    cleared, in-flight requests drain (bounded by the request-deadline
    machinery), and both listeners close via ``shutdown()`` instead of
    dying mid-request."""
    import signal

    srv = Server(bind_address, probe_address, backend, max_steps,
                 request_deadline_s=request_deadline_s, sched=sched,
                 sched_max_wait_ms=sched_max_wait_ms,
                 sched_max_fill=sched_max_fill, cache_size=cache_size,
                 mesh_devices=mesh_devices, incremental=incremental,
                 incremental_max_delta=incremental_max_delta,
                 incremental_index_size=incremental_index_size,
                 slo=slo, portfolio=portfolio, speculate=speculate,
                 speculate_max_backlog=speculate_max_backlog,
                 replica=replica, fair=fair,
                 tenant_weights=tenant_weights,
                 obs_stream=obs_stream, obs_flush_ms=obs_flush_ms,
                 obs_baseline=obs_baseline, fleet_router=fleet_router,
                 fleet_advertise=fleet_advertise, opt=opt,
                 opt_max_iterations=opt_max_iterations,
                 opt_iter_budget=opt_iter_budget,
                 opt_max_weight=opt_max_weight,
                 route_learn=route_learn,
                 route_shadow_rate=route_shadow_rate,
                 route_registry=route_registry,
                 sessions=sessions, session_lease_s=session_lease_s,
                 session_max=session_max,
                 session_max_per_tenant=session_max_per_tenant)
    srv.start()
    stop = threading.Event()

    def _on_sigterm(signum, frame):
        srv.ready.clear()  # flip /readyz before draining
        stop.set()

    def _on_sigusr2(signum, frame):
        # Operator-triggered flight-recorder dump (ISSUE 4): every
        # retained request trace goes to the JSONL sink as `trace`
        # events — `kill -USR2 $PID` then `deppy trace ID --file ...`.
        n = telemetry.trace.default_recorder().dump(reason="sigusr2")
        print(f"[service] SIGUSR2: dumped {n} flight-recorder trace(s) "
              f"to {telemetry.default_registry().sink_path or '(no sink)'}",
              file=sys.stderr, flush=True)

    # Handler goes in before the startup banner: the banner is the "ready
    # to be signaled" cue for process supervisors (and the e2e test).
    prev = signal.signal(signal.SIGTERM, _on_sigterm)
    prev_usr2 = None
    if hasattr(signal, "SIGUSR2"):  # absent on Windows
        prev_usr2 = signal.signal(signal.SIGUSR2, _on_sigusr2)
    print(
        f"deppy service listening on :{srv.api_port} "
        f"(probes on :{srv.probe_port})",
        flush=True,
    )
    try:
        while not stop.is_set():
            stop.wait(3600)
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, prev)
        if prev_usr2 is not None:
            signal.signal(signal.SIGUSR2, prev_usr2)
        srv.shutdown()
        # Host worker pool (ISSUE 5): after shutdown() drained requests
        # and stopped the scheduler loop, nothing can dispatch to the
        # pool — drain (the pool lock serializes against any straggler
        # dispatch) and terminate the workers.  Owned here, at the
        # PROCESS entry point, not in Server.shutdown: the pool is
        # process-global like the breaker, and embedded servers come
        # and go without owning it.
        from . import hostpool

        hostpool.shutdown_default_pool()
