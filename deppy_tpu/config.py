"""Typed registry of every ``DEPPY_TPU_*`` environment knob (ISSUE 7).

The env surface grew one knob at a time across six subsystems — 100+
read sites over 20+ files — with the docs chasing the code by hand.
This module is the single declaration point: every knob's name, type,
default, consuming module, and help text live HERE, and three things
hang off the declaration:

  * **Typed reads.**  :func:`env_raw` (and the typed wrappers
    :func:`env_str` / :func:`env_int` / :func:`env_float` /
    :func:`env_bool`) resolve the environment *through* the registry —
    reading an undeclared ``DEPPY_TPU_*`` name raises
    :class:`UndeclaredEnvVar` at the call site instead of silently
    minting a knob nobody documented.  The fault layer's defensive
    parsers (``faults.env_float``, the subsystems' ``_env_int``) call
    :func:`require` first, so every legacy read site resolves through
    the registry without changing its parse-or-degrade semantics.
  * **Generated docs.**  :func:`render_markdown` emits the
    docs/configuration.md table (``python -m deppy_tpu.config``);
    tests/test_doc_sync.py pins the checked-in file against it both
    ways, the same way the observability metric tables are pinned.
  * **Lint.**  The ``registry-sync`` checker (``deppy lint``,
    :mod:`deppy_tpu.analysis.registry_sync`) scans the whole tree for
    ``DEPPY_TPU_*`` tokens and fails on any name missing from this
    registry — and on any declared name no code mentions.

Import-light on purpose (stdlib ``os``/``dataclasses`` only): every
subsystem — including :mod:`deppy_tpu.faults.policy` at the bottom of
the import order — can import it without cycles.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional

_PREFIX = "DEPPY_TPU_"


class UndeclaredEnvVar(KeyError):
    """A ``DEPPY_TPU_*`` read of a name missing from :data:`REGISTRY`."""


@dataclass(frozen=True)
class EnvVar:
    """One declared environment knob.  ``flag`` / ``config_key`` declare
    the knob's CLI-flag and ResolverConfig-file mirrors (ISSUE 8): the
    ``registry-sync`` checker pins them against ``deppy_tpu/cli.py``
    both ways, so a flag added without its env mirror (or a mirror
    declared here without its flag) is a lint finding."""

    name: str
    type: str       # "int" | "float" | "str" | "bool" | "path"
    default: object  # documented default; None = unset/off
    consumer: str   # primary reading module (dotted path)
    help: str
    flag: Optional[str] = None        # mirrored CLI flag (--foo-bar)
    config_key: Optional[str] = None  # mirrored ResolverConfig file key


def _v(name: str, type: str, default, consumer: str, help: str,
       flag: Optional[str] = None,
       config_key: Optional[str] = None) -> EnvVar:
    return EnvVar(name=name, type=type, default=default,
                  consumer=consumer, help=help, flag=flag,
                  config_key=config_key)


# Declaration order groups by subsystem; rendering sorts by name so the
# doc table is stable under insertion.
_DECLARATIONS: List[EnvVar] = [
    # --- telemetry -------------------------------------------------------
    _v("DEPPY_TPU_TELEMETRY_FILE", "path", None, "deppy_tpu.telemetry.registry",
       "JSONL event sink for spans/reports/fault events (also "
       "--telemetry-file); summarize with `deppy stats`.",
       flag="--telemetry-file"),
    _v("DEPPY_TPU_TRACE_RING", "int", 64, "deppy_tpu.telemetry.trace",
       "Flight-recorder capacity: recent completed request traces."),
    _v("DEPPY_TPU_TRACE_ERROR_RING", "int", 256, "deppy_tpu.telemetry.trace",
       "Flight-recorder error ring: errored traces retained separately "
       "so healthy bursts cannot evict incident context."),
    # --- profiler / SLO --------------------------------------------------
    _v("DEPPY_TPU_PROFILE", "str", "off", "deppy_tpu.profile.ledger",
       "Engine cost profiler: 'on' records the per-dispatch trip "
       "ledger (`profile` sink events, deppy_profile_* families, "
       "SolveReport ledger columns; also --profile).  Disarmed is "
       "byte-identical to the pre-profiler pipeline.",
       flag="--profile", config_key="profile"),
    _v("DEPPY_TPU_PROFILE_SAMPLE", "float", 1.0,
       "deppy_tpu.profile.ledger",
       "Fraction of dispatches the armed profiler samples, in (0, 1] "
       "(deterministic 1-in-N; also --profile-sample) — bounds the "
       "armed overhead.",
       flag="--profile-sample", config_key="profileSample"),
    _v("DEPPY_TPU_SLO", "str", None, "deppy_tpu.profile.slo",
       "Declarative per-tenant SLO config: inline JSON, @FILE, or a "
       "path mapping tenant -> {target_p99_s, error_budget} (also "
       "--slo); burn rates render on /metrics and /debug/slo.",
       flag="--slo", config_key="slo"),
    # --- faults ----------------------------------------------------------
    _v("DEPPY_TPU_FAULT_PLAN", "str", None, "deppy_tpu.faults.inject",
       "Fault-injection plan: inline JSON, @FILE, or a file path (also "
       "--fault-plan); see docs/robustness.md.",
       flag="--fault-plan"),
    _v("DEPPY_TPU_FAULT_RETRIES", "int", 2, "deppy_tpu.faults.policy",
       "Total attempts per device dispatch group (2 = one retry)."),
    _v("DEPPY_TPU_FAULT_BACKOFF_S", "float", 0.05, "deppy_tpu.faults.policy",
       "Base exponential-backoff sleep between dispatch retries."),
    _v("DEPPY_TPU_FAULT_BACKOFF_MAX_S", "float", 2.0,
       "deppy_tpu.faults.policy",
       "Backoff clamp: no retry sleeps longer than this."),
    _v("DEPPY_TPU_CHUNK_DEADLINE_S", "float", 0.0, "deppy_tpu.faults.policy",
       "Wall-clock bound on ONE dispatch attempt; exceeding it counts "
       "deppy_deadline_exceeded and charges the breaker (0 = off)."),
    _v("DEPPY_TPU_BATCH_DEADLINE_S", "float", None, "deppy_tpu.faults.policy",
       "Ambient wall-clock budget for a whole resolve batch (also "
       "--deadline / X-Deppy-Deadline-S); expiry degrades undispatched "
       "lanes to Incomplete.",
       flag="--deadline"),
    _v("DEPPY_TPU_BREAKER_THRESHOLD", "int", 3, "deppy_tpu.faults.breaker",
       "Consecutive device failures that trip the accelerator circuit "
       "breaker open (host-only serving)."),
    _v("DEPPY_TPU_BREAKER_RESET_S", "float", 30.0, "deppy_tpu.faults.breaker",
       "Breaker cooldown before one half-open probe dispatch."),
    # --- scheduler / cache ----------------------------------------------
    _v("DEPPY_TPU_SCHED", "str", "on", "deppy_tpu.service",
       "Cross-request continuous-batching scheduler ('off' restores "
       "byte-identical per-request dispatch; also --sched).",
       flag="--sched", config_key="sched"),
    _v("DEPPY_TPU_SCHED_MAX_WAIT_MS", "float", 5.0,
       "deppy_tpu.sched.scheduler",
       "Flush policy: max milliseconds the oldest queued problem waits "
       "for batchmates (also --sched-max-wait-ms).",
       flag="--sched-max-wait-ms", config_key="schedMaxWaitMs"),
    _v("DEPPY_TPU_SCHED_MAX_FILL", "int", 256, "deppy_tpu.sched.scheduler",
       "Flush policy: dispatch once a size class has this many lanes "
       "queued (also --sched-max-fill).",
       flag="--sched-max-fill", config_key="schedMaxFill"),
    _v("DEPPY_TPU_SCHED_MAX_DEPTH", "int", 4096, "deppy_tpu.sched.scheduler",
       "Queue depth past which admission returns 503 + Retry-After "
       "(0 = unbounded)."),
    _v("DEPPY_TPU_SCHED_LANES_PER_DEVICE", "int", 256,
       "deppy_tpu.sched.scheduler",
       "Mesh serving: a full flush targets n_devices x this many lanes "
       "so every device gets a full shard."),
    _v("DEPPY_TPU_CACHE_SIZE", "int", 1024, "deppy_tpu.sched.scheduler",
       "Canonical-form result-cache capacity in entries (0 disables; "
       "also --cache-size).",
       flag="--cache-size", config_key="cacheSize"),
    # --- portfolio racing -------------------------------------------------
    _v("DEPPY_TPU_PORTFOLIO", "str", "auto", "deppy_tpu.sched.scheduler",
       "Portfolio engine racing: 'on' races the top-K candidate "
       "backends per cold flush and serves the first definitive "
       "finisher; 'auto' races only size classes holding a measured "
       "`portfolio` row; 'off' restores the single-backend dispatch "
       "path byte for byte (also --portfolio).",
       flag="--portfolio", config_key="portfolio"),
    _v("DEPPY_TPU_PORTFOLIO_K", "int", 2, "deppy_tpu.sched.scheduler",
       "Top-K candidate backends raced per coalesced flush (min 2)."),
    _v("DEPPY_TPU_PORTFOLIO_SAMPLE_CHECK", "float", 0.0625,
       "deppy_tpu.sched.scheduler",
       "Deterministic 1-in-N fraction of non-canonical race wins "
       "cross-checked against the canonical backend's answer "
       "(mismatches serve canonical and raise a race_mismatch fault "
       "event; 0 disables)."),
    # --- speculative pre-resolution --------------------------------------
    _v("DEPPY_TPU_SPECULATE", "str", "on", "deppy_tpu.sched.scheduler",
       "Speculative pre-resolution: catalog publishes (POST "
       "/v1/catalog/publish, `deppy publish`) invalidate retracted "
       "cache entries and pre-solve affected cached families at idle "
       "priority, and POST /v1/resolve/preview serves read-only "
       "what-if resolutions ('off' restores pre-change dispatch byte "
       "for byte and 404s both endpoints; also --speculate).",
       flag="--speculate", config_key="speculate"),
    _v("DEPPY_TPU_SPECULATE_MAX_BACKLOG", "int", 2048,
       "deppy_tpu.sched.scheduler",
       "Speculative pre-solve backlog cap in lanes; pre-solves past it "
       "are dropped and counted (a drop costs a later cold solve, "
       "never an answer; also --speculate-max-backlog).",
       flag="--speculate-max-backlog", config_key="speculateMaxBacklog"),
    # --- incremental tier ------------------------------------------------
    _v("DEPPY_TPU_INCREMENTAL", "str", "on", "deppy_tpu.sched.scheduler",
       "Delta-aware incremental resolution: clause-set index + "
       "warm-start lane class in front of the exact result cache "
       "('off' restores pre-tier dispatch byte for byte; also "
       "--incremental).",
       flag="--incremental", config_key="incremental"),
    _v("DEPPY_TPU_INCREMENTAL_MAX_DELTA", "float", 0.25,
       "deppy_tpu.sched.scheduler",
       "Warm-start cutoff: deltas whose touched cone covers more than "
       "this fraction of the problem's variables cold-solve instead "
       "(also --incremental-max-delta).",
       flag="--incremental-max-delta", config_key="incrementalMaxDelta"),
    _v("DEPPY_TPU_INCREMENTAL_INDEX_SIZE", "int", 512,
       "deppy_tpu.sched.scheduler",
       "Clause-set index capacity in solved-problem entries (0 "
       "disables the tier; also --incremental-index-size).",
       flag="--incremental-index-size",
       config_key="incrementalIndexSize"),
    # --- optimization tier (ISSUE 18) ------------------------------------
    _v("DEPPY_TPU_OPT", "str", "on", "deppy_tpu.service",
       "Optimization tier: POST /v1/optimize (`deppy optimize` / "
       "`deppy explain`) serves minimal-change upgrade planning, "
       "weighted soft constraints, and explain-why-not blocking sets "
       "via the bound-tightening loop ('off' 404s the endpoint and "
       "restores pre-tier /v1/resolve byte for byte; also --opt).",
       flag="--opt", config_key="opt"),
    _v("DEPPY_TPU_OPT_MAX_ITERATIONS", "int", 64,
       "deppy_tpu.optimize.loop",
       "Bound-tightening iteration cap per optimize request; hitting "
       "it returns the best model found so far flagged non-optimal "
       "(also --opt-max-iterations).",
       flag="--opt-max-iterations", config_key="optMaxIterations"),
    _v("DEPPY_TPU_OPT_ITER_BUDGET", "int", 1048576,
       "deppy_tpu.optimize.loop",
       "Engine-step budget per tightening probe; an exhausted probe "
       "degrades the request to best-so-far instead of stalling a "
       "speculative-class lane (also --opt-iter-budget).",
       flag="--opt-iter-budget", config_key="optIterBudget"),
    _v("DEPPY_TPU_OPT_MAX_WEIGHT", "int", 64, "deppy_tpu.optimize.loop",
       "Largest accepted soft-constraint weight; heavier requests are "
       "rejected as malformed (a weight cap keeps objective values — "
       "and the tightening distance — bounded; also --opt-max-weight).",
       flag="--opt-max-weight", config_key="optMaxWeight"),
    # --- stateful sessions (ISSUE 20) ------------------------------------
    _v("DEPPY_TPU_SESSIONS", "str", "on", "deppy_tpu.service",
       "Stateful resolution sessions: POST /v1/session + "
       "/v1/session/{id}/op serve interactive assume/test/untest "
       "exploration against a retained catalog epoch ('off' constructs "
       "none of it — the endpoints 404 byte-identically, no session "
       "metric family registers, /v1/resolve is untouched; also "
       "--sessions).",
       flag="--sessions", config_key="sessions"),
    _v("DEPPY_TPU_SESSION_LEASE_S", "float", 300.0, "deppy_tpu.sessions",
       "Session lease in seconds: every op renews it; the sweeper "
       "expires sessions whose lease lapsed (also --session-lease-s).",
       flag="--session-lease-s", config_key="sessionLeaseS"),
    _v("DEPPY_TPU_SESSION_MAX", "int", 256, "deppy_tpu.sessions",
       "Hard cap on live sessions per replica; at the cap, expired "
       "sessions are LRU-evicted first and creation sheds 503 with a "
       "counted shed once none remain (also --session-max).",
       flag="--session-max", config_key="sessionMax"),
    _v("DEPPY_TPU_SESSION_MAX_PER_TENANT", "int", 64, "deppy_tpu.sessions",
       "Per-tenant session cap: unauthenticated session creation must "
       "not become a memory DoS, so a tenant at its cap sheds 503 even "
       "with global headroom (also --session-max-per-tenant).",
       flag="--session-max-per-tenant", config_key="sessionMaxPerTenant"),
    # --- fleet (ISSUE 15) ------------------------------------------------
    _v("DEPPY_TPU_FLEET_REPLICAS", "str", None, "deppy_tpu.fleet.router",
       "Replica addresses the affinity router fronts, comma-separated "
       "host:port (also --replicas on `deppy route`).",
       flag="--replicas"),
    _v("DEPPY_TPU_FLEET_VNODES", "int", 64, "deppy_tpu.fleet.router",
       "Virtual nodes per replica on the consistent-hash ring (also "
       "--vnodes); more vnodes = smoother arc split on membership "
       "churn.",
       flag="--vnodes"),
    _v("DEPPY_TPU_FLEET_PROBE_INTERVAL_S", "float", 2.0,
       "deppy_tpu.fleet.router",
       "Seconds between router health probes per replica (also "
       "--probe-interval; 0 disables probing — forwards still charge "
       "the breaker).",
       flag="--probe-interval"),
    _v("DEPPY_TPU_FLEET_PROBE_FAILURES", "int", 3,
       "deppy_tpu.fleet.router",
       "Consecutive transport failures (probe or live forward) that "
       "mark a replica dead and reassign its ring arcs (also "
       "--probe-failures); a later successful probe revives it.",
       flag="--probe-failures"),
    _v("DEPPY_TPU_REPLICA", "str", None, "deppy_tpu.service",
       "This replica's serving identity in a fleet (also --replica): "
       "labels the per-tenant SLO families, /debug/slo, and the "
       "service.request span so burn rate is attributable per tenant "
       "per replica; unset keeps single-process surfaces unchanged.",
       flag="--replica", config_key="replica"),
    # --- elastic membership (ISSUE 17) -----------------------------------
    _v("DEPPY_TPU_FLEET", "str", "elastic", "deppy_tpu.fleet.membership",
       "Fleet membership mode (also --membership on `deppy route`): "
       "'elastic' arms runtime joins (POST /fleet/join — chunked "
       "warm-state streaming, then an atomic arc flip), drain-as-leave "
       "ring removal with a membership epoch, peer gossip (POST "
       "/fleet/sync), and GET /fleet/policy; 'static' restores the "
       "PR 15 immutable-ring surface byte for byte.",
       flag="--membership"),
    _v("DEPPY_TPU_FLEET_PEERS", "str", None, "deppy_tpu.fleet.router",
       "Peer router addresses for membership gossip, comma-separated "
       "host:port (also --peers on `deppy route`): routers exchange "
       "epoch-versioned ring views so clients can hit any of them and "
       "a dead router is not an outage.",
       flag="--peers"),
    _v("DEPPY_TPU_FLEET_SYNC_INTERVAL_S", "float", 2.0,
       "deppy_tpu.fleet.router",
       "Seconds between membership gossip rounds with the peer list "
       "(jittered like the probe loop; 0 disables the background loop "
       "— inbound POST /fleet/sync still reconciles).",),
    _v("DEPPY_TPU_FLEET_PROBE_JITTER", "float", 0.2,
       "deppy_tpu.fleet.router",
       "Random fraction of the probe (and gossip) interval added to "
       "each cycle's sleep, clamped to [0, 1] — the lease renew_jitter "
       "pattern, so a large fleet's probes do not thunder in lockstep."),
    _v("DEPPY_TPU_FLEET_JOIN_CHUNK", "int", 64,
       "deppy_tpu.fleet.membership",
       "Warm-state entries per checksummed join-stream chunk: a "
       "joining replica's inherited index entries and cache seeds "
       "stream in bounded, individually sealed chunks so a truncated "
       "transfer is rejected loudly and resumes per chunk."),
    _v("DEPPY_TPU_FLEET_JOIN_RETRIES", "int", 2,
       "deppy_tpu.fleet.membership",
       "Resend attempts per failed join-stream chunk before the join "
       "aborts (membership unchanged — the arc flip only happens once "
       "the whole stream lands)."),
    _v("DEPPY_TPU_FLEET_ROUTER", "str", None, "deppy_tpu.service",
       "Fleet router address this replica announces itself to (also "
       "--fleet-router): POST /fleet/join once serving starts, and the "
       "drain handoff (leave) on graceful shutdown; unset keeps the "
       "standalone lifecycle byte for byte.",
       flag="--fleet-router", config_key="fleetRouter"),
    _v("DEPPY_TPU_FLEET_ADVERTISE", "str", None, "deppy_tpu.service",
       "host:port this replica advertises when joining a fleet (also "
       "--fleet-advertise); defaults to 127.0.0.1:<api-port>, which "
       "only holds for single-host fleets.",
       flag="--fleet-advertise", config_key="fleetAdvertise"),
    _v("DEPPY_TPU_FLEET_BURN_UP", "float", 1.0, "deppy_tpu.fleet.policy",
       "Per-tenant SLO burn-rate threshold above which the autoscale "
       "policy recommends scale_up (no cold capacity) or rebalance "
       "(cold capacity exists) on GET /fleet/policy."),
    _v("DEPPY_TPU_FLEET_BURN_DOWN", "float", 0.25,
       "deppy_tpu.fleet.policy",
       "Per-tenant SLO burn-rate floor: every replica under it with an "
       "idle queue recommends scale_down; execution stays "
       "operator-driven (`deppy fleet scale --apply` is the "
       "local-process mode for the bench/soak harness)."),
    # --- scheduler fairness (ISSUE 15) -----------------------------------
    _v("DEPPY_TPU_SCHED_FAIR", "str", "on", "deppy_tpu.sched.scheduler",
       "Weighted-fair per-tenant admission + priority lanes: 'on' "
       "sheds each tenant at its weighted share of the queue and "
       "orders flush heads by tenant priority class; 'off' restores "
       "the global-depth 503 and strict FIFO byte for byte (also "
       "--sched-fair).",
       flag="--sched-fair", config_key="schedFair"),
    _v("DEPPY_TPU_SCHED_TENANT_WEIGHTS", "str", None,
       "deppy_tpu.sched.scheduler",
       "Declarative tenant weights/priorities for the fair gate: "
       "inline JSON, @FILE, or a path mapping tenant -> weight number "
       "or {weight, priority} ('default' covers unlisted tenants; "
       "also --sched-tenant-weights).",
       flag="--sched-tenant-weights", config_key="schedTenantWeights"),
    # --- observability plane (ISSUE 16) ----------------------------------
    _v("DEPPY_TPU_OBS_STREAM", "str", None, "deppy_tpu.obs.stream",
       "Fleet telemetry streaming: aggregator address (host:port, "
       "normally the router) this replica batch-pushes its sink events "
       "to via POST /fleet/telemetry (also --obs-stream); unset keeps "
       "the local-sink-only pipeline byte for byte.",
       flag="--obs-stream", config_key="obsStream"),
    _v("DEPPY_TPU_OBS_FLUSH_MS", "float", 200.0, "deppy_tpu.obs.stream",
       "Max milliseconds a queued telemetry event waits before the "
       "streamer flushes a batch to the aggregator (also "
       "--obs-flush-ms).",
       flag="--obs-flush-ms", config_key="obsFlushMs"),
    _v("DEPPY_TPU_OBS_QUEUE", "int", 4096, "deppy_tpu.obs.stream",
       "Streamer queue capacity in events; a slow aggregator fills it "
       "and further events are DROPPED and counted "
       "(deppy_obs_stream_dropped_total) instead of stalling serving."),
    _v("DEPPY_TPU_OBS_BATCH", "int", 256, "deppy_tpu.obs.stream",
       "Max events per streamed POST /fleet/telemetry batch."),
    _v("DEPPY_TPU_OBS_BACKOFF_MAX_S", "float", 5.0,
       "deppy_tpu.obs.stream",
       "Ceiling in seconds on the streamer's bounded exponential "
       "hold-off after a failed telemetry POST (resumed streaks are "
       "counted on deppy_obs_stream_reconnects_total); the final "
       "close() flush bypasses the hold-off."),
    _v("DEPPY_TPU_OBS_SINK", "path", None, "deppy_tpu.obs.aggregate",
       "Router-side merged fleet sink: JSONL path the telemetry "
       "aggregator appends replica-stamped events to (also --obs-sink "
       "on `deppy route`); unset 404s POST /fleet/telemetry.",
       flag="--obs-sink"),
    _v("DEPPY_TPU_OBS_BASELINE", "path", None, "deppy_tpu.obs.drift",
       "Cost-model baseline artifact for the drift watchdog: a "
       "BENCH_rNN.json (or any JSON with a `costmodel` section) whose "
       "per-size-class µs/trip the live regression is compared "
       "against (also --obs-baseline); unset disarms the watchdog "
       "byte for byte.",
       flag="--obs-baseline", config_key="obsBaseline"),
    _v("DEPPY_TPU_OBS_DRIFT_BAND", "float", 0.5, "deppy_tpu.obs.drift",
       "Relative drift band for the cost-model watchdog: a live "
       "per-size-class µs/trip fit farther than this fraction from "
       "the baseline emits a costmodel_drift event and pushes "
       "deppy_costmodel_drift_ratio past the band."),
    _v("DEPPY_TPU_OBS_DRIFT_MIN", "int", 8, "deppy_tpu.obs.drift",
       "Minimum sampled device dispatches per size class before the "
       "drift watchdog trusts its regression enough to compare."),
    # --- route health ----------------------------------------------------
    _v("DEPPY_TPU_ROUTE_LEARN", "str", "off", "deppy_tpu.routes",
       "Route-health plane: 'off' (default) arms nothing — no regret "
       "ledger, no route_* metric families, responses byte-identical; "
       "'observe' runs the regret ledger, staleness watcher, and "
       "shadow probing; 'on' adds the online route registry that "
       "adopts learned portfolio rows onto the in-memory overlay "
       "(also --route-learn).  Audit with `deppy routes`.",
       flag="--route-learn", config_key="routeLearn"),
    _v("DEPPY_TPU_ROUTE_SHADOW_RATE", "float", 0.0625,
       "deppy_tpu.routes.shadow",
       "Fraction of a STALE-flagged class's flushes duplicated to one "
       "non-serving backend at idle priority (deterministic 1-in-N "
       "per class; 0 disables probing; also --route-shadow-rate).",
       flag="--route-shadow-rate", config_key="routeShadowRate"),
    _v("DEPPY_TPU_ROUTE_MAX_AGE_S", "float", 604800.0,
       "deppy_tpu.routes.staleness",
       "Measured-defaults provenance age past which a live-observed "
       "class's routing row is flagged stale (default 7 days)."),
    _v("DEPPY_TPU_ROUTE_MIN_SAMPLES", "int", 8, "deppy_tpu.routes.learn",
       "Uncensored live observations per (class, backend) before the "
       "online route registry trusts its decayed estimate enough to "
       "re-rank."),
    _v("DEPPY_TPU_ROUTE_DECAY", "float", 0.2, "deppy_tpu.routes.ledger",
       "EWMA weight of the newest observation in the regret ledger's "
       "per-(class, backend) wall estimates, in (0, 1]."),
    _v("DEPPY_TPU_ROUTE_REGISTRY", "path", None, "deppy_tpu.routes.learn",
       "Optional path where live-learned routing rows persist through "
       "the shared flock-guarded defaults store (also "
       "--route-registry); unset keeps adoptions in-memory only.",
       flag="--route-registry", config_key="routeRegistry"),
    # --- service ---------------------------------------------------------
    _v("DEPPY_TPU_REQUEST_DEADLINE_S", "float", None, "deppy_tpu.service",
       "Default wall-clock budget per /v1/resolve request (clients "
       "override via X-Deppy-Deadline-S; also --request-deadline).",
       flag="--request-deadline", config_key="requestDeadlineSeconds"),
    _v("DEPPY_TPU_DRAIN_S", "float", None, "deppy_tpu.service",
       "Graceful-shutdown bound on draining in-flight requests "
       "(default: the request deadline, else 10s)."),
    _v("DEPPY_TPU_REPROBE", "float", 600.0, "deppy_tpu.service",
       "Seconds between background accelerator re-probes while serving "
       "degraded (0 disables)."),
    # --- hostpool --------------------------------------------------------
    _v("DEPPY_TPU_HOST_WORKERS", "int", None, "deppy_tpu.hostpool.pool",
       "Host-engine worker pool size (default min(cpu_count, 8); 0 = "
       "inline serial engine; also --host-workers).",
       flag="--host-workers", config_key="hostWorkers"),
    _v("DEPPY_TPU_HOST_WORKER_RECYCLE", "int", 256,
       "deppy_tpu.hostpool.pool",
       "Solves per worker before it is retired and replaced (leak "
       "hygiene; 0 = never)."),
    _v("DEPPY_TPU_HOSTPOOL_SPAWN_TIMEOUT_S", "float", 30.0,
       "deppy_tpu.hostpool.pool",
       "Bound on a spawned worker's ready handshake; a sandbox that "
       "allows fork but hangs it must not hang the solve path."),
    _v("DEPPY_TPU_HOSTPOOL_START_METHOD", "str", "forkserver",
       "deppy_tpu.hostpool.pool",
       "multiprocessing start method for pool workers."),
    # --- mesh serving ----------------------------------------------------
    _v("DEPPY_TPU_MESH_DEVICES", "int", None, "deppy_tpu.parallel.mesh",
       "Shard each coalesced micro-batch across N devices ('all'/-1 = "
       "every local device; unset/0/1 = single-device dispatch; also "
       "--mesh-devices).",
       flag="--mesh-devices", config_key="meshDevices"),
    # --- engine ----------------------------------------------------------
    _v("DEPPY_TPU_MAX_LANES", "int", 512, "deppy_tpu.engine.driver",
       "Per-dispatch lane cap; oversized programs crash the tunneled "
       "TPU worker, so batches chunk to this width."),
    _v("DEPPY_TPU_PROBE_LANES", "int", 512, "deppy_tpu.engine.driver",
       "Lane width of the backend-usability probe dispatch."),
    _v("DEPPY_TPU_HOST_CORE_NCONS", "int", 768, "deppy_tpu.engine.driver",
       "Constraint count above which UNSAT-core extraction routes to "
       "the host engine."),
    _v("DEPPY_TPU_SPEC_CORE", "str", "auto", "deppy_tpu.engine.driver",
       "Speculative phase-3 core extraction: auto/on/off."),
    _v("DEPPY_TPU_SPEC_CORE_CAP", "int", 32768, "deppy_tpu.engine.driver",
       "Cost-proxy cap above which speculative core extraction is "
       "skipped."),
    _v("DEPPY_TPU_STAGE1_STEPS", "int", 0, "deppy_tpu.engine.driver",
       "Stage-1 step budget of the escalation ladder (0 = measured "
       "default)."),
    _v("DEPPY_TPU_BCP", "str", "auto", "deppy_tpu.engine.core",
       "BCP propagation implementation: auto/gather/bits/pallas/"
       "blockwise/watched ('watched' = the compressed-clause-bank "
       "implication-driven path; 'auto' resolves through the "
       "measured-defaults registry, falling back to 'bits'; also "
       "--bcp).",
       flag="--bcp", config_key="bcp"),
    _v("DEPPY_TPU_BANK_OCC_CAP", "int", 0, "deppy_tpu.engine.driver",
       "Watched-bank occurrence-width cap: a dispatch whose max "
       "per-literal clause count exceeds the cap ships dummy banks and "
       "runs the dense propagation program instead (0 = the dispatch's "
       "size-class OCC cap from deppy_tpu.size_classes)."),
    _v("DEPPY_TPU_SIZE_LADDER", "str", "on", "deppy_tpu.engine.driver",
       "Size-class partitioner: 'on' = the shared ladder "
       "(deppy_tpu.size_classes), 'off' = the legacy adjacent-jump "
       "splitter (A/B only)."),
    _v("DEPPY_TPU_BCP_UNROLL", "int", 1, "deppy_tpu.engine.core",
       "Propagation-loop unroll factor (trip-overhead amortization)."),
    _v("DEPPY_TPU_DPLL_UNROLL", "int", 1, "deppy_tpu.engine.core",
       "DPLL decision-loop unroll factor."),
    _v("DEPPY_TPU_CTL_UNROLL", "int", 1, "deppy_tpu.engine.core",
       "Control-loop unroll factor."),
    _v("DEPPY_TPU_SEARCH", "str", "auto", "deppy_tpu.engine.core",
       "Search-phase implementation: auto/xla/fused (fused = the "
       "whole-search Pallas kernel)."),
    _v("DEPPY_TPU_MEASURED_DEFAULTS", "path", None, "deppy_tpu.engine.core",
       "Override path of the measured-defaults registry JSON (default: "
       "the package-local engine/measured_defaults.json)."),
    _v("DEPPY_TPU_BLOCK_ROWS", "int", 2048,
       "deppy_tpu.engine.pallas_blockwise",
       "Clause-row block height of the blockwise BCP kernel."),
    # --- platform / tooling ---------------------------------------------
    _v("DEPPY_TPU_COMPILE_CACHE", "path", None,
       "deppy_tpu.utils.platform_env",
       "Persistent XLA compile-cache directory ('off'/'0' disables; "
       "default on only for non-CPU platforms)."),
    _v("DEPPY_TPU_REVAL_LOG", "path", None, "scripts.tpu_revalidate",
       "JSONL record log shared by the revalidation ladder and "
       "bench.py's accelerator records."),
    # --- analysis --------------------------------------------------------
    _v("DEPPY_TPU_LOCKDEP", "bool", False, "deppy_tpu.analysis.lockdep",
       "Runtime lock-order assertion mode: named locks track "
       "acquisition order per thread, raise on lock-order inversions "
       "and self-deadlocks, and emit `lockdep` telemetry events."),
    _v("DEPPY_TPU_COMPILE_GUARD", "bool", False,
       "deppy_tpu.analysis.compileguard",
       "Runtime compile-guard mode: every registered jit entry's "
       "trace/compile is emitted as a `compileguard` telemetry event, "
       "and retracing one abstract signature past the entry's budget "
       "raises CompileGuardError (summarize with `deppy compiles`)."),
    _v("DEPPY_TPU_COMPILE_BUDGET", "int", None,
       "deppy_tpu.analysis.compileguard",
       "Per-signature trace budget for compile-guarded jit entries "
       "(default: 2 x local device count — per-device placement keys "
       "jit's cache once per device)."),
]

REGISTRY: "dict[str, EnvVar]" = {v.name: v for v in _DECLARATIONS}
assert len(REGISTRY) == len(_DECLARATIONS), "duplicate EnvVar declaration"


def declared(name: str) -> bool:
    return name in REGISTRY


def require(name: str) -> Optional[EnvVar]:
    """Assert ``name`` is a declared knob.  Only ``DEPPY_TPU_*`` names
    are enforced — the defensive parse helpers are shared with
    non-namespaced knobs (tests, DEPPY_BENCH_*) that this registry does
    not own.  Returns the declaration (None for foreign names)."""
    if not name.startswith(_PREFIX):
        return None
    try:
        return REGISTRY[name]
    except KeyError:
        raise UndeclaredEnvVar(
            f"{name} is not declared in deppy_tpu.config.REGISTRY — "
            f"declare it (name, type, default, consumer, help) so "
            f"docs/configuration.md and `deppy lint` stay in sync"
        ) from None


def env_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """``os.environ.get`` through the registry: the declaration is
    asserted, the value comes back verbatim (callers keep their own
    parse-or-degrade semantics)."""
    require(name)
    return os.environ.get(name, default)


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    raw = env_raw(name)
    return raw if raw is not None and raw.strip() else default


def env_int(name: str, default: Optional[int] = None,
            strict: bool = True) -> Optional[int]:
    """Typed int read.  ``strict`` raises on a malformed value (the
    engine's import-time knobs fail loud); ``strict=False`` degrades to
    the default like the fault layer's parsers."""
    raw = env_raw(name)
    if raw is None or not raw.strip():
        return default
    try:
        return int(raw)
    except ValueError:
        if strict:
            raise
        return default


def env_float(name: str, default: Optional[float] = None,
              strict: bool = True) -> Optional[float]:
    raw = env_raw(name)
    if raw is None or not raw.strip():
        return default
    try:
        return float(raw)
    except ValueError:
        if strict:
            raise
        return default


_TRUE = frozenset(("1", "true", "yes", "on"))
_FALSE = frozenset(("0", "false", "no", "off", ""))


def env_bool(name: str, default: bool = False) -> bool:
    raw = env_raw(name)
    if raw is None:
        return default
    token = raw.strip().lower()
    if token in _TRUE:
        return True
    if token in _FALSE:
        return False
    return default


# ------------------------------------------------------------------ docs


def _fmt_default(v: EnvVar) -> str:
    if v.default is None:
        return "(unset)"
    if v.type == "bool":
        return "on" if v.default else "off"
    return str(v.default)


def render_markdown() -> str:
    """The docs/configuration.md body, generated from the registry.
    ``python -m deppy_tpu.config`` regenerates the file;
    tests/test_doc_sync.py pins the checked-in copy against this."""
    lines = [
        "# Configuration",
        "",
        "<!-- GENERATED FILE — do not edit by hand.",
        "     Regenerate with: python -m deppy_tpu.config > "
        "docs/configuration.md",
        "     Source of truth: deppy_tpu/config.py (REGISTRY). -->",
        "",
        "Every `DEPPY_TPU_*` environment knob, generated from the typed",
        "registry in `deppy_tpu/config.py`.  The `registry-sync` checker",
        "(`deppy lint`) fails on any knob read in code but missing here,",
        "and `tests/test_doc_sync.py` pins this file against the",
        "registry both ways.  The Mirrors column names the knob's",
        "declared CLI-flag / ResolverConfig-key twins; `registry-sync`",
        "pins those against `deppy_tpu/cli.py` both ways too.",
        "",
        "| Name | Type | Default | Consumer | Mirrors | Description |",
        "| --- | --- | --- | --- | --- | --- |",
    ]
    for name in sorted(REGISTRY):
        v = REGISTRY[name]
        mirrors = " ".join(
            f"`{m}`" for m in (v.flag, v.config_key) if m) or "—"
        lines.append(
            f"| `{v.name}` | {v.type} | `{_fmt_default(v)}` | "
            f"`{v.consumer}` | {mirrors} | {v.help} |")
    lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":
    import sys

    sys.stdout.write(render_markdown())
