"""Clause-sharded solve: intra-problem parallelism for giant problems.

The batch axis (mesh.py) scales *many* problems; this module scales *one*
problem past a single core — the framework's honest translation of
sequence-length scaling (SURVEY.md §5 "long-context"): a problem whose
clause planes exceed one core's VMEM/HBM budget is sharded along the
**clause row axis** over the mesh.  Every device runs the identical,
replicated solve control flow (baseline Test, guess search, DPLL leaves,
minimization, core extraction — all of :func:`deppy_tpu.engine.core
.solve_full`); only boolean-constraint propagation touches the sharded
rows, and each round combines the per-shard forced-literal masks and
conflict flags with one fused OR all-gather (:class:`core.clause_axis`).
That is the entire communication pattern — a few dozen packed words per
round over ICI, no resharding, no host round trips inside the solve.

This is SPMD by construction: control state (assignment planes, stacks,
deques) is replicated, so every device computes identical values and the
collectives are the only cross-device dependence.  Results decode exactly
like the batched path's.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..sat.constraints import Variable
from ..sat.encode import Problem, encode
from ..sat.errors import (BackendCapabilityError, Incomplete,
                          InternalSolverError, NotSatisfiable)
from ..analysis import compileguard
from ..engine import core, driver
from ._compat import shard_map

CLAUSE_AXIS = "clause"


def clause_mesh(devices=None) -> Mesh:
    """A 1-D mesh over ``devices`` with the clause-row axis."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (CLAUSE_AXIS,))


# ProblemTensors fields whose leading axis is the clause (C) or
# cardinality (NA) row axis — these shard; everything else replicates.
_ROW_SHARDED = {
    "clauses", "card_ids", "card_n", "card_act", "card_valid",
    "pos_bits", "neg_bits", "card_member_bits", "card_act_bits",
    "pos_bits_r", "neg_bits_r", "card_member_bits_r",
}


def _specs(axis: str) -> core.ProblemTensors:
    return core.ProblemTensors(**{
        f: (P(axis) if f in _ROW_SHARDED else P())
        for f in core.ProblemTensors._fields
    })


class _ShardDims(driver._Dims):
    """Batch dims with the row axes padded to a multiple of the mesh size
    (power-of-two meshes keep per-shard rows a power of two, which the
    halving-tree OR-reduce in round_planes relies on)."""

    def __init__(self, problems, n_devices: int):
        super().__init__(problems, 1)
        for f in ("C", "NA"):
            v = getattr(self, f)
            setattr(self, f, -(-v // n_devices) * n_devices)


@functools.lru_cache(maxsize=64)
def _sharded_fn(mesh: Mesh, V: int, NCON: int, NV: int,
                with_core: bool = True):
    """Compiled clause-sharded solve for one (mesh, space) signature —
    memoized like the driver's batched_* entry points, so same-shaped
    giant problems compile once.  Input-shape variation within a
    signature retraces via jit's own cache; callers must hold
    :class:`core.clause_axis` around invocations so those retraces pick
    up the collectives.  ``with_core=False`` compiles the deletion arm
    out (host-routed core extraction, driver.HOST_CORE_NCONS)."""
    devices = tuple(d.id for d in mesh.devices.flat)
    return jax.jit(compileguard.observe(
        "clause_shard.sharded_fn",
        shard_map(
            functools.partial(core.solve_full, V=V, NCON=NCON, NV=NV,
                              with_core=with_core),
            mesh=mesh,
            in_specs=(_specs(CLAUSE_AXIS), P()),
            out_specs=core.SolveResult(
                *[P()] * len(core.SolveResult._fields)),
            check_vma=False,
        ),
        static=(devices, V, NCON, NV, with_core),
    ))


def solve_sharded(
    problem: Problem,
    mesh: Optional[Mesh] = None,
    max_steps: Optional[int] = None,
) -> core.SolveResult:
    """Solve ONE lowered problem with its clause rows sharded over the
    mesh.  Use for problems too large for a single core; for fleets of
    normal-sized problems use the batched driver."""
    if problem.errors:
        raise InternalSolverError(problem.errors)
    if core._resolved_impl() != "bits":
        # Only the bitplane round kernel carries the per-round OR
        # collective; the gather/pallas paths would propagate per-shard
        # with no cross-device combine and silently return wrong answers.
        # Typed (not a raw NotImplementedError): callers that never chose
        # an impl — the facade, the service — get a clean
        # backend-capability verdict they can render, not an internal
        # crash.
        raise BackendCapabilityError(
            "clause_shard", core._resolved_impl(),
            hint="clause-sharded solve carries its per-round OR "
            "collective only in the 'bits' BCP round kernel; unset "
            "DEPPY_TPU_BCP or select bits",
        )
    if mesh is None:
        mesh = clause_mesh()
    n_dev = mesh.devices.size
    d = _ShardDims([problem], n_dev)
    pts = driver.pad_problem(problem, d, pack=True)
    budget = driver._budget(max_steps)

    # Giant problems (which clause sharding exists for) host-route their
    # core extraction exactly like the batched driver: the deletion
    # sweep's kept-member probes are full SAT searches a serial engine
    # resolves faster, and a minutes-long device program endangers the
    # tunneled worker (BASELINE.md round-3 notes).
    host_core = problem.n_cons > driver.HOST_CORE_NCONS
    with core.clause_axis(CLAUSE_AXIS):
        res = _sharded_fn(mesh, d.V, d.NCON, d.NV,
                          with_core=not host_core)(pts, budget)
    res = jax.device_get(core.SolveResult(*res))
    if host_core and int(res.outcome) == core.UNSAT:
        cores_, steps_ = driver._host_core_rows(
            [problem], [0], d, budget, np.asarray([int(res.steps)])
        )
        total = int(res.steps) + int(steps_[0])
        res = res._replace(
            core=cores_[0],
            steps=np.int64(total),
            outcome=np.int32(core.RUNNING if total > int(budget)
                             else res.outcome),
        )
    return res


def solve_one_sharded(
    variables: List[Variable],
    mesh: Optional[Mesh] = None,
    max_steps: Optional[int] = None,
) -> List[Variable]:
    """End-to-end single-problem entry with clause sharding: same contract
    as ``Solver.solve()`` — installed variables, or :class:`NotSatisfiable`
    with the minimal constraint core, or :class:`Incomplete`."""
    problem = encode(variables)
    res = solve_sharded(problem, mesh=mesh, max_steps=max_steps)
    if int(res.outcome) == core.SAT:
        return driver._decode_installed(problem, np.asarray(res.installed))
    if int(res.outcome) == core.UNSAT:
        raise driver._decode_core(problem, np.asarray(res.core))
    raise Incomplete()
