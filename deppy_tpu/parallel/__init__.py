"""Device-mesh scale-out for batched resolution.

The reference has no distributed runtime at all (SURVEY.md §2.7) — its only
concurrency is two TODO comments and controller leader election, which
serializes work.  This package is therefore new, tpu-first design: the batch
axis of independent resolution problems is sharded over a
``jax.sharding.Mesh`` with ``NamedSharding``; XLA partitions the vmapped
solve with zero steady-state cross-device traffic (problems are independent
— the only collective is the implicit final gather of outcome tensors back
to host).  The same code scales to multi-host DCN fleets via
``jax.distributed`` initialization.
"""

from .mesh import BATCH_AXIS, default_mesh, initialize_distributed, shard_batch

__all__ = ["BATCH_AXIS", "default_mesh", "initialize_distributed", "shard_batch"]
