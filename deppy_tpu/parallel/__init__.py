"""Device-mesh scale-out: batch sharding and clause sharding.

The reference has no distributed runtime at all (SURVEY.md §2.7) — its only
concurrency is two TODO comments and controller leader election, which
serializes work.  This package is therefore new, tpu-first design, with two
orthogonal parallelism axes:

  * **Batch axis** (:mod:`.mesh`) — N independent problems sharded over a
    ``jax.sharding.Mesh`` with ``NamedSharding``; XLA partitions the
    vmapped solve with zero steady-state cross-device traffic (the only
    collective is the implicit final gather of outcome tensors).  The
    fleet-scale path.
  * **Clause axis** (:mod:`.clause_shard`) — ONE problem's clause rows
    sharded over the mesh via ``shard_map``, replicated control flow, one
    OR all-gather of forced-literal masks per propagation round.  The
    giant-problem path (the honest analog of sequence parallelism,
    SURVEY.md §5).

Both scale to multi-host DCN fleets via ``jax.distributed`` initialization.
"""

from ._compat import resolve_shard_map, shard_map
from .clause_shard import clause_mesh, solve_one_sharded, solve_sharded
from .mesh import (BATCH_AXIS, batch_sharding, default_mesh,
                   initialize_distributed, mesh_devices_from_env,
                   replicated_sharding, serving_mesh, shard_batch)

__all__ = [
    "BATCH_AXIS", "batch_sharding", "default_mesh",
    "initialize_distributed", "mesh_devices_from_env",
    "replicated_sharding", "resolve_shard_map", "serving_mesh",
    "shard_batch", "shard_map",
    "clause_mesh", "solve_one_sharded", "solve_sharded",
]
