"""JAX version-compat shims for the sharded path.

``shard_map`` has moved twice and renamed a kwarg once across the JAX
releases this repo has met in the wild:

  * ≤ 0.4.x — ``jax.experimental.shard_map.shard_map(..., check_rep=)``;
  * ≥ 0.5/0.6 — promoted to ``jax.shard_map(..., check_vma=)`` (the
    replication check was generalized to "varying manual axes").

The sharded solve paths must run on whichever spelling the installed
JAX carries — an AttributeError at dispatch time took out 17 tier-1
tests on 0.4.37 (ROADMAP open item 1).  This module resolves the
callable once, inspects its *actual* signature, and maps whichever of
``check_rep``/``check_vma`` the caller used onto the parameter the
installed build accepts, so both old and new call sites survive the
next rename.
"""

from __future__ import annotations

import functools
import inspect
from typing import Callable, Optional


@functools.lru_cache(maxsize=1)
def resolve_shard_map() -> Callable:
    """The installed build's ``shard_map`` callable, wherever it lives."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    return fn


@functools.lru_cache(maxsize=1)
def _check_param() -> Optional[str]:
    """Which replication-check kwarg the installed ``shard_map`` takes:
    ``"check_rep"``, ``"check_vma"``, or None when neither exists (the
    check is dropped rather than guessed — passing an unknown kwarg is
    the exact failure class this shim removes)."""
    try:
        params = inspect.signature(resolve_shard_map()).parameters
    except (TypeError, ValueError):  # C-accelerated/builtin: no signature
        return None
    for name in ("check_rep", "check_vma"):
        if name in params:
            return name
    if any(p.kind is inspect.Parameter.VAR_KEYWORD
           for p in params.values()):
        # **kwargs swallows anything; prefer the modern spelling.
        return "check_vma"
    return None


def shard_map(f, mesh, in_specs, out_specs, check_rep=None,
              check_vma=None, **kwargs):
    """Version-portable ``shard_map``.

    ``check_rep`` and ``check_vma`` are aliases for the same knob (the
    per-output replication/varying check); pass either and it reaches
    the installed build under whatever name that build expects.  Extra
    kwargs pass through untouched.
    """
    check = check_vma if check_vma is not None else check_rep
    if check is not None:
        param = _check_param()
        if param is not None:
            kwargs[param] = check
    return resolve_shard_map()(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, **kwargs)
