"""Mesh construction and batch-axis sharding.

The communication design (SURVEY.md §2.7, §5): one 1-D logical axis,
``"batch"``, laid over all available devices (ICI within a host/slice, DCN
across hosts).  Each device solves its shard of the problem batch in
lockstep; no collectives are needed during the solve because problems are
independent — an all-gather of the small outcome/selection tensors happens
implicitly when results are fetched.  This replaces, tpu-natively, what a
NCCL/MPI backend would be in a GPU framework: the mesh axes + shardings ARE
the communication topology, and XLA inserts the transfers.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.platform_env import assert_env_platform

# ``default_mesh()``/``clause_mesh()`` are often a user process's first
# backend query; make ``JAX_PLATFORMS=cpu`` limit plugin discovery before
# it happens (a wedged accelerator plugin hangs init otherwise — see
# platform_env.assert_env_platform).
assert_env_platform()

BATCH_AXIS = "batch"


def default_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D mesh over ``devices`` (default: all local devices) with the
    single ``"batch"`` axis used by the batched solver."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (BATCH_AXIS,))


def mesh_devices_from_env() -> Optional[int]:
    """Parse ``DEPPY_TPU_MESH_DEVICES`` (the ``--mesh-devices`` env
    mirror): ``all`` or ``-1`` → -1 (every local device, the same
    spelling the CLI flag documents), a positive integer → that many,
    unset/empty/``0``/``1`` → None (mesh serving off — the historical
    single-device dispatch).  Malformed values warn and degrade to off,
    like every other fault-layer env knob."""
    from .. import config

    raw = (config.env_raw("DEPPY_TPU_MESH_DEVICES") or "").strip().lower()
    if not raw or raw in ("0", "1", "off", "none"):
        return None
    if raw == "all":
        return -1
    try:
        n = int(raw)
    except ValueError:
        n = None
    if n == -1:
        return -1
    if n is None or n < 0:
        import sys

        print(f"[deppy] ignoring malformed DEPPY_TPU_MESH_DEVICES={raw!r} "
              f"(want an integer or 'all'); mesh serving stays off",
              file=sys.stderr, flush=True)
        return None
    return n if n > 1 else None


def serving_mesh(n_devices: Optional[int] = None) -> Optional[Mesh]:
    """The batch-axis mesh the scheduler's sharded drain dispatches over
    (ISSUE 6), or None when mesh serving is off.  ``n_devices`` -1 (or
    ``DEPPY_TPU_MESH_DEVICES=all``) takes every local device; a count
    above the platform's device total clamps with a warning rather than
    failing serving.  Callers resolve this lazily — only after the
    backend probe said the device platform is usable — because
    enumerating devices is exactly the call that hangs on a wedged
    accelerator plugin (see assert_env_platform)."""
    if n_devices is None:
        n_devices = mesh_devices_from_env()
    if n_devices is None:
        return None
    devs = jax.devices()
    if n_devices == -1:
        n_devices = len(devs)
    if n_devices > len(devs):
        import sys

        print(f"[deppy] mesh-devices={n_devices} > {len(devs)} local "
              f"devices; clamping to {len(devs)}", file=sys.stderr,
              flush=True)
        n_devices = len(devs)
    if n_devices < 2:
        return None
    return default_mesh(devs[:n_devices])


def batch_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard a rank-``ndim`` array's leading (batch) axis over the mesh;
    all trailing axes replicated per shard."""
    return NamedSharding(mesh, PartitionSpec(BATCH_AXIS, *([None] * (ndim - 1))))


def shard_batch(mesh: Mesh, tree):
    """Device-put every leaf of a stacked problem pytree with its batch axis
    sharded over the mesh.  Scalars-per-problem (rank-1 leaves) shard too;
    the batch size must divide evenly (the driver pads to a multiple of the
    mesh size).

    Works on multi-process meshes too: when the sharding spans devices
    this process cannot address (a ``jax.distributed`` fleet),
    ``device_put`` of a host array is illegal, so each process instead
    contributes only its addressable shards via
    ``make_array_from_callback`` — every process holds the same full
    host-side batch (the deterministic build happens everywhere), and
    the callback slices out the local pieces."""
    def put(leaf):
        arr = np.asarray(leaf)
        sharding = batch_sharding(mesh, arr.ndim)
        if sharding.is_fully_addressable:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    return jax.tree_util.tree_map(put, tree)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated output sharding: jitting a batched solve with this
    as ``out_shardings`` makes XLA all-gather the (small) result tensors,
    so every process of a multi-host fleet can ``device_get`` the global
    outcome without a host-side gather step."""
    return NamedSharding(mesh, PartitionSpec())


def initialize_distributed(**kwargs) -> None:
    """Multi-host entry: initialize the JAX distributed runtime so
    ``jax.devices()`` spans the fleet and ``default_mesh()`` lays the batch
    axis over ICI + DCN.  Thin passthrough to ``jax.distributed.initialize``
    (coordinator_address / num_processes / process_id kwargs); call once per
    process before building a mesh.  On a single host with no cluster
    environment it is a no-op convenience so launch scripts can call it
    unconditionally; when a cluster IS configured (kwargs given or a
    recognized cluster environment), failures propagate — silently falling
    back to single-host there would make every host redundantly solve the
    full batch."""
    if not kwargs:
        try:
            from jax._src.clusters import ClusterEnv

            detected = any(c.is_env_present() for c in ClusterEnv._cluster_types)
        # deppy: lint-ok[exception-hygiene] probe fallback: absence of a cluster env IS the verdict
        except Exception:  # private API moved: assume plain single-host
            detected = False
        if not detected:
            return  # plain single-process launch: nothing to initialize
    if (os.environ.get("JAX_PLATFORMS") or "").strip() == "cpu":
        # Cross-process collectives on XLA:CPU need an explicit transport
        # (TPU fleets ride ICI/DCN natively); without this the first
        # collective hangs.  Gloo ships with jaxlib; config name guarded
        # so a jax that drops the option degrades to its own default.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        # deppy: lint-ok[exception-hygiene] optional config on older jax; initialize() below fails loud
        except Exception:
            pass
    jax.distributed.initialize(**kwargs)
