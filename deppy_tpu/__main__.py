"""``python -m deppy_tpu`` — the CLI entry point (reference cmd/main.go)."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
