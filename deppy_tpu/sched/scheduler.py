"""Cross-request continuous-batching scheduler (ISSUE 3 tentpole).

One :class:`Scheduler` sits between request producers (the service's
``/v1/resolve`` handler threads, ``BatchResolver`` callers) and the
engine driver.  Producers call :meth:`Scheduler.submit` and block; a
single dispatch-loop thread drains the queue into coalesced device
dispatches, so concurrent traffic shares one pad/pack + ``device_put`` +
kernel launch instead of paying one each — continuous batching, applied
to constraint resolution.

Design points, in the order the issue states them:

  * **Size-class-aware micro-batch queue.**  Each submit becomes one
    *group* (its problems never split across dispatches, so per-request
    semantics — escalation staging, report shape — match the
    unscheduled path).  Groups carry a size class — the power-of-two
    bucket of their largest :func:`engine.driver._cost_proxy` value, the
    same cost proxy ``driver.partition_buckets`` splits on — and a flush
    coalesces only same-class, same-budget groups, so one giant catalog
    problem never inflates every lane of a burst of tiny ones.
  * **Max-wait / max-fill flush.**  A flush fires when the oldest
    group has waited ``max_wait_ms`` (a lone request keeps low latency)
    or the head's class has ``max_fill`` lanes queued (a burst fills
    lanes).  Dispatches run through the driver's existing fault-domain
    recovery (``_recovering``: retry → split → host fallback, breaker
    charging) — the scheduler adds no new failure semantics.
  * **Deadlines.**  Each lane carries its request's
    :class:`faults.Deadline` object (captured on the submitting thread,
    ambient env deadline included).  Expired lanes degrade to
    ``Incomplete`` at triage — their coalesced batchmates dispatch
    unharmed — and the dispatch itself runs under the *loosest* live
    lane's deadline scope, so no batchmate is cut short by a stranger's
    tighter budget.
  * **Result cache.**  Misses queue; hits (see :mod:`.cache`) bypass the
    queue entirely and cost zero engine steps.
  * **Admission.**  :meth:`admission_retry_after` converts queue depth
    beyond ``max_depth`` into the service's 503 + Retry-After machinery;
    an open accelerator breaker does NOT reject the queue — backend
    resolution degrades ``auto`` to the host engine and the queue keeps
    draining (host-only mode).

The dispatch loop resolves the backend with ``block=False``: it must
never stall every queued request behind a first-use 75s engine probe
(the service pre-warm owns that probe).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from .. import faults, telemetry
from .. import profile as _profile
from ..sat.constraints import Variable
from ..sat.encode import Problem, encode
from ..sat.errors import Incomplete, InternalSolverError, NotSatisfiable
from .cache import MISS, ResultCache, fingerprint

# Knob defaults + env mirrors (CLI flags --sched-max-wait-ms,
# --sched-max-fill, --cache-size, --mesh-devices override; see
# deppy_tpu.cli).
DEFAULT_MAX_WAIT_MS = 5.0
DEFAULT_MAX_FILL = 256
DEFAULT_CACHE_SIZE = 1024
DEFAULT_MAX_DEPTH = 4096
DEFAULT_INCREMENTAL_INDEX = 512
DEFAULT_INCREMENTAL_MAX_DELTA = 0.25
# Portfolio racing (ISSUE 13): top-K backends raced per cold flush, and
# the deterministic 1-in-N fraction of non-canonical race wins that are
# cross-checked against the canonical backend through the differential
# machinery (the canonical entrant is exempted from cancellation on
# sampled races so its answer exists to compare).
DEFAULT_PORTFOLIO_K = 2
DEFAULT_PORTFOLIO_SAMPLE_CHECK = 0.0625
# Speculative pre-resolution (ISSUE 14): catalog publishes queue
# pre-solves on a SEPARATE idle-priority queue the dispatch loop drains
# only while no live group is queued — live traffic preempts at every
# flush boundary, and the backlog is capped (publishes are bursty and a
# pre-solve is pure opportunism: dropping one costs a cold solve later,
# never an answer).
DEFAULT_SPECULATE_MAX_BACKLOG = 2048

# The "incremental" size class (ISSUE 10): warm-started lanes coalesce
# with each other — their cost is a handful of host propagation passes,
# not a device dispatch, so padding them into a cold batch's lanes would
# waste device width AND serialize near-lookups behind a solve.  Cold
# classes are power-of-two cost buckets (>= 1), so -1 can never collide.
INCREMENTAL_CLASS = -1

# The "session" size class (ISSUE 20): a stateful session's incremental
# cold solves dispatch in their own bucket — they carry assumption-
# conditioned answers that must never coalesce into (or pad out) a
# stateless cold batch, and their results bypass the shared result
# cache entirely (see ``_maybe_cache``).  Warm session lanes ride
# INCREMENTAL_CLASS like any other warm-started lane: the warm flush
# machinery is per-lane and scoped-ness travels on the lane itself.
SESSION_CLASS = -2


def _env_int(name: str, default: int) -> int:
    v = faults.env_float(name, float(default), warn=True)
    return int(v if v is not None else default)


def _single_tenant(lanes: List["_Lane"]) -> Optional[str]:
    """The one tenant a flush serves, or None when mixed — profile
    events are tenant-stamped only when attribution is unambiguous."""
    tenants = {lane.tenant for lane in lanes}
    return tenants.pop() if len(tenants) == 1 else None


def _solution_dict(problem: Problem, installed_idx) -> dict:
    """The host-lane decode convention, shared by the host drain and the
    warm path: every entity id mapped to False, installed set True —
    exactly what ``driver.decode_results`` renders for a SAT lane."""
    solution = {v.identifier: False for v in problem.variables}
    for i in installed_idx:
        solution[problem.variables[i].identifier] = True
    return solution


class _Lane:
    """One problem awaiting dispatch, plus its result slot.

    ``degraded`` marks a lane the deadline triage actually expired —
    distinct from a budget-exhaustion ``Incomplete`` whose deadline
    merely ran out by readback time (ISSUE 4: only the former is an
    incident worth the flight recorder's error ring)."""

    __slots__ = ("problem", "key", "max_steps", "budget", "deadline",
                 "result", "steps", "degraded", "warm", "backtracks",
                 "index_steps", "tenant", "scoped", "session_index")

    def __init__(self, problem: Problem, key: str,
                 max_steps: Optional[int], budget: int, deadline,
                 warm=None, tenant: str = "default"):
        self.problem = problem
        self.key = key
        self.max_steps = max_steps
        self.budget = budget
        self.deadline = deadline  # faults.Deadline or None
        self.result = None
        self.steps = 0
        self.degraded = False
        # ISSUE 10: the lane's WarmPlan (incremental size class), and
        # the solve's observed search-backtrack count — None until a
        # path that measures it reports in (the clause-set index seeds
        # warm starts only from zero-backtrack solves, so an unmeasured
        # lane must never be indexed as zero).  ``index_steps`` is the
        # COLD-equivalent step cost to index under when it differs from
        # ``steps``: a warm-served lane's own step count is a fraction
        # of what a cold solve would spend, and indexing it verbatim
        # would erode the budget gate that keeps a warm SAT from
        # shadowing a cold Incomplete at tight budgets.
        self.warm = warm
        self.backtracks = None
        self.index_steps = None
        # ISSUE 11: the submitting request's tenant (X-Deppy-Tenant),
        # carried per lane so a deadline expiry at triage attributes to
        # the tenant whose lane expired, never a coalesced batchmate's.
        self.tenant = tenant
        # ISSUE 20: a scoped lane answers under a session's open
        # assumption stack — its result is assumption-conditioned and
        # must never be admitted to the shared exact LRU or clause-set
        # index (it would poison stateless traffic); instead the model
        # lands in the session's OWN index so the next op warm-starts
        # from the session's last model.
        self.scoped = False
        self.session_index = None


class _Group:
    """All queued lanes of one submit() call — flushed atomically.

    ``parent`` carries the submitting request's trace context across the
    thread hop to the dispatch loop (ISSUE 4) so a coalesced dispatch
    can link back to every request it serves; ``timing`` receives the
    request's queue-wait/dispatch/solve/decode breakdown."""

    __slots__ = ("lanes", "enq_t", "size_class", "budget", "event",
                 "error", "report", "parent", "timing", "speculative",
                 "tenant", "priority", "shadow_backend", "shadow_class",
                 "immediate")

    def __init__(self, lanes: List[_Lane], size_class: int, budget: int,
                 speculative: bool = False, priority: int = 1,
                 immediate: bool = False):
        self.lanes = lanes
        self.enq_t = time.monotonic()
        self.size_class = size_class
        self.budget = budget
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self.report = None
        self.parent = telemetry.trace.capture_parent()
        self.timing: dict = {}
        # ISSUE 14: a speculative pre-solve group — queued on the idle
        # queue, no submitter waits on its event, and a dispatch failure
        # is a sink event rather than a raised request error.
        self.speculative = speculative
        # ISSUE 15: groups are single-tenant by construction (one
        # submit = one request = one tenant), so per-tenant queue
        # accounting and the priority-ordered flush head key off the
        # group, not per lane.
        self.tenant = lanes[0].tenant if lanes else "default"
        self.priority = priority
        # ISSUE 19: a shadow route probe — the group re-solves an
        # already-answered flush via ONE named backend on the idle
        # queue; its results feed the route ledger, never a response.
        self.shadow_backend: Optional[str] = None
        self.shadow_class: Optional[str] = None
        # ISSUE 20: a blocking interactive lane (a session op) flushes
        # as soon as it reaches the head — a human is synchronously
        # waiting on ONE lane, so holding it the coalescing window's
        # max-wait buys nothing and costs the whole window.  Batchmates
        # that are already queued still coalesce into the flush.
        self.immediate = immediate


def _count_lane_outcome(rep, r) -> None:
    """Fold one HostLaneResult into a SolveReport — exactly the
    accounting the host drain performs (degraded lanes count as
    incomplete with no engine counters)."""
    if r.degraded:
        rep.count_outcome("incomplete")
        return
    rep.count_outcome(r.outcome)
    rep.steps += r.steps
    rep.decisions += r.decisions
    rep.propagation_rounds += r.propagation_rounds
    rep.backtracks += r.backtracks


def _apply_lane_result(lane: "_Lane", r, point: str,
                       canonical: bool = True) -> None:
    """Decode one HostLaneResult onto its lane — the host drain's
    decode convention, shared so racing cannot grow a second decode
    path.  ``canonical=False`` (a race won by a non-canonical backend)
    clears the lane's backtrack observation: the winner's count is not
    the canonical engine's, and the clause-set index must never seed a
    warm start from a non-canonical cost observation."""
    if r.degraded:
        faults.note_deadline_exceeded(point, tenant=lane.tenant)
        lane.result = Incomplete()
        lane.degraded = True
        return
    if r.outcome == "sat":
        lane.result = _solution_dict(lane.problem, r.installed_idx)
    elif r.outcome == "unsat":
        lane.result = NotSatisfiable(
            [lane.problem.applied[j] for j in r.core_idx])
    else:
        lane.result = Incomplete()
    lane.steps = r.steps
    lane.backtracks = r.backtracks if canonical else None


class _RacePlan:
    """One flush's race decision: the candidate backends and the class
    they were ranked for."""

    __slots__ = ("names", "class_name", "canonical")

    def __init__(self, names: List[str], class_name: str,
                 canonical: str):
        self.names = names
        self.class_name = class_name
        self.canonical = canonical


# Abandoned race losers (a device program mid-execution, a grad descent
# mid-compile) must not be killed as daemon threads while they hold XLA
# runtime locks — the C++ runtime calls std::terminate at interpreter
# teardown.  Every race thread registers here and an atexit hook joins
# the stragglers (bounded: losers see the stop flag at their next step
# boundary; a device program runs out its dispatch).
_RACE_THREADS: List[threading.Thread] = []
_RACE_THREADS_LOCK = threading.Lock()
_RACE_ATEXIT = [False]


def _note_race_thread(t: threading.Thread) -> None:
    with _RACE_THREADS_LOCK:
        _RACE_THREADS[:] = [x for x in _RACE_THREADS if x.is_alive()]
        _RACE_THREADS.append(t)
        if not _RACE_ATEXIT[0]:
            import atexit

            atexit.register(_join_race_threads)
            _RACE_ATEXIT[0] = True


def _join_race_threads(timeout_s: float = 60.0) -> None:
    deadline = time.monotonic() + timeout_s
    with _RACE_THREADS_LOCK:
        threads = list(_RACE_THREADS)
    for t in threads:
        t.join(max(deadline - time.monotonic(), 0.0))


class PortfolioRacer:
    """First-finisher-wins racing across registered engine backends
    (ISSUE 13 tentpole).

    One coalesced cold flush is dispatched to the top-K candidate
    backends of its size class concurrently (:mod:`deppy_tpu.engine.
    registry` ranks them — measured ``portfolio`` rows first, the
    static canonical-first order otherwise); the first DEFINITIVE
    finisher (every lane answered) wins, and the losers are
    cancelled: host lanes check a cooperative stop flag at step
    boundaries, device programs run to completion with their fetch
    dropped, hostpool dispatches are abandoned.  A deterministic
    1-in-N sample of non-canonical wins is cross-checked against the
    canonical backend's answer through the differential lane
    comparison — a mismatch is a loud ``race_mismatch`` fault event
    and the canonical answer is served.

    Modes: ``on`` races wherever ≥2 candidates serve the class;
    ``auto`` races only classes with a measured ``portfolio`` row
    (the tpu_ab-learned default posture).  ``off`` never constructs a
    racer — the scheduler's dispatch path is byte-identical to the
    pre-portfolio tree."""

    def __init__(self, mode: str, k: int, sample_check: float,
                 registry: "telemetry.Registry"):
        self.mode = mode
        self.k = max(int(k), 2)
        rate = max(float(sample_check), 0.0)
        self._check_interval = (int(round(1.0 / min(rate, 1.0)))
                                if rate > 0 else 0)
        # Non-canonical wins since the last cross-check.  The sampling
        # contract is 1-in-N NON-CANONICAL WINS (not 1-in-N races —
        # counting races would let deterministic aliasing against the
        # flush pattern starve the check forever); seeded so the very
        # FIRST non-canonical win is checked.  The cancel exemption
        # must be decided before racing, so the check arms whenever
        # the next non-canonical win would be the Nth.
        self._check_lock = threading.Lock()
        self._since_check = max(self._check_interval - 1, 0)
        self._registry = registry

    # ------------------------------------------------------------- plan

    def plan(self, live: List["_Lane"], backend: str) -> Optional[_RacePlan]:
        """Decide whether THIS flush races: candidate backends for its
        ladder class, capability- and availability-filtered.  None
        means the canonical single-backend path runs untouched."""
        from ..engine import registry as engine_registry
        from ..engine.driver import padded_class

        class_name = padded_class([lane.problem for lane in live])
        device_ok = (backend != "host"
                     and not faults.default_breaker().blocks_device())
        need_card = any(lane.problem.card_act.shape[0] > 0
                        and (lane.problem.card_act >= 0).any()
                        for lane in live)
        names, measured = engine_registry.candidates(
            class_name, self.k, device_ok=device_ok,
            cardinality=need_card)
        if self.mode == "auto" and not measured:
            return None
        if len(names) < 2:
            return None
        canonical = "host" if backend == "host" else "device"
        if canonical == "device" and not device_ok:
            canonical = "host"
        return _RacePlan(names, class_name, canonical)

    # ------------------------------------------------------------- race

    def race(self, plan: _RacePlan, live: List["_Lane"], rep,
             timing: dict, mesh_fn) -> bool:
        """Run one race.  Returns True when a winner's results were
        applied to the lanes (and merged into ``rep``); False when no
        entrant finished definitively — the caller falls back to the
        canonical path exactly as if racing were off."""
        from ..engine import registry as engine_registry
        from ..sat.host import SolveCancelled

        reg = self._registry
        problems = [lane.problem for lane in live]
        deadlines = [lane.deadline for lane in live]
        dl = faults.current_deadline()
        mesh = mesh_fn() if "device" in plan.names else None
        stop = threading.Event()
        with self._check_lock:
            check = (self._check_interval > 0
                     and plan.canonical in plan.names
                     and self._since_check + 1 >= self._check_interval)
        cv = threading.Condition()
        finished: List[tuple] = []  # (name, dt, out, err, srep) in
        #                             completion order

        def run(name: str, t0: float) -> None:
            srep, owns = telemetry.begin_report(backend=name)
            out = None
            err = None
            try:
                if stop.is_set() and not (check
                                          and name == plan.canonical):
                    raise SolveCancelled()
                with faults.deadline_scope(dl):
                    faults.inject(f"sched.race.{name}")
                    out = engine_registry.solve_via(
                        name, problems, max_steps=live[0].max_steps,
                        deadlines=deadlines,
                        cancel=(None if (check and name == plan.canonical)
                                else stop),
                        mesh=mesh if name == "device" else None)
                if name != "device" and out is not None:
                    # Non-device backends don't flow through the
                    # driver's report plumbing: account their lanes
                    # here, on the entrant's own report (merged only
                    # if this entrant wins / cross-checks).
                    for r in out:
                        if r is not None:
                            _count_lane_outcome(srep, r)
            except SolveCancelled:
                err = "cancelled"
            except BaseException as e:  # noqa: BLE001 — entrant-local
                err = e
            finally:
                telemetry.detach_report(srep, owns)
            with cv:
                finished.append((name, time.perf_counter() - t0, out,
                                 err, srep))
                cv.notify_all()

        t0 = time.perf_counter()
        with reg.span("race", lanes=len(live), entrants=len(plan.names),
                      size_class=plan.class_name) as sp:
            threads = {}
            for name in plan.names:
                reg.counter(
                    "deppy_race_starts_total",
                    "Portfolio race entrant launches, by backend.",
                    labelname="backend").inc(label=name)
                t = threading.Thread(target=run, args=(name, t0),
                                     name=f"deppy-race-{name}",
                                     daemon=True)
                threads[name] = t
                _note_race_thread(t)
                t.start()

            def _definitive(name, out):
                """A non-canonical entrant's budget-exhaustion
                'incomplete' is that ENGINE's verdict, not the
                canonical one (step accounting is engine-relative) —
                letting it win would serve (and cache) Incomplete
                where racing-off decides.  Only the canonical entrant
                may call Incomplete; deadline-degraded lanes pass
                (deadline behavior is timing-dependent and never
                cached)."""
                if out is None:
                    return False
                for r in out:
                    if r is None:
                        return False
                    if (r.outcome == "incomplete" and not r.degraded
                            and name != plan.canonical):
                        return False
                return True

            def _winner_locked():
                for entry in finished:
                    name, _, out, err, _ = entry
                    if err is None and _definitive(name, out):
                        return entry
                return None

            with cv:
                winner = _winner_locked()
                while winner is None and len(finished) < len(plan.names):
                    cv.wait()
                    winner = _winner_locked()
            stop.set()
            if winner is None:
                sp.set(winner="none")
                telemetry.default_registry().event(
                    "race", size_class_name=plan.class_name,
                    entrants=list(plan.names), lanes=len(live),
                    default=plan.names[0], winner=None)
                return False

            noncanonical_win = winner[0] != plan.canonical
            checked = None
            if check and noncanonical_win:
                # Sampled differential cross-check: the canonical
                # entrant was exempt from cancellation — wait for its
                # answer and compare outcome/model/core per lane.
                # Deadline-degraded lanes are excluded on either side:
                # degradation is pure timing (the entrants admitted
                # the lane at different instants), not disagreement.
                with cv:
                    while not any(e[0] == plan.canonical
                                  for e in finished):
                        cv.wait()
                    canon = next(e for e in finished
                                 if e[0] == plan.canonical)
                if canon[3] is None and canon[2] is not None and all(
                        r is not None for r in canon[2]):
                    mismatch = any(
                        (w.outcome, tuple(w.installed_idx),
                         tuple(w.core_idx))
                        != (c.outcome, tuple(c.installed_idx),
                            tuple(c.core_idx))
                        for w, c in zip(winner[2], canon[2])
                        if not w.degraded and not c.degraded)
                    checked = "mismatch" if mismatch else "ok"
                    if mismatch:
                        reg.counter(
                            "deppy_race_check_mismatch_total",
                            "Sampled race cross-checks that disagreed "
                            "with the canonical backend (served "
                            "canonical; investigate).").inc()
                        telemetry.default_registry().event(
                            "fault", fault="race_mismatch",
                            winner=winner[0],
                            canonical=plan.canonical,
                            lanes=len(live))
                        winner = canon  # serve the canonical answer
            if noncanonical_win:
                with self._check_lock:
                    if check:
                        self._since_check = 0
                    else:
                        self._since_check += 1

            wname, wdt, wout, _, wsrep = winner
            with cv:
                # ISSUE 19 satellite: a cancelled loser can surface as
                # a PARTIAL completion — err None but a None lane (a
                # grad descent cancelled mid-certification) — whose
                # wall clock measures when the cancel landed, not how
                # fast the backend solves.  Such entrants are CENSORED:
                # recorded as losers so the regret ledger can count
                # cancels distinctly, but excluded from win-margin
                # stats and per-backend wall estimates.
                losers = []
                for e in finished:
                    if e[0] == wname:
                        continue
                    censored = (e[3] is not None or e[2] is None
                                or any(r is None for r in e[2]))
                    losers.append({"backend": e[0],
                                   "wall_s": round(e[1], 6),
                                   "censored": bool(censored)})
                done = {e[0] for e in finished}
                margins = [e[1] - wdt for e in finished
                           if e[0] != wname and e[3] is None
                           and e[2] is not None
                           and all(r is not None for r in e[2])]
                clean_done = {e[0] for e in finished if e[3] is None}
            for name in plan.names:
                if name != wname and name not in done:
                    # Still running at event time (abandoned in the
                    # background): censored, no usable wall clock.
                    losers.append({"backend": name, "wall_s": None,
                                   "censored": True})
            for name in plan.names:
                if name != wname and name not in clean_done:
                    reg.counter(
                        "deppy_race_cancels_total",
                        "Race entrants cancelled or abandoned after "
                        "losing, by backend.",
                        labelname="backend").inc(label=name)
            reg.counter(
                "deppy_race_wins_total",
                "Races won (first definitive finisher), by backend.",
                labelname="backend").inc(label=wname)
            margin = min(margins) if margins else None
            if margin is not None:
                reg.histogram(
                    "deppy_race_win_margin_seconds",
                    "Winner-vs-best-finished-loser wall-clock margin "
                    "per race.").observe(max(margin, 0.0))
            sp.set(winner=wname)
            telemetry.default_registry().event(
                "race", size_class_name=plan.class_name, winner=wname,
                canonical=plan.canonical, default=plan.names[0],
                entrants=list(plan.names),
                lanes=len(live),
                cancelled=[n for n in plan.names
                           if n != wname and n not in clean_done],
                losers=losers,
                win_margin_s=(round(margin, 6)
                              if margin is not None else None),
                checked=checked, wall_s=round(wdt, 6))
        rep.merge(wsrep)
        canonical_won = wname == plan.canonical
        for lane, r in zip(live, wout):
            _apply_lane_result(lane, r, "sched.race",
                               canonical=canonical_won)
        timing["solve_s"] = timing.get("solve_s", 0.0) + wdt
        return True


class Scheduler:
    """Coalesce concurrent resolve requests into shared dispatches."""

    def __init__(
        self,
        backend: str = "auto",
        max_steps: Optional[int] = None,
        max_wait_ms: Optional[float] = None,
        max_fill: Optional[int] = None,
        cache_size: Optional[int] = None,
        max_depth: Optional[int] = None,
        registry: Optional[telemetry.Registry] = None,
        mesh=None,
        mesh_devices: Optional[int] = None,
        lanes_per_device: Optional[int] = None,
        incremental: Optional[str] = None,
        incremental_max_delta: Optional[float] = None,
        incremental_index_size: Optional[int] = None,
        portfolio: Optional[str] = None,
        portfolio_k: Optional[int] = None,
        portfolio_sample_check: Optional[float] = None,
        speculate: Optional[str] = None,
        speculate_max_backlog: Optional[int] = None,
        fair: Optional[str] = None,
        tenant_weights: Optional[str] = None,
    ):
        self.backend = backend
        self.max_steps = max_steps
        # Mesh serving (ISSUE 6): device dispatches shard each coalesced
        # micro-batch over a jax mesh.  ``mesh`` pins one explicitly
        # (tests, library callers); otherwise ``mesh_devices`` (or the
        # DEPPY_TPU_MESH_DEVICES env mirror) sizes one LAZILY on the
        # first device dispatch — enumerating devices up front is
        # exactly the call that hangs on a wedged accelerator plugin,
        # and the scheduler must never probe (see _prewarm_backend).
        self._mesh = mesh
        self._mesh_devices = mesh_devices
        self._mesh_resolved = mesh is not None
        self._max_fill_explicit = max_fill is not None
        if lanes_per_device is None:
            lanes_per_device = _env_int("DEPPY_TPU_SCHED_LANES_PER_DEVICE",
                                        DEFAULT_MAX_FILL)
        self.lanes_per_device = max(int(lanes_per_device), 1)
        if max_wait_ms is None:
            max_wait_ms = faults.env_float(
                "DEPPY_TPU_SCHED_MAX_WAIT_MS", DEFAULT_MAX_WAIT_MS,
                warn=True)
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1000.0
        if max_fill is None:
            max_fill = _env_int("DEPPY_TPU_SCHED_MAX_FILL",
                                DEFAULT_MAX_FILL)
        self.max_fill = max(int(max_fill), 1)
        if max_depth is None:
            max_depth = _env_int("DEPPY_TPU_SCHED_MAX_DEPTH",
                                 DEFAULT_MAX_DEPTH)
        self.max_depth = int(max_depth)
        if cache_size is None:
            cache_size = _env_int("DEPPY_TPU_CACHE_SIZE",
                                  DEFAULT_CACHE_SIZE)
        self._registry = registry if registry is not None \
            else telemetry.default_registry()
        # Incremental tier (ISSUE 10): a delta-aware clause-set index in
        # front of the exact-fingerprint LRU.  Default on;
        # DEPPY_TPU_INCREMENTAL=off removes the tier entirely, restoring
        # the pre-change dispatch byte for byte.
        from .. import config

        if incremental is None:
            incremental = config.env_raw("DEPPY_TPU_INCREMENTAL", "on")
        index = None
        if str(incremental).strip().lower() not in ("off", "0", "false",
                                                    "no"):
            if incremental_max_delta is None:
                incremental_max_delta = faults.env_float(
                    "DEPPY_TPU_INCREMENTAL_MAX_DELTA",
                    DEFAULT_INCREMENTAL_MAX_DELTA, warn=True)
            if incremental_index_size is None:
                incremental_index_size = _env_int(
                    "DEPPY_TPU_INCREMENTAL_INDEX_SIZE",
                    DEFAULT_INCREMENTAL_INDEX)
            from ..incremental import ClauseSetIndex

            index = ClauseSetIndex(
                capacity=incremental_index_size,
                max_delta_ratio=incremental_max_delta,
                registry=self._registry)
        self.incremental = index
        self.cache = ResultCache(cache_size, registry=self._registry,
                                 incremental=index)
        # Portfolio engine racing (ISSUE 13).  "off" constructs no
        # racer at all — the dispatch path is byte-identical to the
        # pre-portfolio tree; "auto" (the default) races only size
        # classes holding a measured `portfolio` row; "on" races
        # wherever ≥2 candidate backends serve the class.
        if portfolio is None:
            portfolio = config.env_raw("DEPPY_TPU_PORTFOLIO", "auto")
        mode = str(portfolio).strip().lower()
        self._racer: Optional[PortfolioRacer] = None
        if mode not in ("off", "0", "false", "no"):
            if portfolio_k is None:
                portfolio_k = _env_int("DEPPY_TPU_PORTFOLIO_K",
                                       DEFAULT_PORTFOLIO_K)
            if portfolio_sample_check is None:
                portfolio_sample_check = faults.env_float(
                    "DEPPY_TPU_PORTFOLIO_SAMPLE_CHECK",
                    DEFAULT_PORTFOLIO_SAMPLE_CHECK, warn=True)
            self._racer = PortfolioRacer(
                "on" if mode in ("on", "1", "true", "yes") else "auto",
                portfolio_k, portfolio_sample_check, self._registry)
        # Route-health plane (ISSUE 19): installed by
        # deppy_tpu.routes.start_plane.  None (the default) leaves the
        # dispatch path byte-identical — no flush observation, no
        # shadow groups, no route events.
        self._route_plane = None
        # Weighted-fair per-tenant admission + priority lanes (ISSUE
        # 15).  "off" restores the global-depth-only gate and strict
        # FIFO flush head byte for byte; "on" (the default) is ALSO
        # byte-identical while one tenant is queued — the fairness math
        # only bites under multi-tenant contention.
        if fair is None:
            fair = config.env_raw("DEPPY_TPU_SCHED_FAIR", "on")
        self.fair = str(fair).strip().lower() not in ("off", "0",
                                                      "false", "no")
        from .fair import TenantPolicy

        if tenant_weights is None:
            tenant_weights = config.env_raw(
                "DEPPY_TPU_SCHED_TENANT_WEIGHTS")
        self.tenant_policy = TenantPolicy.from_spec(tenant_weights)
        # Queued lanes per tenant (CV-guarded, live queue only — the
        # speculative backlog has its own cap and nobody's SLO rides
        # it).
        self._tenant_depth: dict = {}
        reg = self._registry
        self._c_tenant_sheds = reg.counter(
            "deppy_sched_tenant_sheds_total",
            "Admissions shed by the weighted-fair per-tenant gate, by "
            "tenant (the offender's 503s; victims under their share "
            "keep admitting).", labelname="tenant")
        self._g_depth = reg.gauge(
            "deppy_sched_queue_depth",
            "Problems queued for a coalesced dispatch right now.")
        self._g_depth.set(0)
        self._h_coalesced = reg.histogram(
            "deppy_sched_coalesced_batch_size",
            "Problems per coalesced scheduler dispatch.",
            buckets=telemetry.LANE_BUCKETS)
        self._c_dispatches = reg.counter(
            "deppy_sched_dispatches_total",
            "Coalesced dispatch groups drained from the queue.")
        self._c_requests = reg.counter(
            "deppy_sched_coalesced_requests_total",
            "Requests (submit calls) served per drained dispatch.")
        self._c_flushes = reg.counter(
            "deppy_sched_flushes_total",
            "Queue flushes by trigger (wait = max-wait elapsed, fill = "
            "lane target reached, immediate = blocking interactive "
            "lane at the head, drain = shutdown, inline = loop not "
            "running).", labelname="reason")
        from ..analysis import lockdep

        # Named CV (ISSUE 7): lockdep-instrumented when armed.
        self._cv = lockdep.make_condition("sched.queue")
        self._queue: List[_Group] = []
        self._depth = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # EWMA of dispatch wall clock, seeding the Retry-After estimate.
        self._dispatch_ewma_s = 0.05
        # Speculative pre-resolution (ISSUE 14).  "off" constructs no
        # manager, no idle queue consumer, no metric families — the
        # submit and dispatch paths are byte-identical to the
        # pre-speculation tree.
        self._spec_queue: List[_Group] = []
        self._spec_depth = 0
        # Fingerprints queued or mid-dispatch on the idle queue (CV-
        # guarded): a duplicate publish burst arriving before the first
        # pre-solves have stored must not double-burn the backlog cap
        # solving the same families twice.
        self._spec_keys: set = set()
        if speculate is None:
            speculate = config.env_raw("DEPPY_TPU_SPECULATE", "on")
        self.speculate = None
        self._g_spec_depth = None
        if str(speculate).strip().lower() not in ("off", "0", "false",
                                                  "no"):
            if speculate_max_backlog is None:
                speculate_max_backlog = _env_int(
                    "DEPPY_TPU_SPECULATE_MAX_BACKLOG",
                    DEFAULT_SPECULATE_MAX_BACKLOG)
            self.spec_max_backlog = max(int(speculate_max_backlog), 0)
            from ..speculate import SpeculationManager

            self.speculate = SpeculationManager(self,
                                                registry=self._registry)
            self._g_spec_depth = reg.gauge(
                "deppy_speculate_backlog",
                "Speculative pre-solve lanes queued at idle priority "
                "right now.")
            self._g_spec_depth.set(0)
        # Deferred background engine re-probe (ISSUE 14 satellite): a
        # breaker-open host drain kicks ONE background probe loop that
        # upgrades `auto` routing once the accelerator recovers, instead
        # of waiting for a process restart (the service's startup
        # pre-warm loop exits once a verdict lands and never watches
        # the breaker).
        self._reprobe_stop = threading.Event()
        self._reprobe_thread: Optional[threading.Thread] = None
        self._reprobe_s = faults.env_float("DEPPY_TPU_REPROBE", 600.0,
                                           warn=True) or 0.0
        if self._mesh is not None:
            self._apply_mesh_sizing(self._mesh)

    # ----------------------------------------------------------------- mesh

    def _apply_mesh_sizing(self, mesh) -> None:
        """Size micro-batches to the mesh: ``n_devices ×
        lanes_per_device`` lanes per flush (ISSUE 6), so a full flush
        hands every device a full shard.  An explicitly passed
        ``max_fill`` wins — the operator said what they meant."""
        if mesh is None or self._max_fill_explicit:
            return
        self.max_fill = max(int(mesh.size) * self.lanes_per_device, 1)

    def _resolve_mesh(self):
        """The serving mesh, resolved lazily on the first device
        dispatch (never on the submit/queue path): by then the backend
        probe has already established that touching the device platform
        is safe.  Resolution failures degrade to single-device dispatch
        — mesh serving must never take down serving."""
        if self._mesh_resolved:
            return self._mesh
        try:
            from ..parallel.mesh import serving_mesh

            self._mesh = serving_mesh(self._mesh_devices)
        except Exception as e:  # noqa: BLE001 — degrade, don't die
            import sys

            print(f"[sched] mesh resolution failed ({e}); serving "
                  f"single-device", file=sys.stderr, flush=True)
            # On the sink too (ISSUE 7 exception-hygiene): a service
            # meant to shard across 8 chips silently serving
            # single-device is an incident, not a log line.
            telemetry.default_registry().event(
                "fault", fault="sched_mesh_unavailable",
                error=type(e).__name__)
            self._mesh = None
        self._mesh_resolved = True
        self._apply_mesh_sizing(self._mesh)
        return self._mesh

    # -------------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Start the dispatch-loop thread (idempotent)."""
        # Event, not CV state: internally synchronized, touched outside
        # the lock on purpose (stop() and the re-probe loop read it
        # lock-free).
        self._reprobe_stop.clear()
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name="deppy-sched", daemon=True)
            self._thread.start()
        self._prewarm_backend()

    def _prewarm_backend(self) -> None:
        """The dispatch loop resolves the backend with ``block=False``
        (it must never stall the queue behind the 75s engine probe), so
        ``auto`` answers "host" until SOMETHING establishes the
        usability verdict.  The service's startup pre-warm owns that on
        the served path; a standalone Scheduler (library callers) would
        otherwise route host forever on a device platform — kick one
        background probe here so auto routing upgrades once it lands."""
        import os

        if self.backend != "auto":
            return
        from ..sat import solver as sat_solver

        if (sat_solver._ENGINE_USABLE is not None
                or (os.environ.get("JAX_PLATFORMS") or "").strip()
                == "cpu"):
            return
        threading.Thread(target=lambda: sat_solver.resolve_backend("auto"),
                         name="deppy-sched-prewarm", daemon=True).start()

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the loop; queued LIVE groups drain (dispatch) first so
        no submitter is left hanging — the speculative backlog is
        discarded instead (nobody waits on a pre-solve).  Submits after
        stop dispatch inline."""
        self._reprobe_stop.set()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout)
        with self._cv:
            self._thread = None

    @property
    def running(self) -> bool:
        # Under the CV (ISSUE 7 concurrency-discipline): _thread/_stop
        # are written by start()/stop() on other threads, and a torn
        # pair here could route a submit inline while the loop drains
        # the same group.  Reentrant: _enqueue reads this while holding
        # the CV.
        with self._cv:
            t = self._thread
            return t is not None and t.is_alive() and not self._stop

    # ------------------------------------------------------------- admission

    def queue_depth(self) -> int:
        with self._cv:
            return self._depth

    def admission_retry_after(
            self, tenant: str = "default") -> Optional[float]:
        """Seconds a client should back off, or None to admit — the
        service mirrors this into its 503 + Retry-After response.

        With the fair gate off this is the historical GLOBAL check:
        shed everyone once total depth reaches ``max_depth``.  With it
        on (ISSUE 15) the shed is PER TENANT: a tenant sheds once its
        own queued lanes reach its weighted share of ``max_depth``
        among the tenants queued right now — a lone tenant's share is
        the whole queue (identical behavior), while under contention
        the noisy tenant sheds at its share and the victim's lanes
        always find room.  A hard GLOBAL backstop at
        2x ``max_depth`` still bounds the aggregate: per-tenant caps
        sum to ``max_depth`` for any FIXED tenant set, but
        X-Deppy-Tenant is client-controlled and sequentially minted
        fresh tenants could otherwise ratchet total depth to
        ``max_depth * H(T)`` unbounded (each new tenant's share is
        computed against the tenants queued at ITS arrival).  The
        estimate is the number of flushes needed to drain the
        relevant backlog times the recent dispatch wall clock (EWMA),
        floored at 1s."""
        if self.max_depth <= 0:
            return None
        with self._cv:
            depth = self._depth
            ewma = self._dispatch_ewma_s
            if self.fair:
                t_depth = self._tenant_depth.get(tenant, 0)
                active = [t for t, n in self._tenant_depth.items()
                          if n > 0]
            else:
                t_depth, active = depth, []
        if not self.fair:
            if depth < self.max_depth:
                return None
        elif depth >= 2 * self.max_depth:
            # Aggregate backstop: overload protection (memory, drain
            # latency) must not depend on client-chosen tenant labels.
            self._c_tenant_sheds.inc(label=tenant)
            t_depth = max(t_depth, depth)
        else:
            cap = self.tenant_policy.cap(tenant, self.max_depth, active)
            if t_depth < cap:
                return None
            self._c_tenant_sheds.inc(label=tenant)
        flushes = max(t_depth / float(self.max_fill), 1.0)
        return max(flushes * ewma, 1.0)

    # ---------------------------------------------------------------- submit

    def submit(
        self,
        problem_vars: Sequence[Sequence[Variable]],
        deadline_s: Optional[float] = None,
        max_steps: Optional[int] = None,
        stats: Optional[dict] = None,
        tenant: str = "default",
    ) -> List[object]:
        """Resolve ``problem_vars`` through the shared queue; blocks
        until every problem has an answer and returns them in input
        order (Solution dict / NotSatisfiable / Incomplete — the
        BatchResolver contract).  ``stats`` receives ``{"steps": N,
        "report": SolveReport-or-None}`` like the driver's entry
        points, plus ``deadline_misses`` — the count of THIS submit's
        lanes the deadline triage degraded (ISSUE 11: the service's
        per-tenant SLO accountant attributes them to ``tenant``, which
        also rides every lane for fault-event attribution).

        Raises what the unscheduled path raises: DuplicateIdentifier
        from encoding, InternalSolverError for unresolvable references
        (screened here, per lane, BEFORE anything queues — a malformed
        request must never abort a coalesced batchmate's dispatch)."""
        from ..engine.driver import _budget

        if max_steps is None:
            max_steps = self.max_steps
        budget = int(_budget(max_steps))
        problems = [encode(vs) for vs in problem_vars]
        for p in problems:
            if p.errors:
                raise InternalSolverError(p.errors)
        # Capture the request's effective deadline (explicit scope,
        # enclosing scope, or ambient env) as an OBJECT: its clock keeps
        # ticking across the thread hop to the dispatch loop.
        with faults.deadline_scope(deadline_s), faults.ambient_deadline():
            dl = faults.current_deadline()
        results: List[object] = [None] * len(problems)
        pending: List[tuple] = []
        warm_pending: List[tuple] = []
        for i, p in enumerate(problems):
            key = fingerprint(p)
            if self.speculate is not None:
                # ISSUE 14: retain the served family so a later catalog
                # publish can be applied to it and pre-solved.
                self.speculate.observe(key, problem_vars[i])
            hit, plan = self.cache.lookup_or_plan(p, key, budget)
            if hit is not MISS:
                results[i] = hit  # bypasses the queue entirely
            elif plan is not None:
                # ISSUE 10: a certified warm plan queues in the
                # incremental size class — warm lanes coalesce with each
                # other instead of padding out a cold batch.
                warm_pending.append(
                    (i, _Lane(p, key, max_steps, budget, dl, warm=plan,
                              tenant=tenant)))
            else:
                pending.append((i, _Lane(p, key, max_steps, budget, dl,
                                         tenant=tenant)))
        steps = 0
        deadline_misses = 0
        report = None
        timing: dict = {}
        groups: List[tuple] = []
        prio = (self.tenant_policy.priority(tenant) if self.fair
                else 1)
        if pending:
            groups.append(
                (pending, self._make_group([lane for _, lane in pending],
                                           budget, priority=prio)))
        if warm_pending:
            groups.append(
                (warm_pending,
                 _Group([lane for _, lane in warm_pending],
                        INCREMENTAL_CLASS, budget, priority=prio)))
        for _, group in groups:
            self._enqueue(group)
        for grp_pending, group in groups:
            group.event.wait()
            if group.error is not None:
                raise group.error
            if group.report is not None:
                if report is None:
                    report = group.report
                else:
                    # Never merge IN PLACE: a group's report object is
                    # shared with every request coalesced into the same
                    # dispatch — fold both into a fresh one instead.
                    merged = telemetry.SolveReport(
                        backend=report.backend)
                    merged.n_problems = 0
                    merged.merge(report)
                    merged.merge(group.report)
                    report = merged
            for k, v in group.timing.items():
                # A mixed submit spans two dispatches (cold + warm
                # groups): sequential stage durations ADD — letting the
                # second group's few-ms warm flush overwrite the first's
                # device dispatch would misreport the breakdown — but
                # the groups QUEUE concurrently, so overlapped waits
                # take the max, not the sum.
                if isinstance(v, (int, float)) and k in timing:
                    timing[k] = (max(timing[k], v)
                                 if k == "queue_wait_s"
                                 else timing[k] + v)
                else:
                    timing[k] = v
            for i, lane in grp_pending:
                results[i] = lane.result
                steps += lane.steps
                if lane.degraded:
                    deadline_misses += 1
                    # Precise error attribution (ISSUE 4): the deadline
                    # fault event rode the shared dispatch trace, but
                    # only THIS request's lane was triaged expired —
                    # flag this trace, not the batchmates', and not a
                    # budget-exhaustion Incomplete whose deadline
                    # happened to lapse by readback time.
                    telemetry.trace.mark_error()
            qw = group.timing.get("queue_wait_s")
            if qw is not None:
                # Recorded on the submitting thread so the span joins
                # THIS request's trace (the wait was measured on the
                # dispatch loop's clock).
                telemetry.default_registry().record_span(
                    "sched.queue_wait", qw, lanes=len(group.lanes))
        if stats is not None:
            stats["steps"] = steps
            stats["report"] = report
            stats["timings"] = dict(timing)
            stats["deadline_misses"] = deadline_misses
        return results

    def _make_group(self, lanes: List[_Lane], budget: int,
                    speculative: bool = False,
                    priority: int = 1) -> _Group:
        from ..engine.driver import _bucket, _cost_proxy

        size_class = _bucket(max(_cost_proxy(l.problem) for l in lanes))
        return _Group(lanes, size_class, budget, speculative=speculative,
                      priority=priority)

    # --------------------------------------------- sessions (ISSUE 20)

    def submit_session(
        self,
        problem_vars: Sequence[Variable],
        deadline_s: Optional[float] = None,
        max_steps: Optional[int] = None,
        stats: Optional[dict] = None,
        tenant: str = "default",
        warm_index=None,
        session_key: Optional[str] = None,
        scope_entry_key: Optional[str] = None,
        scope_seed=None,
        problem: Optional[Problem] = None,
    ) -> object:
        """Blocking single-problem submit for a stateful session's (or an
        open test scope's) incremental solve.  The answer is exactly what
        ``submit`` would return for the same variables — same engines,
        same racing, same deadline/breaker/fair-admission semantics — but
        the lane is **scoped**: it skips the shared result cache entirely
        (no lookup, no store — assumption-conditioned answers must never
        serve or poison stateless traffic, satellite 2 of ISSUE 20), its
        cold dispatch rides the dedicated ``SESSION_CLASS`` bucket, and
        warm starts plan against ``warm_index`` — the session's private
        clause-set index holding the session's own last model — rather
        than the shared index.  An ``assume`` appends constraints without
        touching the vocabulary, so the derived problem's delta cone
        against the session's previous solve is small and the PR 9 warm
        machinery applies unchanged.

        A per-step scoped solve must not re-pay O(problem) bookkeeping
        the caller already knows the answer to, so the session facade
        may hand over what it tracks: ``session_key`` replaces the
        canonical ``fingerprint(p)`` as the lane key — legitimate ONLY
        because scoped lanes never touch the shared result cache, the
        key's sole job is entry identity inside the session's private
        index — and ``scope_entry_key`` + ``scope_seed`` (the previous
        scoped solve's key and the assumption-stack delta's variable
        indices) let the index plan O(delta) via
        :meth:`ClauseSetIndex.plan_for_scope` instead of re-hashing and
        re-scanning the whole problem.  When the declared predecessor
        is missing (first solve, UNSAT last step, post-handoff import)
        the generic classifier answers, and when no plan survives the
        gates the lane cold-solves — identity holds on every path.
        ``problem`` is the already-lowered form of ``problem_vars``
        (the facade's ``encode_assumed`` splice) — same dense tensors
        a fresh ``encode`` would produce, without the catalog re-walk.

        Returns the single result (Solution dict / NotSatisfiable /
        Incomplete); raises what ``submit`` raises for malformed input."""
        from ..engine.driver import _budget

        if max_steps is None:
            max_steps = self.max_steps
        budget = int(_budget(max_steps))
        p = problem if problem is not None else encode(problem_vars)
        if p.errors:
            raise InternalSolverError(p.errors)
        with faults.deadline_scope(deadline_s), faults.ambient_deadline():
            dl = faults.current_deadline()
        key = session_key if session_key is not None else fingerprint(p)
        plan = None
        if warm_index is not None:
            if scope_entry_key is not None:
                plan = warm_index.plan_for_scope(
                    p, key, budget, scope_entry_key, scope_seed or ())
            if plan is None:
                plan = warm_index.plan(p, key, budget)
        lane = _Lane(p, key, max_steps, budget, dl, warm=plan,
                     tenant=tenant)
        lane.scoped = True
        lane.session_index = warm_index
        prio = (self.tenant_policy.priority(tenant) if self.fair
                else 1)
        if plan is not None:
            group = _Group([lane], INCREMENTAL_CLASS, budget,
                           priority=prio, immediate=True)
        else:
            group = _Group([lane], SESSION_CLASS, budget, priority=prio,
                           immediate=True)
        self._enqueue(group)
        group.event.wait()
        if group.error is not None:
            raise group.error
        if lane.degraded:
            telemetry.trace.mark_error()
        qw = group.timing.get("queue_wait_s")
        if qw is not None:
            telemetry.default_registry().record_span(
                "sched.queue_wait", qw, lanes=1)
        if stats is not None:
            stats["steps"] = lane.steps
            stats["report"] = group.report
            stats["timings"] = dict(group.timing)
            stats["deadline_misses"] = 1 if lane.degraded else 0
            stats["warm"] = plan is not None
        return lane.result

    # ------------------------------------------------ speculation (ISSUE 14)

    def speculative_depth(self) -> int:
        """Speculative pre-solve lanes queued at idle priority."""
        with self._cv:
            return self._spec_depth

    def submit_speculative(
        self,
        problem_vars: Sequence[Sequence[Variable]],
        max_steps: Optional[int] = None,
    ) -> tuple:
        """Queue pre-solves at IDLE priority and return immediately with
        ``(queued, dropped)`` lane counts — fire-and-forget: results
        land in the result cache and the clause-set index exactly like
        ordinary solves, and nobody blocks on them.  The dispatch loop
        drains these groups only while no live group is queued, so live
        traffic preempts at every flush boundary.  Malformed families,
        already-cached fingerprints, and within-call duplicates are
        skipped; lanes past the backlog cap (or arriving while the loop
        is not running — a pre-solve must never dispatch inline on a
        publisher's thread) are dropped."""
        if self.speculate is None:
            return 0, len(problem_vars)
        from ..engine.driver import _budget

        if max_steps is None:
            max_steps = self.max_steps
        budget = int(_budget(max_steps))
        dropped = 0
        seen: set = set()
        cold: List[_Lane] = []
        warm: List[_Lane] = []
        for vs in problem_vars:
            try:
                p = encode(vs)
            except Exception as e:  # noqa: BLE001 — a malformed family
                # must never abort the rest of a publish burst; it is a
                # counted drop with a sink event, not a request error
                # (no requester exists to answer).
                telemetry.default_registry().event(
                    "fault", fault="speculate_encode_failed",
                    error=type(e).__name__)
                dropped += 1
                continue
            if p.errors:
                dropped += 1
                continue
            key = fingerprint(p)
            if key in seen:
                continue
            seen.add(key)
            if self.cache.peek(key, budget):
                continue  # the answer is already served from cache
            plan = (self.incremental.plan(p, key, budget)
                    if self.incremental is not None else None)
            lane = _Lane(p, key, max_steps, budget, None, warm=plan,
                         tenant="speculate")
            (warm if plan is not None else cold).append(lane)
            # Retain the POST-publish family under its new fingerprint:
            # a later publish must compose on this state, not the
            # superseded one the publish just retired.
            self.speculate.observe(key, vs)
        groups: List[_Group] = []
        # One group per cold family keeps size classes honest (the
        # spec drain coalesces same-class neighbors like the live
        # drain); warm lanes coalesce as the incremental class.
        for lane in cold:
            groups.append(self._make_group([lane], budget,
                                           speculative=True))
        if warm:
            groups.append(_Group(warm, INCREMENTAL_CLASS, budget,
                                 speculative=True))
        queued = 0
        with self._cv:
            admit = self.running
            for g in groups:
                # Drop lanes whose fingerprint is already queued or
                # mid-dispatch (a duplicate publish burst): neither
                # queued nor dropped — the answer is already on its
                # way.  The cache is re-peeked HERE because a pre-solve
                # can complete (store + key release) between the
                # pre-encode peek above and this enqueue; peek is a
                # leaf lock, safe under the CV.
                g.lanes = [lane for lane in g.lanes
                           if lane.key not in self._spec_keys
                           and not self.cache.peek(lane.key, budget)]
                if not g.lanes:
                    continue
                if (not admit or self._spec_depth + len(g.lanes)
                        > self.spec_max_backlog):
                    dropped += len(g.lanes)
                    continue
                self._spec_keys.update(lane.key for lane in g.lanes)
                self._spec_queue.append(g)
                self._spec_depth += len(g.lanes)
                queued += len(g.lanes)
            if self._g_spec_depth is not None:
                self._g_spec_depth.set(self._spec_depth)
            if queued:
                self._cv.notify_all()
        return queued, dropped

    def submit_optimize(
        self,
        problem_vars: Sequence[Sequence[Variable]],
        deadline_s: Optional[float] = None,
        max_steps: Optional[int] = None,
        stats: Optional[dict] = None,
        tenant: str = "default",
    ) -> List[object]:
        """Blocking :meth:`submit` sibling for optimize-tier bound
        probes (ISSUE 18), queued at IDLE priority: probe groups ride
        the speculative queue, so a long bound-tightening loop coalesces
        at flush boundaries like churn and live resolution traffic
        preempts every iteration — but unlike pre-solves a submitter IS
        waiting, so probes are never cap-dropped (the blocked caller is
        the backpressure) and dispatch errors re-raise here.

        Probes skip the result cache and the warm-plan index on purpose:
        a probe's answer doubles as an optimality proof, so it must come
        from an actual solve, and its model (biased by the synthetic
        bound variable) must not seed warm starts for plain requests."""
        from ..engine.driver import _budget

        if max_steps is None:
            max_steps = self.max_steps
        budget = int(_budget(max_steps))
        problems = [encode(vs) for vs in problem_vars]
        for p in problems:
            if p.errors:
                raise InternalSolverError(p.errors)
        with faults.deadline_scope(deadline_s), faults.ambient_deadline():
            dl = faults.current_deadline()
        lanes = [_Lane(p, fingerprint(p), max_steps, budget, dl,
                       tenant=tenant) for p in problems]
        group = self._make_group(lanes, budget, speculative=True)
        inline = False
        with self._cv:
            if self.running:
                self._spec_queue.append(group)
                self._spec_depth += len(group.lanes)
                if self._g_spec_depth is not None:
                    self._g_spec_depth.set(self._spec_depth)
                self._cv.notify_all()
            else:
                inline = True
        if inline:
            # No loop thread (library use, or post-shutdown stragglers):
            # the probe dispatches on the caller's thread like _enqueue.
            self._dispatch([group], reason="inline")
        group.event.wait()
        if group.error is not None:
            raise group.error
        deadline_misses = 0
        for lane in lanes:
            if lane.degraded:
                deadline_misses += 1
                telemetry.trace.mark_error()
        qw = group.timing.get("queue_wait_s")
        if qw is not None:
            telemetry.default_registry().record_span(
                "sched.queue_wait", qw, lanes=len(group.lanes))
        if stats is not None:
            stats["steps"] = sum(lane.steps for lane in lanes)
            stats["report"] = group.report
            stats["timings"] = dict(group.timing)
            stats["deadline_misses"] = deadline_misses
        return [lane.result for lane in lanes]

    # ---------------------------------------------- route plane (ISSUE 19)

    def set_route_plane(self, plane) -> None:
        """Install (or, with None, remove) the route-health plane.  The
        plane observes every cold live flush after its answers are
        served and may enqueue shadow route probes via
        :meth:`submit_shadow`."""
        self._route_plane = plane

    def submit_shadow(self, backend_name: str, class_name: str,
                      problems: Sequence[Problem],
                      max_steps: Optional[int] = None) -> bool:
        """Queue one shadow route probe (ISSUE 19) at IDLE priority:
        re-solve an already-coalesced flush's problems via ONE named
        backend, timing it for the route ledger.  Rides the speculative
        queue, so live traffic preempts every shadow dispatch at the
        flush boundary; results are emitted as a ``route`` sink event
        and NEVER touch a lane result, the cache, or the warm index.
        Returns False when dropped (loop not running, or the idle
        backlog is full — a shadow probe is pure opportunism)."""
        from ..engine.driver import _budget

        if max_steps is None:
            max_steps = self.max_steps
        budget = int(_budget(max_steps))
        lanes = [_Lane(p, "", max_steps, budget, None, tenant="shadow")
                 for p in problems]
        # The size class carries a shadow-only sentinel so the idle
        # drain's coalescing can never mix a shadow probe into an
        # optimize/pre-solve flush (those dispatch through the normal
        # solve path; shadow groups do not).
        group = _Group(lanes, f"shadow:{class_name}:{backend_name}",
                       budget, speculative=True)
        group.shadow_backend = backend_name
        group.shadow_class = class_name
        cap = getattr(self, "spec_max_backlog",
                      DEFAULT_SPECULATE_MAX_BACKLOG)
        with self._cv:
            if (not self.running
                    or self._spec_depth + len(lanes) > cap):
                return False
            self._spec_queue.append(group)
            self._spec_depth += len(lanes)
            if self._g_spec_depth is not None:
                self._g_spec_depth.set(self._spec_depth)
            self._cv.notify_all()
        return True

    def _dispatch_shadow(self, groups: List[_Group]) -> None:
        """Drain shadow route probes: one timed ``solve_via`` dispatch
        per group, answers discarded, wall clock + definitiveness
        emitted as a ``route`` event for the ledger/learner.  Failures
        are counted on the sink — a shadow probe must never take down
        the dispatch loop."""
        from ..engine import registry as engine_registry

        for g in groups:
            problems = [lane.problem for lane in g.lanes]
            name = g.shadow_backend
            out = None
            err = None
            t1 = time.perf_counter()
            try:
                faults.inject(f"sched.shadow.{name}")
                mesh = (self._resolve_mesh() if name == "device"
                        else None)
                out = engine_registry.solve_via(
                    name, problems, max_steps=g.lanes[0].max_steps,
                    mesh=mesh)
            except BaseException as e:  # noqa: BLE001 — probe-local
                err = type(e).__name__
            finally:
                wall = time.perf_counter() - t1
                ok = (err is None and out is not None
                      and all(r is not None and not r.degraded
                              for r in out))
                telemetry.default_registry().event(
                    "route", phase="shadow",
                    size_class_name=g.shadow_class, backend=name,
                    lanes=len(g.lanes), wall_s=round(wall, 6),
                    ok=bool(ok), error=err)
                g.event.set()

    def _enqueue(self, group: _Group) -> None:
        with self._cv:
            if self.running:
                self._queue.append(group)
                self._depth += len(group.lanes)
                self._tenant_depth[group.tenant] = (
                    self._tenant_depth.get(group.tenant, 0)
                    + len(group.lanes))
                self._g_depth.set(self._depth)
                self._cv.notify_all()
                return
        # No loop thread (library use, or post-shutdown stragglers):
        # dispatch on the caller's thread — same code path, no queue.
        self._dispatch([group], reason="inline")

    # --------------------------------------------------------- dispatch loop

    def _loop(self) -> None:
        try:
            self._loop_inner()
        finally:
            # A normal stop drains the queue through dispatches; this
            # only fires on an unexpected loop crash — fail any still-
            # queued groups loudly so no submitter waits forever.
            with self._cv:
                orphans, self._queue = self._queue, []
                self._depth = 0
                self._tenant_depth.clear()
                self._g_depth.set(0)
                # Speculative orphans fail loudly too (ISSUE 18): a
                # pre-solve's event has no waiter, but an optimize
                # probe's does — leaving it unset parks that submitter
                # forever.
                orphans += self._spec_queue
                self._spec_queue = []
                self._spec_depth = 0
                self._spec_keys.clear()
                if self._g_spec_depth is not None:
                    self._g_spec_depth.set(0)
            for g in orphans:
                if not g.event.is_set():
                    g.error = RuntimeError(
                        "scheduler dispatch loop exited unexpectedly")
                    g.event.set()

    def _loop_inner(self) -> None:
        while True:
            discarded = 0
            spec_orphans: List[_Group] = []
            groups: List[_Group] = []
            reason = None
            with self._cv:
                while (not self._queue and not self._spec_queue
                       and not self._stop):
                    self._cv.wait()
                if self._stop and self._spec_queue:
                    # Shutdown discards the speculative backlog: no
                    # submitter waits on a pre-solve, and opportunistic
                    # work must never slow a drain.  Optimize probes
                    # (ISSUE 18) ride this queue WITH a waiter — their
                    # groups are failed below, outside the lock.
                    discarded = self._spec_depth
                    spec_orphans = self._spec_queue
                    self._spec_queue = []
                    self._spec_depth = 0
                    self._spec_keys.clear()
                    if self._g_spec_depth is not None:
                        self._g_spec_depth.set(0)
                if self._queue:
                    groups, reason = self._drain_locked(force=self._stop)
                    if not groups:
                        # A live flush is pending but not yet due.  The
                        # speculative queue is NOT consulted in this
                        # window: a pre-solve dispatch here could push
                        # the live flush past max_wait — idle priority
                        # means idle, not "between live flushes".
                        head_due = (self._head_locked().enq_t
                                    + self.max_wait_s)
                        delay = head_due - time.monotonic()
                        self._cv.wait(timeout=max(delay, 0.001))
                        continue
                elif self._spec_queue:
                    # ISSUE 14: live lanes are empty — drain ONE
                    # speculative flush.  Live submits arriving during
                    # the dispatch preempt at the next loop iteration
                    # (the flush boundary).
                    groups, reason = self._drain_spec_locked()
            for g in spec_orphans:
                if not g.event.is_set():
                    g.error = RuntimeError(
                        "scheduler stopped before optimize dispatch")
                    g.event.set()
            if discarded and self.speculate is not None:
                self.speculate.note_discarded(discarded)
            if not groups:
                return  # stopped and drained
            self._dispatch(groups, reason)

    def _drain_spec_locked(self):
        """Pick one speculative flush (caller holds the lock): the
        oldest speculative group plus its same-class, same-budget
        neighbors up to ``max_fill`` lanes — the live drain's coalescing
        rule applied to the idle queue."""
        head = self._spec_queue[0]
        take = [head]
        lanes = len(head.lanes)
        for g in self._spec_queue[1:]:
            if lanes >= self.max_fill:
                break
            if (g.size_class == head.size_class
                    and g.budget == head.budget
                    and lanes + len(g.lanes) <= self.max_fill):
                take.append(g)
                lanes += len(g.lanes)
        taken = set(map(id, take))
        self._spec_queue = [g for g in self._spec_queue
                            if id(g) not in taken]
        self._spec_depth -= lanes
        if self._g_spec_depth is not None:
            self._g_spec_depth.set(self._spec_depth)
        return take, "spec"

    # A queued group older than this many coalescing windows becomes
    # the flush head regardless of priority class: a sustained urgent
    # stream must not starve bulk lanes forever (their submitter
    # threads block on group.event with no timeout — the historical
    # FIFO head guaranteed dispatch within ~max_wait).
    PRIORITY_AGING_WINDOWS = 100

    def _head_locked(self) -> _Group:
        """The next flush head (caller holds the lock): the oldest
        group of the most urgent priority class queued (ISSUE 15 —
        priority lanes; with every group at the default priority this
        is exactly the historical FIFO head), unless the globally
        oldest group has aged past PRIORITY_AGING_WINDOWS coalescing
        windows — starvation beats priority."""
        oldest = min(self._queue, key=lambda g: g.enq_t)
        aging_s = max(self.max_wait_s * self.PRIORITY_AGING_WINDOWS,
                      0.5)
        if time.monotonic() - oldest.enq_t >= aging_s:
            return oldest
        return min(self._queue, key=lambda g: (g.priority, g.enq_t))

    def _drain_locked(self, force: bool = False):
        """Pick the flushable group set (caller holds the lock): the
        priority head plus every queued group in its size class and
        budget, up to ``max_fill`` lanes.  Coalescing ignores priority
        — same-class batchmates share the head's dispatch, which is a
        free ride for them, never a delay for the head.  Returns
        ([], None) when no flush is due yet."""
        head = self._head_locked()
        take = [head]
        lanes = len(head.lanes)
        for g in self._queue:
            if lanes >= self.max_fill:
                break
            if (g is not head and g.size_class == head.size_class
                    and g.budget == head.budget
                    and lanes + len(g.lanes) <= self.max_fill):
                take.append(g)
                lanes += len(g.lanes)
        if force:
            reason = "drain"
        elif lanes >= self.max_fill:
            reason = "fill"
        elif head.immediate:
            reason = "immediate"
        elif time.monotonic() - head.enq_t >= self.max_wait_s:
            reason = "wait"
        else:
            return [], None
        taken = set(map(id, take))
        self._queue = [g for g in self._queue if id(g) not in taken]
        self._depth -= lanes
        for g in take:
            left = self._tenant_depth.get(g.tenant, 0) - len(g.lanes)
            if left > 0:
                self._tenant_depth[g.tenant] = left
            else:
                self._tenant_depth.pop(g.tenant, None)
        self._g_depth.set(self._depth)
        return take, reason

    def _dispatch(self, groups: List[_Group], reason: str) -> None:
        if groups and groups[0].shadow_backend is not None:
            # Shadow route probes (ISSUE 19) never coalesce with real
            # groups (their size-class sentinel is shadow-only), so a
            # drained set is homogeneous.
            self._dispatch_shadow(groups)
            return
        lanes = [lane for g in groups for lane in g.lanes]
        t0 = time.monotonic()
        report = None
        timing: dict = {}
        # Everything — telemetry included — runs inside the try: the
        # finally below is the only thing standing between a failure
        # here and submitters parked forever on their group events.
        try:
            for g in groups:
                g.timing["queue_wait_s"] = max(t0 - g.enq_t, 0.0)
            self._c_flushes.inc(label=reason)
            self._c_dispatches.inc()
            self._c_requests.inc(len(groups))
            self._h_coalesced.observe(len(lanes))
            # Trace scope (ISSUE 4): on the loop thread this is a fresh
            # dispatch trace whose root span LINKS to every parent
            # request — each request's flight record then contains the
            # shared dispatch's whole span tree; inline (caller-thread)
            # dispatches nest under the request's own trace instead.
            reg = telemetry.default_registry()
            with telemetry.trace.dispatch_scope(
                    [g.parent for g in groups]) as dctx:
                with reg.span("sched.dispatch", lanes=len(lanes),
                              requests=len(groups), reason=reason) as sp:
                    if dctx is not None:
                        for link in dctx.links:
                            sp.link(link["trace_id"],
                                    link.get("span_id"))
                    faults.inject("sched.dispatch")
                    report = self._solve_lanes(lanes, timing)
            for lane in lanes:
                self._maybe_cache(lane)
        except BaseException as e:  # noqa: BLE001 — re-raised per request
            for g in groups:
                g.error = e
            if any(g.speculative for g in groups):
                # No submitter exists to re-raise a speculative group's
                # error into (ISSUE 14) — surface it on the sink: a
                # publish burst silently failing to pre-solve would
                # read as "speculation working, cache cold".
                telemetry.default_registry().event(
                    "fault", fault="speculate_dispatch_failed",
                    error=type(e).__name__,
                    lanes=sum(len(g.lanes) for g in groups
                              if g.speculative))
        finally:
            dur = time.monotonic() - t0
            # Read-modify-write under the CV: admission_retry_after
            # reads the EWMA from handler threads while the dispatch
            # loop updates it here (the first real finding the
            # concurrency audit fixed; pinned by
            # tests/test_analysis.py::TestSchedulerEwmaRegression).
            with self._cv:
                self._dispatch_ewma_s = (0.8 * self._dispatch_ewma_s
                                         + 0.2 * dur)
                for g in groups:
                    if g.speculative:
                        # The pre-solve is stored (or failed) — later
                        # duplicates dedupe through the cache peek, not
                        # the in-flight key set.
                        self._spec_keys.difference_update(
                            lane.key for lane in g.lanes)
            timing["dispatch_s"] = dur
            for g in groups:
                g.timing.update(timing)
                g.report = report
                g.event.set()

    def _maybe_cache(self, lane: _Lane) -> None:
        r = lane.result
        if lane.scoped:
            # ISSUE 20: assumption-conditioned answers never reach the
            # shared exact LRU or clause-set index — they would poison
            # stateless traffic with results that only hold under the
            # session's assumption stack.  The session's private index
            # takes the model instead (same eligibility gate as the
            # shared index: measured, zero-backtrack-certifiable, not
            # degraded) so the session's NEXT op warm-starts from it.
            if (lane.session_index is not None and isinstance(r, dict)
                    and not lane.degraded and lane.backtracks is not None):
                model = np.fromiter(
                    (bool(r[v.identifier])
                     for v in lane.problem.variables),
                    dtype=bool, count=lane.problem.n_vars)
                lane.session_index.store(
                    lane.key, lane.problem, model,
                    lane.index_steps if lane.index_steps is not None
                    else lane.steps,
                    lane.backtracks, lazy_rows=True)
            return
        if isinstance(r, (dict, NotSatisfiable)):
            self.cache.store(lane.key, lane.budget, r)
        elif isinstance(r, Incomplete) and lane.deadline is None:
            # Budget exhaustion is reproducible; deadline degradation
            # is not — only the former may be cached.
            self.cache.store(lane.key, lane.budget, r)
        # ISSUE 10: SAT models feed the clause-set index so the NEXT
        # delta against this problem warm-starts.  Only lanes whose path
        # measured the search-backtrack count are eligible (the index
        # keeps zero-backtrack seeds only — the warm certification
        # precondition); degraded lanes never are.
        if (self.incremental is not None and isinstance(r, dict)
                and not lane.degraded and lane.backtracks is not None):
            model = np.fromiter(
                (bool(r[v.identifier])
                 for v in lane.problem.variables),
                dtype=bool, count=lane.problem.n_vars)
            self.incremental.store(
                lane.key, lane.problem, model,
                lane.index_steps if lane.index_steps is not None
                else lane.steps,
                lane.backtracks)

    # -------------------------------------------------------------- solving

    def _solve_lanes(self, lanes: List[_Lane], timing: Optional[dict] = None):
        """Solve one coalesced lane set; fills each lane's result/steps
        and returns the dispatch's SolveReport.  ``timing``, when given,
        receives the solve/decode wall-clock split (ISSUE 4)."""
        from ..sat.solver import resolve_backend

        if timing is None:
            timing = {}

        live: List[_Lane] = []
        for lane in lanes:
            if lane.deadline is not None and lane.deadline.expired():
                # Expired at triage: degrade THIS lane only — its
                # batchmates dispatch unharmed.  The fault event carries
                # the lane's tenant (ISSUE 11) so deadline misses are
                # attributable per tenant from the sink alone.
                faults.note_deadline_exceeded("sched.dispatch",
                                              tenant=lane.tenant)
                lane.result = Incomplete()
                lane.steps = 0
                lane.degraded = True
            else:
                live.append(lane)
        if not live:
            return None
        # The dispatch runs under the LOOSEST live deadline (the driver
        # degrades whole groups past the scope's expiry, and a
        # stranger's tighter budget must not cut a batchmate short).
        # Any unbounded lane means an unbounded dispatch.
        scope = None
        deadlines = [lane.deadline for lane in live]
        if all(d is not None for d in deadlines):
            scope = max(deadlines, key=lambda d: d.remaining())
        backend = resolve_backend(self.backend, block=False)
        if (self.backend == "auto" and backend == "host"
                and faults.default_breaker().blocks_device()):
            # ISSUE 14 satellite: this flush is a breaker-open host
            # drain — kick the deferred background re-probe so auto
            # routing upgrades once the accelerator recovers, instead
            # of waiting for a restart.
            self._kick_reprobe()
        rep, owns = telemetry.begin_report(backend=backend,
                                           n_problems=len(live))
        cold_flush = False
        try:
            with faults.deadline_scope(scope):
                if all(lane.warm is not None for lane in live):
                    # ISSUE 10: an incremental-class flush — warm
                    # attempts first, cold fallbacks drain through the
                    # normal backend path below.
                    t1 = time.perf_counter()
                    self._solve_incremental(live, rep, timing, backend)
                    timing["solve_s"] = time.perf_counter() - t1
                    return rep
                cold_flush = True
                # Portfolio racing (ISSUE 13): cold flushes only.  A
                # None plan (racing off / auto with no measured row /
                # <2 candidates) leaves the canonical single-backend
                # path below byte-identical to the pre-portfolio tree.
                plan = (self._racer.plan(live, backend)
                        if self._racer is not None else None)
                finisher = None
                raced = False
                try:
                    if plan is not None:
                        live, finisher = self._triage_stragglers(
                            live, plan.class_name)
                        if live:
                            raced = self._racer.race(
                                plan, live, rep, timing,
                                self._resolve_mesh)
                        else:
                            raced = True
                    if not raced:
                        if backend == "host":
                            t1 = time.perf_counter()
                            self._solve_host(live, rep)
                            timing["solve_s"] = (time.perf_counter()
                                                 - t1)
                        else:
                            self._solve_device(live, timing)
                finally:
                    if finisher is not None:
                        finisher(rep)
        finally:
            telemetry.end_report(rep, owns)
        if (self._route_plane is not None and cold_flush and live
                and any(lane.tenant not in ("speculate", "shadow")
                        for lane in live)):
            # ISSUE 19: the route plane observes the flush after its
            # answers are computed — O(1) bookkeeping plus at most one
            # idle-queue enqueue; the shadow solve itself runs later,
            # only while the live queue is empty.  Observability must
            # never fail serving.
            try:
                self._route_plane.observe_flush(self, live)
            # deppy: lint-ok[exception-hygiene] route-health bookkeeping must never fail a flush that already has answers
            except Exception:
                pass
        return rep

    def _solve_device(self, live: List[_Lane], timing: dict) -> None:
        from ..engine import driver

        problems = [lane.problem for lane in live]
        # All live lanes share one normalized budget (the flush policy
        # only coalesces equal-budget groups).  Under a serving mesh
        # (ISSUE 6) the coalesced micro-batch drains through the
        # sharded entry point — lane axis split across devices,
        # per-shard fault domains; otherwise solve_problems runs the
        # group under the process-wide fault-domain recovery wrapper.
        # Both merge their telemetry into the report begun above.
        mesh = self._resolve_mesh()
        t1 = time.perf_counter()
        if mesh is not None:
            results = driver.solve_problems_sharded(
                problems, mesh=mesh, max_steps=live[0].max_steps)
        else:
            results = driver.solve_problems(problems,
                                            max_steps=live[0].max_steps)
        timing["solve_s"] = time.perf_counter() - t1
        t1 = time.perf_counter()
        decoded = driver.decode_results(problems, results)
        timing["decode_s"] = time.perf_counter() - t1
        for lane, res, dec in zip(live, results, decoded):
            lane.steps = int(res.steps)
            lane.backtracks = int(res.trace_n)
            lane.result = dec

    def _solve_incremental(self, live: List[_Lane], rep,
                           timing: dict, backend: str) -> None:
        """Drain one incremental-class flush: device-screen the warm
        prefixes (lockstep, device backend only), run the surviving
        warm attempts on the host spec engine, and cold-solve every
        fallback through the NORMAL backend path — fault domain and
        breaker semantics unchanged.  Per-lane deadlines are admission
        checks before each warm attempt (the hostpool convention: a
        lane never preempts mid-solve), so a lapse during the flush
        degrades only the lanes not yet started."""
        from .. import incremental as inc

        prof_t0 = _profile.dispatch_t0("warm")
        warm_served = 0
        warm_steps = 0
        plans = [lane.warm for lane in live]
        screened = [True] * len(live)
        if (backend != "host" and len(live) > 1
                and not faults.default_breaker().blocks_device()):
            # The batched device lane variant: one lockstep pass over
            # the whole warm class instead of per-lane host prefix
            # tests.  Router only — failures degrade to all-pass — and
            # an OPEN breaker skips it outright: its contract is zero
            # device attempts, and a wedged accelerator would hang the
            # dispatch loop here, not raise (explicit-tpu with an open
            # breaker is already 503'd at admission; this covers the
            # race and library callers).
            screened = inc.screen(plans)
        cold: List[_Lane] = []
        for lane, plan, ok in zip(live, plans, screened):
            if lane.deadline is not None and lane.deadline.expired():
                faults.note_deadline_exceeded("sched.dispatch",
                                              tenant=lane.tenant)
                rep.count_outcome("incomplete")
                lane.result = Incomplete()
                lane.degraded = True
                continue
            res = inc.attempt(plan, lane.max_steps) if ok else None
            if res is None:
                if self.incremental is not None:
                    self.incremental.note_fallback()
                cold.append(lane)
                continue
            lane.result = _solution_dict(lane.problem, res.installed_idx)
            lane.steps = res.steps
            lane.backtracks = res.backtracks
            # Index under a cold-equivalent cost: the seeding entry's
            # cold steps plus this cone's work bounds what a cold solve
            # of THIS problem would spend far better than the warm
            # attempt's own count does.
            lane.index_steps = plan.entry_steps + res.steps
            warm_served += 1
            warm_steps += res.steps
            rep.count_outcome("sat")
            rep.steps += res.steps
            rep.decisions += res.decisions
            rep.propagation_rounds += res.propagation_rounds
            if self.incremental is not None:
                self.incremental.note_served()
        if prof_t0 is not None and warm_served:
            # ISSUE 11: warm-tier cost attribution — the screen + warm
            # attempts up to here; cold fallbacks account under their
            # own backend (device via the driver ledger, host below).
            _profile.record_backend_flush(
                "warm", warm_served, warm_steps,
                time.perf_counter() - prof_t0,
                tenant=_single_tenant(live))
        if cold:
            if backend == "host":
                self._solve_host(cold, rep)
            else:
                self._solve_device(cold, timing)

    def _triage_stragglers(self, live: List[_Lane], class_name: str):
        """Per-lane deadline triage (ISSUE 13): lanes whose remaining
        wall-clock budget cannot survive the expected device dispatch
        (the dispatch EWMA, floored by the engine registry's per-class
        device estimate — the ledger-informed cost model) are
        resubmitted to the host pool, where they start immediately
        instead of pinning — or expiring inside — a lockstep device
        batch.  Returns (kept lanes, finisher|None); the finisher joins
        the resubmission and merges its report.  Racing-path only: with
        the portfolio off, deadline semantics are untouched."""
        from ..engine import registry as engine_registry

        with self._cv:
            est = self._dispatch_ewma_s
        est = max(est,
                  engine_registry.estimate_us("device", class_name) / 1e6)
        resub = [lane for lane in live
                 if lane.deadline is not None
                 and 0.0 < lane.deadline.remaining() < est]
        if not resub:
            return live, None
        keep = [lane for lane in live
                if not any(lane is r for r in resub)]
        reg = self._registry
        reg.counter(
            "deppy_race_straggler_resubmits_total",
            "Deadline-straggler lanes resubmitted to the host pool "
            "instead of riding a device batch.").inc(len(resub))
        telemetry.default_registry().event(
            "race", resubmitted=len(resub),
            size_class_name=class_name)
        box: dict = {}

        def side() -> None:
            from .. import hostpool

            srep, owns = telemetry.begin_report(backend="hostpool")
            try:
                results = hostpool.solve_host_problems(
                    [lane.problem for lane in resub],
                    max_steps=[lane.max_steps for lane in resub],
                    deadlines=[lane.deadline for lane in resub])
                for lane, r in zip(resub, results):
                    _count_lane_outcome(srep, r)
                    _apply_lane_result(lane, r, "sched.race",
                                       canonical=False)
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                box["error"] = e
            finally:
                telemetry.detach_report(srep, owns)
                box["rep"] = srep

        t = threading.Thread(target=side, name="deppy-race-resubmit",
                             daemon=True)
        t.start()

        def finisher(rep) -> None:
            t.join()
            rep.merge(box["rep"])
            if "error" in box:
                import sys

                if sys.exc_info()[1] is not None:
                    # A primary exception is already propagating out of
                    # the dispatch (the finisher runs in its finally):
                    # re-raising here would MASK it — surface the side
                    # failure on the sink instead.
                    telemetry.default_registry().event(
                        "fault", fault="race_resubmit_failed",
                        error=type(box["error"]).__name__,
                        lanes=len(resub))
                    return
                raise box["error"]

        return keep, finisher

    # ------------------------------------------- deferred re-probe (ISSUE 14)

    def _kick_reprobe(self) -> None:
        """Start the background re-probe loop (once) after a
        breaker-open host drain.  The loop waits out the breaker
        cooldown, then runs the killable subprocess engine probe OFF
        the serving path — a success resets the breaker and replaces
        the ``auto`` verdict (``sat.solver.reprobe_engine``), so
        routing upgrades without risking a live dispatch on the
        half-open probe; a failure retries on the
        ``DEPPY_TPU_REPROBE`` interval while the breaker stays open."""
        if self._reprobe_s <= 0:
            return
        with self._cv:
            t = self._reprobe_thread
            if t is not None and t.is_alive():
                return
            t = threading.Thread(target=self._reprobe_loop,
                                 name="deppy-sched-reprobe", daemon=True)
            self._reprobe_thread = t
        t.start()

    def _reprobe_loop(self) -> None:
        from ..sat import solver as sat_solver

        c_reprobes = self._registry.counter(
            "deppy_sched_reprobes_total",
            "Deferred background engine re-probes after a breaker-open "
            "host drain, by result.", labelname="result")
        # First wake lands right after the cooldown elapses (probing a
        # still-open breaker earlier would burn the probe timeout
        # re-learning the failure that opened it — whatever the
        # configured interval); FAILED probes retry on the full
        # DEPPY_TPU_REPROBE interval — remaining_s() is 0 once the
        # cooldown lapses, and a 75s subprocess probe must not hot-loop
        # against a dead accelerator.
        delay = max(faults.default_breaker().remaining_s(), 1.0)
        while True:
            if self._reprobe_stop.wait(delay):
                return
            state = faults.default_breaker().state()
            if state == "closed":
                # Recovered through the normal dispatch path while we
                # slept — nothing left to upgrade.  A HALF-OPEN breaker
                # is exactly what this loop exists for: probe it off
                # the serving path so no live request pays the
                # half-open dispatch gamble.
                return
            if state == "open":
                # Re-opened (or still cooling) while we slept: wait out
                # the (new) cooldown instead of probing a breaker that
                # already knows the answer.
                delay = max(faults.default_breaker().remaining_s(), 1.0)
                continue
            try:
                ok = sat_solver.reprobe_engine()
            # deppy: lint-ok[exception-hygiene] probe failure = not recovered; retried next tick
            except Exception:
                ok = False
            c_reprobes.inc(label="upgraded" if ok else "failed")
            if ok:
                telemetry.default_registry().event(
                    "fault", fault="sched_reprobe_upgraded")
                return
            delay = max(self._reprobe_s, 1.0)

    def _solve_host(self, live: List[_Lane], rep) -> None:
        """Host-engine drain — the breaker's host-only mode and the
        explicit host backend.  Lanes run through the shared hostpool
        entry (ISSUE 5): concurrent across the host worker pool when one
        is available, so a wedged accelerator degrades throughput to
        the host's cores instead of one; inline (bit-identical)
        otherwise.  Each LANE's own deadline rides along per lane:
        completed lanes keep their answers, expired ones degrade
        individually without poisoning their pool batchmates."""
        from .. import hostpool

        reg = telemetry.default_registry()
        prof_t0 = _profile.dispatch_t0("host")
        with reg.span("sched.host_solve", problems=len(live)):
            results = hostpool.solve_host_problems(
                [lane.problem for lane in live],
                max_steps=[lane.max_steps for lane in live],
                deadlines=[lane.deadline for lane in live])
            if prof_t0 is not None:
                _profile.record_backend_flush(
                    "host", len(live),
                    int(sum(r.steps for r in results)),
                    time.perf_counter() - prof_t0,
                    tenant=_single_tenant(live))
            for lane, r in zip(live, results):
                # The ONE lane decode + accounting (shared with the
                # racer's winner application and the straggler
                # resubmission, so the paths cannot drift).
                _count_lane_outcome(rep, r)
                _apply_lane_result(lane, r, "sched.host_solve")
