"""Per-tenant weighted-fair admission policy (ISSUE 15, piece 2).

PR 3's admission gate is a GLOBAL queue-depth 503: one noisy tenant
filling the queue starves every other tenant at the door.  With
``DEPPY_TPU_SCHED_FAIR`` on (the default) the scheduler instead keeps
per-tenant queued-lane accounting and sheds a tenant only when it
exceeds its own weighted share of the queue:

    cap(tenant) = max_depth * weight(tenant) / sum(weights of tenants
                                                   queued right now)

A lone tenant's cap is the whole queue — single-tenant behavior is
byte-identical to the global gate — while under contention the caps
split the queue by weight, so the offender sheds at its share and the
victim's lanes always find room.  (The scheduler adds a hard
aggregate backstop at 2x max_depth: caps sum to max_depth for any
FIXED tenant set, but tenant labels are client-controlled and
sequentially minted fresh tenants could otherwise ratchet total
depth unbounded.)  Weights (and priority lanes) are
declarative, the ``DEPPY_TPU_SLO`` spec convention: inline JSON,
``@FILE``, or a path mapping tenant to a bare weight number or
``{"weight": W, "priority": P}``; the ``"default"`` entry covers
unlisted tenants.

**Priority lanes.**  ``priority`` (0 = urgent, larger = later; default
1) orders the dispatch loop's flush-head selection: the oldest queued
group of the MOST urgent priority class present flushes first, so a
latency-tier tenant's lanes never wait behind a bulk tenant's backlog.
Groups still coalesce across priorities (same size class + budget share
a dispatch — a free ride, never a delay).
"""

from __future__ import annotations

import json
from typing import Dict, Optional

DEFAULT_WEIGHT = 1.0
DEFAULT_PRIORITY = 1


class TenantPolicy:
    """Declarative per-tenant weights and priority classes."""

    def __init__(self, tenants: Optional[Dict[str, object]] = None):
        self.tenants: Dict[str, dict] = {}
        for name, spec in (tenants or {}).items():
            if isinstance(spec, (int, float)) \
                    and not isinstance(spec, bool):
                spec = {"weight": float(spec)}
            if not isinstance(spec, dict):
                raise ValueError(
                    f"tenant-weight entry for {name!r} must be a "
                    f"number or an object, got {type(spec).__name__}")
            weight = float(spec.get("weight", DEFAULT_WEIGHT))
            if weight <= 0:
                raise ValueError(
                    f"tenant {name!r}: weight must be positive")
            self.tenants[str(name)] = {
                "weight": weight,
                "priority": int(spec.get("priority", DEFAULT_PRIORITY)),
            }

    def _entry(self, tenant: str) -> dict:
        return self.tenants.get(tenant) or self.tenants.get("default") \
            or {"weight": DEFAULT_WEIGHT, "priority": DEFAULT_PRIORITY}

    def weight(self, tenant: str) -> float:
        return self._entry(tenant)["weight"]

    def priority(self, tenant: str) -> int:
        return self._entry(tenant)["priority"]

    def cap(self, tenant: str, max_depth: int,
            active_tenants) -> float:
        """``tenant``'s queued-lane cap given who is queued right now
        (``tenant`` itself always counts as active — its own admission
        is the question being asked)."""
        names = set(active_tenants) | {tenant}
        total = sum(self.weight(t) for t in names)
        return max_depth * self.weight(tenant) / max(total, 1e-9)

    @classmethod
    def from_spec(cls, spec: Optional[str]) -> "TenantPolicy":
        """Inline JSON, ``@FILE``, or a file path — the fault-plan /
        SLO spec convention.  Raises ``ValueError``/``OSError`` on a
        malformed spec: an operator fairness policy that silently
        parses to nothing would admit the noisy tenant it was written
        to shed."""
        if not spec:
            return cls()
        text = spec.strip()
        if text.startswith("@"):
            with open(text[1:], "r", encoding="utf-8") as fh:
                text = fh.read()
        elif not text.startswith(("{", "[")):
            with open(text, "r", encoding="utf-8") as fh:
                text = fh.read()
        doc = json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError(
                f"tenant-weight spec must be a tenant->weight mapping, "
                f"got {type(doc).__name__}")
        return cls(doc)
