"""deppy_tpu.sched — cross-request continuous batching (ISSUE 3).

The paper's headline claim is throughput: thousands of independent
resolutions sharded across one device mesh.  PR 1 made the waste of the
per-request dispatch model visible (batch-fill histograms near zero
under concurrent traffic: every ``/v1/resolve`` paid its own pad/pack +
``device_put`` + kernel launch); PR 2 made dispatches survivable.  This
package makes them *shared* — the same move continuous batching makes in
inference serving:

  * **scheduler** — :class:`Scheduler`: a size-class-aware micro-batch
    queue (reusing the engine driver's ``partition_buckets`` cost
    proxies so a giant catalog problem never inflates a burst of tiny
    ones) with a max-wait / max-fill flush policy, drained by one
    dispatch-loop thread through the existing fault-domain recovery path
    (``driver._recovering``: retry → split → host fallback, breaker
    charging).  Each request's deadline rides along on its lanes; an
    expired lane degrades to ``Incomplete`` without poisoning its
    coalesced batchmates, and an open accelerator breaker routes the
    queue to the host engine instead of rejecting traffic.
  * **cache** — :class:`ResultCache` + :func:`fingerprint`: problems are
    fingerprinted after encoding (sorted clause tensor hash + budget);
    hits bypass the queue entirely, entries are invalidated on budget
    escalation, and hit/miss/evict counters land in telemetry.

Metric families (registered on the scheduler's registry — the service
passes its ``/metrics`` registry): ``deppy_sched_queue_depth``,
``deppy_sched_coalesced_batch_size``, ``deppy_sched_dispatches_total``,
``deppy_sched_flushes_total``, ``deppy_cache_hit_ratio`` and the
``deppy_cache_*_total`` counters.  See docs/serving.md.
"""

from .cache import ResultCache, fingerprint
from .scheduler import Scheduler

__all__ = [
    "ResultCache",
    "Scheduler",
    "fingerprint",
]
