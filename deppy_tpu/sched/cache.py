"""Canonical-form result cache (ISSUE 3 tentpole, piece 3).

Catalog traffic is heavily repetitive — thousands of cluster states
re-resolving the same problem against the same catalog — so the
scheduler fingerprints every problem *after* encoding and serves repeats
straight from memory, bypassing the queue and the device entirely.

**Fingerprint.**  :func:`fingerprint` hashes the lowered
:class:`deppy_tpu.sat.encode.Problem`: the clause tensor in row-sorted
(canonical) order with its per-clause constraint map permuted alongside,
every other dense tensor (cardinality rows, anchors, choice tables) with
shape and dtype, and the decode vocabulary (ordered entity identifiers
and applied-constraint strings) — the response is rendered from that
vocabulary, so two problems may share an entry only when their rendered
responses are byte-identical.

**Budget semantics.**  Entries record the step budget they were solved
under; the solver is deterministic, so

  * a **definitive** result (sat / unsat) found within budget *B* is the
    answer for every request budget ≥ *B* — those hit;
  * an **incomplete** result at budget *B* (budget exhaustion only —
    deadline-degraded lanes are never cached) stays incomplete for every
    request budget ≤ *B* — those hit; a request with a *larger* budget
    is a **budget escalation**: the stale entry is invalidated
    (``deppy_cache_invalidations_total``) and the problem re-solves.

Eviction is LRU at ``capacity`` entries.  Hit/miss/evict counters and
the ``deppy_cache_hit_ratio`` gauge land on the registry the scheduler
was built with (the service passes its ``/metrics`` registry).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from .. import telemetry
from ..sat.encode import Problem
from ..sat.errors import Incomplete, NotSatisfiable

# Sentinel distinguishing "no cached answer" from a cached None.
MISS = object()


def fingerprint(problem: Problem) -> str:
    """Canonical content hash of one encoded problem (hex digest).

    Clause rows are sorted lexicographically (with ``clause_con``
    permuted alongside) so the hash is invariant to clause emission
    order; everything the decode path reads — identifiers, applied
    constraint strings, every dense tensor with its shape — is folded
    in, so key equality implies byte-identical rendered responses.

    Memoized on the problem object (ISSUE 10 satellite): a Problem's
    tensors never change after ``encode()``, and the delta tier's
    lookup/store pairs would otherwise re-row-sort the clause tensor on
    every consultation."""
    memo = problem.__dict__.get("_fp_digest")
    if memo is not None:
        return memo
    h = hashlib.sha256()

    def feed(tag: str, arr: np.ndarray) -> None:
        a = np.ascontiguousarray(arr)
        h.update(tag.encode())
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())

    c = problem.clauses
    order = np.lexsort(c.T[::-1]) if c.size else np.arange(c.shape[0])
    feed("clauses", c[order])
    feed("clause_con", problem.clause_con[order])
    feed("card_ids", problem.card_ids)
    feed("card_n", problem.card_n)
    feed("card_act", problem.card_act)
    feed("card_con", problem.card_con)
    feed("anchors", problem.anchors)
    feed("choice_cand", problem.choice_cand)
    feed("var_choices", problem.var_choices)
    # Decode vocabulary: the response carries identifiers and applied
    # constraint strings, so they are part of the problem's identity.
    h.update(("\x1f".join(str(v.identifier) for v in problem.variables)
              ).encode())
    h.update(("\x1f".join(str(c) for c in problem.applied)).encode())
    digest = h.hexdigest()
    problem.__dict__["_fp_digest"] = digest
    return digest


def _result_nbytes(result) -> int:
    """Rough per-entry footprint estimate for the ``deppy_cache_bytes``
    gauge: identifier strings dominate a Solution dict, constraint
    strings an unsat core.  Documented as an estimate — it sizes
    capacity planning, not an allocator."""
    if isinstance(result, dict):
        return 96 + sum(len(str(k)) + 28 for k in result)
    cons = getattr(result, "constraints", None)
    if cons is not None:
        return 96 + sum(len(str(c)) + 28 for c in cons)
    return 96


class _Entry:
    __slots__ = ("budget", "result", "definitive", "nbytes")

    def __init__(self, budget: int, result, definitive: bool):
        self.budget = budget
        self.result = result  # Solution dict | NotSatisfiable | None
        self.definitive = definitive
        self.nbytes = _result_nbytes(result)


class ResultCache:
    """Thread-safe LRU keyed by :func:`fingerprint` digests."""

    def __init__(self, capacity: int = 1024,
                 registry: Optional[telemetry.Registry] = None,
                 incremental=None):
        from ..analysis import lockdep

        self.capacity = max(int(capacity), 0)
        # Delta-aware tier (ISSUE 10): a ClauseSetIndex consulted on
        # exact misses so near-identical problems warm-start instead of
        # cold-solving.  None = tier off (DEPPY_TPU_INCREMENTAL=off) —
        # lookup/store behave exactly as before.
        self.incremental = incremental
        self._lock = lockdep.make_lock("sched.cache")
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        reg = registry if registry is not None \
            else telemetry.default_registry()
        self._hits = reg.counter(
            "deppy_cache_hits_total",
            "Scheduler result-cache hits (queue bypassed).")
        self._misses = reg.counter(
            "deppy_cache_misses_total",
            "Scheduler result-cache misses (problem queued).")
        self._evictions = reg.counter(
            "deppy_cache_evictions_total",
            "Result-cache entries evicted by LRU capacity pressure.")
        self._invalidations = reg.counter(
            "deppy_cache_invalidations_total",
            "Result-cache entries invalidated by budget escalation.")
        self._ratio = reg.gauge(
            "deppy_cache_hit_ratio",
            "Lifetime result-cache hit ratio (hits / lookups).")
        self._ratio.set(0.0)
        self._g_entries = reg.gauge(
            "deppy_cache_entries",
            "Result-cache entries resident right now.")
        self._g_entries.set(0)
        self._g_bytes = reg.gauge(
            "deppy_cache_bytes",
            "Estimated resident result-cache footprint in bytes "
            "(identifier/constraint string heuristic).")
        self._g_bytes.set(0)
        self._bytes = 0
        self._n_hits = 0
        self._n_lookups = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def _size_changed_locked(self) -> None:
        self._g_entries.set(len(self._entries))
        self._g_bytes.set(self._bytes)

    def _account(self, hit: bool) -> None:
        """Caller holds the lock."""
        self._n_lookups += 1
        if hit:
            self._n_hits += 1
            self._hits.inc()
        else:
            self._misses.inc()
        self._ratio.set(round(self._n_hits / self._n_lookups, 4))

    def lookup(self, key: str, budget: int):
        """Cached result for ``key`` under ``budget``, or :data:`MISS`.

        Hits return a fresh Solution dict copy (callers may mutate), the
        shared :class:`NotSatisfiable` (immutable by convention), or a
        fresh :class:`Incomplete` marker."""
        if self.capacity == 0:
            return MISS
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self._account(hit=False)
                return MISS
            if e.definitive:
                if e.budget > budget:
                    # Solved only with MORE steps than this request
                    # grants: the smaller budget might not have finished.
                    self._account(hit=False)
                    return MISS
                self._entries.move_to_end(key)
                self._account(hit=True)
                if isinstance(e.result, dict):
                    return dict(e.result)
                return e.result
            # Incomplete entry: still incomplete at any smaller budget;
            # a larger budget escalates — invalidate and re-solve.
            if budget <= e.budget:
                self._entries.move_to_end(key)
                self._account(hit=True)
                return Incomplete()
            self._bytes -= e.nbytes
            del self._entries[key]
            self._invalidations.inc()
            self._size_changed_locked()
            self._account(hit=False)
            return MISS

    def peek(self, key: str, budget: int) -> bool:
        """True when :meth:`lookup` would hit — WITHOUT the hit/miss
        accounting or the LRU touch.  The speculation tier (ISSUE 14)
        consults this before queuing a pre-solve: a probe must not
        distort the serving hit ratio or refresh recency on behalf of
        traffic that never arrived."""
        if self.capacity == 0:
            return False
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return False
            if e.definitive:
                return e.budget <= budget
            return budget <= e.budget

    def invalidate_keys(self, keys) -> int:
        """Publish-driven invalidation (ISSUE 14 satellite): evict the
        entries whose fingerprints a catalog publish retracted or
        contradicted — they describe pre-publish states that can no
        longer be re-asked and must not be served stale.  Returns the
        eviction count; each one lands on the existing
        ``deppy_cache_invalidations_total`` family."""
        n = 0
        with self._lock:
            for key in keys:
                e = self._entries.pop(key, None)
                if e is None:
                    continue
                self._bytes -= e.nbytes
                self._invalidations.inc()
                n += 1
            if n:
                self._size_changed_locked()
        return n

    def export_seeds(self) -> list:
        """``(key, budget, solution-dict)`` for every definitive SAT
        entry, least recently used first — the fleet snapshot/handoff
        surface (ISSUE 15).  UNSAT and Incomplete entries are not
        exported: cores hold live constraint objects and incompletes
        are budget-relative; both re-solve cold once on the inheritor.
        Solution dicts are copied, so the snapshot cannot alias live
        entries."""
        out = []
        with self._lock:
            for key, e in self._entries.items():
                if e.definitive and isinstance(e.result, dict):
                    out.append((key, e.budget, dict(e.result)))
        return out

    def lookup_or_plan(self, problem: Problem, key: str, budget: int):
        """Exact lookup, then the delta tier: returns ``(hit, None)`` on
        an exact hit, ``(MISS, WarmPlan)`` when the incremental index
        can plan a certified warm start for this problem, and
        ``(MISS, None)`` otherwise (cold path)."""
        hit = self.lookup(key, budget)
        if hit is not MISS:
            if self.incremental is not None:
                # Exact hits never reach the solve/store path, so the
                # index's scan-window recency must be refreshed here or
                # a cycling catalog drifts it off the revisited states.
                self.incremental.touch(key)
            return hit, None
        if self.incremental is None:
            return MISS, None
        return MISS, self.incremental.plan(problem, key, budget)

    def store(self, key: str, budget: int, result) -> None:
        """Record one solved problem.  ``result`` is a Solution dict, a
        :class:`NotSatisfiable`, or an :class:`Incomplete` (cache it
        only for lanes that had NO deadline — deadline degradation says
        nothing about the step budget; the scheduler enforces that)."""
        if self.capacity == 0:
            return
        definitive = isinstance(result, (dict, NotSatisfiable))
        if not definitive and not isinstance(result, Incomplete):
            return  # unknown result shape: never cache defensively
        if isinstance(result, dict):
            # Private copy: the caller holds (and may mutate) the very
            # dict being stored — lookup() copies on the way out, store
            # must copy on the way in or mutation poisons future hits.
            result = dict(result)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                if definitive and (not e.definitive or budget < e.budget):
                    # A definitive answer supersedes an incomplete one,
                    # and a smaller sufficient budget widens the entry's
                    # hit range (definitive-at-B serves every B' >= B).
                    self._bytes -= e.nbytes
                    e = _Entry(budget, result, True)
                    self._entries[key] = e
                    self._bytes += e.nbytes
                elif (not definitive and not e.definitive
                        and budget > e.budget):
                    # A deeper incomplete widens the incomplete range.
                    self._bytes -= e.nbytes
                    e = _Entry(budget, None, False)
                    self._entries[key] = e
                    self._bytes += e.nbytes
                self._entries.move_to_end(key)
                self._size_changed_locked()
                return
            e = _Entry(budget, result if definitive else None, definitive)
            self._entries[key] = e
            self._bytes += e.nbytes
            while len(self._entries) > self.capacity:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes
                self._evictions.inc()
            self._size_changed_locked()
