"""Entities: the unit of content a resolution selects.

Rebuild of /root/reference/pkg/entitysource/entity.go — an entity is an
opaque identifier plus a string-valued property bag (e.g. an operator
bundle with its package/version/GVK properties).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Mapping

EntityID = str


class EntityPropertyNotFoundError(KeyError):
    """Raised by :meth:`Entity.get_property` for missing keys
    (reference entity.go:7-11)."""

    def __init__(self, key: str):
        self.key = key
        super().__init__(key)

    def __str__(self) -> str:
        return f"Property '({self.key})' Not Found"


@dataclass(frozen=True)
class Entity:
    """An identified bag of string properties (reference entity.go:14-35).

    Hashable by ``id`` (ids are unique within a store), so entities can be
    deduplicated across Group sources; equality still compares properties.
    """

    id: EntityID
    properties: Mapping[str, str] = field(default_factory=dict, hash=False)

    def __hash__(self) -> int:
        return hash(self.id)

    def get_property(self, key: str) -> str:
        """Return the property value or raise
        :class:`EntityPropertyNotFoundError` (reference entity.go:29-35)."""
        try:
            return self.properties[key]
        except KeyError:
            raise EntityPropertyNotFoundError(key) from None
