"""Entity sources: query + content interfaces over entity stores.

Rebuild of /root/reference/pkg/entitysource/entity_source.go and
cache_querier.go.  Protocols replace Go interfaces; iteration helpers are
Python generators.  ``Group`` multiplexes several sources behind one
interface (entity_source.go:47-110) — with the reference's ``GetContent``
inverted-condition bug (entity_source.go:103-110, returns content only when
``err != nil``) deliberately fixed, per SURVEY.md §2.3's "do NOT replicate".
"""

from __future__ import annotations

from typing import (
    Any,
    Callable,
    Dict,
    Iterator,
    List,
    Mapping,
    Optional,
    Protocol,
    Sequence,
    runtime_checkable,
)

from .entity import Entity, EntityID
from .query import EntityList, EntityListMap, Predicate

GroupByFunction = Callable[[Entity], Sequence[str]]


@runtime_checkable
class EntityQuerier(Protocol):
    """Query interface over an entity store (entity_source.go:24-29)."""

    def get(self, id: EntityID) -> Optional[Entity]: ...

    def filter(self, predicate: Predicate) -> EntityList: ...

    def group_by(self, fn: GroupByFunction) -> EntityListMap: ...

    def iterate(self) -> Iterator[Entity]: ...


@runtime_checkable
class EntityContentGetter(Protocol):
    """Fetches the installable payload linked to an entity
    (entity_source.go:33-35)."""

    def get_content(self, id: EntityID) -> Any: ...


@runtime_checkable
class EntitySource(EntityQuerier, EntityContentGetter, Protocol):
    """A queryable store that can also deliver content
    (entity_source.go:38-41)."""


class CacheQuerier:
    """In-memory entity store with linear-scan queries
    (reference cache_querier.go:7-53).  Insertion order is preserved and
    observable through filter/iterate, unlike the reference's map ordering."""

    def __init__(self, entities: Mapping[EntityID, Entity]):
        self._entities: Dict[EntityID, Entity] = dict(entities)

    @classmethod
    def from_entities(cls, entities: Sequence[Entity]) -> "CacheQuerier":
        return cls({e.id: e for e in entities})

    def get(self, id: EntityID) -> Optional[Entity]:
        return self._entities.get(id)

    def filter(self, predicate: Predicate) -> EntityList:
        return [e for e in self._entities.values() if predicate(e)]

    def group_by(self, fn: GroupByFunction) -> EntityListMap:
        out: EntityListMap = {}
        for e in self._entities.values():
            for key in fn(e):
                out.setdefault(key, []).append(e)
        return out

    def iterate(self) -> Iterator[Entity]:
        return iter(self._entities.values())


class NoContentSource:
    """Content getter stub returning nothing (reference no_content.go:5-11)."""

    def get_content(self, id: EntityID) -> Any:
        return None


class Group:
    """Multiplexes several entity sources behind the single-source interface
    (reference entity_source.go:47-110): first-hit ``get``, concatenating
    ``filter``, merging ``group_by``, sequential ``iterate``, first-hit
    ``get_content``."""

    def __init__(self, *sources: Any):
        self._sources: List[Any] = list(sources)

    def get(self, id: EntityID) -> Optional[Entity]:
        for s in self._sources:
            e = s.get(id)
            if e is not None:
                return e
        return None

    def filter(self, predicate: Predicate) -> EntityList:
        out: EntityList = []
        for s in self._sources:
            out.extend(s.filter(predicate))
        return out

    def group_by(self, fn: GroupByFunction) -> EntityListMap:
        out: EntityListMap = {}
        for s in self._sources:
            for key, entities in s.group_by(fn).items():
                out.setdefault(key, []).extend(entities)
        return out

    def iterate(self) -> Iterator[Entity]:
        for s in self._sources:
            yield from s.iterate()

    def get_content(self, id: EntityID) -> Any:
        for s in self._sources:
            getter = getattr(s, "get_content", None)
            if getter is None:
                continue
            content = getter(id)
            if content is not None:
                return content
        return None
