"""Entity/data layer.

Rebuild of the reference's ``pkg/entitysource``
(/root/reference/pkg/entitysource/): entities with opaque string
properties, query interfaces over entity stores, an in-memory cache
querier, a multiplexing source group, and predicate combinators.
"""

from .entity import Entity, EntityID, EntityPropertyNotFoundError
from .source import (
    CacheQuerier,
    EntityContentGetter,
    EntityQuerier,
    EntitySource,
    Group,
    NoContentSource,
)
from .query import EntityList, EntityListMap, and_, collect_ids, not_, or_

__all__ = [
    "CacheQuerier",
    "Entity",
    "EntityContentGetter",
    "EntityID",
    "EntityList",
    "EntityListMap",
    "EntityPropertyNotFoundError",
    "EntityQuerier",
    "EntitySource",
    "Group",
    "NoContentSource",
    "and_",
    "collect_ids",
    "not_",
    "or_",
]
