"""Entity list helpers and predicate combinators
(rebuild of /root/reference/pkg/entitysource/query.go).

Predicates are plain callables ``Entity -> bool``; ``and_``/``or_`` short-
circuit like the reference combinators (query.go:28-58).  Sorting uses
Python's stable sort directly.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List

from .entity import Entity, EntityID

Predicate = Callable[[Entity], bool]
EntityList = List[Entity]
EntityListMap = Dict[str, EntityList]


def and_(*predicates: Predicate) -> Predicate:
    def combined(entity: Entity) -> bool:
        return all(p(entity) for p in predicates)

    return combined


def or_(*predicates: Predicate) -> Predicate:
    def combined(entity: Entity) -> bool:
        return any(p(entity) for p in predicates)

    return combined


def not_(predicate: Predicate) -> Predicate:
    def negated(entity: Entity) -> bool:
        return not predicate(entity)

    return negated


def collect_ids(entities: Iterable[Entity]) -> List[EntityID]:
    """IDs of ``entities`` in order (reference query.go:19-26)."""
    return [e.id for e in entities]
