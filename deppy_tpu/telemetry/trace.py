"""Per-request distributed tracing + flight recorder (ISSUE 4 tentpole).

Since the scheduler (ISSUE 3), one ``/v1/resolve`` request's lifecycle
crosses the handler thread, the shared dispatch-loop thread, the fault
ladder, and decode — and the flat PR 1 spans could not answer "why was
*this* request slow".  This module adds the request dimension:

  * **Trace context.**  The service mints one :class:`TraceContext` per
    request (honoring an inbound W3C ``traceparent`` or
    ``X-Deppy-Request-Id`` header) and activates it on the handler
    thread.  While a context is active, every :class:`registry.Span`
    opened on that thread is stamped with ``trace_id`` / ``span_id`` /
    ``parent_id`` (spans nest via a thread-local span stack), and every
    ``Registry.event`` (fault, breaker, deadline) is stamped and
    attached to the request's trace — the JSONL sink schema stays
    append-only, untraced callers emit byte-identical events.
  * **Cross-thread propagation.**  The scheduler captures each submit's
    context (:func:`capture_parent`) and re-installs it around the
    coalesced dispatch (:func:`dispatch_scope`): a dispatch serving N
    requests runs under its own trace whose root span records **span
    links** to every parent request, and every span/event it produces is
    mirrored into each parent's trace — so one request's flight record
    is self-contained even when its solve was shared.
  * **Flight recorder.**  A bounded in-memory ring of the last-N
    completed request traces plus a separate (larger) ring that retains
    *every* errored trace — request failures, deadline expiries, fault
    events, breaker trips.  Served at ``GET /debug/traces`` (+ ``?id=``
    lookup), dumped to the JSONL sink as ``trace`` events on SIGUSR2 and
    on breaker-open, and reconstructable offline with ``deppy trace ID``.

With no active context every hook is a single thread-local ``getattr``
— the ≤5 % bench bound of PR 1 still holds.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

# ID formats follow the W3C trace-context wire format: 16-byte trace ids
# and 8-byte span ids, lowercase hex.
_HEX = frozenset("0123456789abcdef")


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """Parse a W3C ``traceparent`` header (``00-<trace>-<span>-<flags>``)
    into ``(trace_id, parent_span_id)``; None on anything malformed —
    a bad header must degrade to a minted id, never to a 500."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    if not (set(version) <= _HEX and set(trace_id) <= _HEX
            and set(span_id) <= _HEX):
        return None
    # All-zero ids and the reserved version 0xff are invalid per spec.
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


class TraceContext:
    """One trace: a request's (or a coalesced dispatch's) span tree.

    Completed span events and stamped fault/breaker events accumulate on
    the context (thread-safe — the dispatch loop appends while the
    handler thread may be finishing); ``parents`` makes a dispatch
    context mirror everything it records into each request it serves."""

    __slots__ = ("trace_id", "request_id", "parent_span_id",
                 "root_span_id", "spans", "events", "links", "error",
                 "ts", "parents", "_lock")

    def __init__(self, trace_id: Optional[str] = None,
                 request_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 parents: Sequence["ParentRef"] = ()):
        self.trace_id = trace_id or new_trace_id()
        self.request_id = request_id or self.trace_id
        self.parent_span_id = parent_span_id
        self.root_span_id: Optional[str] = None
        self.spans: List[dict] = []
        self.events: List[dict] = []
        self.links: List[dict] = []
        self.error = False
        self.ts = round(time.time(), 3)
        self.parents: Tuple["ParentRef", ...] = tuple(parents)
        from ..analysis import lockdep

        self._lock = lockdep.make_lock("telemetry.trace_context")

    def note(self, event: dict, kind: str,
             errored: Optional[bool] = None) -> None:
        """Attach one completed span event (or stamped fault/breaker
        event) to this trace, and mirror it into every parent trace.

        Error marking is deliberately narrow: fault events, a breaker
        tripping OPEN, and spans that raised.  Benign breaker recovery
        transitions (``closed`` / ``half_open``) ride the tree without
        flagging healthy requests into the error ring.  A
        ``deadline_exceeded`` fault is lane-scoped: raised under a
        coalesced dispatch (this context has parents) it must NOT flag
        the dispatch's healthy batchmates — the scheduler marks the one
        request whose lane actually expired (:func:`mark_error`);
        raised directly under a request's own trace it flags it."""
        if errored is None:
            if kind == "fault" and event.get("fault") == "deadline_exceeded":
                errored = not self.parents
            else:
                # lockdep violations (ISSUE 7) are incidents like
                # faults: the trace lands in the error ring.
                errored = (kind in ("fault", "lockdep")
                           or (kind == "breaker"
                               and event.get("state") == "open")
                           or "error" in event.get("attrs", {}))
        with self._lock:
            (self.spans if kind == "span" else self.events).append(event)
            if errored:
                self.error = True
        for parent, _span_id in self.parents:
            parent.note(event, kind, errored=errored)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "trace_id": self.trace_id,
                "request_id": self.request_id,
                "ts": self.ts,
                "error": self.error,
                "root_span_id": self.root_span_id,
                "links": list(self.links),
                "spans": list(self.spans),
                "events": list(self.events),
            }


# (context, span_id-to-link-under) — what capture_parent hands across
# the submit → dispatch-loop thread hop.
ParentRef = Tuple[TraceContext, Optional[str]]

_TLS = threading.local()


def current_context() -> Optional[TraceContext]:
    """The trace context active on this thread, if any."""
    return getattr(_TLS, "ctx", None)


@contextmanager
def activate(ctx: TraceContext) -> Iterator[TraceContext]:
    """Make ``ctx`` the active trace on this thread; spans opened inside
    nest under it (the stack starts at the inbound parent span, so a
    proxy-propagated ``traceparent`` parents our root correctly)."""
    prev_ctx = getattr(_TLS, "ctx", None)
    prev_stack = getattr(_TLS, "stack", None)
    _TLS.ctx = ctx
    _TLS.stack = [ctx.parent_span_id] if ctx.parent_span_id else []
    try:
        yield ctx
    finally:
        _TLS.ctx = prev_ctx
        _TLS.stack = prev_stack


def mark_error() -> None:
    """Flag the active trace errored — precise attribution for
    conditions only the caller can see (the scheduler marks the one
    request whose lane was deadline-degraded, not its batchmates)."""
    ctx = getattr(_TLS, "ctx", None)
    if ctx is not None:
        ctx.error = True


def capture_parent() -> Optional[ParentRef]:
    """Snapshot (active context, current span id) for a thread hop —
    the scheduler stores this on each queued group so the dispatch loop
    can link back to the submitting request."""
    ctx = getattr(_TLS, "ctx", None)
    if ctx is None:
        return None
    stack = getattr(_TLS, "stack", None)
    span_id = stack[-1] if stack else ctx.root_span_id
    return (ctx, span_id)


@contextmanager
def dispatch_scope(
    parents: Sequence[Optional[ParentRef]],
) -> Iterator[Optional[TraceContext]]:
    """Trace scope for one coalesced dispatch.  With no traced parents
    this is a no-op (library callers pay nothing).  An inline dispatch
    on the submitting request's own thread keeps that request's context
    (spans nest naturally, no link indirection).  Otherwise — the
    dispatch-loop thread — a fresh dispatch trace is created whose
    spans/events mirror into every parent request's trace; the caller
    records span links on its root span (see ``TraceContext.links``)."""
    refs = [p for p in parents if p is not None]
    if not refs:
        yield None
        return
    cur = current_context()
    if cur is not None and len(refs) == 1 and refs[0][0] is cur:
        yield None  # inline on the request's own thread
        return
    ctx = TraceContext(parents=refs)
    ctx.links = [{"trace_id": p.trace_id, "span_id": sid}
                 for p, sid in refs]
    with activate(ctx):
        yield ctx


# ------------------------------------------------------------ span hooks
#
# Called by registry.Span.__enter__/__exit__ and Registry._record_span /
# Registry.event.  All no-ops (one getattr) without an active context.


def enter_span(span) -> None:
    ctx = getattr(_TLS, "ctx", None)
    if ctx is None:
        return
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    span.trace_id = ctx.trace_id
    span.span_id = new_span_id()
    span.parent_id = stack[-1] if stack else None
    if ctx.root_span_id is None:
        ctx.root_span_id = span.span_id
    stack.append(span.span_id)


def exit_span(span) -> None:
    if getattr(span, "span_id", None) is None:
        return
    stack = getattr(_TLS, "stack", None)
    if stack and stack[-1] == span.span_id:
        stack.pop()


def note_span_event(span, event: dict) -> None:
    """Stamp a completed span's ids onto its JSONL event and attach it
    to the active trace.  Untraced spans leave the event untouched
    (schema append-only: the new keys are simply absent)."""
    if span.trace_id is None:
        return
    event["trace_id"] = span.trace_id
    event["span_id"] = span.span_id
    if span.parent_id:
        event["parent_id"] = span.parent_id
    if span.links:
        event["links"] = list(span.links)
    ctx = getattr(_TLS, "ctx", None)
    if ctx is not None and ctx.trace_id == span.trace_id:
        ctx.note(event, "span")


# Per-process sequence for stamped events: two genuinely distinct fault
# events can be field-identical (two lanes expiring in the same ms), so
# consumers deduplicating live sink lines against flight-recorder dumps
# need an identity that distinguishes them.  itertools.count's __next__
# is atomic under CPython.
_EVENT_SEQ = itertools.count(1)


def stamp_event(event: dict, kind: str) -> None:
    """Stamp an ad-hoc registry event (fault / breaker / deadline) with
    the active trace's ids, a per-process ``seq``, and attach it to the
    trace — this is how the fault layer's retries, group splits, host
    routing, and breaker transitions land on the request's span tree."""
    ctx = getattr(_TLS, "ctx", None)
    if ctx is None:
        return
    stack = getattr(_TLS, "stack", None)
    event["trace_id"] = ctx.trace_id
    event["seq"] = next(_EVENT_SEQ)
    if stack and stack[-1]:
        event["parent_id"] = stack[-1]
    ctx.note(event, kind)


# --------------------------------------------------------- request entry


def context_from_headers(traceparent: Optional[str] = None,
                         request_id: Optional[str] = None) -> TraceContext:
    """Build a request's context from its inbound headers: a valid W3C
    ``traceparent`` wins (its trace id is adopted and our root span
    parents under the caller's span); else ``X-Deppy-Request-Id`` (used
    verbatim as the request id, and as the trace id when it already is
    one); else both ids are minted."""
    rid = request_id.strip() if request_id else None
    parsed = parse_traceparent(traceparent)
    if parsed is not None:
        trace_id, parent_span_id = parsed
        return TraceContext(trace_id=trace_id, request_id=rid or trace_id,
                            parent_span_id=parent_span_id)
    if rid:
        low = rid.lower()
        trace_id = low if len(low) == 32 and set(low) <= _HEX else None
        return TraceContext(trace_id=trace_id, request_id=rid)
    return TraceContext()


def traceparent_of(ctx: TraceContext) -> str:
    """The ``traceparent`` a response echoes: our trace, our root span."""
    return f"00-{ctx.trace_id}-{ctx.root_span_id or new_span_id()}-01"


# -------------------------------------------------------- flight recorder

DEFAULT_RING = 64
DEFAULT_ERROR_RING = 256


def _env_cap(name: str, default: int) -> int:
    from .. import config

    try:
        return max(int(config.env_raw(name, "") or default), 1)
    except ValueError:
        return default


class FlightRecorder:
    """Bounded in-memory ring of completed request traces.

    Two rings: ``capacity`` recent traces of any outcome, and a separate
    ``error_capacity`` ring holding only errored traces (HTTP >= 400
    other than deliberate 503 load sheds, fault/breaker events, deadline
    expiries) so a burst of healthy traffic — or of sheds — can never
    evict the one trace that explains an incident.
    """

    def __init__(self, capacity: Optional[int] = None,
                 error_capacity: Optional[int] = None):
        self.capacity = capacity if capacity is not None \
            else _env_cap("DEPPY_TPU_TRACE_RING", DEFAULT_RING)
        self.error_capacity = error_capacity if error_capacity is not None \
            else _env_cap("DEPPY_TPU_TRACE_ERROR_RING", DEFAULT_ERROR_RING)
        from ..analysis import lockdep

        self._lock = lockdep.make_lock("telemetry.flight_recorder")
        # Rings keyed by a per-record sequence number, NOT the trace id:
        # several requests legitimately share one inbound W3C trace id
        # (a proxy fanning out under one distributed trace), and keying
        # by it would let a later request — or a successful retry —
        # silently overwrite an earlier (possibly errored) record.
        self._seq = 0
        self._ring: "Dict[int, dict]" = {}     # insertion-ordered
        self._errors: "Dict[int, dict]" = {}

    def record(self, ctx: TraceContext, status: Optional[int] = None,
               timings: Optional[dict] = None) -> dict:
        """File one completed request's trace; returns the stored dict."""
        trace = ctx.to_dict()
        trace["status"] = status
        if timings:
            trace["timings"] = {k: round(float(v), 6)
                                for k, v in timings.items()}
        # 503 is deliberate load shedding (queue depth / open breaker /
        # unmeetable deadline), not a request failure: a shed burst must
        # not flood the error ring (evicting real incident traces) or
        # pay a sink write per rejection on the shedding path.  Sheds
        # whose trace carries a fault event (e.g. the unmeetable-
        # deadline counter) still arrive with ctx.error already set.
        errored = bool(trace["error"]
                       or (status is not None and status >= 400
                           and status != 503))
        trace["error"] = errored
        with self._lock:
            self._seq += 1
            key = self._seq
            self._ring[key] = trace
            while len(self._ring) > self.capacity:
                del self._ring[next(iter(self._ring))]
            if errored:
                self._errors[key] = trace
                while len(self._errors) > self.error_capacity:
                    del self._errors[next(iter(self._errors))]
        if errored:
            # Errored traces go to the JSONL sink the moment they
            # complete (no-op without a sink): the requests that rode a
            # breaker-tripping dispatch finish recording only AFTER the
            # trip, so a dump-at-open alone could never contain them —
            # this is what actually puts incident traces on disk before
            # any operator restart.
            self._emit(trace, reason="error")
        return trace

    def _emit(self, trace: dict, reason: str) -> None:
        from .registry import default_registry

        reg = default_registry()
        # Forwarders count as an output: a fleet replica streaming to
        # the aggregator (ISSUE 16) dumps into the merged sink even
        # with no local sink file configured.
        if reg.sink_path is None and not reg.forwarding:
            return
        reg.emit({"ts": round(time.time(), 3), "kind": "trace",
                  "reason": reason, "trace": trace})

    def get(self, trace_or_request_id: str) -> Optional[dict]:
        """Lookup by trace id or request id (the ``?id=`` parameter);
        with several records under one shared trace id, the most recent
        wins (the index at ``/debug/traces`` lists each separately)."""
        wanted = trace_or_request_id
        best_key = -1
        best = None
        with self._lock:
            for ring in (self._ring, self._errors):
                for key, trace in ring.items():
                    if key > best_key and (
                            trace["trace_id"] == wanted
                            or trace.get("request_id") == wanted):
                        best_key, best = key, trace
        return best

    def traces(self) -> List[dict]:
        """Every retained trace, most recent first (error-ring entries
        evicted from the main ring included, deduplicated)."""
        with self._lock:
            merged = dict(self._errors)
            merged.update(self._ring)
            return [merged[k] for k in sorted(merged, reverse=True)]

    def summaries(self) -> List[dict]:
        """Index view for the ``/debug/traces`` listing."""
        return [{
            "trace_id": t["trace_id"],
            "request_id": t["request_id"],
            "ts": t["ts"],
            "status": t.get("status"),
            "error": t["error"],
            "spans": len(t["spans"]),
        } for t in self.traces()]

    def dump(self, reason: str = "") -> int:
        """Write every retained trace to the default registry's JSONL
        sink as ``trace`` events (no-op without a sink); returns the
        number written.  Triggered by SIGUSR2 and by breaker-open —
        the breaker-open dump preserves the *healthy* context leading
        up to a trip; the incident requests themselves (still in
        flight at trip time) land via the errored-trace write in
        :meth:`record`."""
        from .registry import default_registry

        reg = default_registry()
        if reg.sink_path is None and not reg.forwarding:
            return 0
        traces = self.traces()
        for trace in traces:
            self._emit(trace, reason=reason)
        return len(traces)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._errors.clear()


_DEFAULT: Optional[FlightRecorder] = None
_DEFAULT_LOCK = threading.Lock()


def default_recorder() -> FlightRecorder:
    """The process-wide flight recorder (one service, one black box)."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = FlightRecorder()
    return _DEFAULT


def set_default_recorder(
        recorder: Optional[FlightRecorder]) -> Optional[FlightRecorder]:
    """Swap the process recorder (tests); returns the previous one."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        prev, _DEFAULT = _DEFAULT, recorder
    return prev


def notify_breaker_open() -> None:
    """Breaker-open hook: dump the flight recorder to the JSONL sink so
    the traces that *led up to* the trip are on disk before the host-only
    window (and any operator restart) discards them.  Never raises — the
    breaker's own transition must not die to observability."""
    try:
        default_recorder().dump(reason="breaker_open")
    # deppy: lint-ok[exception-hygiene] the breaker transition must never die to observability
    except Exception:
        pass
