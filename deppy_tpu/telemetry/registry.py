"""Lightweight span/counter/histogram registry — no third-party deps.

The observability backbone of the solve pipeline (SURVEY.md §5): the
engine driver, the SAT facades, the service, and the benchmarks all
record into a :class:`Registry`, which renders the Prometheus text
exposition format (the same surface the reference's controller-runtime
metrics registry serves, /root/reference/main.go:63-64) and can mirror
every span to a JSONL event sink for offline analysis.

Design constraints, in order:

  * **Cheap when idle.**  Counters are one lock + one add; spans are two
    ``perf_counter`` calls and a dict.  With no sink configured nothing
    is formatted or written — the pipeline's telemetry overhead must
    stay within noise (ISSUE acceptance: ≤5% on the bench suite).
  * **Thread-safe.**  The service observes from request-handler threads
    while ``/metrics`` renders concurrently.
  * **Deterministic exposition.**  Families render in registration
    order, labeled samples in sorted label order, so scrapes diff
    cleanly and tests can pin exact lines.

The JSONL sink (``DEPPY_TPU_TELEMETRY_FILE`` or ``--telemetry-file``)
receives one object per event::

    {"ts": 1722700000.123, "kind": "span", "name": "driver.pad_pack",
     "dur_s": 0.0123, "attrs": {"problems": 64, "lanes": 64}}
    {"ts": ..., "kind": "report", "report": {...SolveReport...}}

See docs/observability.md for the full event schema and metric table.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# Default histogram buckets for wall-clock seconds: sub-ms dispatch
# overheads through minutes-long giant-catalog solves.
SECONDS_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
# Ratio buckets (fill / waste ratios live in [0, 1]).
RATIO_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)
# Escalation stages: 0 = single-stage, 1 = stage-1 sufficed, 2 = stage-2.
STAGE_BUCKETS = (0.0, 1.0, 2.0, 3.0)
# Lane-count buckets (coalesced batch sizes, queue drains): powers of two
# up to the widest probed dispatch width (scripts/lane_probe.py).
LANE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                512.0, 1024.0, 2048.0, 4096.0)


def iter_sink_events(path: str):
    """Yield one item per non-empty line of a JSONL sink file: the
    parsed event dict, or None for a malformed line (callers count
    those).  The read-side twin of :meth:`Registry.emit`, shared by
    every sink consumer (`deppy stats`/`trace`/`compiles`/`profile`
    and :mod:`deppy_tpu.profile.report`)."""
    # errors="replace": a torn write can leave invalid UTF-8 on the
    # final line of a live sink file — it must count as one malformed
    # line, not raise UnicodeDecodeError mid-summary.
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                yield None
                continue
            yield ev if isinstance(ev, dict) else None


def iter_merged_sink_events(paths):
    """Yield events from several sink files as ONE deduplicated stream
    (ISSUE 16: `deppy stats/trace/profile --file a.jsonl --file
    b.jsonl` merges replica sinks and the fleet aggregator's merged
    sink without hand-concatenation).  Dedupe keys, in order:

      * stamped events — ``(replica, trace_id, seq)``: ``seq`` is the
        per-process event sequence (telemetry.trace), unique within a
        replica; the ``replica`` stamp (added by the fleet aggregator)
        disambiguates seq collisions across replicas;
      * span events — ``(replica, trace_id, span_id)``;
      * everything else — the event's canonical JSON.

    Malformed lines yield None, like :func:`iter_sink_events`."""
    seen = set()
    for path in paths:
        for ev in iter_sink_events(path):
            if ev is None:
                yield None
                continue
            replica, tid = ev.get("replica"), ev.get("trace_id")
            if ev.get("seq") is not None:
                key = (replica, tid, "e", ev["seq"])
            elif ev.get("kind") == "span" and ev.get("span_id"):
                key = (replica, tid, "s", ev["span_id"])
            else:
                key = json.dumps(ev, sort_keys=True, default=str)
            if key in seen:
                continue
            seen.add(key)
            yield ev


def percentile(sorted_vals, q):
    """Nearest-rank percentile over pre-sorted values (0 on empty) —
    THE percentile statistic, shared by `deppy stats`, the trip
    ledger's lane-work distribution, and the SLO window's p99 so the
    three can never silently diverge."""
    import math

    n = len(sorted_vals)
    if n == 0:
        return 0
    idx = min(max(int(math.ceil(q / 100.0 * n)) - 1, 0), n - 1)
    return sorted_vals[idx]


def _fmt(v) -> str:
    """Sample-value formatting: ints stay ints, floats render via str()
    (matching the service's historical f-string rendering, so pinned
    scrape lines like ``deppy_solve_seconds_total 0.5`` are preserved)."""
    return str(v)


def _fmt_le(bound: float) -> str:
    """Bucket bound label: Prometheus convention ('%g': 0.005, 1, +Inf)."""
    if bound == float("inf"):
        return "+Inf"
    return "%g" % bound


class Counter:
    """Monotonic counter, optionally labeled by one label name.

    Unlabeled: ``inc(n)``.  Labeled: ``inc(n, label_value)``.  Values
    keep their Python numeric type (int stays int) so exposition matches
    the historical hand-rendered lines byte for byte.
    """

    kind = "counter"

    def __init__(self, name: str, help: str, lock,
                 labelname: Optional[str] = None, initial=0):
        self.name = name
        self.help = help
        self._lock = lock
        self.labelname = labelname
        self._value = initial
        self._labeled: Dict[str, int] = {}

    def inc(self, n=1, label: Optional[str] = None) -> None:
        with self._lock:
            if label is None:
                self._value = self._value + n
            else:
                self._labeled[label] = self._labeled.get(label, 0) + n

    def preset(self, *labels: str) -> "Counter":
        """Pre-register label values at 0 so they render before first
        increment (the service's outcome counters always expose all
        three outcomes)."""
        with self._lock:
            for lab in labels:
                self._labeled.setdefault(lab, 0)
        return self

    @property
    def value(self):
        with self._lock:
            if self.labelname is None:
                return self._value
            return dict(self._labeled)

    def _render(self) -> List[str]:
        # The shared registry RLock: re-entrant under render_lines'
        # snapshot, real protection for a standalone render (ISSUE 7
        # concurrency-discipline: a concurrent first-time label was a
        # dict-changed-during-iteration away).
        with self._lock:
            lines = [f"# HELP {self.name} {self.help}",
                     f"# TYPE {self.name} counter"]
            if self.labelname is None:
                lines.append(f"{self.name} {_fmt(self._value)}")
            else:
                for lab, n in sorted(self._labeled.items()):
                    lines.append(
                        f'{self.name}{{{self.labelname}="{lab}"}} {_fmt(n)}'
                    )
            return lines


class Gauge:
    """Last-write-wins gauge.  Renders only once set (the service's
    verdict gauges are absent until a verdict exists)."""

    kind = "gauge"

    def __init__(self, name: str, help: str, lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._value = None

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self):
        with self._lock:
            return self._value

    def _render(self) -> List[str]:
        with self._lock:
            if self._value is None:
                return []
            return [f"# HELP {self.name} {self.help}",
                    f"# TYPE {self.name} gauge",
                    f"{self.name} {_fmt(self._value)}"]


class Histogram:
    """Fixed-bucket histogram with cumulative (monotonic) bucket counts,
    rendered as the standard ``_bucket``/``_sum``/``_count`` series."""

    kind = "histogram"

    def __init__(self, name: str, help: str, lock,
                 buckets: Sequence[float] = SECONDS_BUCKETS):
        self.name = name
        self.help = help
        self._lock = lock
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> List[Tuple[str, int]]:
        """(le_label, cumulative_count) per bucket, +Inf last."""
        out = []
        with self._lock:
            running = 0
            for b, c in zip(self.buckets, self._counts):
                running += c
                out.append((_fmt_le(b), running))
            out.append((_fmt_le(float("inf")), running + self._counts[-1]))
        return out

    def _render(self) -> List[str]:
        with self._lock:  # re-entrant: cumulative() re-takes it
            lines = [f"# HELP {self.name} {self.help}",
                     f"# TYPE {self.name} histogram"]
            for le, n in self.cumulative():
                lines.append(f'{self.name}_bucket{{le="{le}"}} {n}')
            lines.append(f"{self.name}_sum {_fmt(self._sum)}")
            lines.append(f"{self.name}_count {self._count}")
            return lines


class Span:
    """One timed pipeline stage, used as a context manager.

    Attributes set during the span (``sp[\"stage\"] = 2`` or
    ``sp.set(lanes=64)``) ride along into the JSONL event.  Duration is
    available as ``sp.dur_s`` after exit.

    When a trace context is active on the thread (ISSUE 4,
    :mod:`deppy_tpu.telemetry.trace`), the span is stamped with
    ``trace_id``/``span_id``/``parent_id`` on entry (nesting via the
    thread's span stack) and its completed event joins the request's
    trace; without one, behavior — and the emitted event — is
    byte-identical to the pre-trace schema.
    """

    __slots__ = ("name", "attrs", "_registry", "_t0", "dur_s",
                 "trace_id", "span_id", "parent_id", "links")

    def __init__(self, registry: "Registry", name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self._registry = registry
        self._t0 = 0.0
        self.dur_s = 0.0
        self.trace_id: Optional[str] = None
        self.span_id: Optional[str] = None
        self.parent_id: Optional[str] = None
        self.links: Optional[List[dict]] = None

    def __setitem__(self, key: str, value) -> None:
        self.attrs[key] = value

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def link(self, trace_id: str, span_id: Optional[str] = None) -> None:
        """Record a span link (a causal reference to a span in another
        trace — W3C/OTel links): how a coalesced dispatch points back at
        every request it serves."""
        if self.links is None:
            self.links = []
        link = {"trace_id": trace_id}
        if span_id:
            link["span_id"] = span_id
        self.links.append(link)

    def __enter__(self) -> "Span":
        from . import trace as _trace

        self._t0 = time.perf_counter()
        _trace.enter_span(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        from . import trace as _trace

        self.dur_s = time.perf_counter() - self._t0
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        _trace.exit_span(self)
        self._registry._record_span(self)


class Registry:
    """Metric families + span stream, with optional JSONL sink.

    One lock guards every family (contention is negligible at the
    pipeline's per-batch observation rate, and a single lock keeps
    render atomic).
    """

    def __init__(self, sink_path: Optional[str] = None):
        from ..analysis import lockdep

        # RLock: render_lines holds it across every family's _render so a
        # scrape is one consistent snapshot (no torn histograms, no
        # dict-changed-during-iteration from a concurrent first-time
        # label), while the family accessors re-enter it freely.
        # Named factory (ISSUE 7): DEPPY_TPU_LOCKDEP=1 swaps in the
        # order-asserting proxy; disarmed, this IS threading.RLock().
        self._lock = lockdep.make_rlock("telemetry.registry")
        self._families: Dict[str, object] = {}
        self._order: List[str] = []
        self._sink_lock = lockdep.make_lock("telemetry.registry.sink")
        self._sink_path = sink_path
        self._sink_file = None
        # Event forwarders (ISSUE 16): callables handed every emitted
        # event alongside (or instead of) the sink file — the fleet
        # telemetry streamer registers here.  Stored as an immutable
        # tuple swapped atomically under _sink_lock so emit() can read
        # it without taking the lock (empty tuple = pre-obs fast path).
        self._forwarders: Tuple = ()
        # Bounded in-memory span tail for `deppy stats` on a live
        # process and for tests; not a durable record (the sink is).
        self._recent_spans: List[dict] = []
        self._recent_cap = 256

    # ------------------------------------------------------------ families

    def _family(self, cls, name: str, help: str, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, self._lock, **kw)
                self._families[name] = fam
                self._order.append(name)
            elif not isinstance(fam, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            return fam

    def counter(self, name: str, help: str = "",
                labelname: Optional[str] = None, initial=0) -> Counter:
        return self._family(Counter, name, help, labelname=labelname,
                            initial=initial)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._family(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = SECONDS_BUCKETS) -> Histogram:
        return self._family(Histogram, name, help, buckets=buckets)

    # -------------------------------------------------------------- spans

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def _record_span(self, span: Span) -> None:
        from . import trace as _trace

        event = {"ts": round(time.time(), 3), "kind": "span",
                 "name": span.name, "dur_s": round(span.dur_s, 6),
                 "attrs": span.attrs}
        _trace.note_span_event(span, event)
        with self._sink_lock:
            self._recent_spans.append(event)
            if len(self._recent_spans) > self._recent_cap:
                del self._recent_spans[: -self._recent_cap]
        self.emit(event)

    def record_span(self, name: str, dur_s: float, **attrs) -> None:
        """Record a span whose duration was measured elsewhere (the
        scheduler's queue-wait: the wait happens on the dispatch loop's
        clock, the span belongs to the submitting request's trace).
        Same stamping/sink path as a context-managed span."""
        sp = Span(self, name, attrs)
        sp.dur_s = dur_s
        from . import trace as _trace

        _trace.enter_span(sp)
        _trace.exit_span(sp)
        self._record_span(sp)

    def recent_spans(self) -> List[dict]:
        with self._sink_lock:
            return list(self._recent_spans)

    def event(self, kind: str, **fields) -> None:
        """Emit one ad-hoc event to the JSONL sink, and — when a trace
        context is active on this thread (ISSUE 4) — stamp it with the
        trace's ids and attach it to the request's trace, sink or not.
        The fault-domain layer (ISSUE 2) uses this for ``fault`` and
        ``breaker`` events; ``kind`` becomes the event's ``kind`` field
        alongside the usual ``ts``.  With neither a sink nor an active
        trace this stays a two-branch no-op."""
        from . import trace as _trace

        traced = _trace.current_context() is not None
        # deppy: lint-ok[concurrency-discipline] deliberate unlocked fast-path read; emit() re-checks under the lock
        if self._sink_path is None and not traced and not self._forwarders:
            return
        event = {"ts": round(time.time(), 3), "kind": kind, **fields}
        if traced:
            _trace.stamp_event(event, kind)
        self.emit(event)

    # --------------------------------------------------------------- sink

    def configure_sink(self, path: Optional[str]) -> None:
        """Point the JSONL sink at ``path`` (None disables).  The file is
        opened lazily on first event and appended to, one JSON object
        per line."""
        with self._sink_lock:
            if self._sink_file is not None:
                try:
                    self._sink_file.close()
                except OSError:
                    pass
                self._sink_file = None
            self._sink_path = path

    @property
    def sink_path(self) -> Optional[str]:
        with self._sink_lock:
            return self._sink_path

    @property
    def forwarding(self) -> bool:
        """True when at least one event forwarder is registered —
        emitted events have somewhere to go even without a sink file
        (the flight recorder's dump gate checks both)."""
        # deppy: lint-ok[concurrency-discipline] atomic tuple swap; a one-swap-stale verdict only gates a dump
        return bool(self._forwarders)

    def add_forwarder(self, fn) -> None:
        """Register a callable handed every emitted event (ISSUE 16:
        the fleet telemetry streamer).  Forwarders run before the sink
        write and must never block or raise into the pipeline — emit()
        swallows their exceptions."""
        with self._sink_lock:
            if fn not in self._forwarders:
                self._forwarders = self._forwarders + (fn,)

    def remove_forwarder(self, fn) -> None:
        with self._sink_lock:
            self._forwarders = tuple(
                f for f in self._forwarders if f is not fn)

    def emit(self, event: dict) -> None:
        """Append one event object to the sink, if configured, and hand
        it to every registered forwarder.  Sink I/O failures disable
        the sink rather than failing the solve — the pipeline must
        never die to observability."""
        # Forwarders first: streaming works without a local sink.  The
        # tuple is swapped atomically, so the unlocked read sees a
        # consistent (possibly one-swap-stale) set.
        # deppy: lint-ok[concurrency-discipline] atomic tuple swap; emit must not serialize on the sink lock
        for fn in self._forwarders:
            try:
                fn(event)
            # deppy: lint-ok[exception-hygiene] a broken forwarder must never fail the solve; the streamer counts its own errors
            except Exception:
                pass
        # deppy: lint-ok[concurrency-discipline] double-checked: the unlocked read only skips work, the locked one decides
        if self._sink_path is None:
            return
        with self._sink_lock:
            if self._sink_path is None:
                return
            try:
                if self._sink_file is None:
                    self._sink_file = open(self._sink_path, "a",
                                           encoding="utf-8")
                self._sink_file.write(json.dumps(event) + "\n")
                self._sink_file.flush()
            except OSError:
                self._sink_path = None
                self._sink_file = None

    # ------------------------------------------------------------- render

    def render_lines(self) -> List[str]:
        with self._lock:
            lines: List[str] = []
            for name in self._order:
                lines.extend(self._families[name]._render())
            return lines

    def render_families(self, names: Sequence[str]) -> List[str]:
        """Exposition lines for just the named families, in the given
        order (absent names skipped) — one consistent snapshot, like
        :meth:`render_lines`.  Lets another surface (the service's
        ``/metrics``) mirror a subset of this registry without reaching
        into family internals."""
        with self._lock:
            lines: List[str] = []
            for name in names:
                fam = self._families.get(name)
                if fam is not None:
                    lines.extend(fam._render())
            return lines

    def render(self) -> str:
        return "\n".join(self.render_lines()) + "\n"

    def snapshot(self) -> dict:
        """Plain-dict view of every family (for JSON output / tests)."""
        out: Dict[str, object] = {}
        with self._lock:
            families = [(n, self._families[n]) for n in self._order]
        for name, fam in families:
            if isinstance(fam, Histogram):
                out[name] = {"count": fam.count, "sum": fam.sum}
            else:
                out[name] = fam.value
        return out


_DEFAULT: Optional[Registry] = None
_DEFAULT_LOCK = threading.Lock()


def default_registry() -> Registry:
    """The process-wide registry the pipeline instruments against.  Its
    sink is configured from ``DEPPY_TPU_TELEMETRY_FILE`` at creation;
    ``configure_sink`` / ``--telemetry-file`` can override later."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                from .. import config

                _DEFAULT = Registry(
                    sink_path=config.env_raw("DEPPY_TPU_TELEMETRY_FILE")
                    or None
                )
    return _DEFAULT


def set_default_registry(registry: Optional[Registry]) -> Optional[Registry]:
    """Swap the process-default registry (tests); returns the previous."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        prev, _DEFAULT = _DEFAULT, registry
    return prev


def configure_sink(path: Optional[str]) -> None:
    """Point the default registry's JSONL sink at ``path``."""
    default_registry().configure_sink(path)
