"""deppy_tpu.telemetry — pipeline-wide observability (ISSUE 1 + 4).

A dependency-free span/counter/histogram registry plus the structured
per-batch :class:`SolveReport`, threaded through encode → pad/pack →
device transfer → solve → decode.  The service's ``/metrics`` endpoint,
the ``deppy stats`` CLI, the JSONL event sink, and the benchmark BENCH
rows all read from here.  ISSUE 4 adds the request dimension: per-request
trace contexts (W3C ``traceparent`` interop), span trees with links
across coalesced dispatches, and the :class:`trace.FlightRecorder`
behind ``GET /debug/traces`` and ``deppy trace``.  See
docs/observability.md for the metric/span name table and the JSONL
event schema.
"""

from . import trace  # noqa: F401 — re-exported subsystem (ISSUE 4)
from .registry import (
    LANE_BUCKETS,
    RATIO_BUCKETS,
    SECONDS_BUCKETS,
    STAGE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    Span,
    configure_sink,
    default_registry,
    iter_merged_sink_events,
    iter_sink_events,
    percentile,
    set_default_registry,
)
from .report import (
    SolveReport,
    begin_report,
    current_report,
    detach_report,
    end_report,
    last_report,
)

__all__ = [
    "trace",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "Span",
    "SolveReport",
    "LANE_BUCKETS",
    "RATIO_BUCKETS",
    "SECONDS_BUCKETS",
    "STAGE_BUCKETS",
    "begin_report",
    "configure_sink",
    "current_report",
    "default_registry",
    "detach_report",
    "end_report",
    "iter_merged_sink_events",
    "iter_sink_events",
    "last_report",
    "percentile",
    "set_default_registry",
]
