"""Structured per-batch solve reports.

A :class:`SolveReport` is the per-batch observability record the whole
pipeline contributes to: the engine driver fills in padding/packing
economics, device-transfer and solve wall-clock, escalation staging, and
host-fallback routing; the SAT facades add outcome/step/decision
counters; the service and the benchmarks read it back out (histograms on
``/metrics``, occupancy columns in BENCH rows).

The active report travels through the driver on a thread-local rather
than through function signatures: the driver's internal phase functions
(``_solve_split`` et al.) are monkeypatched by tests and their
signatures are pinned.  ``begin_report``/``end_report`` bracket one
batch; nested ``solve_problems`` calls (the checkpointed group loop)
merge into the enclosing report instead of starting their own.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class SolveReport:
    """One batch's pipeline telemetry (ISSUE 1 tentpole).

    ``escalation_stage``: 0 = single-stage dispatch (escalation disabled
    or not profitable), 1 = the stage-1 small budget resolved every
    lane, 2 = stage-2 (straggler redo or full-budget rerun) was needed.
    A multi-bucket batch reports the maximum stage any bucket reached.
    """

    backend: str = "tpu"
    n_problems: int = 0
    outcomes: Dict[str, int] = field(
        default_factory=lambda: {"sat": 0, "unsat": 0, "incomplete": 0}
    )
    # Engine iteration counters.  ``decisions`` / ``propagation_rounds``
    # are exact on the host engine (StatsTracer); the tensor engine
    # reports ``steps`` (tests + DPLL iterations) and ``backtracks``
    # (SolveResult.trace_n, counted even with tracing off).
    steps: int = 0
    backtracks: int = 0
    decisions: int = 0
    propagation_rounds: int = 0
    # Padding economics (SURVEY.md §7.3): lanes dispatched vs live
    # problems, and padded clause-matrix cells vs live cells.
    batch_lanes: int = 0
    live_lanes: int = 0
    pad_cells: int = 0
    live_cells: int = 0
    n_chunks: int = 0
    n_buckets: int = 0
    escalation_stage: int = 0
    # Rows whose unsat-core extraction routed to the host spec engine
    # (driver.HOST_CORE_NCONS) — the "silent host fallback" made loud.
    host_fallback_rows: int = 0
    # Problems the FAULT layer solved on the host engine (device dispatch
    # failed or the breaker was open; ISSUE 2) — distinct from the
    # core-extraction routing above, mirroring the
    # deppy_fault_host_routed_total counter.
    fault_host_routed: int = 0
    # Trip-ledger fields (ISSUE 11): filled only for dispatches the
    # profiler sampled (deppy_tpu.profile; DEPPY_TPU_PROFILE=on), zero
    # otherwise.  All four are sums, so they merge exactly like the
    # other sequential-stage counters (mesh shards, checkpoint groups,
    # mixed cold/warm submits): ledger_trips sums per-chunk lockstep
    # while-trips (max lane steps per chunk), ledger_trip_slots sums
    # trips x chunk lanes (the lockstep lane-step slots paid),
    # ledger_lane_steps sums live lanes' useful iterations, and
    # ledger_p99_trips sums per-chunk p99 lane work (the straggler
    # numerator).  The derived ratios below are what the bench
    # economics columns carry.
    profiled_dispatches: int = 0
    ledger_trips: int = 0
    ledger_trip_slots: int = 0
    ledger_lane_steps: int = 0
    ledger_p99_trips: int = 0
    # Wall-clock per pipeline stage, seconds: pad_pack, device_put,
    # solve (whole driver call), plus anything a caller adds.
    wall: Dict[str, float] = field(default_factory=dict)

    # ----------------------------------------------------------- recording

    def add_wall(self, stage: str, seconds: float) -> None:
        self.wall[stage] = self.wall.get(stage, 0.0) + seconds

    def record_batch(self, live_lanes: int, batch_lanes: int,
                     live_cells: int, pad_cells: int,
                     n_chunks: int = 1) -> None:
        """One dispatched bucket's padding economics (accumulates across
        buckets and checkpoint groups)."""
        self.live_lanes += live_lanes
        self.batch_lanes += batch_lanes
        self.live_cells += live_cells
        self.pad_cells += pad_cells
        self.n_chunks += n_chunks
        self.n_buckets += 1

    def note_escalation(self, stage: int) -> None:
        self.escalation_stage = max(self.escalation_stage, stage)

    def record_ledger(self, trips: int, trip_slots: int, lane_steps: int,
                      p99_trips: int) -> None:
        """One sampled dispatch's trip ledger (ISSUE 11; accumulates
        across buckets, chunks, shards, and checkpoint groups)."""
        self.profiled_dispatches += 1
        self.ledger_trips += trips
        self.ledger_trip_slots += trip_slots
        self.ledger_lane_steps += lane_steps
        self.ledger_p99_trips += p99_trips

    def merge(self, other: "SolveReport") -> None:
        """Fold a sub-report into this one — the mesh-serving path runs
        one pipeline per device on worker threads, each filling its own
        thread-local report (the driver internals find their report via
        ``current_report()``, so shards cannot share the parent's
        without racing its unlocked ``+=`` counters); the parent merges
        them after the join.  Counters add, the escalation stage keeps
        its max (same convention as multi-bucket batches), wall clock
        sums per stage (threads overlap, so merged wall is cumulative
        device-time, not elapsed — same reading as multi-chunk rows)."""
        self.n_problems += other.n_problems
        for k, v in other.outcomes.items():
            self.outcomes[k] = self.outcomes.get(k, 0) + v
        for field_name in ("steps", "backtracks", "decisions",
                           "propagation_rounds", "batch_lanes",
                           "live_lanes", "pad_cells", "live_cells",
                           "n_chunks", "n_buckets", "host_fallback_rows",
                           "fault_host_routed", "profiled_dispatches",
                           "ledger_trips", "ledger_trip_slots",
                           "ledger_lane_steps", "ledger_p99_trips"):
            setattr(self, field_name,
                    getattr(self, field_name) + getattr(other, field_name))
        self.escalation_stage = max(self.escalation_stage,
                                    other.escalation_stage)
        for k, v in other.wall.items():
            self.add_wall(k, v)

    def count_outcome(self, outcome: str, n: int = 1) -> None:
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + n

    # ------------------------------------------------------------- derived

    @property
    def batch_fill_ratio(self) -> float:
        """Live lanes / dispatched lanes — 1.0 means no lane padding."""
        if self.batch_lanes <= 0:
            return 1.0
        return self.live_lanes / self.batch_lanes

    @property
    def pad_waste_ratio(self) -> float:
        """Fraction of padded clause-matrix cells that carry no data."""
        if self.pad_cells <= 0:
            return 0.0
        return 1.0 - self.live_cells / self.pad_cells

    @property
    def useful_work_ratio(self) -> float:
        """Useful lane steps / lockstep trip-lane slots over the
        profiled dispatches (ISSUE 11; 0.0 when nothing was sampled).
        Low means while-trips were spent idling behind padding and
        stragglers — the quantity the watched-literal rewrite must
        raise."""
        if self.ledger_trip_slots <= 0:
            return 0.0
        return self.ledger_lane_steps / self.ledger_trip_slots

    @property
    def straggler_p99_ratio(self) -> float:
        """p99 lane work / batch trips over the profiled dispatches
        (trips-weighted; 0.0 when nothing was sampled).  Low means the
        slowest lane — past even the p99 lane — drove the batch's trip
        count alone."""
        if self.ledger_trips <= 0:
            return 0.0
        return self.ledger_p99_trips / self.ledger_trips

    @classmethod
    def from_dict(cls, d: dict) -> "SolveReport":
        """Rebuild a report from its :meth:`to_dict` JSON form (the
        ``report`` events in a telemetry sink), tolerating missing keys
        so older sink files keep parsing.  Derived ratios are recomputed
        from the raw lane/cell counts."""
        rep = cls(backend=d.get("backend", "?"),
                  n_problems=int(d.get("n_problems", 0) or 0))
        outcomes = d.get("outcomes")
        if isinstance(outcomes, dict):
            rep.outcomes = {str(k): int(v) for k, v in outcomes.items()}
        for field_name in ("steps", "backtracks", "decisions",
                           "propagation_rounds", "batch_lanes",
                           "live_lanes", "pad_cells", "live_cells",
                           "n_chunks", "n_buckets", "escalation_stage",
                           "host_fallback_rows", "fault_host_routed",
                           "profiled_dispatches", "ledger_trips",
                           "ledger_trip_slots", "ledger_lane_steps",
                           "ledger_p99_trips"):
            setattr(rep, field_name, int(d.get(field_name, 0) or 0))
        walls = d.get("wall_s")
        if isinstance(walls, dict):
            rep.wall = {str(k): float(v) for k, v in walls.items()}
        return rep

    def to_dict(self) -> dict:
        return {
            "backend": self.backend,
            "n_problems": self.n_problems,
            "outcomes": dict(self.outcomes),
            "steps": self.steps,
            "backtracks": self.backtracks,
            "decisions": self.decisions,
            "propagation_rounds": self.propagation_rounds,
            "batch_lanes": self.batch_lanes,
            "live_lanes": self.live_lanes,
            "batch_fill_ratio": round(self.batch_fill_ratio, 4),
            "pad_cells": self.pad_cells,
            "live_cells": self.live_cells,
            "pad_waste_ratio": round(self.pad_waste_ratio, 4),
            "n_chunks": self.n_chunks,
            "n_buckets": self.n_buckets,
            "escalation_stage": self.escalation_stage,
            "host_fallback_rows": self.host_fallback_rows,
            "fault_host_routed": self.fault_host_routed,
            "profiled_dispatches": self.profiled_dispatches,
            "ledger_trips": self.ledger_trips,
            "ledger_trip_slots": self.ledger_trip_slots,
            "ledger_lane_steps": self.ledger_lane_steps,
            "ledger_p99_trips": self.ledger_p99_trips,
            "useful_work_ratio": round(self.useful_work_ratio, 4),
            "straggler_p99_ratio": round(self.straggler_p99_ratio, 4),
            "wall_s": {k: round(v, 6) for k, v in self.wall.items()},
        }

    def format_table(self) -> str:
        """Human-readable report (the `deppy stats` / bench rendering)."""
        d = self.to_dict()
        lines = [
            f"solve report ({d['backend']} backend, "
            f"{d['n_problems']} problems)",
            "  outcomes:          "
            + " ".join(f"{k}={v}" for k, v in d["outcomes"].items()),
            f"  steps:             {d['steps']}"
            f"  (backtracks {d['backtracks']}, decisions {d['decisions']},"
            f" propagation rounds {d['propagation_rounds']})",
            f"  batch fill:        {d['batch_fill_ratio']:.3f}"
            f"  ({d['live_lanes']}/{d['batch_lanes']} lanes,"
            f" {d['n_buckets']} buckets, {d['n_chunks']} chunks)",
            f"  padding waste:     {d['pad_waste_ratio']:.3f}"
            f"  ({d['live_cells']}/{d['pad_cells']} clause cells live)",
            f"  escalation stage:  {d['escalation_stage']}",
            f"  host fallback:     {d['host_fallback_rows']} rows"
            f"  (fault-routed problems: {d['fault_host_routed']})",
        ]
        if d["profiled_dispatches"]:
            lines.append(
                f"  trip ledger:       useful {d['useful_work_ratio']:.3f}"
                f"  straggler-p99 {d['straggler_p99_ratio']:.3f}"
                f"  ({d['ledger_trips']} trips over "
                f"{d['profiled_dispatches']} sampled dispatches)")
        if d["wall_s"]:
            walls = "  ".join(
                f"{k}={v * 1e3:.1f}ms" for k, v in sorted(d["wall_s"].items())
            )
            lines.append(f"  wall:              {walls}")
        return "\n".join(lines)


_TLS = threading.local()


def current_report() -> Optional[SolveReport]:
    """The report the pipeline is currently filling on this thread."""
    return getattr(_TLS, "active", None)


def last_report() -> Optional[SolveReport]:
    """The most recently finished report on this thread."""
    return getattr(_TLS, "last", None)


def begin_report(backend: str = "tpu",
                 n_problems: int = 0) -> "tuple[SolveReport, bool]":
    """Make a report active for this thread.  Returns ``(report, owns)``
    — when a report is already active (nested solve, e.g. checkpoint
    groups), the existing one is returned with ``owns=False`` and the
    nested call merges into it instead of finishing it."""
    active = current_report()
    if active is not None:
        active.n_problems += n_problems
        return active, False
    rep = SolveReport(backend=backend, n_problems=n_problems)
    _TLS.active = rep
    return rep, True


def detach_report(rep: SolveReport, owns: bool) -> None:
    """End an owned report WITHOUT publishing it (no ``last_report``,
    no sink event): the mesh shard workers bracket their per-thread
    reports with ``begin_report``/``detach_report`` and hand them back
    for the parent batch's report to :meth:`SolveReport.merge` — eight
    shards must not emit eight ``report`` sink events for one batch.
    No-op for non-owning (nested) callers, like :func:`end_report`."""
    if owns and current_report() is rep:
        _TLS.active = None


def end_report(rep: SolveReport, owns: bool) -> None:
    """Finish an owned report: clears the active slot, publishes it as
    ``last_report()``, and emits it as a ``report`` event on the default
    registry's JSONL sink.  No-op for non-owning (nested) callers."""
    if not owns:
        return
    _TLS.active = None
    _TLS.last = rep
    from .registry import default_registry

    reg = default_registry()
    if reg.sink_path is not None:
        import time

        reg.emit({"ts": round(time.time(), 3), "kind": "report",
                  "report": rep.to_dict()})
