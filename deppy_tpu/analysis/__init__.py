"""deppy_tpu.analysis — static analysis + runtime lock discipline (ISSUE 7).

The serving spine is six threaded subsystems around a jit/pjit/
shard_map/pallas hot path — exactly the two failure classes no test
tier can see: silent host-sync/recompile hazards *inside* traced code,
and unsynchronized shared state *across* threads.  This package is the
invariant gate those classes are held to:

  * **checkers** — four AST checkers behind ``deppy lint``
    (:mod:`.purity`, :mod:`.concurrency`, :mod:`.registry_sync`,
    :mod:`.exceptions`), with a findings baseline
    (``analysis/baseline.json``) so CI fails only on NEW findings while
    the existing ones burn down (see docs/analysis.md);
  * **lockdep** — a runtime lock-order assertion mode
    (``DEPPY_TPU_LOCKDEP=1``, :mod:`.lockdep`): the subsystems' locks
    are created through named factories, and with the mode armed every
    acquisition is checked against the process's observed lock order —
    inversions and self-deadlocks raise *before* they deadlock, and
    emit ``lockdep`` events onto the telemetry sink / flight recorder.

The checkers are import-light (stdlib ``ast`` only) so ``deppy lint``
runs without JAX; lockdep imports telemetry lazily, only on violation.
"""

from .core import (
    CHECKERS,
    Baseline,
    Finding,
    baseline_path,
    repo_root,
    run_checkers,
)
from .lockdep import (
    LockdepError,
    lockdep_enabled,
    make_condition,
    make_lock,
    make_rlock,
)

__all__ = [
    "Baseline",
    "CHECKERS",
    "Finding",
    "LockdepError",
    "baseline_path",
    "lockdep_enabled",
    "make_condition",
    "make_lock",
    "make_rlock",
    "repo_root",
    "run_checkers",
]
