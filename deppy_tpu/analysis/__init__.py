"""deppy_tpu.analysis — static analysis + runtime lock discipline (ISSUE 7).

The serving spine is six threaded subsystems around a jit/pjit/
shard_map/pallas hot path — exactly the two failure classes no test
tier can see: silent host-sync/recompile hazards *inside* traced code,
and unsynchronized shared state *across* threads.  This package is the
invariant gate those classes are held to:

  * **checkers** — six AST checkers behind ``deppy lint``
    (:mod:`.purity`, :mod:`.concurrency`, :mod:`.registry_sync`,
    :mod:`.exceptions`, and the ISSUE 8 compile-contract tier
    :mod:`.compile_surface` + :mod:`.block_contract`), with a findings
    baseline (``analysis/baseline.json``) so CI fails only on NEW
    findings while the existing ones burn down (see docs/analysis.md);
  * **lockdep** — a runtime lock-order assertion mode
    (``DEPPY_TPU_LOCKDEP=1``, :mod:`.lockdep`): the subsystems' locks
    are created through named factories, and with the mode armed every
    acquisition is checked against the process's observed lock order —
    inversions and self-deadlocks raise *before* they deadlock, and
    emit ``lockdep`` events onto the telemetry sink / flight recorder;
  * **compileguard** — lockdep's compile-discipline twin
    (``DEPPY_TPU_COMPILE_GUARD=1``, :mod:`.compileguard`): the
    engine's jit/pjit entries are created through
    ``compileguard.observe``, every trace/compile is recorded as a
    ``compileguard`` sink event, and retracing one abstract signature
    past its budget raises *before* a compile storm eats the serving
    path (``deppy compiles`` summarizes the sink).

The checkers are import-light (stdlib ``ast`` only) so ``deppy lint``
runs without JAX; lockdep and compileguard import telemetry lazily.
"""

from . import compileguard
from .compileguard import CompileGuardError
from .core import (
    CHECKERS,
    Baseline,
    Finding,
    baseline_path,
    repo_root,
    run_checkers,
)
from .lockdep import (
    LockdepError,
    lockdep_enabled,
    make_condition,
    make_lock,
    make_rlock,
)

__all__ = [
    "Baseline",
    "CHECKERS",
    "CompileGuardError",
    "Finding",
    "LockdepError",
    "baseline_path",
    "compileguard",
    "lockdep_enabled",
    "make_condition",
    "make_lock",
    "make_rlock",
    "repo_root",
    "run_checkers",
]
