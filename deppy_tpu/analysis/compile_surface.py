"""compile-surface: construction discipline of the jit/pjit surface.

The engine's cost model is dominated by compile/dispatch discipline,
not arithmetic (~175µs of while-trip overhead per ~10µs of useful
work), and the failure classes all live at jit *construction* sites:
a jit built inside a per-call path rebuilds its cache every call
(compile storm), an undeclared static retraces per value, a closure
over mutable module state silently pins a stale config into the
compiled program, and a Mosaic-hostile op inside a kernel body fails
only on real hardware (the PR 6 integer ``reduce_*`` class).  This
checker walks every ``jit`` / ``pjit`` / ``shard_map`` /
``pallas_call`` wrapping in the tree into a **jit-surface registry**
(:func:`jit_surface`) and enforces four rules over it:

  * ``jit-no-memo`` — ``jax.jit``/``pjit`` called inside a function
    with no memo (``functools.lru_cache``/``cache``) on it or any
    enclosing def: each call builds a fresh jit cache, so every call
    retraces (the runtime compile-guard's ``retrace-budget`` assertion
    is this rule's trace-time twin);
  * ``undeclared-static-arg`` — the wrapped function has keyword-only
    parameters (the repo's static-configuration idiom: ``*, V, NCON,
    NV``) that are neither bound by a ``functools.partial`` in the
    wrapping chain nor named in ``static_argnames``: a tracer leaks
    into shape arithmetic, or the value silently retraces per call;
  * ``mutable-closure`` — a traced function (transitively, via the
    module-local call graph) reads a module global that some function
    rebinds (``global X``) or that the module assigns more than once:
    the value is baked in at trace time and the compiled program goes
    stale without a cache invalidation;
  * ``mosaic-int-reduce`` — a Pallas kernel body (the function handed
    to ``pallas_call``, plus its module-local callees) calls an
    integer reduction (``jnp.sum``/``.min``/``.max``/``.prod``/
    ``argmin``/``argmax`` or the method forms): the installed Mosaic
    lowering rejects every integer ``reduce_*`` primitive — use the
    halving-tree folds (``core.tree_sum``/``tree_min``/``tree_max``),
    the permanent encoding of the PR 6 fix.

Wrapping chains are resolved through the transparent combinators the
repo composes (``vmap``, ``functools.partial``,
``compileguard.observe``, ``shard_map``), so
``jax.jit(observe("e", vmap(partial(fn, V=V))))`` attributes to
``fn``.  Like every checker here: stdlib ``ast`` only, module-local
call graphs, baseline/suppression semantics from :mod:`.core`.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .core import Checker, Finding, SourceFile
from .core import dotted as _dotted

# Calls that register a jit-surface entry.
_SURFACE = {"jit", "pjit", "shard_map", "pallas_call"}
# Only these rebuild a trace cache per construction (pallas_call inside
# an already-traced function is the normal idiom; shard_map without jit
# is eager).
_CACHED_SURFACE = {"jit", "pjit"}
_MEMO_DECORATORS = {"lru_cache", "cache"}
# Combinators that forward to an inner function without ending the
# wrapping chain; the value maps a combinator to the positional index
# of its function argument.
_TRANSPARENT = {"vmap": 0, "partial": 0, "observe": 1, "shard_map": 0,
                "wraps": 0, "checkify": 0, "remat": 0, "checkpoint": 0}
_INT_REDUCES = {"sum", "min", "max", "prod", "argmin", "argmax"}



def _leaf(node: ast.AST) -> str:
    return (_dotted(node) or "").rsplit(".", 1)[-1]


@dataclass
class JitEntry:
    """One jit-surface registry row."""

    path: str        # repo-relative
    line: int
    kind: str        # jit | pjit | shard_map | pallas_call
    name: str        # enclosing def / assigned target / wrapped fn
    memoized: bool   # under an lru_cache/cache factory
    observed: bool   # wrapped with compileguard.observe
    in_function: bool  # constructed per-call (vs once at import)

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "kind": self.kind,
                "name": self.name, "memoized": self.memoized,
                "observed": self.observed,
                "in_function": self.in_function}


class _Parents(ast.NodeVisitor):
    """child -> parent map (the stdlib ast has no parent pointers)."""

    def __init__(self, tree: ast.AST):
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node

    def enclosing_defs(self, node: ast.AST) -> List[ast.FunctionDef]:
        """Innermost-first function chain around ``node``.  A call
        sitting in a def's decorator list executes at the *enclosing*
        scope, not inside the def — skip that def."""
        out: List[ast.FunctionDef] = []
        cur: Optional[ast.AST] = node
        prev: Optional[ast.AST] = None
        while cur is not None:
            parent = self.parent.get(cur)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                in_decorators = prev is not None and any(
                    prev is d or any(prev is sub for sub in ast.walk(d))
                    for d in cur.decorator_list)
                if not in_decorators:
                    out.append(cur)
            prev, cur = cur, parent
        return out


def _has_memo(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _leaf(target) in _MEMO_DECORATORS:
            return True
    return False


def _local_env(fn: Optional[ast.FunctionDef]) -> Dict[str, ast.AST]:
    """Single-target local assignments inside ``fn`` (the factory
    idiom: ``fn = functools.partial(solve_full, V=V); jax.jit(vmap(fn))``
    — the chain resolver follows the name back to its value)."""
    if fn is None:
        return {}
    env: Dict[str, ast.AST] = {}
    for stmt in ast.walk(fn):
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            env[stmt.targets[0].id] = stmt.value
    return env


def _unwrap(node: ast.AST, module_funcs: Dict[str, ast.FunctionDef],
            local_env: Optional[Dict[str, ast.AST]] = None
            ) -> Tuple[Optional[str], Set[str], bool]:
    """Follow a wrapping chain down to a module-local function name.
    Returns (name-or-None, keyword names bound by partials along the
    way, whether compileguard.observe appears in the chain)."""
    bound: Set[str] = set()
    observed = False
    local_env = local_env or {}
    cur: Optional[ast.AST] = node
    for _ in range(16):  # chains are short; bound-loop paranoia
        if isinstance(cur, ast.Name):
            if cur.id in module_funcs:
                return cur.id, bound, observed
            nxt = local_env.get(cur.id)
            if nxt is None or nxt is cur:
                return None, bound, observed
            cur = nxt
            continue
        if not isinstance(cur, ast.Call):
            return None, bound, observed
        leaf = _leaf(cur.func)
        if leaf not in _TRANSPARENT:
            return None, bound, observed
        if leaf == "observe":
            observed = True
        if leaf == "partial":
            bound |= {kw.arg for kw in cur.keywords if kw.arg}
        idx = _TRANSPARENT[leaf]
        if len(cur.args) <= idx:
            return None, bound, observed
        cur = cur.args[idx]
    return None, bound, observed


def _static_names(call: ast.Call) -> Optional[Set[str]]:
    """Names in ``static_argnames`` (None when the keyword is absent —
    distinct from an explicit empty declaration)."""
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names: Set[str] = set()
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str):
                    names.add(sub.value)
            return names
    return None


def _surface_calls(sf: SourceFile):
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) and _leaf(node.func) in _SURFACE:
            yield node


def _entry_name(call: ast.Call, parents: _Parents,
                module_funcs: Dict[str, ast.FunctionDef]) -> str:
    defs = parents.enclosing_defs(call)
    if defs:
        return defs[0].name
    # Module-level construction: prefer the assignment target.
    cur: Optional[ast.AST] = call
    while cur is not None:
        parent = parents.parent.get(cur)
        if isinstance(parent, ast.Assign) and parent.targets:
            target = parent.targets[0]
            name = _dotted(target)
            if name:
                return name
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Module)):
            break
        cur = parent
    wrapped, _, _ = _unwrap(call.args[0] if call.args else call,
                            module_funcs)
    return wrapped or "<module>"


def jit_surface(files: Optional[List[SourceFile]] = None
                ) -> List[JitEntry]:
    """The repo-wide jit-surface registry: one row per ``jit`` /
    ``pjit`` / ``shard_map`` / ``pallas_call`` construction, with its
    memoization and compile-guard status.  ``deppy compiles --surface``
    prints it; tests pin the engine's known entries against it."""
    if files is None:
        from .core import SourceFile as SF
        from .core import _iter_py_files, repo_root

        root = repo_root()
        files = [SF.load(p, root)
                 for p in _iter_py_files(root, ("deppy_tpu",))]
    entries: List[JitEntry] = []
    for sf in files:
        if sf.tree is None:
            continue
        parents = _Parents(sf.tree)
        module_funcs = {
            n.name: n for n in ast.walk(sf.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for call in _surface_calls(sf):
            kind = _leaf(call.func)
            defs = parents.enclosing_defs(call)
            memoized = any(_has_memo(fn) for fn in defs)
            env = _local_env(defs[0]) if defs else {}
            _, _, observed = _unwrap(
                call.args[0] if call.args else call, module_funcs, env)
            entries.append(JitEntry(
                path=sf.rel, line=call.lineno, kind=kind,
                name=_entry_name(call, parents, module_funcs),
                memoized=memoized, observed=observed,
                in_function=bool(defs)))
    entries.sort(key=lambda e: (e.path, e.line))
    return entries


class CompileSurfaceChecker(Checker):
    name = "compile-surface"
    default_scope = ("deppy_tpu",)

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        out: List[Finding] = []
        for sf in files:
            self._check_module(out, sf)
        return out

    # ------------------------------------------------------------- module

    def _check_module(self, out: List[Finding], sf: SourceFile) -> None:
        parents = _Parents(sf.tree)
        module_funcs = {
            n.name: n for n in ast.walk(sf.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

        kernel_roots: Set[str] = set()
        traced_roots: Set[str] = set()
        for call in _surface_calls(sf):
            kind = _leaf(call.func)
            defs = parents.enclosing_defs(call)
            env = _local_env(defs[0]) if defs else {}
            if kind in _CACHED_SURFACE:
                self._check_no_memo(out, sf, call, parents)
                self._check_static_args(out, sf, call, module_funcs,
                                        env)
            wrapped, _, _ = _unwrap(
                call.args[0] if call.args else call, module_funcs, env)
            if wrapped:
                (kernel_roots if kind == "pallas_call"
                 else traced_roots).add(wrapped)
        # Decorator-wrapped defs join the traced set (@jax.jit).
        for name, fn in module_funcs.items():
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _leaf(target) in _CACHED_SURFACE:
                    traced_roots.add(name)
                    if isinstance(dec, ast.Call):
                        self._check_decorated_static(out, sf, fn, dec)
                elif (isinstance(dec, ast.Call)
                        and _leaf(target) == "partial" and dec.args
                        and _leaf(dec.args[0]) in _CACHED_SURFACE):
                    traced_roots.add(name)
                    self._check_decorated_static(out, sf, fn, dec)

        calls = self._callgraph(module_funcs)
        self._check_mutable_closure(
            out, sf, self._reach(traced_roots | kernel_roots, calls),
            module_funcs)
        self._check_mosaic(out, sf, self._reach(kernel_roots, calls),
                           module_funcs)

    @staticmethod
    def _callgraph(module_funcs) -> Dict[str, Set[str]]:
        graph: Dict[str, Set[str]] = {}
        for name, fn in module_funcs.items():
            callees: Set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Name) and sub.id in module_funcs:
                    callees.add(sub.id)
            callees.discard(name)
            graph[name] = callees
        return graph

    @staticmethod
    def _reach(roots: Set[str], graph: Dict[str, Set[str]]) -> Set[str]:
        seen: Set[str] = set()
        stack = list(roots)
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            stack.extend(graph.get(name, ()))
        return seen

    # ------------------------------------------------------------ rule 1

    def _check_no_memo(self, out: List[Finding], sf: SourceFile,
                       call: ast.Call, parents: _Parents) -> None:
        defs = parents.enclosing_defs(call)
        if not defs:
            return  # module-level construction compiles once per import
        if any(_has_memo(fn) for fn in defs):
            return
        kind = _leaf(call.func)
        self.finding(
            out, sf, call.lineno, "jit-no-memo",
            f"{defs[0].name}:{kind}",
            f"`{kind}(...)` constructed inside `{defs[0].name}` with no "
            f"lru_cache/cache memo on the call path — every call builds "
            f"a fresh trace cache and recompiles; memoize the factory "
            f"or hoist the wrapping to module level")

    # ------------------------------------------------------------ rule 2

    def _missing_statics(self, fn: ast.FunctionDef, bound: Set[str],
                         declared: Optional[Set[str]]) -> List[str]:
        kwonly = [a.arg for a in fn.args.kwonlyargs]
        declared = declared or set()
        return [n for n in kwonly if n not in bound and n not in declared]

    def _check_static_args(self, out: List[Finding], sf: SourceFile,
                           call: ast.Call, module_funcs,
                           local_env=None) -> None:
        if not call.args:
            return
        wrapped, bound, _ = _unwrap(call.args[0], module_funcs,
                                    local_env)
        if wrapped is None:
            return
        missing = self._missing_statics(module_funcs[wrapped], bound,
                                        _static_names(call))
        if missing:
            self._static_finding(out, sf, call.lineno, wrapped, missing)

    def _check_decorated_static(self, out: List[Finding], sf: SourceFile,
                                fn: ast.FunctionDef,
                                dec: ast.Call) -> None:
        missing = self._missing_statics(fn, set(), _static_names(dec))
        if missing:
            self._static_finding(out, sf, fn.lineno, fn.name, missing)

    def _static_finding(self, out, sf, line, fname, missing) -> None:
        names = ", ".join(missing)
        self.finding(
            out, sf, line, "undeclared-static-arg",
            f"{fname}:{names}",
            f"keyword-only parameter(s) `{names}` of jitted `{fname}` "
            f"are neither bound by functools.partial nor declared in "
            f"static_argnames — a tracer leaks into shape arithmetic, "
            f"or the value silently retraces per call")

    # ------------------------------------------------------------ rule 3

    def _check_mutable_closure(self, out: List[Finding], sf: SourceFile,
                               traced: Set[str], module_funcs) -> None:
        mutable: Set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Global):
                mutable.update(node.names)
        assigned_counts: Dict[str, int] = {}
        for stmt in sf.tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    assigned_counts[t.id] = assigned_counts.get(t.id,
                                                                0) + 1
        mutable |= {n for n, c in assigned_counts.items() if c > 1}
        if not mutable:
            return
        for fname in sorted(traced):
            fn = module_funcs[fname]
            local = {a.arg for a in (fn.args.args + fn.args.kwonlyargs
                                     + fn.args.posonlyargs)}
            for sub in ast.walk(fn):
                if isinstance(sub, ast.FunctionDef):
                    local.update(a.arg for a in sub.args.args)
            for sub in ast.walk(fn):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in mutable and sub.id not in local):
                    self.finding(
                        out, sf, sub.lineno, "mutable-closure",
                        f"{fname}:{sub.id}",
                        f"traced function `{fname}` reads mutable "
                        f"module state `{sub.id}` — the value is baked "
                        f"in at trace time and the compiled program "
                        f"goes stale unless every write invalidates "
                        f"the jit caches")

    # ------------------------------------------------------------ rule 4

    def _check_mosaic(self, out: List[Finding], sf: SourceFile,
                      kernels: Set[str], module_funcs) -> None:
        # Module roots whose .sum/.min/... are host-side calls, not
        # array-method reductions (jnp/lax ARE flagged — they lower to
        # the rejected reduce_* primitives like the method forms).
        host_roots = {"np", "numpy", "math", "os", "functools",
                      "builtins"}
        for fname in sorted(kernels):
            for sub in ast.walk(module_funcs[fname]):
                if not isinstance(sub, ast.Call):
                    continue
                if not isinstance(sub.func, ast.Attribute):
                    continue  # bare min()/max() builtins: trace-time
                leaf = sub.func.attr
                if leaf not in _INT_REDUCES:
                    continue
                target = _dotted(sub.func) or f".{leaf}"
                root = target.rsplit(".", 1)[0].split(".", 1)[0]
                if root in host_roots:
                    continue
                hit = (target if root in ("jnp", "jax", "lax")
                       else f".{leaf}")
                if hit:
                    self.finding(
                        out, sf, sub.lineno, "mosaic-int-reduce",
                        f"{fname}:{hit}",
                        f"`{hit}(...)` inside Pallas kernel `{fname}` — "
                        f"the installed Mosaic lowering rejects integer "
                        f"reduce_* primitives on hardware (PR 6); use "
                        f"the halving-tree folds core.tree_sum/"
                        f"tree_min/tree_max")
