"""Checker framework: findings, suppressions, baseline, runner.

Design points:

  * **Findings have a stable identity** (``checker:path:code:symbol``)
    that deliberately excludes the line number, so the baseline file
    survives unrelated edits above a finding.  Identical findings in
    one file are *counted* — the baseline stores ``key -> count`` and
    only a count increase is "new".
  * **Suppressions are in-line and reasoned.**  A
    ``# deppy: lint-ok[checker] reason`` comment on the flagged line
    (or the line above it) suppresses that checker there; ``[*]``
    suppresses all.  The reason is mandatory culture, not syntax — the
    burn-down satellite removes suppressions, it never adds bare ones.
  * **The runner is pure stdlib** (``ast`` + ``json``): ``deppy lint``
    must run in CI before JAX imports are even possible.

See docs/analysis.md for the operator view of each checker.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# checker name -> in-line suppression token.
SUPPRESS_RE = re.compile(r"#\s*deppy:\s*lint-ok\[([a-z*\-]+)\]")


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None — the one AST
    helper every checker needs (shared here so a fix lands once)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def repo_root() -> Path:
    """The checkout root: the parent of the ``deppy_tpu`` package."""
    return Path(__file__).resolve().parent.parent.parent


def baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


@dataclass
class Finding:
    """One checker hit.  ``symbol`` names the offending thing (an env
    var, a lock attribute, a function) — it is part of the baseline
    identity, the line number is display-only."""

    checker: str
    path: str       # repo-relative, forward slashes
    line: int
    code: str       # short kebab-case slug of the rule
    symbol: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.checker}:{self.path}:{self.code}:{self.symbol}"

    def to_dict(self) -> dict:
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "code": self.code,
                "symbol": self.symbol, "message": self.message}

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.checker}/{self.code}] "
                f"{self.message}")


@dataclass
class SourceFile:
    """One parsed module handed to every checker (parse once)."""

    path: Path          # absolute
    rel: str            # repo-relative
    text: str
    lines: List[str] = field(default_factory=list)
    tree: Optional[ast.AST] = None
    parse_error: Optional[str] = None

    @classmethod
    def load(cls, path: Path, root: Path) -> "SourceFile":
        text = path.read_text(encoding="utf-8")
        sf = cls(path=path, rel=path.relative_to(root).as_posix(),
                 text=text, lines=text.splitlines())
        try:
            sf.tree = ast.parse(text)
        except SyntaxError as e:  # a broken file is itself a finding
            sf.parse_error = str(e)
        return sf

    _anchor_map: Optional[Dict[int, int]] = None

    def _anchors(self) -> Dict[int, int]:
        """line -> anchor line for findings attributed mid-statement.

        Two cases (ISSUE 8 satellite — the pre-span rule only looked at
        the flagged line and the one above, so a suppression on a
        multi-line statement's first line missed findings attributed to
        its continuation lines, and one on a ``def`` line missed
        findings on its decorator lines):

          * a **simple multi-line statement** (call, assignment,
            return, ...): every continuation line anchors to the
            statement's first line — compound statements (``if``/
            ``with``/``for``/``def`` bodies) deliberately do NOT
            anchor, a suppression on an ``if`` must not blanket its
            whole body;
          * a **decorated def/class**: every decorator line anchors to
            the ``def``/``class`` line (checkers attribute decorator
            hazards to the decorator expression's own line).
        """
        if self._anchor_map is None:
            anchors: Dict[int, int] = {}
            simple = (ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign,
                      ast.Return, ast.Assert, ast.Raise, ast.Delete)
            if self.tree is not None:
                for node in ast.walk(self.tree):
                    if isinstance(node, simple):
                        end = getattr(node, "end_lineno", node.lineno)
                        for ln in range(node.lineno + 1, end + 1):
                            anchors.setdefault(ln, node.lineno)
                    elif isinstance(node, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef)):
                        for dec in node.decorator_list:
                            end = getattr(dec, "end_lineno", dec.lineno)
                            for ln in range(dec.lineno, end + 1):
                                anchors[ln] = node.lineno
            self._anchor_map = anchors
        return self._anchor_map

    def suppressed(self, line: int, checker: str) -> bool:
        """True when ``line`` (1-based), the line above it, or the
        line's statement anchor (first line of a multi-line simple
        statement; the ``def`` line for decorator lines — see
        :meth:`_anchors`) carries a ``# deppy: lint-ok[checker]`` (or
        ``[*]``) comment."""
        candidates = [line, line - 1]
        anchor = self._anchors().get(line)
        if anchor is not None and anchor != line:
            candidates += [anchor, anchor - 1]
        for ln in candidates:
            if 1 <= ln <= len(self.lines):
                for m in SUPPRESS_RE.finditer(self.lines[ln - 1]):
                    if m.group(1) in (checker, "*"):
                        return True
        return False


class Checker:
    """Base: subclasses set ``name``/``default_scope`` and implement
    ``check``.  ``default_scope`` is a list of repo-relative glob
    prefixes the checker runs over when the CLI is given none.

    ``partial`` is set by the runner on ``--changed`` runs (the file
    set is a git-diff subset, not the whole scope): checkers whose
    reverse-direction rules need the full tree (declared-but-unused
    knobs, stale fault points, flag mirrors) must skip those when it
    is True — a subset scan proves presence, never absence."""

    name = "checker"
    default_scope: Tuple[str, ...] = ("deppy_tpu",)
    partial = False

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        raise NotImplementedError

    # Helper for subclasses: emit unless suppressed.
    def finding(self, out: List[Finding], sf: SourceFile, line: int,
                code: str, symbol: str, message: str) -> None:
        if sf.suppressed(line, self.name):
            return
        out.append(Finding(checker=self.name, path=sf.rel, line=line,
                           code=code, symbol=symbol, message=message))


class Baseline:
    """``key -> count`` of accepted findings (``analysis/baseline.json``).

    ``diff`` returns the findings beyond the baseline's counts — the
    ones a CI run fails on — and the stale keys the baseline carries
    for findings that no longer exist (burn-down bookkeeping: stale
    keys warn, they do not fail)."""

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls()
        doc = json.loads(path.read_text(encoding="utf-8"))
        if not isinstance(doc, dict) or not isinstance(
                doc.get("findings"), dict):
            raise ValueError(
                f"{path}: expected {{\"findings\": {{key: count}}}}")
        return cls({str(k): int(v) for k, v in doc["findings"].items()})

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.key] = counts.get(f.key, 0) + 1
        return cls(counts)

    def save(self, path: Path) -> None:
        doc = {
            "_comment": [
                "deppy lint findings baseline: key -> accepted count.",
                "CI fails on findings NOT covered here; burn this file",
                "down, never grow it by hand (deppy lint",
                "--update-baseline regenerates it).",
            ],
            "findings": {k: self.counts[k] for k in sorted(self.counts)},
        }
        path.write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")

    def diff(self, findings: List[Finding]) -> Tuple[List[Finding],
                                                     List[str]]:
        seen: Dict[str, int] = {}
        new: List[Finding] = []
        for f in findings:
            seen[f.key] = seen.get(f.key, 0) + 1
            if seen[f.key] > self.counts.get(f.key, 0):
                new.append(f)
        stale = [k for k, n in sorted(self.counts.items())
                 if seen.get(k, 0) < n]
        return new, stale


# ---------------------------------------------------------------- runner


def _iter_py_files(root: Path, scopes: Iterable[str]) -> List[Path]:
    out: List[Path] = []
    seen = set()
    for scope in scopes:
        base = root / scope
        if base.is_file():
            paths = [base]
        else:
            paths = sorted(base.rglob("*.py"))
        for p in paths:
            if "__pycache__" in p.parts or p in seen:
                continue
            seen.add(p)
            out.append(p)
    return out


def checker_registry() -> Dict[str, Callable[[], Checker]]:
    # Local imports: each checker module is tiny, but keeping the
    # registry lazy means a syntax error in one checker doesn't take
    # down `deppy lint --checker <other>`.
    from . import (block_contract, compile_surface, concurrency,
                   exceptions, purity, registry_sync)

    return {
        purity.TracePurityChecker.name: purity.TracePurityChecker,
        concurrency.ConcurrencyChecker.name:
            concurrency.ConcurrencyChecker,
        registry_sync.RegistrySyncChecker.name:
            registry_sync.RegistrySyncChecker,
        exceptions.ExceptionHygieneChecker.name:
            exceptions.ExceptionHygieneChecker,
        compile_surface.CompileSurfaceChecker.name:
            compile_surface.CompileSurfaceChecker,
        block_contract.BlockContractChecker.name:
            block_contract.BlockContractChecker,
    }


CHECKERS = ("trace-purity", "concurrency-discipline", "registry-sync",
            "exception-hygiene", "compile-surface", "block-contract")


def changed_files(root: Path, base: str = "HEAD") -> List[str]:
    """Repo-relative paths changed vs ``base`` (``git diff
    --name-only`` plus untracked files): the ``deppy lint --changed``
    fast-mode file set.  Raises ``RuntimeError`` when git is absent or
    the ref is unknown — fast mode must fail loudly, not silently lint
    nothing."""
    import subprocess

    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", base, "--"],
            cwd=root, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"cannot run git for --changed: {e}") from e
    if diff.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {base} failed: "
            f"{diff.stderr.strip() or diff.returncode}")
    names = set(diff.stdout.splitlines())
    if untracked.returncode == 0:
        names.update(untracked.stdout.splitlines())
    return sorted(n.strip() for n in names if n.strip())


def run_checkers(root: Optional[Path] = None,
                 names: Optional[Iterable[str]] = None,
                 paths: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the named checkers (default all) over the repo; returns
    findings sorted by path/line for stable output.  ``paths`` (repo-
    relative) restricts every checker to that file subset — the
    ``--changed`` fast mode; checkers see ``partial=True`` and skip
    their reverse-direction (absence-proving) rules."""
    root = root or repo_root()
    registry = checker_registry()
    wanted = list(names) if names else list(registry)
    unknown = [n for n in wanted if n not in registry]
    if unknown:
        raise ValueError(f"unknown checker(s) {unknown}; "
                         f"have {sorted(registry)}")
    wanted_paths = None
    if paths is not None:
        wanted_paths = {str(p).replace("\\", "/") for p in paths}
    findings: List[Finding] = []
    cache: Dict[Path, SourceFile] = {}
    for name in wanted:
        checker = registry[name]()
        checker.partial = wanted_paths is not None
        files = []
        for path in _iter_py_files(root, checker.default_scope):
            rel = path.relative_to(root).as_posix()
            if wanted_paths is not None and rel not in wanted_paths:
                continue
            sf = cache.get(path)
            if sf is None:
                sf = cache[path] = SourceFile.load(path, root)
            files.append(sf)
        for sf in files:
            if sf.parse_error is not None:
                checker.finding(findings, sf, 1, "syntax-error",
                                sf.rel, f"file does not parse: "
                                f"{sf.parse_error}")
        findings.extend(checker.check(
            [f for f in files if f.tree is not None], root))
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.code))
    return findings
