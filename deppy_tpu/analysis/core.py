"""Checker framework: findings, suppressions, baseline, runner.

Design points:

  * **Findings have a stable identity** (``checker:path:code:symbol``)
    that deliberately excludes the line number, so the baseline file
    survives unrelated edits above a finding.  Identical findings in
    one file are *counted* — the baseline stores ``key -> count`` and
    only a count increase is "new".
  * **Suppressions are in-line and reasoned.**  A
    ``# deppy: lint-ok[checker] reason`` comment on the flagged line
    (or the line above it) suppresses that checker there; ``[*]``
    suppresses all.  The reason is mandatory culture, not syntax — the
    burn-down satellite removes suppressions, it never adds bare ones.
  * **The runner is pure stdlib** (``ast`` + ``json``): ``deppy lint``
    must run in CI before JAX imports are even possible.

See docs/analysis.md for the operator view of each checker.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

# checker name -> in-line suppression token.
SUPPRESS_RE = re.compile(r"#\s*deppy:\s*lint-ok\[([a-z*\-]+)\]")


def repo_root() -> Path:
    """The checkout root: the parent of the ``deppy_tpu`` package."""
    return Path(__file__).resolve().parent.parent.parent


def baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


@dataclass
class Finding:
    """One checker hit.  ``symbol`` names the offending thing (an env
    var, a lock attribute, a function) — it is part of the baseline
    identity, the line number is display-only."""

    checker: str
    path: str       # repo-relative, forward slashes
    line: int
    code: str       # short kebab-case slug of the rule
    symbol: str
    message: str

    @property
    def key(self) -> str:
        return f"{self.checker}:{self.path}:{self.code}:{self.symbol}"

    def to_dict(self) -> dict:
        return {"checker": self.checker, "path": self.path,
                "line": self.line, "code": self.code,
                "symbol": self.symbol, "message": self.message}

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.checker}/{self.code}] "
                f"{self.message}")


@dataclass
class SourceFile:
    """One parsed module handed to every checker (parse once)."""

    path: Path          # absolute
    rel: str            # repo-relative
    text: str
    lines: List[str] = field(default_factory=list)
    tree: Optional[ast.AST] = None
    parse_error: Optional[str] = None

    @classmethod
    def load(cls, path: Path, root: Path) -> "SourceFile":
        text = path.read_text(encoding="utf-8")
        sf = cls(path=path, rel=path.relative_to(root).as_posix(),
                 text=text, lines=text.splitlines())
        try:
            sf.tree = ast.parse(text)
        except SyntaxError as e:  # a broken file is itself a finding
            sf.parse_error = str(e)
        return sf

    def suppressed(self, line: int, checker: str) -> bool:
        """True when ``line`` (1-based) or the line above carries a
        ``# deppy: lint-ok[checker]`` (or ``[*]``) comment."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                for m in SUPPRESS_RE.finditer(self.lines[ln - 1]):
                    if m.group(1) in (checker, "*"):
                        return True
        return False


class Checker:
    """Base: subclasses set ``name``/``default_scope`` and implement
    ``check``.  ``default_scope`` is a list of repo-relative glob
    prefixes the checker runs over when the CLI is given none."""

    name = "checker"
    default_scope: Tuple[str, ...] = ("deppy_tpu",)

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        raise NotImplementedError

    # Helper for subclasses: emit unless suppressed.
    def finding(self, out: List[Finding], sf: SourceFile, line: int,
                code: str, symbol: str, message: str) -> None:
        if sf.suppressed(line, self.name):
            return
        out.append(Finding(checker=self.name, path=sf.rel, line=line,
                           code=code, symbol=symbol, message=message))


class Baseline:
    """``key -> count`` of accepted findings (``analysis/baseline.json``).

    ``diff`` returns the findings beyond the baseline's counts — the
    ones a CI run fails on — and the stale keys the baseline carries
    for findings that no longer exist (burn-down bookkeeping: stale
    keys warn, they do not fail)."""

    def __init__(self, counts: Optional[Dict[str, int]] = None):
        self.counts: Dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls()
        doc = json.loads(path.read_text(encoding="utf-8"))
        if not isinstance(doc, dict) or not isinstance(
                doc.get("findings"), dict):
            raise ValueError(
                f"{path}: expected {{\"findings\": {{key: count}}}}")
        return cls({str(k): int(v) for k, v in doc["findings"].items()})

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.key] = counts.get(f.key, 0) + 1
        return cls(counts)

    def save(self, path: Path) -> None:
        doc = {
            "_comment": [
                "deppy lint findings baseline: key -> accepted count.",
                "CI fails on findings NOT covered here; burn this file",
                "down, never grow it by hand (deppy lint",
                "--update-baseline regenerates it).",
            ],
            "findings": {k: self.counts[k] for k in sorted(self.counts)},
        }
        path.write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")

    def diff(self, findings: List[Finding]) -> Tuple[List[Finding],
                                                     List[str]]:
        seen: Dict[str, int] = {}
        new: List[Finding] = []
        for f in findings:
            seen[f.key] = seen.get(f.key, 0) + 1
            if seen[f.key] > self.counts.get(f.key, 0):
                new.append(f)
        stale = [k for k, n in sorted(self.counts.items())
                 if seen.get(k, 0) < n]
        return new, stale


# ---------------------------------------------------------------- runner


def _iter_py_files(root: Path, scopes: Iterable[str]) -> List[Path]:
    out: List[Path] = []
    seen = set()
    for scope in scopes:
        base = root / scope
        if base.is_file():
            paths = [base]
        else:
            paths = sorted(base.rglob("*.py"))
        for p in paths:
            if "__pycache__" in p.parts or p in seen:
                continue
            seen.add(p)
            out.append(p)
    return out


def checker_registry() -> Dict[str, Callable[[], Checker]]:
    # Local imports: each checker module is tiny, but keeping the
    # registry lazy means a syntax error in one checker doesn't take
    # down `deppy lint --checker <other>`.
    from . import concurrency, exceptions, purity, registry_sync

    return {
        purity.TracePurityChecker.name: purity.TracePurityChecker,
        concurrency.ConcurrencyChecker.name:
            concurrency.ConcurrencyChecker,
        registry_sync.RegistrySyncChecker.name:
            registry_sync.RegistrySyncChecker,
        exceptions.ExceptionHygieneChecker.name:
            exceptions.ExceptionHygieneChecker,
    }


CHECKERS = ("trace-purity", "concurrency-discipline", "registry-sync",
            "exception-hygiene")


def run_checkers(root: Optional[Path] = None,
                 names: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the named checkers (default all) over the repo; returns
    findings sorted by path/line for stable output."""
    root = root or repo_root()
    registry = checker_registry()
    wanted = list(names) if names else list(registry)
    unknown = [n for n in wanted if n not in registry]
    if unknown:
        raise ValueError(f"unknown checker(s) {unknown}; "
                         f"have {sorted(registry)}")
    findings: List[Finding] = []
    cache: Dict[Path, SourceFile] = {}
    for name in wanted:
        checker = registry[name]()
        files = []
        for path in _iter_py_files(root, checker.default_scope):
            sf = cache.get(path)
            if sf is None:
                sf = cache[path] = SourceFile.load(path, root)
            files.append(sf)
        for sf in files:
            if sf.parse_error is not None:
                checker.finding(findings, sf, 1, "syntax-error",
                                sf.rel, f"file does not parse: "
                                f"{sf.parse_error}")
        findings.extend(checker.check(
            [f for f in files if f.tree is not None], root))
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.code))
    return findings
