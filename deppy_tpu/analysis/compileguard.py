"""Runtime compile-guard (``DEPPY_TPU_COMPILE_GUARD=1``).

The static ``compile-surface`` checker (:mod:`.compile_surface`) sees
the *construction* discipline — memoized jit factories, declared
statics.  This is its runtime twin, mirroring lockdep: the engine's
jit/pjit entries are created through :func:`observe`, which wraps the
function **inside** the ``jax.jit`` boundary.  A wrapped function body
only executes when JAX actually (re)traces it, so every execution IS a
trace/compile event:

  * every trace is **counted** per ``(entry, abstract signature)`` —
    always, armed or not; the counter costs one dict update per trace
    and feeds the bench harness's ``n_compiles`` column and
    :func:`snapshot`;
  * armed, every trace additionally emits a ``compileguard`` event onto
    the telemetry sink — entry name, abstract signature, call site,
    trace wall time — stamped onto the active request trace when one is
    live (``deppy compiles`` summarizes these; ``deppy trace`` renders
    them in the span tree);
  * armed, tracing the same signature **past the entry's budget**
    raises :class:`CompileGuardError` (the event goes first, like
    lockdep's ``_violation``): a compile storm — a fresh jit cache per
    call, an undeclared static retracing per value — fails
    ``make test-compileguard`` in seconds instead of silently eating
    the tier-1 time budget (PR 6 paid exactly this by hand).

The *signature* is derived from the tracer avals (dtype, shape, weak
type) plus the entry's static configuration (the factory arguments the
wrap site passes as ``static=``).  A retrace with an identical
signature means a cache was lost — the one thing a healthy entry never
does.  Budgets default to ``DEPPY_TPU_COMPILE_BUDGET`` when set, else
``2 x local_device_count``: the per-device serving composition
legitimately traces each signature once per device (committed inputs
key jit's cache by placement), and committed-vs-uncommitted placement
of the same shapes can double that.  Deliberate cache drops
(``engine.clear_compile_caches`` / ``core.clear_batched_caches``) call
:func:`reset_counts` — the recompiles they cause are the point, not a
storm.

Disarmed (the default), :func:`observe` still wraps — the per-trace
counter is the bench ``n_compiles`` source — but emits nothing and
never raises.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, Optional, Tuple

# Plain lock on purpose: the guard's own bookkeeping must not recurse
# into lockdep instrumentation mid-trace.
_LOCK = threading.Lock()
# (entry, signature) -> trace count; entry -> total traces.
_SIG_COUNTS: Dict[Tuple[str, str], int] = {}
_ENTRY_COUNTS: Dict[str, int] = {}
_TOTAL = 0
# entry -> declared per-signature budget (observe(budget=)/declare_budget).
_BUDGETS: Dict[str, int] = {}
_DEVICE_COUNT: Optional[int] = None


class CompileGuardError(AssertionError):
    """A jit entry retraced one signature past its compile budget."""


def guard_enabled() -> bool:
    """Read ``DEPPY_TPU_COMPILE_GUARD`` live (not cached): entries wrap
    unconditionally, so arming mid-process turns events/assertions on
    for every later trace."""
    from .. import config

    return config.env_bool("DEPPY_TPU_COMPILE_GUARD", False)


def default_budget() -> int:
    """Per-signature trace budget when the entry declares none:
    ``DEPPY_TPU_COMPILE_BUDGET`` if set, else 2 x local_device_count
    (per-device placement keys jit's cache — see module docstring)."""
    from .. import config

    declared = config.env_int("DEPPY_TPU_COMPILE_BUDGET", None,
                              strict=False)
    if declared is not None and declared > 0:
        return declared
    global _DEVICE_COUNT
    if _DEVICE_COUNT is None:
        try:
            import jax

            _DEVICE_COUNT = max(1, jax.local_device_count())
        except Exception:  # deppy: lint-ok[exception-hygiene] backendless probe: the guard must degrade to a host-only budget, not crash the trace
            _DEVICE_COUNT = 1
    return 2 * _DEVICE_COUNT


def declare_budget(entry: str, per_signature: int) -> None:
    """Declare ``entry``'s per-signature trace budget (also settable at
    the wrap site via ``observe(budget=)``)."""
    with _LOCK:
        _BUDGETS[entry] = int(per_signature)


def budget_for(entry: str) -> int:
    with _LOCK:
        declared = _BUDGETS.get(entry)
    return declared if declared is not None else default_budget()


def trace_count() -> int:
    """Total traces observed process-wide (the bench harness diffs this
    around its timed section for the ``n_compiles`` column)."""
    with _LOCK:
        return _TOTAL


def snapshot() -> Dict[str, dict]:
    """Per-entry counters: traces, distinct signatures, retraces
    (traces beyond the first per signature)."""
    with _LOCK:
        out: Dict[str, dict] = {}
        for entry, total in sorted(_ENTRY_COUNTS.items()):
            sigs = [n for (e, _), n in _SIG_COUNTS.items() if e == entry]
            out[entry] = {
                "traces": total,
                "signatures": len(sigs),
                "retraces": sum(n - 1 for n in sigs),
            }
        return out


def reset_counts() -> None:
    """Zero the trace ledger.  Called by the deliberate cache-drop
    paths (``engine.clear_compile_caches``): the recompiles that follow
    a requested drop are expected, and charging them to the budget
    would turn a memory-hygiene call into a false storm."""
    global _TOTAL
    with _LOCK:
        _SIG_COUNTS.clear()
        _ENTRY_COUNTS.clear()
        _TOTAL = 0


# ---------------------------------------------------------------- signature


def _leaf_sig(x) -> Optional[str]:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return None
    weak = getattr(x, "weak_type", None)
    if weak is None:
        weak = getattr(getattr(x, "aval", None), "weak_type", False)
    dims = ",".join(str(d) for d in shape)
    return f"{dtype}[{dims}]" + ("~" if weak else "")


def _walk_sig(x, out) -> None:
    leaf = _leaf_sig(x)
    if leaf is not None:
        out.append(leaf)
        return
    if isinstance(x, (tuple, list)):
        for item in x:
            _walk_sig(item, out)
    elif isinstance(x, dict):
        for key in sorted(x):
            out.append(str(key))
            _walk_sig(x[key], out)
    elif isinstance(x, (int, float, bool, str, type(None))):
        out.append(repr(x))
    else:
        out.append(type(x).__name__)


def signature_of(args, kwargs, static=None) -> str:
    """Abstract signature of one trace: static config + per-leaf
    dtype/shape/weak-type.  Finer than jit's real cache key is safe
    (a genuine cache hit never reaches the wrapper at all); coarser
    would mint false retraces."""
    parts = []
    if static is not None:
        parts.append(f"static={static!r}")
    _walk_sig(tuple(args), parts)
    if kwargs:
        _walk_sig(dict(kwargs), parts)
    return ";".join(parts)


def _call_site() -> str:
    """First stack frame outside this module and outside JAX — the code
    that invoked the jit entry.  Only computed when armed (stack walks
    are not free)."""
    import traceback

    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename.replace("\\", "/")
        if ("/analysis/compileguard" in fn or "/jax/" in fn
                or "/jax_" in fn):
            continue
        return f"{fn.rsplit('/deppy_tpu/', 1)[-1]}:{frame.lineno}"
    return "?"


# ------------------------------------------------------------------ observe


def _bump(entry: str, sig: str) -> int:
    global _TOTAL
    with _LOCK:
        _TOTAL += 1
        _ENTRY_COUNTS[entry] = _ENTRY_COUNTS.get(entry, 0) + 1
        n = _SIG_COUNTS[(entry, sig)] = _SIG_COUNTS.get((entry, sig),
                                                        0) + 1
        return n


def _event(**fields) -> None:
    """Emit one ``compileguard`` sink event, stamped onto the active
    request trace when one is live (the lockdep pattern: the record
    must reach the sink even if a recovery catch swallows the raise)."""
    try:
        from .. import telemetry

        telemetry.default_registry().event("compileguard", **fields)
    except Exception:  # deppy: lint-ok[exception-hygiene] mid-teardown telemetry must not break tracing; the assertion below still fires
        pass


def observe(entry: str, fn, *, static=None, budget: Optional[int] = None):
    """Wrap ``fn`` for placement INSIDE a ``jax.jit``/``pjit`` boundary
    (``jax.jit(observe("core.batched_solve", vfn))``): the wrapper body
    runs once per trace, so each execution records one trace/compile
    event for ``entry``.  ``static`` is the entry's static
    configuration (factory arguments) — it joins the abstract signature
    so two factory instances over the same shapes stay distinct.
    ``budget`` declares the per-signature trace budget (default: see
    :func:`default_budget`)."""
    if budget is not None:
        declare_budget(entry, budget)

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        sig = signature_of(args, kwargs, static)
        n = _bump(entry, sig)
        armed = guard_enabled()
        if armed:
            allowed = budget_for(entry)
            if n > allowed:
                _event(violation="retrace-budget", entry=entry,
                       signature=sig, site=_call_site(), n_trace=n,
                       budget=allowed)
                raise CompileGuardError(
                    f"compileguard: entry `{entry}` traced signature "
                    f"{sig!r} {n} times (budget {allowed}) — a jit "
                    f"cache is being lost or rebuilt per call; see "
                    f"docs/analysis.md (compile-guard)")
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            _event(entry=entry, signature=sig, site=_call_site(),
                   n_trace=n, dur_s=round(time.perf_counter() - t0, 6))
            return out
        return fn(*args, **kwargs)

    return traced
