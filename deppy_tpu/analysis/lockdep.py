"""Runtime lock-order assertions (``DEPPY_TPU_LOCKDEP=1``).

The static concurrency checker sees lexical ``with`` nesting; this is
its runtime twin for the orders that only materialize through call
chains (pool lock → registry lock via a metrics call, breaker lock →
sink lock via an event).  The threaded subsystems create their locks
through the named factories below; with the mode armed, every
acquisition is checked against the process's observed acquisition-order
graph:

  * acquiring B while holding A records the edge A→B (by lock *name* —
    instances of the same subsystem lock share a name and an order);
  * a subsequent acquisition implying B→A (directly or through a
    path) raises :class:`LockdepError` **before** the threads can
    deadlock, and emits a ``lockdep`` event onto the telemetry sink —
    stamped onto the active request trace when one is live, so the
    violation is visible in the flight recorder and ``deppy trace``,
    not just a stderr traceback;
  * re-acquiring a non-reentrant lock on the same thread (self-
    deadlock) raises the same way.

Disarmed (the default), the factories return plain ``threading``
primitives — the hot paths (one registry-lock acquire per counter
increment) pay nothing.  Armed, acquisition costs one thread-local
list walk plus a dict probe per held lock; the chaos/sched/hostpool
suites run under it in CI (``make test-lockdep``).

Same-name nesting is exempt from ordering (two Registry instances
mirror families into each other under one shared name); self-deadlock
detection is by lock *identity*, so that exemption never masks a real
recursive acquire.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class LockdepError(AssertionError):
    """A lock-order inversion or self-deadlock caught before it hangs."""


def lockdep_enabled() -> bool:
    """Read ``DEPPY_TPU_LOCKDEP`` live (not cached): tests arm the mode
    and then construct fresh subsystems; module-level locks created at
    import time stay plain either way."""
    from .. import config

    return config.env_bool("DEPPY_TPU_LOCKDEP", False)


# Acquisition-order graph: (held_name, acquired_name) -> witness site.
_EDGES: Dict[Tuple[str, str], str] = {}
_EDGES_LOCK = threading.Lock()  # plain on purpose: lockdep's own lock
_TLS = threading.local()


def _held_stack() -> List["_LockdepLock"]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _reset_graph() -> None:
    """Drop the observed order graph (tests)."""
    with _EDGES_LOCK:
        _EDGES.clear()


def _path_exists(src: str, dst: str) -> Optional[List[str]]:
    """A recorded acquisition-order path src -> ... -> dst, if any
    (caller holds _EDGES_LOCK)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for (a, b) in _EDGES:
            if a == node and b not in seen:
                seen.add(b)
                stack.append((b, path + [b]))
    return None


def _violation(kind: str, **fields) -> None:
    """Emit the ``lockdep`` telemetry event, then raise.  The event
    goes first: the raise may be swallowed by a broad recovery catch,
    and the whole point is a record that survives to the sink / flight
    recorder.  ``_TLS.reporting`` suspends instrumentation while the
    event is written — the sink's own (instrumented) lock must not
    recurse into the checker."""
    _TLS.reporting = True
    try:
        from .. import telemetry

        telemetry.default_registry().event("lockdep", violation=kind,
                                           **fields)
    except Exception:  # deppy: lint-ok[exception-hygiene] the assertion below must fire even if telemetry is mid-teardown
        pass
    finally:
        _TLS.reporting = False
    detail = " ".join(f"{k}={v}" for k, v in fields.items())
    raise LockdepError(f"lockdep: {kind} ({detail})")


class _LockdepLock:
    """Order-checking proxy around one threading lock."""

    def __init__(self, inner, name: str, reentrant: bool):
        self._inner = inner
        self.name = name
        self._reentrant = reentrant

    # ------------------------------------------------------------ checks

    def _before_acquire(self) -> None:
        if getattr(_TLS, "reporting", False):
            # Violation reporting itself acquires instrumented locks
            # (the telemetry sink's): don't recurse into the checker.
            return
        stack = _held_stack()
        if not self._reentrant and any(h is self for h in stack):
            _violation("self-deadlock", lock=self.name)
        if any(h is self for h in stack):
            return  # reentrant re-acquire: no new ordering information
        held_names = []
        for h in stack:
            if h.name != self.name and h.name not in held_names:
                held_names.append(h.name)
        if not held_names:
            return
        # Decide under the graph lock, report AFTER releasing it: the
        # report path (telemetry event) acquires instrumented locks,
        # which would re-enter this checker and self-deadlock on the
        # plain _EDGES_LOCK.
        inversion = None
        with _EDGES_LOCK:
            for held in held_names:
                back = _path_exists(self.name, held)
                if back is not None:
                    inversion = (held, back)
                    break
                _EDGES.setdefault((held, self.name),
                                  f"{held} -> {self.name}")
        if inversion is not None:
            held, back = inversion
            _violation("order-inversion", lock=self.name, held=held,
                       observed_order=" -> ".join(back))

    # ------------------------------------------------------------ lock API

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._before_acquire()
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held_stack().append(self)
        return got

    def release(self) -> None:
        self._inner.release()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # Condition-variable integration (threading.Condition probes these
    # on its lock; delegate and keep the held stack truthful across
    # wait()'s release/re-acquire).

    def _is_owned(self):
        probe = getattr(self._inner, "_is_owned", None)
        if probe is not None:
            return probe()
        return any(h is self for h in _held_stack())

    def _release_save(self):
        save = getattr(self._inner, "_release_save", None)
        state = save() if save is not None else self._inner.release()
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        return state

    def _acquire_restore(self, state) -> None:
        restore = getattr(self._inner, "_acquire_restore", None)
        # Re-acquiring after a wait() re-enters at the BOTTOM of the
        # order (we held it before everything acquired since); skip the
        # order check — the wait itself proved no deadlock.
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        _held_stack().append(self)


# ------------------------------------------------------------- factories


def make_lock(name: str):
    """A named mutex: plain ``threading.Lock`` unless lockdep is armed."""
    if lockdep_enabled():
        return _LockdepLock(threading.Lock(), name, reentrant=False)
    return threading.Lock()


def make_rlock(name: str):
    """A named reentrant lock (the registry's render-under-lock
    pattern)."""
    if lockdep_enabled():
        return _LockdepLock(threading.RLock(), name, reentrant=True)
    return threading.RLock()


def make_condition(name: str):
    """A named condition variable (the scheduler's queue CV)."""
    if lockdep_enabled():
        return threading.Condition(
            _LockdepLock(threading.RLock(), name, reentrant=True))
    return threading.Condition()
