"""exception-hygiene: blind broad catches on the recovery path.

A ``try/except Exception: pass`` in a recovery layer converts a novel
failure into silence — no fault counter charged, no trace event
stamped, nothing in the flight recorder.  PR 2-6 built an entire
observability vocabulary for failures; this checker makes using it the
default.

A broad handler (bare ``except``, ``except Exception``, ``except
BaseException`` — alone or in a tuple) is flagged as a **blind
swallow** unless its body does at least one of:

  * re-``raise`` (the error propagates, typed or wrapped);
  * call an observability hook — ``Registry.event``, a counter's
    ``.inc``, a histogram's ``.observe``, ``fault_counter``,
    ``note_deadline_exceeded``, ``mark_error``, ``record_span``, a
    logger — so the failure lands on the telemetry sink / trace;
  * capture the failure into state another path surfaces — an
    ``Assign`` whose *value* references the bound exception or whose
    target is an attribute (``self._unavailable = ...``,
    ``g.error = e``).

``print`` deliberately does NOT count: stderr is invisible to the
flight recorder, ``/metrics``, and ``deppy trace`` — the exact gap
this checker exists to close.  Deliberately-silent sites (platform
probes whose failure IS the verdict) carry
``# deppy: lint-ok[exception-hygiene] reason`` suppressions.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional

from .core import Checker, Finding, SourceFile

_BROAD = {"Exception", "BaseException"}
_OBSERVABILITY_CALLS = {
    "event", "inc", "observe", "fault_counter", "note_deadline_exceeded",
    "mark_error", "record_span", "set", "warning", "error", "exception",
    "log", "dump",
}


def _is_broad(handler: ast.ExceptHandler) -> Optional[str]:
    """The broad type name this handler catches, or None."""
    t = handler.type
    if t is None:
        return "bare"
    names = []
    if isinstance(t, ast.Tuple):
        names = [e for e in t.elts]
    else:
        names = [t]
    for n in names:
        leaf = None
        if isinstance(n, ast.Name):
            leaf = n.id
        elif isinstance(n, ast.Attribute):
            leaf = n.attr
        if leaf in _BROAD:
            return leaf
    return None


def _handles(handler: ast.ExceptHandler) -> bool:
    """True when the handler body observably handles the failure."""
    exc_name = handler.name  # `as e` binding, may be None
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            leaf = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if leaf in _OBSERVABILITY_CALLS:
                return True
            # Handing the exception VALUE onward (errors.append(e),
            # queue.put(e)) is handling — someone re-raises or renders
            # it.  print is the one exception: stderr is exactly the
            # place the flight recorder cannot see.
            if leaf != "print" and exc_name is not None and any(
                    isinstance(sub, ast.Name) and sub.id == exc_name
                    for a in list(node.args)
                    + [k.value for k in node.keywords]
                    for sub in ast.walk(a)):
                return True
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in targets):
                return True  # state capture another path surfaces
            if exc_name is not None and any(
                    isinstance(sub, ast.Name) and sub.id == exc_name
                    for sub in ast.walk(node.value)):
                return True  # the exception value is kept
        if isinstance(node, ast.Return) and node.value is not None:
            # Returning a value DERIVED from the exception is handling;
            # returning a bare constant ("probe failed -> False") is a
            # verdict only when the site says so via suppression.
            if exc_name is not None and any(
                    isinstance(sub, ast.Name) and sub.id == exc_name
                    for sub in ast.walk(node.value)):
                return True
    return False


class ExceptionHygieneChecker(Checker):
    name = "exception-hygiene"
    default_scope = ("deppy_tpu",)

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        out: List[Finding] = []
        for sf in files:
            self._walk(out, sf, sf.tree, "<module>")
        return out

    def _walk(self, out: List[Finding], sf: SourceFile, node: ast.AST,
              func: str) -> None:
        for child in ast.iter_child_nodes(node):
            name = func
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
            if isinstance(child, ast.ExceptHandler):
                broad = _is_broad(child)
                if broad is not None and not _handles(child):
                    self.finding(
                        out, sf, child.lineno, "blind-swallow",
                        f"{func}:{broad}",
                        f"broad `except {broad}` in `{func}` swallows "
                        f"the failure with no fault counter, telemetry "
                        f"event, or re-raise — charge a counter / stamp "
                        f"an event, narrow the catch, or suppress with "
                        f"a reason")
            self._walk(out, sf, child, name)
