"""concurrency-discipline: shared state vs its guarding lock.

Scope: the threaded subsystems (``sched/``, ``faults/``, ``hostpool/``,
``telemetry/``) — the scheduler's dispatch loop, the pool's worker
multiplexer, the breaker, and the metrics registry all mutate state
that other threads read.  Three rules:

  * **guarded-attr access** — a mutable instance attribute (or module
    global) that is *ever* mutated under ``with <lock>:`` is mapped to
    that lock; any other *mutation* of it outside a lock context is
    flagged (``unlocked-write``), and plain reads outside a lock are
    flagged at lower confidence (``unlocked-read``) — a torn read of a
    multi-field invariant is the classic scheduler bug.  Methods named
    ``*_locked`` are the repo's caller-holds-the-lock convention and
    count as guarded context.
  * **lock-order** — ``with A: ... with B:`` records the edge A→B per
    lock *name*; a reverse edge anywhere across the scanned subsystems
    is a lock-order inversion (``lock-order``).  The runtime twin of
    this rule is :mod:`deppy_tpu.analysis.lockdep`.
  * **thread-local escape** — a ``threading.local()`` object handed to
    another thread (as a ``Thread``/``submit`` argument) reads the
    *receiving* thread's slots, which is how trace contexts silently
    vanish across a thread hop (``tls-escape``).  The sanctioned hop is
    value capture (``capture_parent`` / explicit Deadline objects).

The inference is deliberately syntactic: it sees ``with self._lock:``
blocks, not lock state through call chains — the registry's "families
share the registry lock and are only rendered under it" pattern is
invisible to it and rides the baseline with suppressions explaining
exactly that.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from .core import Checker, Finding, SourceFile
from .core import dotted as _dotted

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore", "make_lock", "make_rlock",
                   "make_condition"}
_MUTATOR_METHODS = {"append", "extend", "insert", "pop", "popleft",
                    "appendleft", "remove", "clear", "update",
                    "setdefault", "add", "discard"}



def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func) or ""
    return name.rsplit(".", 1)[-1] in _LOCK_FACTORIES


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X`` (one level only)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, module: str, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.locks: Set[str] = set()          # lock attr names
        self.guarded: Dict[str, str] = {}     # attr -> lock attr
        # (attr, lineno, is_write, method, locked) accesses
        self.accesses: List[Tuple[str, int, bool, str, bool]] = []


class ConcurrencyChecker(Checker):
    name = "concurrency-discipline"
    default_scope = ("deppy_tpu/sched", "deppy_tpu/faults",
                     "deppy_tpu/hostpool", "deppy_tpu/telemetry")

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        out: List[Finding] = []
        # lock-name -> lock-name ordered edges, with one witness site.
        edges: Dict[Tuple[str, str], Tuple[SourceFile, int]] = {}
        for sf in files:
            self._check_module(out, sf, edges)
        self._check_lock_order(out, edges)
        return out

    # ------------------------------------------------------------ classes

    def _check_module(self, out: List[Finding], sf: SourceFile,
                      edges) -> None:
        module = sf.rel
        module_locks: Set[str] = set()
        for node in sf.tree.body:
            if (isinstance(node, ast.Assign) and _is_lock_ctor(node.value)
                    and node.targets
                    and isinstance(node.targets[0], ast.Name)):
                module_locks.add(node.targets[0].id)
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                info = self._index_class(module, node, module_locks,
                                         sf, edges)
                self._flag_class(out, sf, info)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Module-level functions contribute lock-order edges on
                # the module's global locks (the singleton double-check
                # pattern lives here).
                self._module_fn_edges(node, module, module_locks, sf,
                                      edges)
        # Module-level thread-local escapes.
        tls_names = {
            t.targets[0].id for t in sf.tree.body
            if isinstance(t, ast.Assign) and t.targets
            and isinstance(t.targets[0], ast.Name)
            and isinstance(t.value, ast.Call)
            and (_dotted(t.value.func) or "").endswith("local")
        }
        if tls_names:
            self._check_tls_escape(out, sf, tls_names)

    def _index_class(self, module: str, node: ast.ClassDef,
                     module_locks: Set[str], sf: SourceFile,
                     edges) -> _ClassInfo:
        info = _ClassInfo(module, node)
        methods = [m for m in node.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        # Pass 1: lock attributes — assigned a lock constructor, or
        # named like one (`self._lock = lock` parameter passing: the
        # registry hands ONE lock to every metric family).
        for m in methods:
            for sub in ast.walk(m):
                if isinstance(sub, ast.Assign):
                    for t in sub.targets:
                        attr = _self_attr(t)
                        if attr and (_is_lock_ctor(sub.value)
                                     or attr.endswith("lock")
                                     or attr.endswith("_cv")):
                            info.locks.add(attr)
        # Pass 2: accesses with lock context, plus lock-order edges.
        for m in methods:
            caller_holds = m.name.endswith("_locked")
            self._walk_method(info, m, module_locks, caller_holds,
                              sf, edges)
        return info

    def _module_fn_edges(self, fn: ast.FunctionDef, module: str,
                         module_locks: Set[str], sf: SourceFile,
                         edges) -> None:
        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, ast.With):
                new_held = held
                for item in node.items:
                    name = _dotted(item.context_expr)
                    if name in module_locks:
                        ln = f"{module}:{name}"
                        for h in held:
                            if h != ln and (h, ln) not in edges:
                                edges[(h, ln)] = (sf, node.lineno)
                        new_held = new_held + (ln,)
                for child in node.body:
                    visit(child, new_held)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, ())

    def _walk_method(self, info: _ClassInfo, m: ast.FunctionDef,
                     module_locks: Set[str], caller_holds: bool,
                     sf: SourceFile, edges) -> None:
        lock_label = f"{info.module}:{info.name}"

        def lock_name_of(item_ctx: ast.AST) -> Optional[str]:
            attr = _self_attr(item_ctx)
            if attr and attr in info.locks:
                return f"{lock_label}.{attr}"
            name = _dotted(item_ctx)
            if name in module_locks:
                return f"{info.module}:{name}"
            return None

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, ast.With):
                new_held = held
                for item in node.items:
                    ln = lock_name_of(item.context_expr)
                    if ln is not None:
                        for h in held:
                            if h != ln and (h, ln) not in edges:
                                edges[(h, ln)] = (sf, node.lineno)
                        new_held = new_held + (ln,)
                for child in node.body:
                    visit(child, new_held)
                return
            # Record self-attr accesses at this nesting.
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _self_attr(t)
                    if attr and attr not in info.locks:
                        info.accesses.append(
                            (attr, node.lineno, True, m.name,
                             caller_holds or bool(held)))
                visit_children(node, held)
                return
            if isinstance(node, ast.Call):
                # self._x.append(...) and friends are writes.
                f = node.func
                if isinstance(f, ast.Attribute):
                    attr = _self_attr(f.value)
                    if (attr and attr not in info.locks
                            and f.attr in _MUTATOR_METHODS):
                        info.accesses.append(
                            (attr, node.lineno, True, m.name,
                             caller_holds or bool(held)))
                visit_children(node, held)
                return
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if (attr and attr not in info.locks
                        and isinstance(node.ctx, ast.Load)):
                    info.accesses.append(
                        (attr, node.lineno, False, m.name,
                         caller_holds or bool(held)))
                visit_children(node, held)
                return
            visit_children(node, held)

        def visit_children(node: ast.AST, held: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                # Nested defs get their own thread of control — a
                # closure run under the method's lock inherits it only
                # dynamically; stay conservative and keep held state
                # (closures here run inline under _solve_locked etc.).
                visit(child, held)

        for stmt in m.body:
            visit(stmt, ())

    def _flag_class(self, out: List[Finding], sf: SourceFile,
                    info: _ClassInfo) -> None:
        if not info.locks:
            return
        # An attribute is lock-guarded when some WRITE happens under a
        # lock outside __init__ (construction is single-threaded).
        guarded: Set[str] = set()
        for attr, _ln, is_write, meth, locked in info.accesses:
            if is_write and locked and meth != "__init__":
                guarded.add(attr)
        write_sites = {(attr, ln) for attr, ln, is_write, _m, _l
                       in info.accesses if is_write}
        for attr, ln, is_write, meth, locked in info.accesses:
            if attr not in guarded or locked or meth == "__init__":
                continue
            if not is_write and (attr, ln) in write_sites:
                continue  # the write finding already covers this site
            if is_write:
                self.finding(
                    out, sf, ln, "unlocked-write",
                    f"{info.name}.{attr}",
                    f"`self.{attr}` is written under a lock elsewhere "
                    f"but mutated without one in `{meth}` — guard it "
                    f"or rename the method `*_locked`")
            else:
                self.finding(
                    out, sf, ln, "unlocked-read",
                    f"{info.name}.{attr}",
                    f"`self.{attr}` is lock-guarded but read without "
                    f"the lock in `{meth}` — torn/stale reads cross "
                    f"threads here")

    # --------------------------------------------------------- lock order

    def _check_lock_order(self, out: List[Finding], edges) -> None:
        for (a, b), (sf, ln) in sorted(edges.items()):
            if (b, a) in edges:
                rsf, rln = edges[(b, a)]
                # Report each inversion once, from the lexically first
                # edge, naming the reverse witness.
                if (a, b) < (b, a):
                    self.finding(
                        out, sf, ln, "lock-order",
                        f"{a}<->{b}",
                        f"lock-order inversion: {a} -> {b} here but "
                        f"{b} -> {a} at {rsf.rel}:{rln} — one thread "
                        f"per order deadlocks")

    # --------------------------------------------------------- tls escape

    def _check_tls_escape(self, out: List[Finding], sf: SourceFile,
                          tls_names: Set[str]) -> None:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            target = _dotted(node.func) or ""
            leaf = target.rsplit(".", 1)[-1]
            if leaf not in ("Thread", "submit", "apply_async", "Process"):
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for sub in ast.walk(arg):
                    if (isinstance(sub, ast.Name)
                            and sub.id in tls_names):
                        self.finding(
                            out, sf, node.lineno, "tls-escape",
                            sub.id,
                            f"thread-local `{sub.id}` handed across a "
                            f"thread boundary — the receiving thread "
                            f"sees empty slots; capture the VALUE "
                            f"before the hop")
