"""registry-sync: string-keyed surfaces vs their typed registries.

The repo has four string-keyed surfaces whose drift was previously
caught only at runtime (or never): the test_doc_sync pattern — scan the
literals, pin them against the declaration — promoted from test into
lint:

  * **env knobs** — every ``DEPPY_TPU_*`` token anywhere in the tree
    (call sites, docstrings, helper strings) must be declared in
    :data:`deppy_tpu.config.REGISTRY` (``undeclared-env``), and every
    declared knob must still be mentioned by some code
    (``unused-env``);
  * **fault points** — every ``faults.inject("point")`` literal must
    be registered in :data:`deppy_tpu.faults.inject.KNOWN_POINTS`
    (``unknown-fault-point``), and every registered point must still
    have an inject site (``stale-fault-point``) — a fault plan written
    against a renamed point silently injects nothing;
  * **telemetry families** — a family name passed to
    ``faults.fault_counter`` / ``hostpool.metrics.gauge|counter|
    histogram`` must exist in its declaration dict
    (``unknown-family``) — today that's a runtime KeyError on the
    *recovery* path, the worst place to discover it;
  * **pytest markers** — every custom ``pytest.mark.X`` used under
    ``tests/`` must be registered in pyproject.toml's ``markers``
    (``unknown-marker``) — an unregistered marker silently drops out
    of ``-m`` tier selection.

The declaration side imports only the registry modules (config,
faults.metrics, hostpool.metrics) — none of them pull JAX.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Set

from .core import Checker, Finding, SourceFile

_ENV_TOKEN = re.compile(r"DEPPY_TPU_[A-Z0-9_]+")
# Builtin / plugin markers that need no registration.
_BUILTIN_MARKS = {"skip", "skipif", "xfail", "parametrize",
                  "usefixtures", "filterwarnings", "timeout"}


def _dotted(node: ast.AST):
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class RegistrySyncChecker(Checker):
    name = "registry-sync"
    default_scope = ("deppy_tpu", "scripts", "tests", "bench.py",
                     "__graft_entry__.py")

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        out: List[Finding] = []
        self._check_env(out, files)
        self._check_fault_points(out, files)
        self._check_families(out, files)
        self._check_markers(out, files, root)
        return out

    # ------------------------------------------------------------ env vars

    def _check_env(self, out: List[Finding],
                   files: List[SourceFile]) -> None:
        from .. import config

        mentioned: Set[str] = set()
        for sf in files:
            if sf.rel == "deppy_tpu/config.py":
                continue  # the registry itself
            for i, line in enumerate(sf.lines, start=1):
                for m in _ENV_TOKEN.finditer(line):
                    token = m.group(0)
                    if token.endswith("_"):
                        continue  # prose wildcard ("DEPPY_TPU_BREAKER_*")
                    mentioned.add(token)
                    if not config.declared(token):
                        self.finding(
                            out, sf, i, "undeclared-env", token,
                            f"`{token}` is not declared in "
                            f"deppy_tpu.config.REGISTRY — declare it "
                            f"(type, default, consumer, help) or fix "
                            f"the name")
        for name in sorted(set(config.REGISTRY) - mentioned):
            # Anchor registry-side findings on the registry file.
            reg_sf = next((f for f in files
                           if f.rel == "deppy_tpu/config.py"), None)
            if reg_sf is not None:
                line = next((i for i, text in enumerate(reg_sf.lines, 1)
                             if name in text), 1)
                self.finding(
                    out, reg_sf, line, "unused-env", name,
                    f"`{name}` is declared in config.REGISTRY but no "
                    f"code mentions it — dead knob or renamed reader")

    # -------------------------------------------------------- fault points

    def _check_fault_points(self, out: List[Finding],
                            files: List[SourceFile]) -> None:
        # NB: `from ..faults import inject` would resolve to the
        # inject() FUNCTION (faults/__init__ re-exports it, shadowing
        # the submodule) — import the submodule path explicitly.
        from ..faults.inject import KNOWN_POINTS

        known = set(KNOWN_POINTS)
        injected: Set[str] = set()
        for sf in files:
            if not sf.rel.startswith("deppy_tpu/"):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                target = _dotted(node.func) or ""
                if target.rsplit(".", 1)[-1] != "inject":
                    continue
                if (node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    point = node.args[0].value
                    injected.add(point)
                    if point not in known:
                        self.finding(
                            out, sf, node.lineno, "unknown-fault-point",
                            point,
                            f"fault point `{point}` is not registered "
                            f"in faults.inject.KNOWN_POINTS — plans "
                            f"written against it cannot be validated")
        inj_sf = next((f for f in files
                       if f.rel == "deppy_tpu/faults/inject.py"), None)
        for point in sorted(known - injected):
            # Dynamic points reach inject() through variables
            # (`_recovering(point="driver.dispatch")`, per-device
            # suffix globs): the point is live if its prefix appears
            # anywhere in package source outside the registry itself.
            prefix = point.rstrip("*").rstrip(".")
            if any(prefix in sf.text for sf in files
                   if sf.rel.startswith("deppy_tpu/")
                   and sf.rel != "deppy_tpu/faults/inject.py"):
                continue
            if inj_sf is not None:
                line = next((i for i, text in enumerate(inj_sf.lines, 1)
                             if f'"{point}"' in text), 1)
                self.finding(
                    out, inj_sf, line, "stale-fault-point", point,
                    f"registered fault point `{point}` has no "
                    f"inject() site — plans naming it silently "
                    f"inject nothing")

    # ---------------------------------------------------- telemetry families

    def _check_families(self, out: List[Finding],
                        files: List[SourceFile]) -> None:
        from ..faults import metrics as fmetrics
        from ..hostpool import metrics as hmetrics

        tables: Dict[str, Set[str]] = {
            "fault_counter": set(fmetrics.FAMILIES),
            "gauge": set(hmetrics.GAUGES),
            "counter": set(hmetrics.COUNTERS),
            "histogram": set(hmetrics.HISTOGRAMS),
        }
        for sf in files:
            if not sf.rel.startswith("deppy_tpu/"):
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call) and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                target = _dotted(node.func) or ""
                leaf = target.rsplit(".", 1)[-1]
                name = node.args[0].value
                if leaf == "fault_counter":
                    table = tables["fault_counter"]
                elif (leaf in ("gauge", "counter", "histogram")
                        and ("metrics." in target
                             or sf.rel == "deppy_tpu/hostpool/metrics.py")
                        and name.startswith("deppy_hostpool_")):
                    table = tables[leaf]
                else:
                    continue
                if name not in table:
                    self.finding(
                        out, sf, node.lineno, "unknown-family", name,
                        f"telemetry family `{name}` is not declared in "
                        f"its metrics table — this is a runtime "
                        f"KeyError on the recovery path")

    # ------------------------------------------------------------- markers

    def _check_markers(self, out: List[Finding], files: List[SourceFile],
                       root: Path) -> None:
        try:
            import tomllib
        except ImportError:  # py<3.11: fall back to a literal scan
            tomllib = None
        registered: Set[str] = set()
        pyproject = root / "pyproject.toml"
        if tomllib is not None and pyproject.exists():
            doc = tomllib.loads(pyproject.read_text(encoding="utf-8"))
            for entry in (doc.get("tool", {}).get("pytest", {})
                          .get("ini_options", {}).get("markers", [])):
                registered.add(str(entry).split(":", 1)[0].strip())
        elif pyproject.exists():
            in_markers = False
            for line in pyproject.read_text(encoding="utf-8").splitlines():
                if line.strip().startswith("markers"):
                    in_markers = True
                    continue
                if in_markers:
                    if line.strip().startswith("]"):
                        break
                    m = re.match(r'\s*"([a-zA-Z0-9_]+)\s*:', line)
                    if m:
                        registered.add(m.group(1))
        for sf in files:
            if not sf.rel.startswith("tests/"):
                continue
            for node in ast.walk(sf.tree):
                mark = self._mark_name(node)
                if (mark and mark not in _BUILTIN_MARKS
                        and mark not in registered):
                    self.finding(
                        out, sf, node.lineno, "unknown-marker", mark,
                        f"pytest marker `{mark}` is not registered in "
                        f"pyproject.toml [tool.pytest.ini_options] "
                        f"markers — it silently drops out of -m tier "
                        f"selection")

    @staticmethod
    def _mark_name(node: ast.AST):
        """``pytest.mark.X`` (bare or called) -> ``X``."""
        if isinstance(node, ast.Call):
            node = node.func
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "mark"
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "pytest"):
            return node.attr
        return None
