"""registry-sync: string-keyed surfaces vs their typed registries.

The repo has four string-keyed surfaces whose drift was previously
caught only at runtime (or never): the test_doc_sync pattern — scan the
literals, pin them against the declaration — promoted from test into
lint:

  * **env knobs** — every ``DEPPY_TPU_*`` token anywhere in the tree
    (call sites, docstrings, helper strings) must be declared in
    :data:`deppy_tpu.config.REGISTRY` (``undeclared-env``), and every
    declared knob must still be mentioned by some code
    (``unused-env``);
  * **fault points** — every ``faults.inject("point")`` literal must
    be registered in :data:`deppy_tpu.faults.inject.KNOWN_POINTS`
    (``unknown-fault-point``), and every registered point must still
    have an inject site (``stale-fault-point``) — a fault plan written
    against a renamed point silently injects nothing;
  * **telemetry families** — a family name passed to
    ``faults.fault_counter`` / ``hostpool.metrics.gauge|counter|
    histogram`` must exist in its declaration dict
    (``unknown-family``) — today that's a runtime KeyError on the
    *recovery* path, the worst place to discover it;
  * **pytest markers** — every custom ``pytest.mark.X`` used under
    ``tests/`` must be registered in pyproject.toml's ``markers``
    (``unknown-marker``) — an unregistered marker silently drops out
    of ``-m`` tier selection.

The declaration side imports only the registry modules (config,
faults.metrics, hostpool.metrics) — none of them pull JAX.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Set

from .core import Checker, Finding, SourceFile
from .core import dotted as _dotted

_ENV_TOKEN = re.compile(r"DEPPY_TPU_[A-Z0-9_]+")
_RE_CAMEL = re.compile(r"(?<!^)([A-Z])")  # camelCase -> snake boundary
# Builtin / plugin markers that need no registration.
_BUILTIN_MARKS = {"skip", "skipif", "xfail", "parametrize",
                  "usefixtures", "filterwarnings", "timeout"}



class RegistrySyncChecker(Checker):
    name = "registry-sync"
    default_scope = ("deppy_tpu", "scripts", "tests", "bench.py",
                     "__graft_entry__.py")

    def __init__(self, mirror_registry=None):
        # Tests seed a small registry; production uses the real one.
        self._mirror_registry = mirror_registry

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        out: List[Finding] = []
        self._check_env(out, files)
        self._check_fault_points(out, files)
        self._check_families(out, files)
        self._check_markers(out, files, root)
        self._check_mirrors(out, files)
        return out

    # ------------------------------------------------------------ env vars

    def _check_env(self, out: List[Finding],
                   files: List[SourceFile]) -> None:
        from .. import config

        mentioned: Set[str] = set()
        for sf in files:
            if sf.rel == "deppy_tpu/config.py":
                continue  # the registry itself
            for i, line in enumerate(sf.lines, start=1):
                for m in _ENV_TOKEN.finditer(line):
                    token = m.group(0)
                    if token.endswith("_"):
                        continue  # prose wildcard ("DEPPY_TPU_BREAKER_*")
                    mentioned.add(token)
                    if not config.declared(token):
                        self.finding(
                            out, sf, i, "undeclared-env", token,
                            f"`{token}` is not declared in "
                            f"deppy_tpu.config.REGISTRY — declare it "
                            f"(type, default, consumer, help) or fix "
                            f"the name")
        if self.partial:
            return  # a subset scan cannot prove a knob is unused
        for name in sorted(set(config.REGISTRY) - mentioned):
            # Anchor registry-side findings on the registry file.
            reg_sf = next((f for f in files
                           if f.rel == "deppy_tpu/config.py"), None)
            if reg_sf is not None:
                line = next((i for i, text in enumerate(reg_sf.lines, 1)
                             if name in text), 1)
                self.finding(
                    out, reg_sf, line, "unused-env", name,
                    f"`{name}` is declared in config.REGISTRY but no "
                    f"code mentions it — dead knob or renamed reader")

    # -------------------------------------------------------- fault points

    def _check_fault_points(self, out: List[Finding],
                            files: List[SourceFile]) -> None:
        # NB: `from ..faults import inject` would resolve to the
        # inject() FUNCTION (faults/__init__ re-exports it, shadowing
        # the submodule) — import the submodule path explicitly.
        from ..faults.inject import KNOWN_POINTS

        known = set(KNOWN_POINTS)
        injected: Set[str] = set()
        for sf in files:
            if not sf.rel.startswith("deppy_tpu/"):
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                target = _dotted(node.func) or ""
                if target.rsplit(".", 1)[-1] != "inject":
                    continue
                if (node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    point = node.args[0].value
                    injected.add(point)
                    if point not in known:
                        self.finding(
                            out, sf, node.lineno, "unknown-fault-point",
                            point,
                            f"fault point `{point}` is not registered "
                            f"in faults.inject.KNOWN_POINTS — plans "
                            f"written against it cannot be validated")
        if self.partial:
            return  # a subset scan cannot prove a point is stale
        inj_sf = next((f for f in files
                       if f.rel == "deppy_tpu/faults/inject.py"), None)
        for point in sorted(known - injected):
            # Dynamic points reach inject() through variables
            # (`_recovering(point="driver.dispatch")`, per-device
            # suffix globs): the point is live if its prefix appears
            # anywhere in package source outside the registry itself.
            prefix = point.rstrip("*").rstrip(".")
            if any(prefix in sf.text for sf in files
                   if sf.rel.startswith("deppy_tpu/")
                   and sf.rel != "deppy_tpu/faults/inject.py"):
                continue
            if inj_sf is not None:
                line = next((i for i, text in enumerate(inj_sf.lines, 1)
                             if f'"{point}"' in text), 1)
                self.finding(
                    out, inj_sf, line, "stale-fault-point", point,
                    f"registered fault point `{point}` has no "
                    f"inject() site — plans naming it silently "
                    f"inject nothing")

    # ---------------------------------------------------- telemetry families

    def _check_families(self, out: List[Finding],
                        files: List[SourceFile]) -> None:
        from ..faults import metrics as fmetrics
        from ..hostpool import metrics as hmetrics

        tables: Dict[str, Set[str]] = {
            "fault_counter": set(fmetrics.FAMILIES),
            "gauge": set(hmetrics.GAUGES),
            "counter": set(hmetrics.COUNTERS),
            "histogram": set(hmetrics.HISTOGRAMS),
        }
        for sf in files:
            if not sf.rel.startswith("deppy_tpu/"):
                continue
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call) and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                target = _dotted(node.func) or ""
                leaf = target.rsplit(".", 1)[-1]
                name = node.args[0].value
                if leaf == "fault_counter":
                    table = tables["fault_counter"]
                elif (leaf in ("gauge", "counter", "histogram")
                        and ("metrics." in target
                             or sf.rel == "deppy_tpu/hostpool/metrics.py")
                        and name.startswith("deppy_hostpool_")):
                    table = tables[leaf]
                else:
                    continue
                if name not in table:
                    self.finding(
                        out, sf, node.lineno, "unknown-family", name,
                        f"telemetry family `{name}` is not declared in "
                        f"its metrics table — this is a runtime "
                        f"KeyError on the recovery path")

    # ------------------------------------------------------------- markers

    def _check_markers(self, out: List[Finding], files: List[SourceFile],
                       root: Path) -> None:
        try:
            import tomllib
        except ImportError:  # py<3.11: fall back to a literal scan
            tomllib = None
        registered: Set[str] = set()
        pyproject = root / "pyproject.toml"
        if tomllib is not None and pyproject.exists():
            doc = tomllib.loads(pyproject.read_text(encoding="utf-8"))
            for entry in (doc.get("tool", {}).get("pytest", {})
                          .get("ini_options", {}).get("markers", [])):
                registered.add(str(entry).split(":", 1)[0].strip())
        elif pyproject.exists():
            in_markers = False
            for line in pyproject.read_text(encoding="utf-8").splitlines():
                if line.strip().startswith("markers"):
                    in_markers = True
                    continue
                if in_markers:
                    if line.strip().startswith("]"):
                        break
                    m = re.match(r'\s*"([a-zA-Z0-9_]+)\s*:', line)
                    if m:
                        registered.add(m.group(1))
        for sf in files:
            if not sf.rel.startswith("tests/"):
                continue
            for node in ast.walk(sf.tree):
                mark = self._mark_name(node)
                if (mark and mark not in _BUILTIN_MARKS
                        and mark not in registered):
                    self.finding(
                        out, sf, node.lineno, "unknown-marker", mark,
                        f"pytest marker `{mark}` is not registered in "
                        f"pyproject.toml [tool.pytest.ini_options] "
                        f"markers — it silently drops out of -m tier "
                        f"selection")

    # ------------------------------------------------------------ mirrors

    def _check_mirrors(self, out: List[Finding],
                       files: List[SourceFile]) -> None:
        """CLI flag <-> env var <-> config-file key, pinned both ways
        (ISSUE 8 satellite).  The registry declares each knob's mirrors
        (``EnvVar.flag`` / ``EnvVar.config_key``); ``deppy_tpu/cli.py``
        carries the actual ``add_argument`` flags and the
        ``_CONFIG_KEYS`` dict.  Drift in either direction is a finding:

          * ``missing-flag-mirror`` / ``missing-config-key`` — the
            registry declares a mirror cli.py no longer has;
          * ``undeclared-flag-mirror`` — an ``add_argument`` whose help
            names a ``DEPPY_TPU_*`` knob ("also via ..."), but the
            knob's declaration does not name that flag back;
          * ``undeclared-config-key`` — a ``_CONFIG_KEYS`` entry whose
            serve kwarg matches a flag-mirrored knob, with no
            ``config_key`` declared for it.
        """
        from .. import config

        registry = (self._mirror_registry if self._mirror_registry
                    is not None else config.REGISTRY)

        cli_sf = next((f for f in files
                       if f.rel == "deppy_tpu/cli.py"), None)
        if cli_sf is None:
            # --changed run that did not touch cli.py: presence can't
            # be proven from a subset, and absence findings would all
            # be false.
            return

        flags: Dict[str, int] = {}          # --flag -> line
        flag_envs: Dict[str, Set[str]] = {}  # --flag -> env names in help
        for node in ast.walk(cli_sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith("--")):
                continue
            flag = node.args[0].value
            flags[flag] = node.lineno
            help_text = ""
            for kw in node.keywords:
                if kw.arg == "help":
                    help_text = "".join(
                        sub.value for sub in ast.walk(kw.value)
                        if isinstance(sub, ast.Constant)
                        and isinstance(sub.value, str))
            # Only the "also via <ENV>" convention marks a MIRROR; a
            # help string merely mentioning a knob (trace --file's
            # "default: $DEPPY_TPU_TELEMETRY_FILE") is not one.
            envs: Set[str] = set()
            for seg in help_text.split("also via")[1:]:
                envs.update(m.group(0)
                            for m in _ENV_TOKEN.finditer(seg)
                            if not m.group(0).endswith("_"))
            flag_envs[flag] = envs

        config_keys: Dict[str, int] = {}
        for node in ast.walk(cli_sf.tree):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "_CONFIG_KEYS"
                            for t in node.targets)
                    and isinstance(node.value, ast.Dict)):
                for key in node.value.keys:
                    if (isinstance(key, ast.Constant)
                            and isinstance(key.value, str)):
                        config_keys[key.value] = key.lineno

        declared_flags = {v.flag: v.name
                          for v in registry.values() if v.flag}
        declared_keys = {v.config_key: v.name
                         for v in registry.values()
                         if v.config_key}

        # Registry -> cli.py direction.
        reg_sf = next((f for f in files
                       if f.rel == "deppy_tpu/config.py"), None)

        def _reg_line(token: str) -> int:
            if reg_sf is None:
                return 1
            return next((i for i, text in enumerate(reg_sf.lines, 1)
                         if token in text), 1)

        anchor = reg_sf or cli_sf
        for flag, env in sorted(declared_flags.items()):
            if flag not in flags:
                self.finding(
                    out, anchor, _reg_line(env), "missing-flag-mirror",
                    f"{env}:{flag}",
                    f"`{env}` declares CLI mirror `{flag}` but cli.py "
                    f"has no such add_argument — the flag was removed "
                    f"or renamed without updating the registry")
        for key, env in sorted(declared_keys.items()):
            if key not in config_keys:
                self.finding(
                    out, anchor, _reg_line(env), "missing-config-key",
                    f"{env}:{key}",
                    f"`{env}` declares config-file mirror `{key}` but "
                    f"cli.py's _CONFIG_KEYS has no such entry")

        # cli.py -> registry direction: the "also via <env knob>"
        # help convention must be declared back.
        for flag, envs in sorted(flag_envs.items()):
            for env in sorted(envs):
                if env not in registry:
                    continue  # undeclared-env already fired
                if registry[env].flag != flag:
                    self.finding(
                        out, cli_sf, flags[flag],
                        "undeclared-flag-mirror", f"{flag}:{env}",
                        f"`{flag}`'s help names `{env}` but the knob's "
                        f"registry declaration does not name "
                        f"`{flag}` as its flag mirror — declare "
                        f"flag=\"{flag}\" on the EnvVar (or fix the "
                        f"help text)")
        # _CONFIG_KEYS -> registry: a camelCase key whose snake-cased
        # form matches a declared knob must be declared back as that
        # knob's config_key.  Matching is by shared prefix either way
        # (longest declared knob wins), not exact reconstruction —
        # `requestDeadlineSeconds` must find DEPPY_TPU_REQUEST_
        # DEADLINE_S even though the spellings differ.  Keys with no
        # env twin (bindAddress, backend) are legitimately
        # registry-free.
        knob_roots = {name[len("DEPPY_TPU_"):]: name
                      for name in registry}
        for key, line in sorted(config_keys.items()):
            snake = _RE_CAMEL.sub(r"_\1", key).upper()
            env = None
            for root_name in sorted(knob_roots, key=len, reverse=True):
                # Exact, or the key extends the knob (SECONDS vs _S
                # suffix drift); a short key must NOT claim a longer
                # knob (`sched` is not `SCHED_MAX_WAIT_MS`'s key).
                if snake == root_name or (len(root_name) > 4
                                          and snake.startswith(
                                              root_name)):
                    env = knob_roots[root_name]
                    break
            if env is None:
                continue
            if registry[env].config_key != key:
                self.finding(
                    out, cli_sf, line, "undeclared-config-key",
                    f"{key}:{env}",
                    f"config key `{key}` mirrors `{env}` but the "
                    f"knob's registry declaration does not name it — "
                    f"declare config_key=\"{key}\" on the EnvVar")

    @staticmethod
    def _mark_name(node: ast.AST):
        """``pytest.mark.X`` (bare or called) -> ``X``."""
        if isinstance(node, ast.Call):
            node = node.func
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "mark"
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "pytest"):
            return node.attr
        return None
