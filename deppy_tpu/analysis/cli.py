"""``deppy lint`` — run the checkers, diff against the baseline.

Exit codes: 0 = clean vs baseline, 1 = new findings (or stale baseline
keys under ``--strict-baseline``), 2 = usage.  ``--github`` prints
workflow annotation lines for new findings so sanity CI marks the
exact source lines in the PR diff.
"""

from __future__ import annotations

import json
import sys
from typing import List

from .core import (Baseline, Finding, baseline_path, changed_files,
                   repo_root, run_checkers)


def run_lint(args) -> int:
    from pathlib import Path

    root = repo_root()
    paths = None
    changed = getattr(args, "changed", None)
    if changed is not None:
        if args.update_baseline:
            # A subset scan would be saved as THE baseline, erasing
            # every accepted key in unscanned files.
            print("error: --changed cannot be combined with "
                  "--update-baseline (baseline bookkeeping needs the "
                  "full scan)", file=sys.stderr)
            return 2
        try:
            paths = changed_files(root, changed)
        except RuntimeError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if not paths:
            print(f"0 finding(s) — no files changed vs {changed}")
            return 0
    try:
        findings = run_checkers(root, names=args.checker, paths=paths)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    bpath = Path(args.baseline) if args.baseline else baseline_path()
    if args.update_baseline:
        updated = Baseline.from_findings(findings)
        if args.checker is not None:
            # Partial run: replace only the selected checkers' keys —
            # the other checkers' accepted findings were not re-scanned
            # and must survive the rewrite.
            try:
                prior = Baseline.load(bpath)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(f"error: cannot load baseline {bpath}: {e}",
                      file=sys.stderr)
                return 2
            prefixes = tuple(f"{c}:" for c in args.checker)
            for key, count in prior.counts.items():
                if not key.startswith(prefixes):
                    updated.counts[key] = count
        updated.save(bpath)
        print(f"baseline updated: {len(updated.counts)} key(s) -> "
              f"{bpath}")
        return 0
    if args.no_baseline:
        baseline = Baseline()
    else:
        try:
            baseline = Baseline.load(bpath)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: cannot load baseline {bpath}: {e}",
                  file=sys.stderr)
            return 2
    new, stale = baseline.diff(findings)
    if changed is not None:
        # A subset scan can't prove a baseline key's finding is gone.
        stale = []
    partial = args.checker is not None and not args.no_baseline
    if partial:
        # A single-checker run must not report every OTHER checker's
        # baseline keys as stale.
        prefixes = tuple(f"{c}:" for c in args.checker)
        stale = [k for k in stale if k.startswith(prefixes)]

    if args.json:
        json.dump({
            "findings": [f.to_dict() for f in findings],
            "new": [f.to_dict() for f in new],
            "stale_baseline_keys": stale,
            "baseline": str(bpath),
        }, sys.stdout, indent=2)
        print()
    else:
        _render_text(findings, new, stale)
    if args.github:
        for f in new:
            # GitHub annotation format; the message must be one line.
            msg = f"[{f.checker}/{f.code}] {f.message}".replace("\n", " ")
            print(f"::warning file={f.path},line={f.line}::{msg}")
    if new:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


def _render_text(findings: List[Finding], new: List[Finding],
                 stale: List[str]) -> None:
    new_keys = {id(f) for f in new}
    for f in findings:
        marker = "NEW " if id(f) in new_keys else "     "
        print(f"{marker}{f.render()}")
    if stale:
        print(f"\n{len(stale)} stale baseline key(s) — findings fixed "
              f"but still accepted; run `deppy lint --update-baseline` "
              f"to burn them down:")
        for k in stale:
            print(f"  {k}")
    print(f"\n{len(findings)} finding(s), {len(new)} new vs baseline"
          + (f", {len(stale)} stale baseline key(s)" if stale else ""))
