"""trace-purity: host effects and sync hazards inside traced code.

Any function reachable from a ``jit`` / ``pjit`` / ``shard_map`` /
``pallas_call`` wrapping (or used as a ``lax`` control-flow body) runs
under a JAX trace.  Three hazard classes hide there:

  * **host side effects** — ``print``/``open``/``os.environ`` inside a
    traced function fire once per *compile*, not per call: silent at
    steady state, misleading during debugging, and a recompile tell;
  * **wall clock / randomness** — ``time.*`` and Python-level
    ``random`` are baked in at trace time; the value the author thinks
    is per-call is a compile-time constant (the trip-overhead model in
    ROADMAP item 3 measures dispatch wall clock *around* traced code
    for exactly this reason);
  * **device syncs / tracer branching** — ``.item()`` /
    ``np.asarray`` / ``.tolist()`` / ``block_until_ready`` force a
    host round-trip per call, and a Python ``if``/``while`` on a
    ``jnp``/``lax`` expression either recompiles per value or raises
    a ``TracerBoolConversionError`` in production shapes that never
    ran in tests.

The call graph is module-local and name-based (the engine's traced
kernels are module functions calling module functions), which keeps
the checker dependency-free and the false-positive surface small; the
baseline absorbs deliberate exceptions (each carries a suppression
with its reason where the hazard is intended, e.g. interpret-mode
debugging helpers).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Set

from .core import Checker, Finding, SourceFile
from .core import dotted as _dotted

# Call spellings that make their function argument(s) traced code.
_TRACING_WRAPPERS = {
    "jit", "pjit", "shard_map", "pallas_call", "vmap", "grad",
    "value_and_grad", "checkify", "custom_vjp", "custom_jvp", "scan",
    "while_loop", "cond", "fori_loop", "switch", "remat", "checkpoint",
}
# Attribute roots that mark an expression as a device-tensor expression.
_TENSOR_ROOTS = {"jnp", "lax", "pltpu", "pl"}

# (qualified-call -> code slug).  Matched against the dotted name of a
# Call's func (``time.perf_counter``, ``np.asarray``, ...).
_HOST_CALLS = {
    "print": "host-effect",
    "open": "host-effect",
    "input": "host-effect",
    "os.environ.get": "host-effect",
    "os.getenv": "host-effect",
    "os.system": "host-effect",
    "time.time": "wall-clock",
    "time.monotonic": "wall-clock",
    "time.perf_counter": "wall-clock",
    "time.sleep": "wall-clock",
    "datetime.datetime.now": "wall-clock",
    "random.random": "randomness",
    "random.randint": "randomness",
    "random.choice": "randomness",
    "np.random.default_rng": "randomness",
    "np.asarray": "device-sync",
    "np.array": "device-sync",
    "numpy.asarray": "device-sync",
    "numpy.array": "device-sync",
    "jax.device_get": "device-sync",
}
# Method names that force a device→host sync on whatever they hang off.
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}



def _func_names_in(node: ast.AST, known: Set[str]) -> Set[str]:
    """Names of module functions referenced anywhere inside ``node``
    (the argument expression of a tracing wrapper)."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in known:
            out.add(sub.id)
    return out


class _ModuleIndex:
    """Per-module function table, call graph, and traced entry set."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.funcs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Last definition wins (overloads by platform guard);
                # name-keyed on purpose — the engine's kernels are
                # module-level functions.
                self.funcs[node.name] = node
        self.calls: Dict[str, Set[str]] = {}
        for name, fn in self.funcs.items():
            callees: Set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    target = _dotted(sub.func)
                    if target in self.funcs:
                        callees.add(target)
                    # A local function handed onward as a value (e.g.
                    # functools.partial(body_fn, ...)) stays traced.
                    for arg in list(sub.args) + [k.value
                                                 for k in sub.keywords]:
                        callees |= _func_names_in(
                            arg, set(self.funcs))
            self.calls[name] = callees
        self.entries = self._traced_entries()

    def _traced_entries(self) -> Set[str]:
        known = set(self.funcs)
        entries: Set[str] = set()
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.Call):
                target = _dotted(node.func) or ""
                leaf = target.rsplit(".", 1)[-1]
                if leaf in _TRACING_WRAPPERS:
                    for arg in list(node.args) + [k.value
                                                  for k in node.keywords]:
                        entries |= _func_names_in(arg, known)
        for name, fn in self.funcs.items():
            for dec in fn.decorator_list:
                target = _dotted(dec if not isinstance(dec, ast.Call)
                                 else dec.func) or ""
                leaf = target.rsplit(".", 1)[-1]
                if leaf in _TRACING_WRAPPERS or (
                        isinstance(dec, ast.Call)
                        and leaf == "partial"
                        and any((_dotted(a) or "").rsplit(".", 1)[-1]
                                in _TRACING_WRAPPERS
                                for a in dec.args)):
                    entries.add(name)
        return entries

    def reachable(self) -> Set[str]:
        seen: Set[str] = set()
        stack = list(self.entries)
        while stack:
            name = stack.pop()
            if name in seen:
                continue
            seen.add(name)
            stack.extend(self.calls.get(name, ()))
        return seen


# Static array metadata: branching on these is trace-time Python, not
# a tracer branch (shapes/dtypes are concrete during tracing).
_STATIC_ATTRS = {"dtype", "shape", "ndim", "size"}
_DTYPE_NAMES = {"bool_", "int8", "int16", "int32", "int64", "uint8",
                "uint16", "uint32", "uint64", "float16", "float32",
                "float64", "bfloat16"}


def _is_tensor_expr(node: ast.AST) -> bool:
    """Heuristic: the expression is (or contains) a device-tensor
    computation — a call or attribute rooted at jnp/lax/pltpu — and is
    not a static shape/dtype check."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return False
    for sub in ast.walk(node):
        if not isinstance(sub, (ast.Attribute, ast.Name)):
            continue
        root = _dotted(sub)
        if not root or root.split(".", 1)[0] not in _TENSOR_ROOTS:
            continue
        if root.rsplit(".", 1)[-1] in _DTYPE_NAMES:
            continue  # jnp.int32 as a dtype constant, not a tensor
        return True
    return False


class TracePurityChecker(Checker):
    name = "trace-purity"
    default_scope = ("deppy_tpu",)

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        out: List[Finding] = []
        for sf in files:
            index = _ModuleIndex(sf)
            traced = index.reachable()
            for fname in sorted(traced):
                self._check_function(out, sf, fname, index.funcs[fname])
        return out

    def _check_function(self, out: List[Finding], sf: SourceFile,
                        fname: str, fn: ast.FunctionDef) -> None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                target = _dotted(node.func)
                if target in _HOST_CALLS:
                    self.finding(
                        out, sf, node.lineno, _HOST_CALLS[target],
                        f"{fname}:{target}",
                        f"`{target}(...)` inside traced function "
                        f"`{fname}` — runs at trace time (once per "
                        f"compile), not per call")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _SYNC_METHODS):
                    self.finding(
                        out, sf, node.lineno, "device-sync",
                        f"{fname}:.{node.func.attr}",
                        f"`.{node.func.attr}()` inside traced function "
                        f"`{fname}` forces a device→host sync per call")
            elif isinstance(node, (ast.If, ast.While)):
                if _is_tensor_expr(node.test):
                    kind = ("if" if isinstance(node, ast.If)
                            else "while")
                    self.finding(
                        out, sf, node.lineno, "tracer-branch",
                        f"{fname}:{kind}",
                        f"Python `{kind}` on a jnp/lax expression "
                        f"inside traced function `{fname}` — branches "
                        f"on a tracer (recompile per value or "
                        f"TracerBoolConversionError); use lax.cond/"
                        f"lax.while_loop or jnp.where")
