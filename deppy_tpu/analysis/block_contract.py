"""block-contract: Pallas BlockSpec/grid arithmetic vs the driver's
declared size classes.

The three Pallas kernels (:mod:`..engine.pallas_bcp`,
:mod:`..engine.pallas_blockwise`, :mod:`..engine.pallas_search`) and
the driver's padding economics share a set of numeric contracts that
today only fail on real hardware (Mosaic rejections) or as silent
padding waste (ROADMAP item 3's "a 64-clause problem pays the
4096-clause pad").  This checker evaluates them statically, per
declared size class, against the kernel/driver sources:

  * ``smem-budget`` — per ``pallas_call`` in the fused search module,
    the number of whole-column ``(B, 1)`` SMEM specs
    (``_smem_scalars``) x ``B=4096`` (the widest lane width
    ``scripts/lane_probe.py`` probes, the ``test_mosaic_lowering``
    regression anchor) x 4 bytes must stay under
    :data:`SMEM_BUDGET_BYTES`, and the column count under
    :data:`MAX_SMEM_COLS`;
  * ``smem-per-row-block`` — an SMEM ``BlockSpec`` with a ``(1, 1)``
    block indexed per grid step: the exact shape Mosaic rejected on
    first hardware compile (2026-08-01 — a block's last two dims must
    be (8, 128)-divisible or equal to the array's).  Permanent
    regression rule for the ``_smem_scalars`` fix;
  * ``block-pad-waste`` — the blockwise kernel's row padding per size
    class: ``br = min(BLOCK_ROWS, C)`` rounded to the 8-sublane
    quantum, then ``C`` padded to a multiple — the pad fraction must
    stay under :data:`BLOCK_PAD_WASTE_MAX` (driver buckets ``C`` to a
    power of two, so a contract-respecting ``BLOCK_ROWS`` divides it
    exactly);
  * ``missing-sublane-round`` — the blockwise kernel must still carry
    the 8-sublane round-up (same 2026-08-01 hardware rejection class);
  * ``padding-waste`` — the ladder's size-class economics: adjacent
    declared classes must differ by at least ``SPLIT_RATIO`` in padded
    cost (else the partitioner could never separate them and the small
    class pays the large class's pad), and the worst within-class cell
    waste under power-of-two bucketing must stay under
    :data:`CLASS_WASTE_MAX`;
  * ``bank-budget`` — the watched-impl clause bank
    (:mod:`..engine.clause_bank`): each class's adjacency tables at its
    declared ``OCC`` cap (``2·V·OCC + NV·OCC`` int32 cells) must fit
    the same VMEM residency budget as the clause planes — a class
    whose bank cannot be resident belongs on the dense rounds, not on
    a silently-thrashing bank;
  * ``contract-drift`` — a source constant this checker evaluates
    (the shared size-class table, ``_smem_scalars``, the sublane
    round) is gone or moved: the contract can no longer be checked,
    which is itself a finding, not a silent pass.

The size classes come from the SHARED ladder
(:mod:`deppy_tpu.size_classes` — import-light, stdlib only), which the
driver's partitioner consumes too (ISSUE 12): the lint contracts and
the runtime economics read one table and can never drift.  Beyond that
import, pure stdlib ``ast`` arithmetic: no JAX, evaluable in CI before
a backend exists.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional

from .core import Checker, Finding, SourceFile
from .core import dotted as _dotted

from .. import size_classes as _shared

# Declared size classes: the SHARED ladder (deppy_tpu.size_classes) the
# driver's partitioner consumes.  C = clause rows, NV = problem vars,
# NCON = applied constraints; V = NV + NCON, Wv = ceil(V / 32) bitplane
# words; OCC = the watched bank's occurrence cap.
SIZE_CLASSES: Dict[str, Dict[str, int]] = _shared.SIZE_CLASSES
# Widest per-problem batch the SMEM scalar columns are probed at
# (scripts/lane_probe.py; tests/test_mosaic_lowering.py B=4096 anchor).
SMEM_ANCHOR_B = 4096
SMEM_BUDGET_BYTES = 128 * 1024
MAX_SMEM_COLS = 8
# Fused-fixpoint VMEM residency: dominant term 2*C*Wv*4 (pos+neg), with
# 2x slack for the member/assignment planes, under the ~16 MiB/core
# budget the pallas_bcp docstring declares.
VMEM_BUDGET_BYTES = 16 * 1024 * 1024
BLOCK_PAD_WASTE_MAX = 0.25
# Power-of-two bucketing bounds each padded dim below 2x its live size;
# clause-cell waste compounds across the row and word dims.
CLASS_WASTE_MAX = 0.75

_BCP = "deppy_tpu/engine/pallas_bcp.py"
_BLOCKWISE = "deppy_tpu/engine/pallas_blockwise.py"
_SEARCH = "deppy_tpu/engine/pallas_search.py"
_DRIVER = "deppy_tpu/engine/driver.py"
_LADDER = "deppy_tpu/size_classes.py"
_BANK = "deppy_tpu/engine/clause_bank.py"


# Cost arithmetic comes from the shared ladder module — the checker
# must evaluate the SAME model the driver partitions by, or the
# economics findings go stale against a retuned proxy.
_wv = _shared.wv
_cost = _shared.class_cost


def _module_const(sf: SourceFile, name: str):
    """Top-level ``NAME = <literal>`` value, or None."""
    for stmt in sf.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == name):
            try:
                return ast.literal_eval(stmt.value)
            except ValueError:
                return None
    return None



class BlockContractChecker(Checker):
    name = "block-contract"
    default_scope = ("deppy_tpu/engine", "deppy_tpu/parallel",
                     "deppy_tpu/size_classes.py")

    def __init__(self, size_classes: Optional[Dict[str, Dict[str, int]]]
                 = None):
        self.size_classes = size_classes or SIZE_CLASSES

    def check(self, files: List[SourceFile], root: Path) -> List[Finding]:
        out: List[Finding] = []
        by_rel = {sf.rel: sf for sf in files}
        if _SEARCH in by_rel:
            self._check_smem(out, by_rel[_SEARCH])
        if _BLOCKWISE in by_rel:
            self._check_blockwise(out, by_rel[_BLOCKWISE])
        if _BCP in by_rel:
            self._check_vmem(out, by_rel[_BCP])
        if _BANK in by_rel:
            self._check_bank(out, by_rel[_BANK])
        for rel in (_BCP, _BLOCKWISE):
            if rel in by_rel:
                self._check_per_row_smem(out, by_rel[rel])
        if _DRIVER in by_rel and not self.partial:
            # Class economics need the ladder's constants: skip on
            # --changed runs that did not touch the driver.
            self._check_classes(out, by_rel.get(_LADDER),
                                by_rel[_DRIVER])
        return out

    # ----------------------------------------------------- SMEM columns

    def _check_smem(self, out: List[Finding], sf: SourceFile) -> None:
        has_scalars_helper = any(
            isinstance(n, ast.FunctionDef) and n.name == "_smem_scalars"
            for n in ast.walk(sf.tree))
        if not has_scalars_helper:
            self.finding(
                out, sf, 1, "contract-drift", "_smem_scalars",
                "pallas_search no longer defines `_smem_scalars` — the "
                "SMEM column contract (B=4096 anchor) cannot be "
                "evaluated; update block_contract.py with the new "
                "spelling")
            return
        for fn in (n for n in ast.walk(sf.tree)
                   if isinstance(n, ast.FunctionDef)):
            # Local names bound to a whole-column scalar spec.
            scalar_cols = {
                t.id
                for stmt in ast.walk(fn)
                if isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Call)
                and (_dotted(stmt.value.func) or "").endswith(
                    "_smem_scalars")
                for t in stmt.targets if isinstance(t, ast.Name)}
            if not scalar_cols:
                continue
            for call in ast.walk(fn):
                if not (isinstance(call, ast.Call)
                        and (_dotted(call.func) or "").endswith(
                            "pallas_call")):
                    continue
                n_cols = 0
                for kw in call.keywords:
                    if kw.arg in ("in_specs", "out_specs"):
                        for sub in ast.walk(kw.value):
                            if (isinstance(sub, ast.Name)
                                    and sub.id in scalar_cols):
                                n_cols += 1
                col_bytes = n_cols * SMEM_ANCHOR_B * 4
                if n_cols > MAX_SMEM_COLS or col_bytes > SMEM_BUDGET_BYTES:
                    self.finding(
                        out, sf, call.lineno, "smem-budget",
                        f"{fn.name}:{n_cols}",
                        f"pallas_call in `{fn.name}` maps {n_cols} "
                        f"whole-column (B, 1) scalar specs into SMEM — "
                        f"{col_bytes} bytes at the probed B="
                        f"{SMEM_ANCHOR_B} anchor (budget "
                        f"{SMEM_BUDGET_BYTES}, max {MAX_SMEM_COLS} "
                        f"columns); see tests/test_mosaic_lowering.py")
        self._check_per_row_smem(out, sf)

    def _check_per_row_smem(self, out: List[Finding],
                            sf: SourceFile) -> None:
        """The 2026-08-01 Mosaic rejection, as a permanent rule: an SMEM
        BlockSpec with a (1, 1) block whose index_map moves with the
        grid — the per-problem scalar block every phase kernel failed
        on before `_smem_scalars`."""
        for call in ast.walk(sf.tree):
            if not (isinstance(call, ast.Call)
                    and (_dotted(call.func) or "").endswith("BlockSpec")):
                continue
            in_smem = any(
                kw.arg == "memory_space"
                and (_dotted(kw.value) or "").endswith("SMEM")
                for kw in call.keywords)
            if not in_smem or not call.args:
                continue
            try:
                block = ast.literal_eval(call.args[0])
            except ValueError:
                continue
            if block != (1, 1) or len(call.args) < 2:
                continue
            index_map = call.args[1]
            if not isinstance(index_map, ast.Lambda):
                continue
            grid_args = {a.arg for a in index_map.args.args}
            moves = any(isinstance(sub, ast.Name) and sub.id in grid_args
                        for sub in ast.walk(index_map.body))
            if moves:
                self.finding(
                    out, sf, call.lineno, "smem-per-row-block",
                    "BlockSpec(1,1)",
                    "SMEM BlockSpec with a (1, 1) block indexed per "
                    "grid step — Mosaic requires a block's last two "
                    "dims be (8, 128)-divisible or equal to the "
                    "array's (the 2026-08-01 hardware rejection); map "
                    "the whole (B, 1) column and index with "
                    "pl.program_id (see pallas_search._smem_scalars)")

    # ------------------------------------------------------- blockwise

    def _check_blockwise(self, out: List[Finding],
                         sf: SourceFile) -> None:
        if "(br + 7) // 8" not in sf.text:
            self.finding(
                out, sf, 1, "missing-sublane-round", "bcp_fixpoint",
                "the blockwise kernel no longer rounds its block rows "
                "to the 8-sublane quantum — Mosaic rejects blocks whose "
                "second-to-minor dim is not 8-divisible (2026-08-01 "
                "hardware compile); restore the round-up or teach "
                "block_contract.py the new spelling")
        from .. import config

        default = config.REGISTRY["DEPPY_TPU_BLOCK_ROWS"].default \
            if "DEPPY_TPU_BLOCK_ROWS" in config.REGISTRY else None
        if not isinstance(default, int):
            self.finding(
                out, sf, 1, "contract-drift", "DEPPY_TPU_BLOCK_ROWS",
                "DEPPY_TPU_BLOCK_ROWS has no integer default in "
                "config.REGISTRY — the blockwise pad-waste contract "
                "cannot be evaluated")
            return
        for cname, cls in sorted(self.size_classes.items()):
            C = cls["C"]
            br = min(default, C)
            br = max(8 * ((br + 7) // 8), 8)
            padded = C + (-C) % br
            waste = (padded - C) / padded
            if waste > BLOCK_PAD_WASTE_MAX:
                self.finding(
                    out, sf, 1, "block-pad-waste", f"{cname}:{C}",
                    f"size class `{cname}` (C={C}) pays "
                    f"{waste:.0%} row padding under BLOCK_ROWS="
                    f"{default} (block {br}, padded {padded}) — over "
                    f"the {BLOCK_PAD_WASTE_MAX:.0%} bound; a "
                    f"64-clause problem must not pay a 4096-row pad "
                    f"(ROADMAP item 3)")

    # ------------------------------------------------------------ VMEM

    def _check_vmem(self, out: List[Finding], sf: SourceFile) -> None:
        for cname, cls in sorted(self.size_classes.items()):
            # pos + neg planes dominate; 2x slack covers the member/
            # activation/assignment residents (the module docstring's
            # budget model).
            resident = 2 * cls["C"] * _wv(cls) * 4 * 2
            if resident > VMEM_BUDGET_BYTES:
                self.finding(
                    out, sf, 1, "vmem-budget", f"{cname}:{cls['C']}",
                    f"size class `{cname}` needs ~{resident} bytes of "
                    f"resident clause planes (2*C*Wv*4 with 2x slack) "
                    f"— past the {VMEM_BUDGET_BYTES} VMEM budget the "
                    f"fused fixpoint kernel declares; route this class "
                    f"to the blockwise kernel")

    # ------------------------------------------------------------ banks

    def _check_bank(self, out: List[Finding], sf: SourceFile) -> None:
        """Watched-impl bank residency (ISSUE 12): each class's
        adjacency tables at its declared OCC cap — occ_pos + occ_neg
        (2·V·OCC) plus card_occ (NV·OCC, Oc bounded by OCC) int32
        cells — must fit the same VMEM budget the clause planes
        declare, with 2x slack for the planes resident beside them."""
        for cname, cls in sorted(self.size_classes.items()):
            occ = cls.get("OCC")
            if not isinstance(occ, int):
                self.finding(
                    out, sf, 1, "contract-drift", f"{cname}:OCC",
                    f"size class `{cname}` declares no integer OCC cap "
                    f"in deppy_tpu.size_classes — the watched-bank "
                    f"residency contract cannot be evaluated")
                continue
            V = cls["NV"] + cls["NCON"]
            resident = (2 * V * occ + cls["NV"] * occ) * 4 * 2
            if resident > VMEM_BUDGET_BYTES:
                self.finding(
                    out, sf, 1, "bank-budget", f"{cname}:{occ}",
                    f"size class `{cname}`'s clause bank needs "
                    f"~{resident} bytes at its OCC={occ} cap (2x slack "
                    f"over (2V+NV)·OCC·4) — past the "
                    f"{VMEM_BUDGET_BYTES} residency budget; lower the "
                    f"class's OCC cap (dispatches past it already fall "
                    f"back to the dense rounds)")

    # ------------------------------------------------- class economics

    def _check_classes(self, out: List[Finding],
                       ladder_sf: Optional[SourceFile],
                       driver_sf: SourceFile) -> None:
        # SPLIT_RATIO lives in the shared ladder module (ISSUE 12);
        # scans without it (checker-test fixtures) fall back to a
        # driver-source literal, the pre-ladder spelling.
        sf = ladder_sf if ladder_sf is not None else driver_sf
        split_ratio = _module_const(sf, "SPLIT_RATIO")
        if not isinstance(split_ratio, (int, float)):
            self.finding(
                out, sf, 1, "contract-drift", "SPLIT_RATIO",
                "SPLIT_RATIO is no longer a module literal in "
                "deppy_tpu/size_classes.py (or the fixture driver) — "
                "the size-class separability contract cannot be "
                "evaluated")
            return
        if ladder_sf is not None and "size_classes" not in driver_sf.text:
            self.finding(
                out, driver_sf, 1, "contract-drift", "size_classes",
                "the driver no longer references the shared "
                "deppy_tpu.size_classes ladder — its partitioner and "
                "these contracts can drift apart")
            return
        ordered = sorted(self.size_classes.items(),
                         key=lambda kv: _cost(kv[1]))
        for (a_name, a), (b_name, b) in zip(ordered, ordered[1:]):
            ratio = _cost(b) / max(_cost(a), 1)
            if ratio < split_ratio:
                self.finding(
                    out, sf, 1, "padding-waste",
                    f"{a_name}->{b_name}",
                    f"size classes `{a_name}` and `{b_name}` differ by "
                    f"only {ratio:.2f}x in padded cost — below "
                    f"driver.SPLIT_RATIO={split_ratio}, so "
                    f"partition_buckets can never separate them and "
                    f"every `{a_name}` problem pays `{b_name}`'s pad")
        for cname, cls in ordered:
            # Worst live problem in the class: one past the previous
            # power-of-two bucket in every dim.
            live = {k: v // 2 + 1 for k, v in cls.items()}
            waste = 1.0 - _cost(live) / _cost(cls)
            if waste > CLASS_WASTE_MAX:
                self.finding(
                    out, sf, 1, "padding-waste", f"{cname}:cell-waste",
                    f"size class `{cname}`'s worst-case cell waste is "
                    f"{waste:.0%} — past the {CLASS_WASTE_MAX:.0%} "
                    f"bound the power-of-two bucketing is supposed to "
                    f"guarantee")
