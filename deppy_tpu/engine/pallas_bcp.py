"""Fused BCP-fixpoint Pallas TPU kernel.

The hot op of the whole framework is boolean-constraint propagation: every
DPLL iteration (engine/core.py:dpll) runs BCP to fixpoint, and each round is
a full pass over the clause set.  The jnp "bits" path already turns that
pass into dense bitplane algebra, but XLA still streams the clause planes
from HBM **once per round**.  This kernel instead pins the positive/negative
literal planes, the AtMost member planes, and the assignment words in VMEM
and iterates the fixpoint *inside* the kernel — clause data crosses
HBM→VMEM once per fixpoint, not once per round.  That is the TPU-native
replacement for the watched-literal scheme gini uses to avoid re-touching
clauses (the reference delegates BCP to gini's CDCL engine; see SURVEY.md
§2.6): where a CPU solver avoids memory traffic with pointers, a TPU kernel
avoids it with residency.

All planes are int32 (Mosaic has no unsigned reductions); bit extraction
uses logical shifts, so the sign bit is just bit 31.  The row dimensions
(C, NA) are padded to powers of two by the driver, which the halving-tree
OR-reduction in :func:`deppy_tpu.engine.core.round_planes` relies on.

Batch use: the caller vmaps :func:`bcp_fixpoint`; Pallas lifts the batch
axis into a grid dimension, so each grid step solves one problem's fixpoint
with its planes resident in VMEM.

Measured reality (v5-lite, 256-problem random-catalog batch, warm): the jnp
"bits" path wins — 368 solves/s vs 206/s for this kernel — because under
vmap XLA vectorizes the *batch* axis of the bitplane algebra across the
8×128 VPU lanes, while the kernel's grid serializes problems.  The kernel
is therefore opt-in (``DEPPY_TPU_BCP=pallas``), aimed at single problems
whose clause planes approach VMEM capacity, where per-round HBM streaming
is the bottleneck instead.

VMEM budget: the dominant term is (pos + neg) = 2·C·Wv·4 bytes.  At the
default caps (C ≤ 8192 clause rows, Wv ≤ 128 words = 4096 vars) that is
8 MiB, within the ~16 MiB/core budget; the driver's padding economics keep
real catalog problems far below it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import core


def _kernel(minw_ref, en_ref, pos_ref, neg_ref, mem_ref, act_ref, cardn_ref,
            min_ref, t0_ref, f0_ref, conf_ref, t_ref, f_ref):
    pos = pos_ref[:]
    neg = neg_ref[:]
    mem = mem_ref[:]
    card_active = act_ref[:] != 0    # [NA, 1] row-activity mask
    card_n2 = cardn_ref[:]
    min_bits = min_ref[:]
    min_w = minw_ref[0, 0]

    def cond(state):
        conflict, _, _, changed = state
        return changed & ~conflict

    def body(state):
        _, t, f, _ = state
        return core.round_planes(
            pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f
        )

    # The lane-gating flag seeds `changed`: a disabled lane runs zero
    # rounds (see core.bcp).
    state = (jnp.bool_(False), t0_ref[:], f0_ref[:], en_ref[0, 0] != 0)
    conflict, t, f, _ = lax.while_loop(cond, body, state)
    conf_ref[0, 0] = conflict.astype(jnp.int32)
    t_ref[:] = t
    f_ref[:] = f


def bcp_fixpoint(pos, neg, mem, card_active, card_n2, min_bits, min_w, t0, f0,
                 enabled=True):
    """Run BCP to fixpoint on bitplanes.  Shapes as in
    :func:`deppy_tpu.engine.core.round_planes` (``card_active`` is the
    precomputed [NA, 1] row-activity mask); returns (conflict, t, f).
    Interprets on non-TPU backends so the same code path is testable on the
    CPU mesh used by the test suite."""
    Wv = pos.shape[1]
    minw2 = jnp.full((1, 1), min_w, jnp.int32)
    en2 = jnp.full((1, 1), enabled, jnp.int32)
    act = card_active.astype(jnp.int32)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    smem = pl.BlockSpec((1, 1), memory_space=pltpu.SMEM)
    conf, t, f = pl.pallas_call(
        _kernel,
        out_shape=(
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, Wv), jnp.int32),
            jax.ShapeDtypeStruct((1, Wv), jnp.int32),
        ),
        in_specs=[
            smem, smem,
            vmem, vmem, vmem, vmem, vmem, vmem, vmem, vmem,
        ],
        out_specs=(
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
            vmem,
            vmem,
        ),
        interpret=jax.default_backend() != "tpu",
    )(minw2, en2, pos, neg, mem, act, card_n2, min_bits, t0, f0)
    return conf[0, 0] != 0, t, f
