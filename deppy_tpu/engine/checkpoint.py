"""Group-wise checkpoint/resume for fleet-scale batch solves.

The reference has no persistence — every solve is stateless from scratch
(a fresh engine per ``NewSolver``, reference solve.go:122) and its only
failure-recovery mechanism is operational (leader election + liveness
probes, main.go:51-81).  For a framework whose unit of work is a 10k-problem
fleet batch on an accelerator, that is not enough: a worker crash mid-batch
(a real failure mode on tunneled TPU workers — see engine/driver.py
MAX_LANES) should not void an hour of completed chunks.

This module checkpoints at the natural boundary the chunked driver already
has: groups of ``group`` problems.  Each completed group's results are
written to ``<dir>/group_<i>.npz`` together with a fingerprint of the
problem batch; re-running the same batch with the same directory loads
completed groups and solves only the remainder.  The fingerprint covers
every problem's lowered tensors, so a changed batch never resumes from
stale results (the directory is then ignored for reading and rewritten).

Results round-trip exactly: ``SolveResult`` is a NamedTuple of numpy
arrays, stacked per group on save and unstacked on load.
"""

from __future__ import annotations

import hashlib
import json
import os
import zipfile
from typing import List, Optional, Sequence

import numpy as np

from .. import faults
from ..sat.encode import Problem
from . import core, driver


def batch_fingerprint(problems: Sequence[Problem]) -> str:
    """Stable content hash of a lowered problem batch (order-sensitive)."""
    h = hashlib.blake2b(digest_size=16)
    h.update(str(len(problems)).encode())
    for p in problems:
        for a in (p.clauses, p.card_ids, p.card_n, p.card_act, p.anchors,
                  p.choice_cand, p.var_choices):
            # Shape + dtype delimit each array: identical bytes under a
            # different padding (e.g. clauses [2,2] vs [1,4]) must not
            # collide, and neither may adjacent arrays' concatenation.
            h.update(repr((a.shape, str(a.dtype))).encode())
            h.update(np.ascontiguousarray(a).tobytes())
        h.update(np.int64([p.n_vars, p.n_cons]).tobytes())
    return h.hexdigest()


def _meta_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, "batch.json")


def _group_path(ckpt_dir: str, i: int) -> str:
    return os.path.join(ckpt_dir, f"group_{i:05d}.npz")


def _pad_to(a: np.ndarray, shape: tuple) -> np.ndarray:
    """Zero-pad ``a`` up to ``shape`` (same rank).  Decode reads masks by
    live index (< n_vars / n_cons), so zero padding is outcome-neutral."""
    if a.shape == shape:
        return a
    out = np.zeros(shape, dtype=a.dtype)
    out[tuple(slice(0, s) for s in a.shape)] = a
    return out


def _save_group(ckpt_dir: str, i: int, results: List[core.SolveResult]) -> None:
    # fault point: a scripted crash here models the real failure this
    # module exists for — the process dying between completed groups
    # (tests/test_checkpoint.py resumes across exactly this).
    faults.inject("checkpoint.save_group")
    arrays = {}
    for f in core.SolveResult._fields:
        vals = [np.asarray(getattr(r, f)) for r in results]
        # Results within one group normally share their bucket's padded
        # dims, but the fault layer can split a failing group or route
        # part of it to the host engine, leaving mixed widths — pad to
        # the widest so the stack (and the resume load) stays exact.
        widest = tuple(max(v.shape[k] for v in vals)
                       for k in range(vals[0].ndim))
        arrays[f] = np.stack([_pad_to(v, widest) for v in vals])
    tmp = _group_path(ckpt_dir, i) + ".tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
        fh.flush()
        os.fsync(fh.fileno())  # data on disk before the rename points at it
    os.replace(tmp, _group_path(ckpt_dir, i))


def _load_group(ckpt_dir: str, i: int, n: int) -> Optional[List[core.SolveResult]]:
    path = _group_path(ckpt_dir, i)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            arrays = {f: z[f] for f in core.SolveResult._fields}
    except (OSError, ValueError, KeyError, EOFError, zipfile.BadZipFile):
        return None  # torn/stale file: recompute the group
    if arrays["outcome"].shape[0] != n:
        return None
    return [
        core.SolveResult(*[arrays[f][j] for f in core.SolveResult._fields])
        for j in range(n)
    ]


def solve_problems_checkpointed(
    problems: Sequence[Problem],
    ckpt_dir: str,
    group: int = 0,
    max_steps: Optional[int] = None,
    mesh=None,
) -> List[core.SolveResult]:
    """:func:`deppy_tpu.engine.driver.solve_problems` with group-wise
    resume.  ``group`` = problems per checkpoint unit (default: the
    driver's per-dispatch lane cap, so one group ≈ one device dispatch).

    Semantics match ``solve_problems`` exactly — per-problem results in
    input order; groups are solved independently, which also bounds the
    padded shape blowup like the driver's size-class bucketing (a group
    never pads to a straggler outside it)."""
    if group <= 0:
        group = driver.MAX_LANES
    os.makedirs(ckpt_dir, exist_ok=True)
    fp = batch_fingerprint(problems)
    # max_steps is part of the key: results computed under a different
    # step budget (e.g. Incomplete at a tiny cap) must not resume.
    meta = {"fingerprint": fp, "n": len(problems), "group": group,
            "max_steps": max_steps}
    meta_ok = False
    try:
        with open(_meta_path(ckpt_dir)) as fh:
            meta_ok = json.load(fh) == meta
    except (OSError, ValueError):
        pass
    if not meta_ok:
        # Different batch (or fresh dir): drop stale groups, write meta.
        for name in os.listdir(ckpt_dir):
            if name.startswith("group_") and name.endswith(".npz"):
                os.unlink(os.path.join(ckpt_dir, name))
        tmp = _meta_path(ckpt_dir) + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(meta, fh)
        os.replace(tmp, _meta_path(ckpt_dir))

    out: List[Optional[core.SolveResult]] = [None] * len(problems)
    resumed = 0
    # ambient_deadline here (not just inside each driver call) so the
    # per-group persistence check below sees the env-configured batch
    # deadline too, not only a caller-installed scope.
    with faults.ambient_deadline() as dl:
        for gi, lo in enumerate(range(0, len(problems), group)):
            chunk = list(problems[lo: lo + group])
            cached = (_load_group(ckpt_dir, gi, len(chunk))
                      if meta_ok else None)
            if cached is None:
                cached = driver.solve_problems(chunk, max_steps=max_steps,
                                               mesh=mesh)
                # A group computed after the batch deadline expired may
                # be deadline-degraded (Incomplete with zero work done)
                # — never persist it: the meta key covers the step
                # budget but not the wall clock, and a resume without
                # the deadline must re-solve these groups, not inherit
                # their degradation.
                if dl is None or not dl.expired():
                    _save_group(ckpt_dir, gi, cached)
            else:
                resumed += len(chunk)
            out[lo: lo + len(chunk)] = cached
    if resumed:
        import sys

        print(f"[checkpoint] resumed {resumed}/{len(problems)} problems "
              f"from {ckpt_dir}", file=sys.stderr)
    return out  # type: ignore[return-value]
