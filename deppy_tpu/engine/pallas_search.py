"""Fused phase-1 search: the whole guess search in ONE Pallas kernel.

Round-3 root cause (BASELINE.md "where the TPU search time goes"): on the
tunneled v5e chip every ``lax.while_loop`` trip costs ~175µs of
scheduling against ~10µs of useful plane algebra, and the search phase
is *made of* while-loop trips — episode control steps, DPLL decisions,
and propagation rounds each pay one.  The batched XLA path therefore
loses to its own CPU fallback on 4 of 6 suite configs (round-3 verdict
weak #1).  This module is the escalation the verdict prescribes: the
entire phase-1 program of :func:`deppy_tpu.engine.core.search_phase` —
baseline fixpoint, episode control loop, inlined DPLL leaves
(decide + propagate + backtrack), budget accounting — runs INSIDE one
``pallas_call``, where the loops are Mosaic-native ``scf.while`` on the
scalar core with zero per-trip dispatch cost.  One kernel invocation per
problem per PHASE, not per round: hundreds of trips collapse into one.

Batch shape: the kernel runs one problem per grid step (grid=(B,)).
Grid steps serialize on a TPU core, which costs the batch-axis VPU
vectorization the jnp "bits" path enjoys — the round-3 measurement that
kept the *fixpoint* kernel opt-in (core.py:398-406).  The bet here is
different: the fused program eliminates ~17× per-trip overhead on every
trip of every loop, far more than the lost lane parallelism on the small
[C, Wr] planes of catalog problems (a full per-problem search is tens of
µs of VPU work vs tens of ms of XLA trip overhead).  Like every other
device bet in this tree it stays **opt-in until measured on the real
chip** (``DEPPY_TPU_SEARCH=fused``; `scripts/tpu_ab.py` carries the
variant) — on CPU XLA the serialized grid is a measured-class loser.

Mosaic constraints shape the implementation:

* No dynamic gathers/scatters: every ``arr[idx]`` / ``arr.at[idx].set``
  of the XLA formulation becomes one-hot select algebra over a
  broadcasted iota (an out-of-range index then matches nothing, which
  reproduces ``mode="drop"`` exactly).
* No (N,1)↔(1,N) relayouts: per-slot bookkeeping vectors live in lane
  orientation [1, N]; the only sublane-indexed arrays are the snapshot
  trails [levels, Wr], written with [levels, 1] row selectors.
* Small static tables (choice candidates Kc, per-var choice lists W)
  are walked with statically unrolled scalar loops — pure scalar-core
  code, no layout hazards.  :func:`fused_supported` gates on their size.
* Tracing (T > 0) stays on the XLA path; the kernel still counts
  backtracks (``tr_n``) so stats-only tracers keep working.

Semantics are pinned by differential tests against
:func:`core.batched_search` (bit-identical results, models, guessed
sets, step counts) — the same three-implementations strategy the BCP
kernels use (tests/test_bcp_impls.py, SURVEY.md §4).

Reference parity: this is still gini ``Solve()`` + the guess loop of
search.go:158-203 / solve.go:53-85 — only the execution substrate moved
into the kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis import compileguard
from . import core

WORD = core.WORD

# Static-unroll caps for the scalar-loop table walks (see module
# docstring).  Catalog lowerings sit far below both; exotic shapes fall
# back to the XLA path via fused_supported().
MAX_KC = 64
MAX_W = 32


def _smem_scalars(B: int) -> "pl.BlockSpec":
    """Whole-column SMEM spec for per-problem ``(B, 1)`` scalars.

    Mosaic requires a block's last two dims to be (8, 128)-divisible or
    equal to the array's, so the natural per-problem (1, 1) block over a
    (B, 1) scalar column is rejected (first hardware compile, 2026-08-01:
    every phase kernel failed exactly here).  Instead every grid step
    maps the whole column into SMEM and the kernel indexes its own row
    with ``pl.program_id(0)`` — SMEM scalar loads/stores are cheap, and
    because the TPU grid is sequential the per-step single-element
    writes compose into the full (B, 1) output.
    """
    return pl.BlockSpec((B, 1), lambda b: (0, 0),
                        memory_space=pltpu.SMEM)


# --------------------------------------------------------------------------
# one-hot indexing primitives (Mosaic-safe dynamic indexing)


def _rows_iota(n: int) -> jax.Array:
    return lax.broadcasted_iota(jnp.int32, (n, 1), 0)


def _lanes_iota(n: int) -> jax.Array:
    return lax.broadcasted_iota(jnp.int32, (1, n), 1)


def _row_read(arr: jax.Array, idx) -> jax.Array:
    """arr [N, W], scalar idx → [1, W] row (zeros when idx not in range)."""
    sel = _rows_iota(arr.shape[0]) == idx
    return core.tree_sum(jnp.where(sel, arr, 0), axis=0, keepdims=True)


def _row_write(arr: jax.Array, idx, row: jax.Array, gate=True) -> jax.Array:
    """Write [1, W] ``row`` at ``idx`` when ``gate``; out-of-range drops."""
    sel = (_rows_iota(arr.shape[0]) == idx) & gate
    return jnp.where(sel, row, arr)


def _lane_read(row: jax.Array, idx) -> jax.Array:
    """row [1, N], scalar idx → scalar (0 when idx not in range)."""
    sel = _lanes_iota(row.shape[1]) == idx
    return core.tree_sum(jnp.where(sel, row, 0))


def _lane_write(row: jax.Array, idx, val, gate=True) -> jax.Array:
    sel = (_lanes_iota(row.shape[1]) == idx) & gate
    return jnp.where(sel, val, row)


def _set_bit(plane: jax.Array, var, on) -> jax.Array:
    """Set bit ``var`` in packed [1, Wv] plane when ``on`` (the kernel
    twin of :func:`core.set_plane_bit`); out-of-range var drops."""
    word = var // WORD
    bit = jnp.int32(1) << (var % WORD)
    sel = (_lanes_iota(plane.shape[1]) == word) & on
    return jnp.where(sel, plane | bit, plane)


def _get_bit(plane: jax.Array, var) -> jax.Array:
    """Bit ``var`` of a packed [1, Wv] plane as 0/1 (0 when out of range)."""
    word = _lane_read(plane, var // WORD)
    return core._srl(word, var % WORD) & 1


def _clear_bit(plane: jax.Array, var, on) -> jax.Array:
    word = var // WORD
    bit = jnp.int32(1) << (var % WORD)
    sel = (_lanes_iota(plane.shape[1]) == word) & on
    return jnp.where(sel, plane & ~bit, plane)


# --------------------------------------------------------------------------
# in-kernel fixpoint / outcome (bits impl, no dispatch)


def _fixpoint(pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f,
              run, card_act_bits=None):
    """:func:`core.planes_fixpoint`'s bits path, inlined: same
    pre-conflict overlap check, same round kernel, no impl dispatch and
    no unroll knob (there is no per-trip dispatch cost to amortize in
    here).

    ``card_act_bits`` (full plane space only — the phase-3 kernel):
    cardinality-row activity is NOT static there — a row is active iff
    its owning constraint's activation literal is TRUE in the ENTRY
    assignment, so it must be derived from ``t`` per fixpoint call
    (core.planes_fixpoint's full-space branch); the static
    ``card_active`` argument is ignored when it is given.  Reduced-space
    callers (phases 1-2) keep passing the static ``card_valid`` mask."""
    if card_act_bits is not None:
        card_active = ((card_act_bits & t) != 0).any(axis=1, keepdims=True)
    pre_conflict = run & ((t & f) != 0).any()
    go = run & ~pre_conflict

    def cond(s):
        c, _, _, ch = s
        return ~c & ch

    def body(s):
        _, t, f, _ = s
        return core.round_planes(
            pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f
        )

    c, t, f, _ = lax.while_loop(cond, body, (jnp.bool_(False), t, f, go))
    return c | pre_conflict, t, f


def _first_unassigned(pvb, t, f):
    """(has_unassigned, lowest unassigned problem var) from packed planes
    — the kernel twin of dpll's packed-bit first-unassigned scan."""
    un = (pvb & ~(t | f))
    nz = un != 0
    has_un = nz.any()
    Wr = un.shape[1]
    wi = core.tree_min(jnp.where(nz, _lanes_iota(Wr), Wr)).astype(jnp.int32)
    word = _lane_read(un, wi)
    lsb = word & -word
    return has_un, wi * WORD + core.popcount32(lsb - 1)


# --------------------------------------------------------------------------
# DPLL (kernel twin of core.dpll, reduced plane space)


def _dpll(pos, neg, mem, card_active, card_n2, pvb, t_init, f_init,
          min_bits, min_w, budget, steps, NV: int, enabled,
          card_act_bits=None):
    """Complete search under a fixed partial assignment — the kernel twin
    of :func:`core.dpll` (gini Solve(), search.go:168; solve.go:107):
    false-first decisions on the lowest unassigned problem var,
    chronological backtracking, trail-style snapshots.  State layouts are
    the one-hot orientations (dec arrays [1, NV], snapshots [NV+1, Wr]);
    the decision order, phases, models, and step counts are bit-identical
    to core.dpll (pinned by tests/test_pallas_search.py)."""
    Wr = pos.shape[1]
    lvl = _lanes_iota(NV)

    conflict0, t0, f0 = _fixpoint(
        pos, neg, mem, card_active, card_n2, min_bits, min_w,
        t_init, f_init, enabled, card_act_bits,
    )
    status0 = jnp.where(conflict0, jnp.int32(core.UNSAT),
                        jnp.int32(core.RUNNING))
    snap_t0 = _row_write(jnp.zeros((NV + 1, Wr), jnp.int32), 0, t0)
    snap_f0 = _row_write(jnp.zeros((NV + 1, Wr), jnp.int32), 0, f0)

    def body(st):
        (dec_var, dec_phase, sp, flip, status, m_t, m_f,
         snap_t, snap_f, steps) = st
        t = _row_read(snap_t, jnp.clip(sp, 0, NV))
        f = _row_read(snap_f, jnp.clip(sp, 0, NV))

        has_un, first_un = _first_unassigned(pvb, t, f)
        sat_now = ~flip & ~has_un
        status = jnp.where(sat_now, jnp.int32(core.SAT), status)
        m_t = jnp.where(sat_now, t, m_t)
        m_f = jnp.where(sat_now, f, m_f)

        do_step = status == core.RUNNING
        var = jnp.where(flip, _lane_read(dec_var, jnp.clip(sp, 0, NV - 1)),
                        first_un)
        neg_phase = ~flip
        dv_idx = jnp.where(do_step & ~flip, jnp.clip(sp, 0, NV - 1), NV)
        dec_var = _lane_write(dec_var, dv_idx, var)
        dec_phase = _lane_write(dec_phase, dv_idx, jnp.int32(core.FALSE))
        fl_idx = jnp.where(do_step & flip, jnp.clip(sp, 0, NV - 1), NV)
        dec_phase = _lane_write(dec_phase, fl_idx, jnp.int32(core.TRUE))

        t2 = _set_bit(t, var, do_step & ~neg_phase)
        f2 = _set_bit(f, var, do_step & neg_phase)
        conflict, t3, f3 = _fixpoint(
            pos, neg, mem, card_active, card_n2, min_bits, min_w,
            t2, f2, do_step, card_act_bits,
        )

        ok = do_step & ~conflict
        sidx = jnp.where(ok, jnp.clip(sp + 1, 0, NV), NV + 1)
        snap_t = _row_write(snap_t, sidx, t3)
        snap_f = _row_write(snap_f, sidx, f3)

        tot = ok & (((pvb & ~(t3 | f3)) == 0).all())
        status = jnp.where(tot, jnp.int32(core.SAT), status)
        m_t = jnp.where(tot, t3, m_t)
        m_f = jnp.where(tot, f3, m_f)

        cand = (lvl <= sp) & (dec_phase == core.FALSE)
        bt_l = core.tree_max(jnp.where(cand, lvl, -1))
        no_bt = bt_l < 0
        bt = do_step & conflict & ~no_bt
        status = jnp.where(do_step & conflict & no_bt,
                           jnp.int32(core.UNSAT), status)
        sp = jnp.where(ok, sp + 1, jnp.where(bt, bt_l, sp))
        flip = jnp.where(ok, jnp.bool_(False),
                         jnp.where(bt, jnp.bool_(True), flip))
        steps = steps + do_step.astype(jnp.int32)
        return (dec_var, dec_phase, sp, flip, status, m_t, m_f,
                snap_t, snap_f, steps)

    def cond(st):
        status, steps = st[4], st[9]
        return enabled & (status == core.RUNNING) & (steps <= budget)

    st = (
        jnp.zeros((1, NV), jnp.int32),
        jnp.zeros((1, NV), jnp.int32),
        jnp.int32(0),
        jnp.bool_(False),
        status0,
        t0, f0,
        snap_t0, snap_f0,
        steps,
    )
    (_, _, _, _, status, m_t, m_f, _, _, steps) = lax.while_loop(
        cond, body, st)
    return status, m_t, m_f, steps


# --------------------------------------------------------------------------
# the fused phase-1 kernel


def _kernel(en_ref, na_ref, budget_ref,
            pos_ref, neg_ref, mem_ref, cardn_ref, cardv_ref,
            choice_ref, varch_ref, t0p_ref, f0p_ref, pvb_ref,
            out0_ref, res_ref, steps_ref, trn_ref,
            t0o_ref, f0o_ref, asm_ref, mt_ref, mf_ref):
    pos = pos_ref[0]
    neg = neg_ref[0]
    mem = mem_ref[0]
    card_n2 = cardn_ref[0]
    card_active = cardv_ref[0] != 0
    choice_cand = choice_ref[0]      # [NC, Kc]
    var_choices = varch_ref[0]       # [NV, W]
    t_seed = t0p_ref[0]              # [1, Wr] anchors-assumed plane
    f_seed = f0p_ref[0]              # [1, Wr] padding pinned false
    pvb = pvb_ref[0]                 # [1, Wr] problem-var mask
    b = pl.program_id(0)
    en = en_ref[b, 0] != 0
    na = na_ref[b, 0]
    budget = budget_ref[0, 0]

    NC, Kc = choice_cand.shape
    NV, W = var_choices.shape
    Wr = pos.shape[1]
    DQ = NC + 1
    GS = NC + 1
    no_min = jnp.zeros((1, Wr), jnp.int32)
    zero_w = jnp.int32(0)

    # ---- baseline Test (solve.go:74-79) --------------------------------
    conflict0, t0, f0 = _fixpoint(
        pos, neg, mem, card_active, card_n2, no_min, zero_w,
        t_seed, f_seed, en,
    )
    outcome0 = core.test_outcome(conflict0, t0, f0, pvb)
    enabled = en & (outcome0 == core.RUNNING)

    # ---- guess search (kernel twin of core.search) ---------------------
    dq_pos = _lanes_iota(DQ)
    dq_c0 = jnp.where(dq_pos < na, dq_pos, 0)
    dq_i0 = jnp.zeros((1, DQ), jnp.int32)
    snap_t0 = _row_write(jnp.zeros((GS + 1, Wr), jnp.int32), 0, t0)
    snap_f0 = _row_write(jnp.zeros((GS + 1, Wr), jnp.int32), 0, f0)
    out_st0 = _lane_write(jnp.zeros((1, GS + 1), jnp.int32), 0, outcome0)

    def body(st):
        (dq_c, dq_i, head, cnt, g_c, g_i, g_v, g_ch, gsp,
         snap_t, snap_f, out_st, result, m_t, m_f, assumed, done,
         need_leaf, steps, tr_n) = st

        is_leaf = (cnt == 0) & (result == core.RUNNING)
        is_bt = ~is_leaf & (result == core.UNSAT)
        is_done = ~is_leaf & ~is_bt & (cnt == 0)
        is_push = ~is_leaf & ~is_bt & ~is_done

        tr_n = tr_n + is_bt.astype(jnp.int32)

        cur_t = _row_read(snap_t, jnp.clip(gsp, 0, GS))
        cur_f = _row_read(snap_f, jnp.clip(gsp, 0, GS))

        # arm 0: park for the episode's leaf DPLL.
        need_leaf = need_leaf | is_leaf

        # arm 1: backtrack bookkeeping (PopGuess, search.go:79-98).
        give_up = is_bt & (gsp == 0)
        bt = is_bt & ~give_up
        gsp2 = gsp - 1
        gc = _lane_read(g_c, jnp.clip(gsp2, 0))
        gi = _lane_read(g_i, jnp.clip(gsp2, 0))
        gv = _lane_read(g_v, jnp.clip(gsp2, 0))
        gch = _lane_read(g_ch, jnp.clip(gsp2, 0))
        head_bt = jnp.mod(head - 1, DQ)

        # arm 3: push bookkeeping (PushGuess, search.go:34-77).
        cid = _lane_read(dq_c, jnp.clip(head, 0, DQ - 1))
        idx = _lane_read(dq_i, jnp.clip(head, 0, DQ - 1))
        head_push = jnp.mod(head + 1, DQ)
        cands = _row_read(choice_cand, jnp.clip(cid, 0, NC - 1))  # [1, Kc]
        ncand = core.tree_sum(cands >= 0)
        cand_var = _lane_read(cands, jnp.clip(idx, 0, Kc - 1))
        var = jnp.where(idx < ncand, cand_var, -1)
        # "some candidate already assumed" — candidate membership test on
        # the packed assumed plane, statically unrolled over Kc (static
        # column extracts, scalar-core work).
        already = jnp.bool_(False)
        for k in range(Kc):
            cv = cands[0, k]
            already = already | ((cv >= 0) & (_get_bit(assumed, cv) != 0))
        var = jnp.where(already, jnp.int32(-1), var)

        head = jnp.where(bt, head_bt, jnp.where(is_push, head_push, head))
        # Backtrack: requeue the popped choice, candidate index advanced
        # past a real guess.
        dq_c = _lane_write(dq_c, jnp.where(bt, head_bt, DQ), gc)
        dq_i = _lane_write(dq_i, jnp.where(bt, head_bt, DQ),
                           gi + (gv >= 0).astype(jnp.int32))
        # Push: enqueue the guessed variable's dependency choices —
        # statically unrolled over the W choice slots (cumulative offset
        # runs in the same loop; an invalid slot targets DQ → dropped).
        ch_row = _row_read(var_choices, jnp.clip(var, 0))  # [1, W]
        nch = jnp.int32(0)
        for w in range(W):
            ch_w = ch_row[0, w]
            valid_w = is_push & (var >= 0) & (ch_w >= 0)
            pos_w = jnp.mod(head_push + (cnt - 1) + nch, DQ)
            tgt_w = jnp.where(valid_w, pos_w, DQ)
            dq_c = _lane_write(dq_c, tgt_w, ch_w)
            dq_i = _lane_write(dq_i, tgt_w, jnp.int32(0))
            nch = nch + valid_w.astype(jnp.int32)
        cnt = jnp.where(bt, cnt - gch + 1,
                        jnp.where(is_push, cnt - 1 + nch, cnt))

        g_idx = jnp.where(is_push, jnp.clip(gsp, 0, GS - 1), GS)
        g_c = _lane_write(g_c, g_idx, cid)
        g_i = _lane_write(g_i, g_idx, idx)
        g_v = _lane_write(g_v, g_idx, var)
        g_ch = _lane_write(g_ch, g_idx, nch)

        assumed = _clear_bit(assumed, jnp.clip(gv, 0), bt & (gv >= 0))
        assumed = _set_bit(assumed, jnp.clip(var, 0), is_push & (var >= 0))

        # Push with a real variable: propagate just the new literal.
        push_test = is_push & (var >= 0)
        t2 = _set_bit(cur_t, jnp.clip(var, 0), push_test)
        conflict, t3, f3 = _fixpoint(
            pos, neg, mem, card_active, card_n2, no_min, zero_w,
            t2, cur_f, push_test,
        )
        push_out = core.test_outcome(conflict, t3, f3, pvb)
        sidx = jnp.where(is_push, jnp.clip(gsp + 1, 0, GS), GS + 1)
        snap_t = _row_write(snap_t, sidx,
                            jnp.where(push_test, t3, cur_t))
        snap_f = _row_write(snap_f, sidx,
                            jnp.where(push_test, f3, cur_f))
        out_st = _lane_write(
            out_st, sidx,
            jnp.where(push_test, push_out,
                      _lane_read(out_st, jnp.clip(gsp, 0, GS))))
        gsp = jnp.where(bt, gsp2, jnp.where(is_push, gsp + 1, gsp))

        pop_restore = bt & (gv >= 0)
        pop_out = _lane_read(out_st, jnp.clip(gsp2, 0, GS))
        result = jnp.where(pop_restore, pop_out,
                           jnp.where(push_test, push_out, result))
        pop_sat = pop_restore & (pop_out == core.SAT)
        m_t = jnp.where(pop_sat, _row_read(snap_t, jnp.clip(gsp2, 0, GS)),
                        m_t)
        m_f = jnp.where(pop_sat, _row_read(snap_f, jnp.clip(gsp2, 0, GS)),
                        m_f)
        push_sat = push_test & (push_out == core.SAT)
        m_t = jnp.where(push_sat, t3, m_t)
        m_f = jnp.where(push_sat, f3, m_f)

        done = done | give_up | is_done
        steps = steps + (bt | is_push).astype(jnp.int32)
        return (dq_c, dq_i, head, cnt, g_c, g_i, g_v, g_ch, gsp,
                snap_t, snap_f, out_st, result, m_t, m_f, assumed, done,
                need_leaf, steps, tr_n)

    def ctl_cond(st):
        done, need_leaf, steps = st[16], st[17], st[18]
        return enabled & ~done & ~need_leaf & (steps <= budget)

    def episode_body(st):
        st = lax.while_loop(ctl_cond, body, st)
        (dq_c, dq_i, head, cnt, g_c, g_i, g_v, g_ch, gsp,
         snap_t, snap_f, out_st, result, m_t, m_f, assumed, done,
         need_leaf, steps, tr_n) = st
        cur_t = _row_read(snap_t, jnp.clip(gsp, 0, GS))
        cur_f = _row_read(snap_f, jnp.clip(gsp, 0, GS))
        leaf_status, leaf_t, leaf_f, steps = _dpll(
            pos, neg, mem, card_active, card_n2, pvb, cur_t, cur_f,
            no_min, zero_w, budget, steps, NV, need_leaf,
        )
        result = jnp.where(need_leaf, leaf_status, result)
        leaf_sat = need_leaf & (leaf_status == core.SAT)
        m_t = jnp.where(leaf_sat, leaf_t, m_t)
        m_f = jnp.where(leaf_sat, leaf_f, m_f)
        need_leaf = jnp.bool_(False)
        return (dq_c, dq_i, head, cnt, g_c, g_i, g_v, g_ch, gsp,
                snap_t, snap_f, out_st, result, m_t, m_f, assumed, done,
                need_leaf, steps, tr_n)

    def episode_cond(st):
        done, steps = st[16], st[18]
        return enabled & ~done & (steps <= budget)

    st = (
        dq_c0, dq_i0, jnp.int32(0), na,
        jnp.zeros((1, GS), jnp.int32), jnp.zeros((1, GS), jnp.int32),
        jnp.zeros((1, GS), jnp.int32), jnp.zeros((1, GS), jnp.int32),
        jnp.int32(0),
        snap_t0, snap_f0, out_st0,
        jnp.int32(core.RUNNING),
        jnp.zeros((1, Wr), jnp.int32), jnp.zeros((1, Wr), jnp.int32),
        jnp.zeros((1, Wr), jnp.int32),
        jnp.bool_(False), jnp.bool_(False), jnp.int32(1),
        jnp.int32(0),
    )
    st = lax.while_loop(episode_cond, episode_body, st)
    (_, _, _, _, _, _, _, _, _, _, _, _,
     result, m_t, m_f, assumed, done, _, steps, tr_n) = st
    result = jnp.where(done, result, jnp.int32(core.RUNNING))

    out0_ref[b, 0] = outcome0
    res_ref[b, 0] = result
    steps_ref[b, 0] = steps
    trn_ref[b, 0] = tr_n
    t0o_ref[0] = t0
    f0o_ref[0] = f0
    asm_ref[0] = assumed
    mt_ref[0] = m_t
    mf_ref[0] = m_f


# --------------------------------------------------------------------------
# fused phase 2: extras-only minimization (kernel twin of
# core.minimize_phase — binary search over the extras bound, each probe a
# full in-kernel DPLL; solve.go:86-113)


def _min_kernel(en_ref, nx_ref, budget_ref, steps_ref,
                pos_ref, neg_ref, mem_ref, cardn_ref, cardv_ref,
                mit_ref, mif_ref, ext_ref, m2t0_ref, pvb_ref,
                found_ref, steps_out_ref, m2t_ref, *, NV: int):
    pos = pos_ref[0]
    neg = neg_ref[0]
    mem = mem_ref[0]
    card_n2 = cardn_ref[0]
    card_active = cardv_ref[0] != 0
    m_init_t = mit_ref[0]
    m_init_f = mif_ref[0]
    extras_bits = ext_ref[0]
    pvb = pvb_ref[0]
    b = pl.program_id(0)
    en = en_ref[b, 0] != 0
    n_extras = nx_ref[b, 0]
    budget = budget_ref[0, 0]
    steps = steps_ref[b, 0]

    def mcond(c):
        lo, hi, _, _, _, steps = c
        return en & (lo < hi) & (steps <= budget)

    def mbody(c):
        lo, hi, best_w, m2_t, found, steps = c
        w = (lo + hi) // 2
        status, mt, _, steps = _dpll(
            pos, neg, mem, card_active, card_n2, pvb,
            m_init_t, m_init_f, extras_bits, w, budget, steps, NV, en,
        )
        sat_w = status == core.SAT
        best_w = jnp.where(sat_w, w, best_w)
        m2_t = jnp.where(sat_w, mt, m2_t)
        found = found | sat_w
        lo = jnp.where(sat_w, lo,
                       jnp.where(status == core.UNSAT, w + 1, hi))
        hi = jnp.where(sat_w, w, hi)
        return lo, hi, best_w, m2_t, found, steps

    _, m_hi, best_w, m2_t, m_found, steps = lax.while_loop(
        mcond, mbody,
        (jnp.int32(0), n_extras, jnp.int32(-1), m2t0_ref[0],
         jnp.bool_(False), steps),
    )
    need_final = en & (best_w != m_hi) & (n_extras > 0)
    f_status, f_t, _, steps = _dpll(
        pos, neg, mem, card_active, card_n2, pvb,
        m_init_t, m_init_f, extras_bits, m_hi, budget, steps, NV,
        need_final,
    )
    m2_t = jnp.where(need_final & (f_status == core.SAT), f_t, m2_t)
    min_found = (jnp.where(need_final, f_status == core.SAT, m_found)
                 | (en & (n_extras == 0)))
    found_ref[b, 0] = min_found.astype(jnp.int32)
    steps_out_ref[b, 0] = steps
    m2t_ref[0] = m2_t


def _minimize_fused_impl(pts: core.ProblemTensors, result, model,
                         guessed, budget, steps, en_lanes):
    """Phase-2 minimization via the fused kernel — the drop-in twin of
    ``core.batched_minimize_gated(...)(pts, result, model, guessed,
    budget, steps, en)`` (reduced plane space)."""
    B = pts.pos_bits_r.shape[0]
    Wr = pts.pos_bits_r.shape[2]
    NV = pts.var_choices.shape[1]

    en = en_lanes & (result == core.SAT)
    idx = jnp.arange(NV, dtype=jnp.int32)
    pv_mask = idx[None, :] < pts.n_vars[:, None]
    extras = (model == core.TRUE) & ~guessed & pv_mask
    excluded = (model != core.TRUE) & ~guessed & pv_mask
    m_init = jax.vmap(lambda p: core._base_assignment_red(p, NV))(pts)
    m_init = jax.vmap(lambda p, a: core._apply_anchors(p, a, NV))(
        pts, m_init)
    m_init = jnp.where(guessed, jnp.int32(core.TRUE), m_init)
    m_init = jnp.where(excluded, jnp.int32(core.FALSE), m_init)
    n_extras = jnp.where(en, extras.sum(axis=1), 0).astype(jnp.int32)

    pack = jax.vmap(lambda m: core.pack_mask(m, Wr))
    m_init_t = pack(m_init == core.TRUE)
    m_init_f = pack(m_init == core.FALSE)
    extras_bits = pack(extras)
    m2t0 = pack(model == core.TRUE)
    pvb = pack(pv_mask)

    smem_b = _smem_scalars(B)
    smem_c = pl.BlockSpec((1, 1), lambda b: (0, 0),
                          memory_space=pltpu.SMEM)

    def vmem(*blk):
        return pl.BlockSpec((1,) + blk, lambda b: (b,) + (0,) * len(blk),
                            memory_space=pltpu.VMEM)

    C = pts.pos_bits_r.shape[1]
    NA = pts.card_member_bits_r.shape[1]
    found, steps_out, m2_t = pl.pallas_call(
        functools.partial(_min_kernel, NV=NV),
        grid=(B,),
        in_specs=[
            smem_b, smem_b, smem_c, smem_b,
            vmem(C, Wr), vmem(C, Wr), vmem(NA, Wr),
            vmem(NA, 1), vmem(NA, 1),
            vmem(1, Wr), vmem(1, Wr), vmem(1, Wr), vmem(1, Wr),
            vmem(1, Wr),
        ],
        out_specs=(smem_b, smem_b, vmem(1, Wr)),
        out_shape=(
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, Wr), jnp.int32),
        ),
        interpret=jax.default_backend() != "tpu",
    )(en.astype(jnp.int32)[:, None], n_extras[:, None],
      jnp.full((1, 1), budget, jnp.int32), steps.astype(jnp.int32)[:, None],
      pts.pos_bits_r, pts.neg_bits_r, pts.card_member_bits_r,
      pts.card_n[:, :, None], pts.card_valid[:, :, None],
      m_init_t, m_init_f, extras_bits, m2t0, pvb)

    min_found = found[:, 0] != 0
    steps_out = steps_out[:, 0]
    installed = (jax.vmap(lambda w: core.unpack_mask(w, NV))(m2_t)
                 & pv_mask & min_found[:, None] & en[:, None])[:, :NV]
    return installed, min_found, steps_out


_batched_minimize_fused = jax.jit(compileguard.observe(
    "pallas_search.batched_minimize_fused", _minimize_fused_impl))


def batched_minimize_fused(pts, result, model, guessed, budget, steps,
                           en_lanes):
    """Public entry for the fused phase-2 program (shape-guarded like
    :func:`batched_search_fused`)."""
    if not fused_supported(pts):
        raise ValueError("fused minimize kernel caps exceeded")
    return _batched_minimize_fused(pts, result, model, guessed, budget,
                                   steps, en_lanes)


def fused_supported(pts: core.ProblemTensors) -> bool:
    """Whether the fused kernel handles this batch's static shapes (the
    static-unroll caps on the table walks)."""
    Kc = pts.choice_cand.shape[-1]
    W = pts.var_choices.shape[-1]
    return Kc <= MAX_KC and W <= MAX_W


# --------------------------------------------------------------------------
# fused phase 3: deletion-based unsat-core minimization (kernel twin of
# core.core_phase — chunk-first deletion sweep, every probe a full
# in-kernel DPLL over the FULL plane space, where activation literals are
# live variables; the analog of gini's Why minimization,
# lit_mapping.go:198-207)


def _core_kernel(en_ref, ncons_ref, nvars_ref, budget_ref, steps_ref,
                 pos_ref, neg_ref, mem_ref, cardn_ref, cardab_ref,
                 pvb_ref, baset_ref, basef_ref,
                 core_ref, steps_out_ref, *, NV: int, NCON: int, G: int):
    """One problem's whole deletion sweep in one kernel invocation.

    Constraint (de)activation is plane algebra: the all-active base
    assignment has every activation literal's TRUE bit set (base_t), and
    a probe's trial assignment clears the dropped constraints' act bits
    — leaving them UNASSIGNED, exactly core._base_assignment's
    ``act_enabled`` semantics.  The permanently-dropped set is carried as
    a packed bit plane (``dropped``) so each probe constructs its trial
    with ≤ G+1 one-hot bit ops instead of re-scattering NCON bits."""
    pos = pos_ref[0]
    neg = neg_ref[0]
    mem = mem_ref[0]
    card_n2 = cardn_ref[0]
    # Full plane space: cardinality-row activity is DERIVED per fixpoint
    # from the probe's activation bits (a dropped constraint's AtMost
    # rows must stop constraining), so the kernel carries the act-bit
    # planes, not a static card_valid mask.
    card_act_bits = cardab_ref[0]
    pvb = pvb_ref[0]
    base_t = baset_ref[0]
    base_f = basef_ref[0]
    b = pl.program_id(0)
    en = en_ref[b, 0] != 0
    n_cons = ncons_ref[b, 0]
    n_vars = nvars_ref[b, 0]
    budget = budget_ref[0, 0]
    steps0 = steps_ref[b, 0]
    Wv = pos.shape[1]
    lanes = _lanes_iota(NCON)
    active0 = ((lanes < n_cons) & en).astype(jnp.int32)
    no_min = jnp.zeros((1, Wv), jnp.int32)
    zero_w = jnp.int32(0)

    def cond(st):
        j, _, _, _, _, steps = st
        return en & (j < n_cons) & (steps <= budget)

    def body(st):
        j, k, chunk_mode, active, dropped, steps = st
        # Trial plane: the dropped set plus this probe's candidates.
        trial_plane = dropped
        for g in range(G):  # static unroll (G = CORE_CHUNK)
            idx = j + g
            on_c = (chunk_mode & (idx < n_cons)
                    & (_lane_read(active, idx) != 0))
            trial_plane = _set_bit(trial_plane, n_vars + idx, on_c)
        idx_m = j + k
        on_m = ~chunk_mode & (idx_m < n_cons)
        trial_plane = _set_bit(trial_plane, n_vars + idx_m, on_m)
        in_chunk = (lanes >= j) & (lanes < j + G)
        trial_act = jnp.where(chunk_mode & in_chunk, 0, active)
        trial_act = jnp.where(~chunk_mode & (lanes == idx_m)
                              & (idx_m < n_cons), 0, trial_act)

        status, _, _, steps = _dpll(
            pos, neg, mem, None, card_n2, pvb,
            base_t & ~trial_plane, base_f, no_min, zero_w,
            budget, steps, NV, en, card_act_bits,
        )
        unsat = status == core.UNSAT
        active = jnp.where(unsat, trial_act, active)
        dropped = jnp.where(unsat, trial_plane, dropped)
        # Control twin of core.core_phase's cbody: chunk probe UNSAT →
        # next chunk; chunk probe SAT → member-by-member; member sweep
        # exhausts the chunk → next chunk.
        k2 = jnp.where(chunk_mode, jnp.int32(0), k + 1)
        chunk_done = chunk_mode & unsat
        member_done = ~chunk_mode & ((k2 >= G) | (j + k2 >= n_cons))
        advance = chunk_done | member_done
        j = jnp.where(advance, j + G, j)
        k2 = jnp.where(advance, jnp.int32(0), k2)
        return j, k2, advance, active, dropped, steps

    st = (jnp.int32(0), jnp.int32(0), jnp.bool_(True), active0,
          jnp.zeros((1, Wv), jnp.int32), steps0)
    _, _, _, core_act, _, steps = lax.while_loop(cond, body, st)
    core_ref[0] = core_act
    steps_out_ref[b, 0] = steps


def _core_fused_impl(pts: core.ProblemTensors, budget, steps, en,
                     *, V: int, NCON: int, NV: int):
    """Phase-3 core extraction via the fused kernel — the drop-in twin of
    ``core.batched_core(V, NCON, NV)(pts, budget, steps, en)``.  Reads
    the FULL-space planes (activation literals live)."""
    B, C, Wv = pts.pos_bits.shape
    NA = pts.card_member_bits.shape[1]
    G = min(core.CORE_CHUNK, max(NCON, 1))

    init = jax.vmap(
        lambda p: core._base_assignment(p, V, NCON))(pts)  # all active
    pack = jax.vmap(lambda m: core.pack_mask(m, Wv))
    base_t = pack(init == core.TRUE)
    base_f = pack(init == core.FALSE)
    idx = jnp.arange(V, dtype=jnp.int32)
    pvb = pack(idx[None, :] < pts.n_vars[:, None])

    smem_b = _smem_scalars(B)
    smem_c = pl.BlockSpec((1, 1), lambda b: (0, 0),
                          memory_space=pltpu.SMEM)

    def vmem(*blk):
        return pl.BlockSpec((1,) + blk, lambda b: (b,) + (0,) * len(blk),
                            memory_space=pltpu.VMEM)

    core_out, steps_out = pl.pallas_call(
        functools.partial(_core_kernel, NV=NV, NCON=NCON, G=G),
        grid=(B,),
        in_specs=[
            smem_b, smem_b, smem_b, smem_c, smem_b,
            vmem(C, Wv), vmem(C, Wv), vmem(NA, Wv),
            vmem(NA, 1), vmem(NA, Wv),
            vmem(1, Wv), vmem(1, Wv), vmem(1, Wv),
        ],
        out_specs=(vmem(1, NCON), smem_b),
        out_shape=(
            jax.ShapeDtypeStruct((B, 1, NCON), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
        ),
        interpret=jax.default_backend() != "tpu",
    )(en.astype(jnp.int32)[:, None],
      pts.n_cons.astype(jnp.int32)[:, None],
      pts.n_vars.astype(jnp.int32)[:, None],
      jnp.full((1, 1), budget, jnp.int32),
      steps.astype(jnp.int32)[:, None],
      pts.pos_bits, pts.neg_bits, pts.card_member_bits,
      pts.card_n[:, :, None], pts.card_act_bits,
      pvb, base_t, base_f)

    return core_out[:, 0, :] != 0, steps_out[:, 0]


_batched_core_fused = jax.jit(
    compileguard.observe("pallas_search.batched_core_fused",
                         _core_fused_impl),
    static_argnames=("V", "NCON", "NV"))


def batched_core_fused(pts, budget, steps, en, *, V, NCON, NV):
    """Public entry for the fused phase-3 program (shape caps shared with
    the phase-1/2 kernels via :func:`fused_supported`; callers fall back
    to the XLA path otherwise)."""
    if not fused_supported(pts):
        raise ValueError("fused core kernel caps exceeded")
    return _batched_core_fused(pts, budget, steps, en,
                               V=V, NCON=NCON, NV=NV)


def _search_fused_impl(pts: core.ProblemTensors, budget, en):
    """Phase-1 search for a padded batch via the fused kernel — the drop-in
    twin of ``core.batched_search(...)(pts, budget, en)`` with T=0.
    Reduced plane space only (the search never disables activations;
    core.phases_reduced)."""
    B, NC, Kc = pts.choice_cand.shape
    NV, W = pts.var_choices.shape[1:]
    Wr = pts.pos_bits_r.shape[2]

    idx = jnp.arange(NV, dtype=jnp.int32)
    pv_mask = idx[None, :] < pts.n_vars[:, None]                # [B, NV]
    anchor_mask = jax.vmap(lambda p: core._anchor_mask(p, NV))(pts)
    pack = jax.vmap(lambda m: core.pack_mask(m, Wr))
    pvb = pack(pv_mask)                                         # [B, 1, Wr]
    t0p = pack(anchor_mask)
    f0p = pack(~pv_mask)
    na = (pts.anchors >= 0).sum(axis=1).astype(jnp.int32)[:, None]
    en2 = en.astype(jnp.int32)[:, None]
    budget2 = jnp.full((1, 1), budget, jnp.int32)
    card_n2 = pts.card_n[:, :, None]
    card_v2 = pts.card_valid[:, :, None]

    smem_b = _smem_scalars(B)
    smem_c = pl.BlockSpec((1, 1), lambda b: (0, 0),
                          memory_space=pltpu.SMEM)

    def vmem(*blk):
        return pl.BlockSpec((1,) + blk, lambda b: (b,) + (0,) * len(blk),
                            memory_space=pltpu.VMEM)

    C = pts.pos_bits_r.shape[1]
    NA = pts.card_member_bits_r.shape[1]
    outs = pl.pallas_call(
        _kernel,
        grid=(B,),
        in_specs=[
            smem_b, smem_b, smem_c,
            vmem(C, Wr), vmem(C, Wr), vmem(NA, Wr),
            vmem(NA, 1), vmem(NA, 1),
            vmem(NC, Kc), vmem(NV, W),
            vmem(1, Wr), vmem(1, Wr), vmem(1, Wr),
        ],
        out_specs=(
            smem_b, smem_b, smem_b, smem_b,
            vmem(1, Wr), vmem(1, Wr), vmem(1, Wr), vmem(1, Wr),
            vmem(1, Wr),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, Wr), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, Wr), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, Wr), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, Wr), jnp.int32),
            jax.ShapeDtypeStruct((B, 1, Wr), jnp.int32),
        ),
        interpret=jax.default_backend() != "tpu",
    )(en2, na, budget2,
      pts.pos_bits_r, pts.neg_bits_r, pts.card_member_bits_r,
      card_n2, card_v2, pts.choice_cand, pts.var_choices,
      t0p, f0p, pvb)

    outcome0, result_s, steps, tr_n, t0o, f0o, asm, m_t, m_f = outs
    outcome0 = outcome0[:, 0]
    result_s = result_s[:, 0]
    steps = steps[:, 0]
    tr_n = tr_n[:, 0]

    to_assign = jax.vmap(lambda t, f: core.planes_to_assign(t, f, NV))
    a0 = to_assign(t0o, f0o)
    s_model = to_assign(m_t, m_f)
    s_guessed = jax.vmap(lambda w: core.unpack_mask(w, NV))(asm)

    need_search = en & (outcome0 == core.RUNNING)
    result = jnp.where(need_search, result_s, outcome0)
    guessed = jnp.where(need_search[:, None], s_guessed, anchor_mask)
    model = jnp.where(need_search[:, None], s_model, a0)
    result = jnp.where(en, result, jnp.int32(core.RUNNING))
    tr_stack = jnp.full((B, 0, NC + 1), -1, jnp.int32)
    return result, guessed, model, steps, tr_stack, tr_n


_batched_search_fused = jax.jit(compileguard.observe(
    "pallas_search.batched_search_fused", _search_fused_impl))


def batched_search_fused(pts: core.ProblemTensors, budget, en):
    """Public entry: shape-guarded fused phase-1 search (see
    :func:`fused_supported`; callers fall back to the XLA path when this
    raises)."""
    if not fused_supported(pts):
        raise ValueError(
            f"fused search kernel caps exceeded: Kc "
            f"{pts.choice_cand.shape[-1]} (max {MAX_KC}), W "
            f"{pts.var_choices.shape[-1]} (max {MAX_W})"
        )
    return _batched_search_fused(pts, budget, en)
