"""Shared flock-guarded store for the measured-defaults registry
(ISSUE 19 satellite).

The read-merge-write of ``measured_defaults.json`` rows grew ad hoc in
two places — ``scripts/tpu_revalidate.py`` (flock + atomic replace +
per-key evidence stamps) and ``scripts/tpu_ab.py`` (a plain unlocked
load/dump that could torn-write against a concurrent ladder) — and
ISSUE 19's online route registry adds a third writer that runs *inside
a serving process*.  This module is the one merge path all three use:

  * the whole read-merge-write runs under an ``flock`` on a sibling
    ``.lock`` file, so concurrent writers (two heal windows, a CPU
    smoke ladder racing a device ladder, a serving replica persisting
    a learned row mid-ladder) compose instead of dropping each other's
    rows;
  * the write is atomic (``.tmp`` + ``os.replace``) so a reader never
    sees a half-written registry;
  * every written key gets a **provenance stamp** nested per key under
    the backend's ``evidence`` map — ``ts`` (epoch seconds), ``box``
    (hostname), plus whatever the caller measured (``platform``,
    ``samples``, ``ladder_log``, ``source``) — the trail the ISSUE 19
    staleness watcher reads to decide whether a row is stale, missing,
    or foreign.

Rows measured by a *later* run that touches only one key keep their
siblings' provenance untouched (the tpu_revalidate contract).
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Dict, Optional


def registry_path(path: Optional[str] = None) -> str:
    """The measured-defaults registry path: explicit argument, the
    ``DEPPY_TPU_MEASURED_DEFAULTS`` override, else the package-local
    file an installed wheel ships."""
    if path:
        return path
    return os.environ.get(
        "DEPPY_TPU_MEASURED_DEFAULTS",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "measured_defaults.json"))


def read_rows(path: Optional[str] = None) -> dict:
    """The whole registry document ({} when absent/corrupt — a missing
    registry is the normal cold state, never an error)."""
    try:
        with open(registry_path(path)) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}


def provenance(backend: str, key: str,
               path: Optional[str] = None,
               doc: Optional[dict] = None) -> Optional[dict]:
    """The evidence stamp recorded for one ``backend``/``key`` row
    (None when the row was never measured, or predates evidence)."""
    if doc is None:
        doc = read_rows(path)
    entry = doc.get(backend)
    if not isinstance(entry, dict):
        return None
    ev = entry.get("evidence")
    if not isinstance(ev, dict):
        return None
    stamp = ev.get(key)
    return stamp if isinstance(stamp, dict) else None


def merge_rows(backend: str, updates: Dict[str, object],
               evidence: Optional[dict] = None,
               path: Optional[str] = None) -> str:
    """Merge ``updates`` into ``backend``'s rows under the registry
    flock; other backends' rows and this backend's other keys survive.
    Each updated key's evidence stamp is replaced with the caller's
    ``evidence`` fields plus ``ts`` and ``box`` — provenance belongs to
    the run that measured the row, so unmeasured siblings keep theirs.
    Returns the path written."""
    import fcntl

    target = registry_path(path)
    stamp = dict(evidence or {})
    stamp.setdefault("ts", round(time.time(), 1))
    stamp.setdefault("box", socket.gethostname())
    with open(target + ".lock", "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            data = read_rows(target)
            entry = data.get(backend)
            if not isinstance(entry, dict):
                entry = {}
            entry.update(updates)
            ev = entry.get("evidence")
            if not isinstance(ev, dict):
                ev = {}
            for key in updates:
                ev[key] = dict(stamp)
            entry["evidence"] = ev
            data[backend] = entry
            tmp = target + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, target)
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)
    return target
