"""Host-side driver: pad, batch, dispatch, decode.

Bridges the symbolic layer (:class:`deppy_tpu.sat.encode.Problem`) and the
tensor engine (:mod:`deppy_tpu.engine.core`):

  * pads each lowered problem's tensors to the batch's common shapes,
    bucketing every dimension up to a power of two so the number of
    distinct compiled programs stays bounded (the padding-economics policy
    from SURVEY.md §7.3);
  * stacks problems along a leading batch axis and dispatches one jitted,
    vmapped solve for the whole batch;
  * decodes outcome masks back to installed variables, and active-constraint
    masks back to :class:`NotSatisfiable` unsat cores, exactly like the
    reference maps lits back through LitMapping
    (/root/reference/pkg/sat/lit_mapping.go:176-207).

Batch entries behind a padded batch dimension are empty problems (zero
variables) which solve trivially and are dropped on decode.
"""

from __future__ import annotations

import functools as _functools
import os
import threading as _threading
import time as _time
from typing import List, Optional, Sequence, Union

import jax
import numpy as np

from .. import config, faults, telemetry
from .. import profile as _profile
from .. import size_classes as _size_classes
from ..analysis import compileguard
from ..sat.constraints import Variable
from ..sat.encode import Problem, encode
from ..sat.errors import Incomplete, InternalSolverError, NotSatisfiable
from ..utils.platform_env import assert_env_platform
from . import core

# Library-level platform guard: importing the tensor engine is the first
# step of every device code path (Solver(backend="tpu"), BatchResolver,
# clause sharding), and a ``JAX_PLATFORMS=cpu`` user process must never
# initialize the accelerator plugin — discovery-time init of the axon
# PJRT plugin hangs for hours when its worker is wedged (see
# platform_env.assert_env_platform).  Process entry points also call
# this via apply_platform_env(); this covers plain library imports.
assert_env_platform()

# Default step budget when the caller sets none: generous enough for any
# realistic catalog problem, small enough that a pathological instance
# yields Incomplete rather than an unbounded device loop (the reference
# quirk of unhonored cancellation — SURVEY.md §3.1 — done better).
DEFAULT_MAX_STEPS = 1 << 24


# ----------------------------------------------------------------- telemetry
#
# Span/counter/report instrumentation for the whole dispatch pipeline
# (ISSUE 1, SURVEY.md §5): pad/pack economics, device transfer, per-chunk
# dispatch, escalation staging, and host-fallback routing all record into
# the default telemetry registry (and into the thread's active SolveReport
# when one exists).  Everything here is a handful of perf_counter calls
# and dict updates per BATCH — nowhere near the per-lane hot path.


def _telem_record_pad(problems, total: int, d: _Dims, n_chunks: int,
                      dur_s: float) -> None:
    """Record one bucket's padding economics: live vs padded lanes, and
    live vs padded clause-matrix cells (the dominant tensor)."""
    reg = telemetry.default_registry()
    n = len(problems)
    live_cells = int(sum(p.clauses.size for p in problems))
    pad_cells = int(total) * d.C * d.K
    reg.histogram(
        "deppy_batch_fill_ratio",
        "Live problems per dispatched batch lane (1.0 = no lane padding).",
        buckets=telemetry.RATIO_BUCKETS,
    ).observe(n / total if total else 1.0)
    reg.counter("deppy_pad_cells_total",
                "Clause-matrix cells dispatched, including padding."
                ).inc(pad_cells)
    reg.counter("deppy_live_cells_total",
                "Clause-matrix cells carrying live problem data."
                ).inc(live_cells)
    reg.counter("deppy_chunks_total",
                "Device dispatch chunks issued.").inc(n_chunks)
    rep = telemetry.current_report()
    if rep is not None:
        rep.record_batch(live_lanes=n, batch_lanes=int(total),
                         live_cells=live_cells, pad_cells=pad_cells,
                         n_chunks=n_chunks)
        rep.add_wall("pad_pack", dur_s)


def _bucket(n: int, minimum: int = 1) -> int:
    """Round up to the next power of two (≥ minimum) — delegated to the
    shared size-class module so class arithmetic and live padding use
    one quantum."""
    return _size_classes.bucket(n, minimum)


def _pad2(a: np.ndarray, rows: int, cols: int, fill: int) -> np.ndarray:
    out = np.full((rows, cols), fill, dtype=np.int32)
    r, c = a.shape
    out[:r, :c] = a
    return out


def _pad1(a: np.ndarray, n: int, fill: int) -> np.ndarray:
    out = np.full((n,), fill, dtype=np.int32)
    out[: a.shape[0]] = a
    return out


class _Dims:
    """Common padded dimensions for a batch of problems."""

    def __init__(self, problems: Sequence[Problem], batch: int, batch_multiple: int = 1):
        self.C = _bucket(max((p.clauses.shape[0] for p in problems), default=1))
        self.K = _bucket(max((p.clauses.shape[1] for p in problems), default=1), 2)
        self.NA = _bucket(max((p.card_ids.shape[0] for p in problems), default=1))
        self.M = _bucket(max((p.card_ids.shape[1] for p in problems), default=1))
        self.A = _bucket(max((p.anchors.shape[0] for p in problems), default=1))
        self.NC = _bucket(max((p.choice_cand.shape[0] for p in problems), default=1))
        self.Kc = _bucket(max((p.choice_cand.shape[1] for p in problems), default=1))
        self.NV = _bucket(max((p.n_vars for p in problems), default=1))
        self.W = _bucket(max((p.var_choices.shape[1] for p in problems), default=1))
        self.NCON = _bucket(max((p.n_cons for p in problems), default=1))
        self.V = self.NV + self.NCON
        self.Wv = -(-self.V // core.WORD)  # bitplane words per variable set
        self.Wr = -(-self.NV // core.WORD)  # reduced (problem-var-only) words
        # Batch padded to a power of two AND a multiple of the mesh size so
        # the batch axis shards evenly.
        b = _bucket(batch)
        if b % batch_multiple:
            b *= batch_multiple // np.gcd(b, batch_multiple)
        self.B = b
        # Clause-bank widths (ISSUE 12) are data-dependent (max literal
        # occurrence / card membership over the batch) and only the
        # watched impl reads them — computed lazily so every other
        # dispatch skips the counting pass.
        self._problems = list(problems)
        self._Ob: Optional[int] = None
        self._Oc: Optional[int] = None

    @property
    def Ob(self) -> int:
        """Bucketed literal-occurrence width of the watched clause bank."""
        if self._Ob is None:
            from . import clause_bank

            self._Ob = _bucket(max(
                (clause_bank.max_occurrence(p.clauses)
                 for p in self._problems), default=0))
        return self._Ob

    @property
    def Oc(self) -> int:
        """Bucketed member→AtMost-row width of the watched bank."""
        if self._Oc is None:
            from . import clause_bank

            self._Oc = _bucket(max(
                (clause_bank.max_card_membership(p.card_ids)
                 for p in self._problems), default=0))
        return self._Oc


def _pack_planes(clauses: np.ndarray, Wv: int) -> tuple:
    """Signed clause matrix → (pos, neg) packed int32 bitplanes."""
    C = clauses.shape[0]
    W = core.WORD
    pos = np.zeros((C, Wv), np.uint32)
    neg = np.zeros((C, Wv), np.uint32)
    for plane, mask in ((pos, clauses > 0), (neg, clauses < 0)):
        r, c = np.nonzero(mask)
        v = np.abs(clauses[r, c]).astype(np.int64) - 1
        np.bitwise_or.at(plane, (r, v // W), np.uint32(1) << np.uint32(v % W))
    return pos.view(np.int32), neg.view(np.int32)


def _pack_index_rows(rows: np.ndarray, Wv: int) -> np.ndarray:
    """0-based index matrix (-1 pad) → packed int32 membership bitplanes."""
    W = core.WORD
    out = np.zeros((rows.shape[0], Wv), np.uint32)
    r, c = np.nonzero(rows >= 0)
    v = rows[r, c].astype(np.int64)
    np.bitwise_or.at(out, (r, v // W), np.uint32(1) << np.uint32(v % W))
    return out.view(np.int32)


def pad_problem(p: Problem, d: _Dims, pack: bool = True) -> core.ProblemTensors:
    """Pad one lowered problem to the batch dims (numpy, host-side).

    ``pack=False`` fills every bitplane field with 1-word dummies: the
    dispatch paths derive planes on device (:func:`core.derive_planes`),
    so host packing time and plane upload bytes are spent only by callers
    that ask for them (single-problem tests, the graft entry)."""
    clauses = _pad2(p.clauses, d.C, d.K, 0)
    card_ids = _pad2(p.card_ids, d.NA, d.M, -1)
    card_act = _pad1(p.card_act, d.NA, -1)
    if pack:
        pos_bits, neg_bits = _pack_planes(clauses, d.Wv)
        member_bits = _pack_index_rows(card_ids, d.Wv)
        act_bits = _pack_index_rows(card_act[:, None], d.Wv)
    else:
        pos_bits = np.zeros((d.C, 1), np.int32)
        neg_bits = np.zeros((d.C, 1), np.int32)
        member_bits = np.zeros((d.NA, 1), np.int32)
        act_bits = np.zeros((d.NA, 1), np.int32)
    # Reduced planes: drop activation-variable literals (constant TRUE in
    # the search/minimization phases, so their ¬act literals fold away).
    # Only the bits impl reads them — other impls get 1-word dummies so
    # neither packing time nor upload bytes are spent on them.
    if pack and core.phases_reduced():
        clauses_r = np.where(np.abs(clauses) <= p.n_vars, clauses, 0)
        pos_bits_r, neg_bits_r = _pack_planes(clauses_r, d.Wr)
        member_r = _pack_index_rows(card_ids, d.Wr)
    else:
        pos_bits_r = np.zeros((d.C, 1), np.int32)
        neg_bits_r = np.zeros((d.C, 1), np.int32)
        member_r = np.zeros((d.NA, 1), np.int32)
    if pack and d.Ob <= _bank_cap(d):
        # Clause banks ride every packed single-problem build (tests
        # flip impls AFTER padding via set_bcp_impl, so the bank must
        # already be there); the dispatch paths (pack=False) derive
        # them on device only when the watched impl is selected.  The
        # size-class OCC cap applies here exactly as on the device
        # path: past it every impl runs dense rounds, so building (and
        # — on the clause-sharded path — replicating) a huge bank a
        # popular literal inflated would be pure dead weight.
        from . import clause_bank

        occ_pos, occ_neg = clause_bank.occ_from_clauses_np(
            clauses, d.V, d.Ob)
        occ_pos_r, occ_neg_r = clause_bank.occ_from_clauses_np(
            clauses, d.NV, d.Ob, n_vars=p.n_vars)
        card_occ = clause_bank.card_occ_np(card_ids, d.NV, d.Oc)
    else:
        occ_pos = occ_neg = np.full((1, 1), -1, np.int32)
        occ_pos_r = occ_neg_r = np.full((1, 1), -1, np.int32)
        card_occ = np.full((1, 1), -1, np.int32)
    return core.ProblemTensors(
        clauses=clauses,
        card_ids=card_ids,
        card_n=_pad1(p.card_n, d.NA, 0),
        card_act=card_act,
        anchors=_pad1(p.anchors, d.A, -1),
        choice_cand=_pad2(p.choice_cand, d.NC, d.Kc, -1),
        var_choices=_pad2(p.var_choices, d.NV, d.W, -1),
        n_vars=np.int32(p.n_vars),
        n_cons=np.int32(p.n_cons),
        pos_bits=pos_bits,
        neg_bits=neg_bits,
        card_member_bits=member_bits,
        card_act_bits=act_bits,
        pos_bits_r=pos_bits_r,
        neg_bits_r=neg_bits_r,
        card_member_bits_r=member_r,
        card_valid=(card_act >= 0).astype(np.int32),
        occ_pos=occ_pos,
        occ_neg=occ_neg,
        occ_pos_r=occ_pos_r,
        occ_neg_r=occ_neg_r,
        card_occ=card_occ,
    )


def _pack_planes_batch(clauses: np.ndarray, Wv: int) -> tuple:
    """Batched signed clause matrices [B, C, K] → (pos, neg) packed int32
    bitplanes [B, C, Wv].  Vectorized over the whole batch: per-word
    OR-reductions instead of the scalar ``np.bitwise_or.at`` scatter."""
    mask = clauses != 0
    v = np.where(mask, np.abs(clauses) - 1, 0).astype(np.int64)
    word = v >> 5
    shifted = np.left_shift(np.uint32(1), (v & 31).astype(np.uint32))
    pos_sh = np.where(clauses > 0, shifted, np.uint32(0))
    neg_sh = np.where(clauses < 0, shifted, np.uint32(0))
    B, C, _ = clauses.shape
    pos = np.zeros((B, C, Wv), np.uint32)
    neg = np.zeros((B, C, Wv), np.uint32)
    for w in range(Wv):
        m = word == w
        pos[:, :, w] = np.bitwise_or.reduce(np.where(m, pos_sh, 0), axis=2)
        neg[:, :, w] = np.bitwise_or.reduce(np.where(m, neg_sh, 0), axis=2)
    return pos.view(np.int32), neg.view(np.int32)


def _pack_index_batch(rows: np.ndarray, Wv: int) -> np.ndarray:
    """Batched 0-based index matrices [B, R, M] (-1 pad) → packed int32
    membership bitplanes [B, R, Wv]."""
    mask = rows >= 0
    v = np.where(mask, rows, 0).astype(np.int64)
    word = v >> 5
    shifted = np.where(
        mask, np.left_shift(np.uint32(1), (v & 31).astype(np.uint32)),
        np.uint32(0),
    )
    B, R, _ = rows.shape
    out = np.zeros((B, R, Wv), np.uint32)
    for w in range(Wv):
        out[:, :, w] = np.bitwise_or.reduce(np.where(word == w, shifted, 0), axis=2)
    return out.view(np.int32)


def pad_stack(problems: Sequence[Problem], d: _Dims, total: int,
              pack: bool = True) -> core.ProblemTensors:
    """Pad and stack a whole problem list to [total, ...] batch tensors in
    one vectorized pass (trailing lanes beyond ``len(problems)`` are empty
    problems).  Equivalent to ``_stack([pad_problem(p, d) ...])`` but ~10×
    faster on fleet-scale batches — per-problem work is one slice
    assignment per field.  ``pack=False`` (what the dispatch paths use)
    skips host bit-packing entirely: plane fields come back as 1-word
    dummies and the device derives the real planes from the compact
    clause tensors (:func:`core.derive_planes`), which both removes the
    dominant host cost of a dispatch and ships fewer bytes."""
    n = len(problems)
    clauses = np.zeros((total, d.C, d.K), np.int32)
    card_ids = np.full((total, d.NA, d.M), -1, np.int32)
    card_n = np.zeros((total, d.NA), np.int32)
    card_act = np.full((total, d.NA), -1, np.int32)
    anchors = np.full((total, d.A), -1, np.int32)
    choice_cand = np.full((total, d.NC, d.Kc), -1, np.int32)
    var_choices = np.full((total, d.NV, d.W), -1, np.int32)
    n_vars = np.zeros(total, np.int32)
    n_cons = np.zeros(total, np.int32)
    for i, p in enumerate(problems):
        c = p.clauses
        clauses[i, : c.shape[0], : c.shape[1]] = c
        ci = p.card_ids
        card_ids[i, : ci.shape[0], : ci.shape[1]] = ci
        card_n[i, : p.card_n.shape[0]] = p.card_n
        card_act[i, : p.card_act.shape[0]] = p.card_act
        anchors[i, : p.anchors.shape[0]] = p.anchors
        cc = p.choice_cand
        choice_cand[i, : cc.shape[0], : cc.shape[1]] = cc
        vc = p.var_choices
        var_choices[i, : vc.shape[0], : vc.shape[1]] = vc
        n_vars[i] = p.n_vars
        n_cons[i] = p.n_cons
    if pack:
        pos_bits, neg_bits = _pack_planes_batch(clauses, d.Wv)
        member_bits = _pack_index_batch(card_ids, d.Wv)
        act_bits = _pack_index_batch(card_act[:, :, None], d.Wv)
    else:
        pos_bits = np.zeros((total, d.C, 1), np.int32)
        neg_bits = np.zeros((total, d.C, 1), np.int32)
        member_bits = np.zeros((total, d.NA, 1), np.int32)
        act_bits = np.zeros((total, d.NA, 1), np.int32)
    if pack and core.phases_reduced():
        clauses_r = np.where(
            np.abs(clauses) <= n_vars[:, None, None], clauses, 0
        )
        pos_bits_r, neg_bits_r = _pack_planes_batch(clauses_r, d.Wr)
        member_r = _pack_index_batch(card_ids, d.Wr)
    else:
        pos_bits_r = np.zeros((total, d.C, 1), np.int32)
        neg_bits_r = np.zeros((total, d.C, 1), np.int32)
        member_r = np.zeros((total, d.NA, 1), np.int32)
    if pack and d.Ob <= _bank_cap(d):
        from . import clause_bank

        occ_pos = np.full((total, d.V, d.Ob), -1, np.int32)
        occ_neg = np.full((total, d.V, d.Ob), -1, np.int32)
        occ_pos_r = np.full((total, d.NV, d.Ob), -1, np.int32)
        occ_neg_r = np.full((total, d.NV, d.Ob), -1, np.int32)
        card_occ = np.full((total, d.NV, d.Oc), -1, np.int32)
        for i, p in enumerate(problems):
            occ_pos[i], occ_neg[i] = clause_bank.occ_from_clauses_np(
                clauses[i], d.V, d.Ob)
            occ_pos_r[i], occ_neg_r[i] = clause_bank.occ_from_clauses_np(
                clauses[i], d.NV, d.Ob, n_vars=int(p.n_vars))
            card_occ[i] = clause_bank.card_occ_np(card_ids[i], d.NV, d.Oc)
    else:
        occ_pos = occ_neg = np.full((total, 1, 1), -1, np.int32)
        occ_pos_r = occ_neg_r = np.full((total, 1, 1), -1, np.int32)
        card_occ = np.full((total, 1, 1), -1, np.int32)
    return core.ProblemTensors(
        clauses=clauses,
        card_ids=card_ids,
        card_n=card_n,
        card_act=card_act,
        anchors=anchors,
        choice_cand=choice_cand,
        var_choices=var_choices,
        n_vars=n_vars,
        n_cons=n_cons,
        pos_bits=pos_bits,
        neg_bits=neg_bits,
        card_member_bits=member_bits,
        card_act_bits=act_bits,
        pos_bits_r=pos_bits_r,
        neg_bits_r=neg_bits_r,
        card_member_bits_r=member_r,
        card_valid=(card_act >= 0).astype(np.int32),
        occ_pos=occ_pos,
        occ_neg=occ_neg,
        occ_pos_r=occ_pos_r,
        occ_neg_r=occ_neg_r,
        card_occ=card_occ,
    )


# Compact fields a dispatch uploads; every bitplane field is derived from
# them on device (core.derive_planes), so no plane bytes ever cross
# host→device and no host time is spent packing.
_COMPACT_FIELDS = (
    "clauses", "card_ids", "card_n", "card_act", "anchors", "choice_cand",
    "var_choices", "n_vars", "n_cons", "card_valid",
)


@_functools.lru_cache(maxsize=128)
def _planes_fn(Wv: int, Wr: int, red: bool, full: bool):
    return jax.jit(compileguard.observe(
        "driver.planes_fn",
        _functools.partial(core.derive_planes, Wv=Wv, Wr=Wr, red=red,
                           full=full),
        static=(Wv, Wr, red, full),
    ))


# Watched-bank occurrence-width cap (0 = the dispatch's size-class OCC
# cap from the shared ladder): a batch whose max per-literal clause
# count exceeds the cap would pay an occ table of V x Ob cells mostly
# for one popular literal — those dispatches ship dummy banks and the
# compiled program statically falls back to the dense rounds.
BANK_OCC_CAP = int(config.env_raw("DEPPY_TPU_BANK_OCC_CAP", "0"))


@_functools.lru_cache(maxsize=128)
def _bank_fn(V: int, NV: int, Ob: int, Oc: int, red: bool, full: bool):
    from . import clause_bank

    return jax.jit(compileguard.observe(
        "driver.bank_fn",
        _functools.partial(clause_bank.derive_banks, V=V, NV=NV, Ob=Ob,
                           Oc=Oc, red=red, full=full),
        static=(V, NV, Ob, Oc, red, full),
    ))


def _bank_cap(d: "_Dims") -> int:
    if BANK_OCC_CAP > 0:
        return BANK_OCC_CAP
    name = _size_classes.class_of_cost((d.C + 2 * d.NV) * d.Wv)
    return _size_classes.occ_cap(name)


def _derive_banks(pts: core.ProblemTensors, d: "_Dims", red: bool,
                  full: bool) -> core.ProblemTensors:
    """Replace the dummy clause-bank fields with device-derived banks
    (watched impl only; reads the chunk's device-resident compact
    tensors).  A batch whose occurrence width exceeds its cap keeps the
    dummies — the watched program detects them statically and runs the
    dense rounds instead."""
    if d.Ob > _bank_cap(d):
        return pts
    occ_pos, occ_neg, occ_pos_r, occ_neg_r, card_occ = _bank_fn(
        d.V, d.NV, d.Ob, d.Oc, red, full
    )(pts.clauses, pts.card_ids, pts.n_vars)
    return pts._replace(occ_pos=occ_pos, occ_neg=occ_neg,
                        occ_pos_r=occ_pos_r, occ_neg_r=occ_neg_r,
                        card_occ=card_occ)


def _derive_planes(pts: core.ProblemTensors, d: _Dims,
                   full: Optional[bool] = None,
                   red: Optional[bool] = None) -> core.ProblemTensors:
    """Replace the (dummy) plane fields with device-derived planes.

    ``full=None`` materializes the full-space planes only when the
    selected impl's search/minimization phases read them — under the
    default bits impl those run in the reduced space, so SAT-dominated
    chunks never hold full planes resident; the unsat-core dispatches ask
    for ``full=True`` explicitly (:func:`_derive_full`).

    The gather impl never reads plane *contents* (its BCP walks the
    compact clause matrices), but the packed DPLL state is still sized by
    ``pos_bits.shape[-1]`` — it gets single-row zero planes carrying only
    that width."""
    if core._resolved_impl() == "gather":
        B = np.shape(pts.clauses)[0]
        z = np.zeros((B, 1, d.Wv), np.int32)
        return pts._replace(
            pos_bits=z, neg_bits=z, card_member_bits=z, card_act_bits=z,
        )
    if full is None:
        full = not core.phases_reduced()
    if red is None:
        red = core.phases_reduced()
    pos, neg, mem, act, pos_r, neg_r, mem_r = _planes_fn(
        d.Wv, d.Wr, red, full
    )(pts.clauses, pts.card_ids, pts.card_act, pts.n_vars)
    pts = pts._replace(
        pos_bits=pos, neg_bits=neg, card_member_bits=mem, card_act_bits=act,
        pos_bits_r=pos_r, neg_bits_r=neg_r, card_member_bits_r=mem_r,
    )
    if core._resolved_impl() == "watched":
        pts = _derive_banks(pts, d, red, full)
    return pts


def _derive_full(pts: core.ProblemTensors, d: _Dims) -> core.ProblemTensors:
    """Add full-space planes to an already-resident chunk (unsat-core
    phase inputs; reads the chunk's device-resident compact tensors, so
    nothing re-crosses the host boundary)."""
    pos, neg, mem, act, _, _, _ = _planes_fn(d.Wv, d.Wr, False, True)(
        pts.clauses, pts.card_ids, pts.card_act, pts.n_vars
    )
    pts = pts._replace(
        pos_bits=pos, neg_bits=neg, card_member_bits=mem, card_act_bits=act,
    )
    if core._resolved_impl() == "watched" and d.Ob <= _bank_cap(d):
        # Full-space banks only — the chunk's reduced banks stay.
        occ_pos, occ_neg, _, _, card_occ = _bank_fn(
            d.V, d.NV, d.Ob, d.Oc, False, True
        )(pts.clauses, pts.card_ids, pts.n_vars)
        pts = pts._replace(occ_pos=occ_pos, occ_neg=occ_neg,
                           card_occ=card_occ)
    return pts


_EMPTY_PROBLEM: Optional[Problem] = None


def _empty_problem() -> Problem:
    global _EMPTY_PROBLEM
    if _EMPTY_PROBLEM is None:
        _EMPTY_PROBLEM = encode([])
    return _EMPTY_PROBLEM


def _stack(pts: Sequence[core.ProblemTensors]) -> core.ProblemTensors:
    return core.ProblemTensors(
        *[np.stack([getattr(p, f) for p in pts]) for f in core.ProblemTensors._fields]
    )


def _budget(max_steps: Optional[int]) -> np.int32:
    return np.int32(min(max_steps if max_steps is not None else DEFAULT_MAX_STEPS,
                        np.iinfo(np.int32).max - 1))


def _to_device(tree, mesh):
    if mesh is None:
        return tree
    from ..parallel.mesh import shard_batch

    return shard_batch(mesh, tree)


def _put_compact(pts: core.ProblemTensors) -> core.ProblemTensors:
    """device_put the compact fields; plane dummies stay host-side."""
    return core.ProblemTensors(**{
        f: (jax.device_put(getattr(pts, f)) if f in _COMPACT_FIELDS
            else getattr(pts, f))
        for f in core.ProblemTensors._fields
    })


def _put_chunk(pts_chunk: core.ProblemTensors, mesh, d: _Dims,
               full: Optional[bool] = None,
               red: Optional[bool] = None) -> core.ProblemTensors:
    """Upload one chunk's compact tensors explicitly (so later phases
    reuse the device-resident buffers instead of re-transferring) and
    derive its bitplanes on device.  Under a mesh the compact fields are
    sharded over the batch axis first; the derived planes inherit that
    sharding (elementwise build)."""
    if mesh is not None:
        return _derive_planes(_to_device(pts_chunk, mesh), d, full, red)
    return _derive_planes(_put_compact(pts_chunk), d, full, red)


def _pad_group(k: int, mesh) -> int:
    """Padded batch size for a compacted phase group: power of two and a
    multiple of the mesh size."""
    b = _bucket(k)
    m = mesh.size if mesh is not None else 1
    if b % m:
        b *= m // np.gcd(b, m)
    return b


def _gather_rows(pts: core.ProblemTensors, idx: np.ndarray, B: int,
                 empty_row: core.ProblemTensors) -> core.ProblemTensors:
    """Compact batch rows ``idx`` out of a stacked pytree, padding to ``B``
    lanes with the empty problem."""
    pad = B - idx.size
    fields = []
    for f in core.ProblemTensors._fields:
        a = getattr(pts, f)[idx]
        e = getattr(empty_row, f)
        if pad:
            a = np.concatenate(
                [a, np.broadcast_to(e[None], (pad,) + e.shape).copy()]
            )
        fields.append(a)
    return core.ProblemTensors(*fields)


def _pad_rows(a: np.ndarray, B: int, fill=0) -> np.ndarray:
    pad = B - a.shape[0]
    if not pad:
        return a
    return np.concatenate(
        [a, np.full((pad,) + a.shape[1:], fill, dtype=a.dtype)]
    )


# Core extraction for problems above this many applied constraints routes
# to the host spec engine instead of the device deletion loop (monolith
# and compacted-split paths; the en-gated UNSAT-heavy fleet path stays on
# device, where batch parallelism amortizes the sweep).  Two measured
# reasons: the sweep's cost is dominated by kept-member probes — full SAT
# searches — which the serial host engine resolves faster than a lockstep
# device program at giant sizes (2.1s vs 7.7s at 1.7k constraints, CPU
# XLA), and on the tunneled TPU a minutes-long single program execution
# can crash the worker (the same failure mode as ≥1024-lane programs).
# Results are bit-identical: HostEngine.unsat_core_mask IS the spec the
# device loop reproduces.
HOST_CORE_NCONS = int(config.env_raw("DEPPY_TPU_HOST_CORE_NCONS", "768"))


# Lane width of one speculative-probe dispatch (stage 1 below).  Bounded
# like MAX_LANES: oversized programs are what crash the tunneled worker.
PROBE_LANES = int(config.env_raw("DEPPY_TPU_PROBE_LANES", "512"))

# Speculative-core policy.  Measured on CPU XLA it LOSES to the host
# spec sweep (27.6s vs 2.1s on the 1.7k-constraint giant catalog): the
# vmapped probe fixpoint runs max-over-lanes propagation rounds, and one
# deep-chain lane drags 512 lanes × full clause planes through ~dozens
# of rounds on one core.  The accelerator bet is bandwidth — the same
# traffic is a few hundred MB of HBM reads — but that bet has ZERO
# accelerator measurements (worker outage, rounds 3-4), and its failure
# mode on the tunneled worker is the minutes-long-single-execution crash
# class.  So "auto" resolves to OFF everywhere until a TPU measurement
# exists (round-3 verdict weak #4): flip auto back to
# accelerator-enabled only alongside a measured giant-catalog row in
# BASELINE.md.  "1"/"0" force it on/off (tests force "1" on CPU).
SPEC_CORE = config.env_raw("DEPPY_TPU_SPEC_CORE", "auto")

# Per-dispatch step budget for the speculative sweep's SEARCH stages
# (stage-2 DPLL lanes and the certifying probe).  The caller's remaining
# budget can be millions of steps, and a 512-lane lockstep program
# running a deep SAT search that long is exactly the
# minutes-long-single-execution class that crashes the tunneled worker
# (BASELINE.md round-3 notes, crash 2).  Exceeding the cap is harmless
# for correctness: capped-out lanes read as RUNNING and the sweep
# returns None, falling back to the host spec sweep with the steps
# spent charged against the budget.
SPEC_CORE_CAP = int(config.env_raw("DEPPY_TPU_SPEC_CORE_CAP", str(1 << 15)))


def _spec_core_enabled() -> bool:
    if SPEC_CORE == "1":
        return True
    if SPEC_CORE == "auto":
        # Measured default per backend: the revalidation ladder's stage
        # H records the full-scale A/B verdict ('on' only when the
        # speculative sweep agreed with the host sweep AND won on time)
        # in the measured-defaults registry; with no measured row the
        # conservative answer stays OFF — the accelerator upside is
        # unmeasured while the downside is a known worker-crash class
        # (see SPEC_CORE above).
        return core.measured_default("spec_core") == "on"
    return False


def _speculative_core_mask(problem, remaining: int):
    """Deletion-sweep shortcut for ONE giant problem: run all n_cons
    single-drop probes as vmap lanes of a batched device program instead
    of n_cons sequential host solves, then certify the result with one
    probe.  Returns (core_mask[n_cons] or None, steps_spent) — on None
    the caller falls back to the host spec sweep (with the leftover
    budget), so correctness never depends on this path succeeding.

    Exactness (trust-but-verify): let K = {j : SAT without j} over the
    INITIAL full active set.  SAT(all\\{j}) implies SAT of every subset,
    so the spec's in-order sweep keeps each j in K at its turn, whatever
    was dropped before — K is a subset of the spec's final core.  If the
    verification probe shows K itself UNSAT, then at every j outside K
    the spec's remaining active set contains K, hence stays UNSAT without
    j, hence the spec drops j — its final core is exactly K.  If K probes
    SAT (overlapping/disjoint cores: order decides), this shortcut proves
    nothing and returns None.  Probes here and in the spec agree
    literally: same base assignment, anchors not assumed
    (core.probe_phase is core_phase's own trial probe).

    Steps: 1 per stage-1 fixpoint probe (the host's near-free probes also
    count ~1) plus the DPLL steps of stage-2 and verification lanes."""
    n = int(problem.n_cons)
    if n == 0 or remaining <= 0:
        return None, 0
    d = _Dims([problem], 1)
    pts1 = _put_compact(pad_stack([problem], d, 1, pack=False))
    pts1 = _derive_planes(pts1, d, full=True, red=False)
    pt = jax.tree_util.tree_map(lambda a: a[0], pts1)
    steps = 0

    # Stage 1: one propagation fixpoint per single-drop probe; a conflict
    # proves that probe UNSAT with zero search (the common case on an
    # overconstrained catalog).
    fp = core.batched_probe_fixpoint(d.V, d.NCON)
    P = min(PROBE_LANES, _bucket(n))
    conflicts = []
    for lo in range(0, n, P):
        drop = np.arange(lo, lo + P, dtype=np.int32)  # tail lanes: j >= n
        conflicts.append(fp(pt, drop))
    conflict = np.concatenate(jax.device_get(conflicts))[:n]
    steps += n

    # Stage 2: finish undetermined probes (core members' SAT probes plus
    # any UNSAT that needs actual search) with full DPLL lanes.
    pend = np.nonzero(~conflict)[0]
    status = np.full(n, core.UNSAT, np.int32)
    if pend.size:
        if pend.size > max(n // 2, PROBE_LANES):
            return None, steps  # propagation settled little: wrong case
        pb = core.batched_probe(d.V, d.NCON, d.NV)
        Q = min(_bucket(min(pend.size, PROBE_LANES)), PROBE_LANES)
        idx32 = np.arange(d.NCON, dtype=np.int32)
        for lo in range(0, pend.size, Q):
            rows = pend[lo: lo + Q]
            trials = (idx32[None, :] < n) & (idx32[None, :] != rows[:, None])
            # Pad lanes probe the EMPTY active set (immediately SAT) — an
            # all-active pad would re-prove the whole problem UNSAT under
            # lockstep, stalling the real lanes.
            trials = np.concatenate(
                [trials, np.zeros((Q - len(rows), d.NCON), bool)])
            st, sp = jax.device_get(
                pb(pt, trials, np.int32(min(remaining, SPEC_CORE_CAP))))
            status[rows] = st[: len(rows)]
            steps += int(sp[: len(rows)].sum())
            if steps > remaining:
                # Budget already blown: don't dispatch chunks whose
                # results the post-loop check would discard anyway.
                return None, steps
        if (status[pend] == core.RUNNING).any():
            # Budget pressure: let the spec sweep own the Incomplete call.
            return None, steps
    else:
        Q = 1

    keep = status == core.SAT
    if not keep.any():
        return None, steps  # every single drop stays UNSAT: order decides

    # Verification: K UNSAT ⇒ the spec sweep's core is exactly K.  Padded
    # to stage 2's lane width so the same compiled program is reused (pad
    # lanes probe the empty set, like stage 2's).
    pb = core.batched_probe(d.V, d.NCON, d.NV)
    vt = np.zeros((Q, d.NCON), bool)
    vt[0, :n] = keep
    st, sp = jax.device_get(
        pb(pt, vt, np.int32(min(remaining, SPEC_CORE_CAP))))
    steps += int(sp[0])
    if int(st[0]) != core.UNSAT or steps > remaining:
        return None, steps
    return keep, steps


def _host_core_rows(problems, idx, d: _Dims, budget, spent,
                    allow_device: bool = False) -> tuple:
    """Host-engine core extraction for the given batch rows.  Returns
    (cores [len(idx), NCON] bool, steps [len(idx)]) — steps to ADD to the
    lane's device count.  Each lane's engine gets only the budget left
    after its device solve (``spent``), so the combined count trips the
    caller's ``steps > budget`` Incomplete check exactly like the device
    core phase, which continues counting from the search's total against
    the same budget — the routing stays outcome-invisible under tight
    budgets, not just generous ones.

    This function is the single source of the routing's steps/outcome
    convention (remaining-budget cap, one-tick-over on exhaustion); its
    three callers — _solve_monolith, _solve_split, and
    parallel.clause_shard.solve_sharded — each add the returned steps to
    the lane's device count and flip the lane to RUNNING when the total
    exceeds the budget.  Change all three together.

    ``allow_device`` (only the monolith caller, single-device runs — the
    split path keeps the host sweep that overlaps its in-flight device
    dispatches) first tries :func:`_speculative_core_mask` — the whole
    sweep as one batched device program plus a certifying probe,
    bit-identical when it succeeds — and falls back to the host spec
    sweep on any ambiguity, with the speculative attempt's steps charged
    against the budget."""
    from ..sat.host import HostEngine

    # The "silent host fallback" made loud: every row routed here counts.
    telemetry.default_registry().counter(
        "deppy_host_fallback_rows_total",
        "UNSAT rows whose core extraction routed to the host spec engine.",
    ).inc(len(idx))
    _rep = telemetry.current_report()
    if _rep is not None:
        _rep.host_fallback_rows += len(idx)

    cores = np.zeros((len(idx), d.NCON), bool)
    steps = np.zeros(len(idx), np.int64)
    for r, i in enumerate(idx):
        remaining = int(budget) - int(spent[r])
        if remaining <= 0:
            steps[r] = 1  # already over: one tick keeps the lane RUNNING
            continue
        spec_steps = 0
        if allow_device and _spec_core_enabled():
            mask, spec_steps = _speculative_core_mask(problems[i], remaining)
            if mask is not None:
                cores[r, : problems[i].n_cons] = mask
                steps[r] = spec_steps
                continue
            if spec_steps >= remaining:
                steps[r] = remaining + 1
                continue
        eng = HostEngine(problems[i], max_steps=remaining - spec_steps)
        try:
            cores[r, : problems[i].n_cons] = eng.unsat_core_mask()
            steps[r] = spec_steps + eng.steps
        except Incomplete:
            # Budget exhausted mid-sweep: mirror the device contract —
            # steps past the budget mark the lane Incomplete on decode.
            steps[r] = remaining + 1
    return cores, steps


def _profile_dispatch(t0, problems, d: _Dims, steps: np.ndarray,
                      live: int, total: int, chunk: int) -> None:
    """Trip-ledger hook shared by the dispatch impls (ISSUE 11): runs
    only for dispatches :func:`profile.dispatch_t0` sampled, strictly
    AFTER the result fetch (host numpy in hand — never inside traced
    code).  ``steps`` are the dispatch's final per-lane counts, live
    lanes first; ``chunk`` is the lockstep program width."""
    cost = max(_cost_proxy(p) for p in problems)
    _profile.record_device_dispatch(
        t0, steps=steps, live=live, chunk=chunk,
        size_class=_bucket(cost),
        size_class_name=_size_classes.class_of_cost(cost),
        pad_cells=int(total) * d.C * d.K,
        live_cells=int(sum(p.clauses.size for p in problems)))


def padded_class(problems) -> str:
    """The ladder class of a dispatch group's PADDED batch dims — the
    same classification :func:`_bank_cap` applies to the same dispatch
    (cost over the bucketed C/NV/NCON maxima), and a function of
    exactly the dims that key jit's shape cache.  The max of
    per-problem cost proxies is NOT such a function (a wide-clause
    problem and a wide-var problem can trade maxima), so per-class
    impl routing must classify here, not there."""
    C = _size_classes.bucket(max((p.clauses.shape[0]
                                  for p in problems), default=1))
    NV = _size_classes.bucket(max((p.n_vars for p in problems),
                                  default=1))
    NCON = _size_classes.bucket(max((p.n_cons for p in problems),
                                    default=1))
    Wv = -(-(NV + NCON) // _size_classes.WORD)
    return _size_classes.class_of_cost((C + 2 * NV) * Wv)


def _class_impl_scoped(fn):
    """Scope a dispatch-group impl to its ladder class's resolved BCP
    impl (ISSUE 13 satellite: the measured-defaults ``bcp`` row is
    keyed per size class, so deep-chain classes run ``watched`` while
    the mixed fleet keeps ``bits``).  The class comes from
    :func:`padded_class` — a function of the padded dims that key the
    compiled programs, so two dispatches reaching the same program
    always resolve the same impl.  With the global knob set, or no
    per-class row measured, the scope resolves to exactly what the
    global resolution would — byte-identical dispatch."""

    @_functools.wraps(fn)
    def wrapped(problems, budget, mesh, trace_cap, **kw):
        if not problems or core._BCP_IMPL != "auto":
            return fn(problems, budget, mesh, trace_cap, **kw)
        with core.impl_scope(
                core.resolved_impl_for(padded_class(problems))):
            return fn(problems, budget, mesh, trace_cap, **kw)

    return wrapped


@_class_impl_scoped
def _solve_monolith(problems, budget, mesh, trace_cap,
                    _spmd_entry: bool = False) -> List[core.SolveResult]:
    """Single-dispatch path (one jitted program, all phases lane-gated):
    the right trade for a batch of one, where phase compaction buys
    nothing and one compile beats three.  ``_spmd_entry`` swaps the
    jitted program for :func:`batched_solve_sharded` — same vmapped
    solve, explicit PartitionSpec shardings over ``mesh`` — the SPMD
    spelling of the mesh entry (:func:`_solve_spmd`)."""
    prof_t0 = _profile.dispatch_t0()
    n = len(problems)
    d = _Dims(problems, max(n, 1), batch_multiple=mesh.size if mesh is not None else 1)
    host_core = any(p.n_cons > HOST_CORE_NCONS for p in problems)
    reg = telemetry.default_registry()
    rep = telemetry.current_report()
    # The single program runs every device phase, so both plane spaces
    # materialize — except under host_core, where the deletion arm (the
    # only reader of the full-space planes under the bits impl) is
    # compiled out and the default derivation suffices.  _put_chunk
    # device_puts the compact tensors first so they cross host→device
    # exactly once.
    with reg.span("driver.pad_pack", problems=n, lanes=int(d.B)) as sp:
        pts_np = pad_stack(problems, d, d.B, pack=False)
    _telem_record_pad(problems, d.B, d, n_chunks=1, dur_s=sp.dur_s)
    with reg.span("driver.device_put", lanes=int(d.B)) as sp:
        faults.inject("driver.device_put")
        pts = _put_chunk(pts_np, mesh, d,
                         full=True if not host_core else None)
    if rep is not None:
        rep.add_wall("device_put", sp.dur_s)
    if _spmd_entry:
        fn = batched_solve_sharded(mesh, d.V, d.NCON, d.NV, trace_cap,
                                   with_core=not host_core)
    else:
        fn = core.batched_solve(d.V, d.NCON, d.NV, trace_cap,
                                with_core=not host_core)
    res = fn(pts, budget)
    # One batched fetch for the whole result tree: each individual
    # device→host transfer pays a full round trip on a tunneled TPU
    # (~70ms+), so per-field np.asarray would cost 6 round trips.
    res = jax.device_get(res)
    outcome = np.asarray(res.outcome)
    installed = np.asarray(res.installed)
    cores = np.asarray(res.core)
    steps = np.asarray(res.steps).astype(np.int64)
    trace_stack = np.asarray(res.trace_stack)
    trace_n = np.asarray(res.trace_n)
    # Ledger steps snapshot BEFORE host-core patching: the trip model
    # is about lockstep device while-trips, and folding the host spec
    # engine's core-sweep iterations into a lane's count would inflate
    # trips with work the device loop never executed (biasing the
    # us/trip regression the profiler exists to produce).
    prof_steps = steps.copy() if (prof_t0 is not None and host_core) \
        else steps
    if host_core:
        outcome, cores, steps = _host_core_patch(
            problems, d, budget, outcome, cores, steps,
            allow_device=mesh is None)
    if prof_t0 is not None:
        _profile_dispatch(prof_t0, problems, d, prof_steps, live=n,
                          total=int(d.B), chunk=int(d.B))
    return [
        core.SolveResult(outcome[i], installed[i], cores[i], steps[i],
                         trace_stack[i], trace_n[i])
        for i in range(n)
    ]


def _host_core_patch(problems, d: _Dims, budget, outcome, cores, steps,
                     allow_device: bool = False):
    """Host-route core extraction for a fetched single-program result's
    UNSAT rows (the ``with_core=False`` compositions: monolith and the
    mesh-serving shard dispatch) — same steps/outcome convention as
    :func:`_host_core_rows`.  Returns (outcome, cores, steps); inputs
    are host numpy, ``cores`` is copied before patching."""
    unsat_idx = np.nonzero(outcome[: len(problems)] == core.UNSAT)[0]
    if unsat_idx.size:
        hc, hs = _host_core_rows(problems, unsat_idx, d, budget,
                                 steps[unsat_idx],
                                 allow_device=allow_device)
        cores = cores.copy()
        cores[unsat_idx] = hc
        steps[unsat_idx] += hs
        outcome = np.where(steps > int(budget), core.RUNNING, outcome)
    return outcome, cores, steps


# Per-dispatch lane cap (power of two).  Two reasons: (1) the axon-tunneled
# v5e worker is unstable executing ≥1024-lane programs of this engine
# (reproducible worker crashes; 512 is rock solid), and (2) smaller
# dispatches bound max-over-lanes lockstep waste while async dispatch keeps
# the device busy across chunks.  One batched fetch per phase still pays a
# single tunnel round trip regardless of chunk count.
MAX_LANES = int(config.env_raw("DEPPY_TPU_MAX_LANES", "512"))


def _chunk_slices(total: int, ch: int) -> List[slice]:
    return [slice(i, i + ch) for i in range(0, total, ch)]


def _rows(pts: core.ProblemTensors, sl: slice) -> core.ProblemTensors:
    return core.ProblemTensors(
        *[getattr(pts, f)[sl] for f in core.ProblemTensors._fields]
    )


@_class_impl_scoped
def _solve_split(problems, budget, mesh, trace_cap) -> List[core.SolveResult]:
    """Chunked three-phase path: search over the batch in ≤ MAX_LANES
    dispatches, then minimization on compacted SAT-lane chunks and core
    extraction on compacted UNSAT-lane chunks.

    Under ``vmap`` every ``while_loop`` runs max-over-lanes iterations, so
    in the single-program composition a batch's few UNSAT lanes serialize
    every lane through the O(n_cons) deletion loop and SAT lanes pay for
    minimization they may not need; compaction confines each phase's cost
    to the lanes that need it (SURVEY.md §7.3 item 4's divergence
    mitigation).  All chunks of a phase dispatch asynchronously (device
    work pipelines) and their results come back in one batched fetch."""
    prof_t0 = _profile.dispatch_t0()
    prof_steps = None  # device-only ledger snapshot (set on host route)
    n = len(problems)
    # MAX_LANES caps every dispatch, mesh or not: sharding divides lanes
    # across devices but each worker still executes its shard of one
    # program, and oversized programs are what crash the axon worker.
    ch_cap = min(max(n, 1), MAX_LANES)
    d = _Dims(problems, ch_cap, batch_multiple=mesh.size if mesh is not None else 1)
    CH = d.B
    n_chunks = max(1, -(-n // CH))
    total = n_chunks * CH
    reg = telemetry.default_registry()
    rep = telemetry.current_report()
    empty_row = pad_problem(_empty_problem(), d, pack=False)
    with reg.span("driver.pad_pack", problems=n, lanes=total,
                  chunks=n_chunks) as sp:
        pts_np = pad_stack(problems, d, total, pack=False)
    _telem_record_pad(problems, total, d, n_chunks=n_chunks, dur_s=sp.dur_s)
    en = np.arange(total) < n
    slices = _chunk_slices(total, CH)

    # Compact problem tensors go to the device in ONE transfer for the
    # whole batch, then chunks are sliced on device: on a tunneled TPU
    # every device_put call pays a full round trip, so per-chunk uploads
    # cost n_chunks round trips (measured 473ms of a 1.2s dispatch at
    # 8 chunks) where one batched upload pays one.  Planes are derived
    # per chunk on device and everything stays resident: phase 2 reuses
    # the buffers directly, so nothing is re-uploaded.  Under a mesh the
    # per-chunk path shards each chunk's batch axis instead (a single
    # upload would fix the whole batch onto one device).
    with reg.span("driver.device_put", lanes=total, chunks=n_chunks) as sp:
        faults.inject("driver.device_put")
        if mesh is None:
            pts_all = _put_compact(pts_np)
            pts_dev = [_derive_planes(_rows(pts_all, sl), d)
                       for sl in slices]
            # The chunk slices are independent buffers; drop the
            # full-batch copy so it doesn't hold HBM alongside them for
            # the whole solve.
            del pts_all
        else:
            pts_dev = [_put_chunk(_rows(pts_np, sl), mesh, d)
                       for sl in slices]
        en_dev = [_to_device(en[sl], mesh) for sl in slices]
    if rep is not None:
        rep.add_wall("device_put", sp.dur_s)

    fn_a = core.batched_search(d.V, d.NCON, d.NV, trace_cap)
    outs = [fn_a(p, budget, e) for p, e in zip(pts_dev, en_dev)]

    # Phase 2 dispatches immediately on the same device-resident chunks,
    # gated per lane by the phase-1 result — no host round trip in between.
    fn_b = core.batched_minimize_gated(d.V, d.NCON, d.NV)
    res_b = [
        fn_b(p, o[0], o[2], o[1], budget, o[3], e)
        for p, o, e in zip(pts_dev, outs, en_dev)
    ]

    # One small fetch decides the phase-3 strategy (results + steps only).
    small = jax.device_get([(o[0], o[3], o[5]) for o in outs])
    result = np.concatenate([s[0] for s in small])
    steps = np.concatenate([s[1] for s in small]).astype(np.int64)
    trace_n = np.concatenate([s[2] for s in small])

    installed = np.zeros((total, d.NV), bool)
    min_found = np.zeros(total, bool)
    cores = np.zeros((total, d.NCON), bool)

    unsat_idx = np.nonzero(en & (result == core.UNSAT))[0]
    sat_any = bool((en & (result == core.SAT)).any())

    res_c: list = []
    core_gated = unsat_idx.size > total // 2
    if unsat_idx.size and core_gated:
        # UNSAT-heavy batch: compaction would re-upload nearly every row —
        # run the deletion loop en-gated on the resident chunks instead.
        # Under the bits impl the resident chunks carry only reduced
        # planes; the core phase probes with activations disabled, so its
        # full-space planes are derived here from the resident compact
        # tensors (no host round trip).
        fn_cg = core.batched_core_gated(d.V, d.NCON, d.NV)
        red = core.phases_reduced()
        # Derive per chunk inside the loop so only one chunk's full planes
        # are live at a time (they free once its dispatch retires).
        res_c = [
            fn_cg(_derive_full(p, d) if red else p, o[0], budget, o[3], e)
            for p, o, e in zip(pts_dev, outs, en_dev)
        ]
    elif unsat_idx.size:
        # Few UNSAT lanes: giant problems route to the host spec engine
        # (HOST_CORE_NCONS — kept-member probes are full SAT searches the
        # serial host resolves faster, and long device programs endanger
        # the tunneled worker); the rest compact into (usually) one small
        # device dispatch — only those rows transfer again (and only
        # their compact tensors — the core phase's full-space planes are
        # derived on device).
        host_idx = unsat_idx[
            [problems[i].n_cons > HOST_CORE_NCONS for i in unsat_idx]
        ]
        dev_idx = unsat_idx[
            [problems[i].n_cons <= HOST_CORE_NCONS for i in unsat_idx]
        ]
        b = 0
        if dev_idx.size:
            fn_c = core.batched_core(d.V, d.NCON, d.NV)
            b = min(_pad_group(dev_idx.size, mesh), CH)
            for idx in [dev_idx[i: i + b]
                        for i in range(0, dev_idx.size, b)]:
                res_c.append(fn_c(
                    # The core phase reads only the full-space planes: skip
                    # the reduced build on these re-gathered rows.
                    _put_chunk(_gather_rows(pts_np, idx, b, empty_row),
                               mesh, d, full=True, red=False),
                    budget,
                    _to_device(_pad_rows(steps[idx], b), mesh),
                    _to_device(np.arange(b) < idx.size, mesh),
                ))
        if host_idx.size:
            # Runs on the host CPU while the device chews on the phase-2/3
            # dispatches above — the final fetch below synchronizes both.
            # allow_device stays False here: these rows overlap with the
            # in-flight phase-2/3 dispatches (the comment below), and a
            # speculative device attempt would queue behind them and
            # block — serializing exactly what this path parallelizes.
            # The monolith path, where the device is idle by core time,
            # is where the speculative probes run.
            host_cores, host_steps = _host_core_rows(
                problems, host_idx, d, budget, steps[host_idx]
            )

    # Final batched fetch: all phase-2 and phase-3 results (and trace
    # buffers if compiled in) in one round trip.
    fetch = {"b": res_b if sat_any else [], "c": res_c}
    if trace_cap > 0:
        fetch["tr"] = [o[4] for o in outs]
    fetched = jax.device_get(fetch)

    if sat_any:
        inst_c = np.concatenate([r[0] for r in fetched["b"]])
        mf_c = np.concatenate([r[1] for r in fetched["b"]])
        st_c = np.concatenate([r[2] for r in fetched["b"]])
        sat_mask = en & (result == core.SAT)
        installed[sat_mask] = inst_c[sat_mask]
        min_found[sat_mask] = mf_c[sat_mask]
        steps[sat_mask] = st_c[sat_mask]
    if unsat_idx.size:
        if core_gated:
            core_c = np.concatenate([r[0] for r in fetched["c"]])
            st_c = np.concatenate([r[1] for r in fetched["c"]])
            cores[unsat_idx] = core_c[unsat_idx]
            steps[unsat_idx] = st_c[unsat_idx]
        else:
            if dev_idx.size:
                core_c = np.concatenate([r[0] for r in fetched["c"]])
                st_c = np.concatenate([r[1] for r in fetched["c"]])
                ks = [min(b, dev_idx.size - j)
                      for j in range(0, dev_idx.size, b)]
                keep = np.concatenate([np.arange(b) < k for k in ks])
                cores[dev_idx] = core_c[keep]
                steps[dev_idx] = st_c[keep]
            if host_idx.size:
                cores[host_idx] = host_cores
                if prof_t0 is not None:
                    # Device-only snapshot for the trip ledger (see
                    # _solve_monolith): host spec-engine core steps are
                    # not lockstep trips.
                    prof_steps = steps.copy()
                steps[host_idx] = steps[host_idx].astype(np.int64) + host_steps
    if trace_cap > 0:
        trace_stack = np.concatenate(fetched["tr"])
    else:
        trace_stack = np.zeros((total, 0, 0), np.int32)

    incomplete = (
        (steps > int(budget))
        | (result == core.RUNNING)
        | ((result == core.SAT) & ~min_found)
    )
    outcome = np.where(incomplete, core.RUNNING, result).astype(np.int32)
    if prof_t0 is not None:
        _profile_dispatch(prof_t0, problems, d,
                          prof_steps if prof_steps is not None else steps,
                          live=n, total=total, chunk=CH)
    return [
        core.SolveResult(outcome[i], installed[i], cores[i], steps[i],
                         trace_stack[i], trace_n[i])
        for i in range(n)
    ]


# Size-class bucketing (SURVEY.md §7.3 items 4-5): a heterogeneous fleet
# batch is partitioned into size classes so one large straggler doesn't
# inflate every lane's padded planes.  The class boundaries come from the
# SHARED ladder (deppy_tpu.size_classes — the same table the
# block-contract lint tier evaluates), so a 64-clause problem lands in
# `xs` and never shares dims with an `l` problem, whatever the cost
# distribution between them looks like.  The pre-ISSUE-12 splitter cut
# only at >= SPLIT_RATIO jumps between ADJACENT sorted costs — on a
# smooth distribution no adjacent jump ever reaches the ratio even when
# the extremes span 64x, which is exactly how a 64-clause problem ended
# up paying a 4096-clause pad (`block-pad-waste`, ROADMAP item 1).  That
# splitter is kept behind DEPPY_TPU_SIZE_LADDER=off for A/B (and
# MAX_BUCKETS keeps its pre-ladder value so that arm reproduces the
# replaced partitioner exactly; under the ladder it caps the jump
# splits WITHIN each class).  Buckets below MIN_BUCKET problems aren't
# worth a separate dispatch and merge with their neighbor.
MAX_BUCKETS = 4
MIN_BUCKET = 16
# Only split at a size-class boundary when the padded per-lane cost ratio
# across it is at least this factor (shared with the lint contracts).
SPLIT_RATIO = _size_classes.SPLIT_RATIO

# Ladder-vs-legacy partitioner selection ('on' = the shared size-class
# ladder; 'off' = the adjacent-jump splitter, kept for A/B).
_SIZE_LADDER = config.env_raw("DEPPY_TPU_SIZE_LADDER", "on")


def _cost_proxy(p: Problem) -> int:
    """Padded per-lane cost proxy (shared model:
    :func:`deppy_tpu.size_classes.cost_proxy`): clause-plane area
    dominates BCP; the var count drives DPLL snapshot size and
    iteration count."""
    return _size_classes.cost_proxy(p.clauses.shape[0], p.n_vars,
                                    p.n_cons)


def _merge_small(buckets: List[List[int]]) -> List[List[int]]:
    """Merge under-MIN_BUCKET buckets into the previous (smaller-class)
    neighbor: a dedicated dispatch for a handful of lanes wastes more
    than the neighbor's re-pad."""
    merged: List[List[int]] = []
    for idxs in buckets:
        if merged and (len(idxs) < MIN_BUCKET
                       or len(merged[-1]) < MIN_BUCKET):
            merged[-1].extend(idxs)
        else:
            merged.append(idxs)
    return merged


def _jump_splits(costs: np.ndarray, order: np.ndarray,
                 max_buckets: int) -> List[List[int]]:
    """Cut a sorted cost run at its largest adjacent-cost jumps (up to
    ``max_buckets - 1`` of them, each >= SPLIT_RATIO)."""
    n = order.size
    sc = costs[order]
    ratios = sc[1:] / np.maximum(sc[:-1], 1)
    cand = np.nonzero(ratios >= SPLIT_RATIO)[0]
    cand = cand[np.argsort(ratios[cand])[::-1][: max_buckets - 1]]
    splits = sorted(int(i) + 1 for i in cand)
    bounds = [0] + splits + [n]
    return [order[lo:hi].tolist()
            for lo, hi in zip(bounds[:-1], bounds[1:])]


def _partition_legacy(costs: np.ndarray, order: np.ndarray,
                      n: int) -> List[List[int]]:
    """Pre-ladder splitter: adjacent-cost jumps only — blind to a
    smooth distribution whose extremes span a class boundary."""
    return _merge_small(_jump_splits(costs, order, MAX_BUCKETS))


def partition_buckets(problems: Sequence[Problem]) -> List[List[int]]:
    """Partition problem indices into size-class buckets: first along
    the shared ladder's class boundaries (a 64-clause problem never
    shares dims with a 4096-clause one, however smooth the cost
    distribution), then at >= SPLIT_RATIO adjacent-cost jumps WITHIN
    each class (a class can still span a big jump — e.g. 24-var and
    96-var problems both landing in `xs`).  Strictly finer than the
    legacy jump-only splitter before the small-bucket merge.  Returns
    index lists; a homogeneous batch comes back as one bucket."""
    n = len(problems)
    if n < 2 * MIN_BUCKET:
        return [list(range(n))]
    costs = np.array([_cost_proxy(p) for p in problems], dtype=np.int64)
    order = np.argsort(costs, kind="stable")
    if _SIZE_LADDER == "off":
        return _partition_legacy(costs, order, n)
    buckets: List[List[int]] = []
    run: List[int] = []
    cur: Optional[str] = None
    for i in order.tolist():
        name = _size_classes.class_of_cost(int(costs[i]))
        if name != cur and run:
            buckets += _jump_splits(costs, np.array(run), MAX_BUCKETS)
            run = []
        cur = name
        run.append(i)
    if run:
        buckets += _jump_splits(costs, np.array(run), MAX_BUCKETS)
    return _merge_small(buckets)


# Progressive budget escalation (SURVEY.md §7.3 item 4's "compaction of
# unfinished problems"): under vmap every lane pays the slowest lane's
# while_loop trip count, and real catalog batches are heavy-tailed
# (config-2 distribution: median 47 steps, p99 213, max 338).  Stage 1
# runs every lane with this small step budget; the few lanes still
# unfinished re-dispatch compacted at the full budget.  0 disables.
# Default OFF: on CPU XLA the re-dispatch overhead loses 4-13% at every
# stage-1 size tried (64/96/128/256 on the 1024-problem config-2 batch) —
# the bet only pays where per-iteration cost grows with lane width, so it
# stays an opt-in to A/B on real TPU before becoming a default.
STAGE1_STEPS = int(config.env_raw("DEPPY_TPU_STAGE1_STEPS", "0"))
# Escalation only pays when stage 1 resolves the vast majority; if more
# than this fraction straggle, the batch is uniformly hard and the whole
# batch re-runs at full budget (stage 1 was mis-sized, bounded waste).
STAGE1_MAX_STRAGGLERS = 0.25
# Batches below this size aren't worth a two-stage dance.
STAGE1_MIN_BATCH = 64


def _record_escalation(stage: int, stragglers: int = 0) -> None:
    """Record the escalation stage a dispatch group reached: 0 = single
    stage (escalation disabled or not profitable), 1 = stage-1 budget
    resolved every lane, 2 = stage-2 (compacted redo or full rerun)."""
    telemetry.default_registry().counter(
        "deppy_escalation_total",
        "Dispatch groups by the budget-escalation stage reached.",
        labelname="stage",
    ).inc(1, label=str(stage))
    rep = telemetry.current_report()
    if rep is not None:
        rep.note_escalation(stage)


# ------------------------------------------------------------- fault domain
#
# ISSUE 2 tentpole: the dispatch path must survive a dying accelerator.
# Every dispatch-group impl call (_solve_monolith / _solve_split, via
# _solve_escalating) runs under _recovering(), which owns the policy:
# retry with backoff, split a group that keeps failing, route to the
# host engine as the last line, and feed the accelerator circuit
# breaker.  The fault-injection harness (faults.inject) scripts device
# failures at the named points so all of this runs in CI on CPU.


def _fault_results_host(problems, budget, reason: str) -> List[core.SolveResult]:
    """Solve one dispatch group entirely on the host engine (fault-path
    fallback: the device dispatch failed or the breaker is open).

    Lanes run through the shared hostpool entry (ISSUE 5) — concurrent
    across the host worker pool when one is available, inline otherwise,
    bit-identical either way — so breaker-open serving scales with the
    host's cores instead of collapsing to one.  Results are
    device-shaped — installed/core masks padded to the group's bucketed
    dims so checkpoint stacking and decode see exactly what a device
    dispatch would have produced; the step budget carries over, so
    budget-exhausted lanes still read Incomplete, and lanes not started
    before the batch deadline expires degrade (one counted event for
    the group, matching the driver's per-group accounting)."""
    from .. import hostpool

    faults.inject("driver.host_fallback")
    reg = telemetry.default_registry()
    faults.fault_counter("deppy_fault_host_routed_total").inc(len(problems))
    reg.event("fault", fault="host_fallback", reason=reason,
              problems=len(problems))
    rep = telemetry.current_report()
    if rep is not None:
        rep.fault_host_routed += len(problems)
    d = _Dims(problems, max(len(problems), 1))
    out: List[core.SolveResult] = []
    dl = faults.current_deadline()
    prof_t0 = _profile.dispatch_t0("hostpool")
    with reg.span("driver.fault_host_fallback", problems=len(problems),
                  reason=reason):
        lanes = hostpool.solve_host_problems(
            problems, max_steps=int(budget),
            deadlines=[dl] * len(problems))
        if prof_t0 is not None:
            # Per-backend cost attribution (ISSUE 11): breaker-open /
            # fault-routed groups account under "hostpool".
            _profile.record_backend_flush(
                "hostpool", len(problems),
                int(sum(r.steps for r in lanes)),
                _time.perf_counter() - prof_t0)
        n_degraded = sum(1 for r in lanes if r.degraded)
        if n_degraded:
            faults.note_deadline_exceeded("driver.host_fallback",
                                          n_degraded)
        for p, lane in zip(problems, lanes):
            installed = np.zeros(d.NV, bool)
            cmask = np.zeros(d.NCON, bool)
            if lane.outcome == "sat":
                installed[lane.installed_idx] = True
                outcome = core.SAT
            elif lane.outcome == "unsat":
                cmask[lane.core_idx] = True
                outcome = core.UNSAT
            else:
                outcome = core.RUNNING
            out.append(core.SolveResult(
                np.int32(outcome), installed, cmask,
                np.int64(lane.steps), np.zeros((0, 0), np.int32),
                np.int32(lane.backtracks)))
    return out


def _deadline_results(problems) -> List[core.SolveResult]:
    """Incomplete results for a group whose batch deadline expired before
    it could dispatch — completed batchmates keep their answers, these
    lanes report exactly what a budget-exhausted solve would."""
    d = _Dims(problems, max(len(problems), 1))
    return [
        core.SolveResult(np.int32(core.RUNNING), np.zeros(d.NV, bool),
                         np.zeros(d.NCON, bool), np.int64(0),
                         np.zeros((0, 0), np.int32), np.int32(0))
        for _ in problems
    ]


def _recovering(impl, breaker=None, point: str = "driver.dispatch",
                on_fault=None):
    """Wrap a dispatch-group impl with the fault-domain policy.

    Order of recovery for a failing group: (1) retry up to
    ``RetryPolicy.max_attempts`` with exponential backoff + jitter,
    (2) split the group in half and recurse (a single poison problem —
    e.g. one that triggers the oversized-program worker crash —
    isolates in log2 steps while its groupmates stay on device),
    (3) host-engine fallback.  Semantic outcomes (NotSatisfiable /
    Incomplete / InternalSolverError) and admission errors pass
    through untouched — only unexpected failures are device faults.

    The breaker sees every failure and success; once open, groups route
    straight to the host engine without paying an attempt, until the
    cooldown's half-open probe dispatch.  ``breaker`` defaults to the
    process-wide accelerator breaker; the mesh-serving path passes a
    per-device breaker and its shard's fault point
    (``driver.shard_dispatch.N``) so a poisoned shard charges — and
    trips — only its own device (ISSUE 6).  ``on_fault`` (optional) is
    called whenever the group leaves the clean path — a dispatch
    failure or a breaker-open host route — possibly more than once per
    call (retries, split halves); callers wanting once-per-group
    semantics dedup themselves (the shard recovery counter does)."""

    def run(problems, budget, mesh, trace_cap):
        policy = faults.RetryPolicy.from_env()
        nonlocal breaker
        if breaker is None:
            breaker = faults.default_breaker()
        reg = telemetry.default_registry()
        dl = faults.current_deadline()
        if dl is not None and dl.expired():
            faults.note_deadline_exceeded(point, len(problems))
            return _deadline_results(problems)
        if not breaker.allow():
            if on_fault is not None:
                on_fault()
            return _fault_results_host(problems, budget,
                                       reason="breaker_open")
        attempt = 0
        while True:
            t0 = _time.monotonic()
            try:
                faults.inject(point)
                results = impl(problems, budget, mesh, trace_cap)
            except (InternalSolverError, NotSatisfiable, Incomplete,
                    faults.DeadlineExceeded):
                # Not a device verdict: if this attempt was the breaker's
                # half-open probe, hand the slot back so the next
                # dispatch can probe (a leaked slot would silently deny
                # the device forever).
                breaker.abandon_probe()
                raise
            except Exception as e:
                attempt += 1
                if on_fault is not None:
                    on_fault()
                breaker.record_failure()
                faults.fault_counter("deppy_fault_failures_total").inc()
                reg.event("fault", fault="dispatch_failed",
                          error=type(e).__name__, attempt=attempt,
                          problems=len(problems), breaker=breaker.state())
                if dl is not None and dl.expired():
                    faults.note_deadline_exceeded(point, len(problems))
                    return _deadline_results(problems)
                if attempt < policy.max_attempts and not breaker.blocks_device():
                    faults.fault_counter("deppy_fault_retries").inc()
                    back = policy.backoff_s(attempt)
                    if dl is not None:
                        back = min(back, max(dl.remaining(), 0.0))
                    if back > 0:
                        _time.sleep(back)
                    continue
                if (len(problems) > 1 and policy.split_failed_groups
                        and not breaker.blocks_device()):
                    reg.event("fault", fault="group_split",
                              problems=len(problems))
                    mid = (len(problems) + 1) // 2
                    return (run(list(problems[:mid]), budget, mesh, trace_cap)
                            + run(list(problems[mid:]), budget, mesh,
                                  trace_cap))
                return _fault_results_host(problems, budget,
                                           reason=type(e).__name__)
            else:
                dur = _time.monotonic() - t0
                if (policy.chunk_deadline_s > 0
                        and dur > policy.chunk_deadline_s):
                    # A dispatch that ran this long is the crash class
                    # the driver documents (minutes-long single
                    # executions wedge the tunneled worker): keep the
                    # valid result, but count it and charge the breaker
                    # so a streak of them trips to host-only.
                    faults.note_deadline_exceeded("driver.chunk",
                                                  len(problems))
                    breaker.record_failure()
                else:
                    breaker.record_success()
                return results

    return run


def _solve_escalating(impl, problems, budget, mesh, trace_cap,
                      breaker=None, point: str = "driver.dispatch",
                      on_fault=None):
    """Run ``impl`` in two budget stages when profitable; transparent
    fallbacks otherwise.  Tracing disables escalation (stage-2 re-runs
    would re-record trace buffers from scratch).  Every impl call is
    wrapped by the fault-domain recovery policy (:func:`_recovering`);
    ``breaker``/``point``/``on_fault`` pass through to it so the
    mesh-serving path runs this same pipeline under a per-device fault
    domain (ISSUE 6)."""
    impl = _recovering(impl, breaker=breaker, point=point,
                       on_fault=on_fault)
    reg = telemetry.default_registry()
    if (
        STAGE1_STEPS <= 0
        or trace_cap > 0
        or len(problems) < STAGE1_MIN_BATCH
        or int(budget) < 8 * STAGE1_STEPS
        # Giant problems host-route their core extraction, and a stage-1
        # budget is too small for that serial sweep to finish — it would
        # run (on the critical path), exhaust, and be redone in stage 2.
        or any(p.n_cons > HOST_CORE_NCONS for p in problems)
    ):
        with reg.span("driver.escalation", problems=len(problems),
                      stage=0):
            results = impl(problems, budget, mesh, trace_cap)
        _record_escalation(0)
        return results
    with reg.span("driver.escalation", problems=len(problems)) as sp:
        results = impl(problems, np.int32(STAGE1_STEPS), mesh, 0)
        stragglers = [
            i for i, r in enumerate(results) if r.outcome == core.RUNNING
        ]
        sp.set(stragglers=len(stragglers))
        if not stragglers:
            sp["stage"] = 1
            _record_escalation(1)
            return results
        sp["stage"] = 2
        _record_escalation(2, stragglers=len(stragglers))
        dl = faults.current_deadline()
        if dl is not None and dl.expired():
            # The batch deadline expired during stage 1: the redo would
            # only hit the recovery wrapper's expired-deadline fast path
            # again (degrading the same lanes and double-counting
            # deppy_deadline_exceeded) — the stage-1 results already
            # carry the right Incomplete verdicts.
            return results
        if len(stragglers) > STAGE1_MAX_STRAGGLERS * len(problems):
            redo = impl(problems, budget, mesh, trace_cap)
            # A lane the redo left undecided (fault/deadline degradation
            # inside the recovery wrapper) keeps its stage-1 decision:
            # completed lanes must never be un-solved by a redo that was
            # only ever about the stragglers.
            return [
                r1 if (int(r2.outcome) == core.RUNNING
                       and int(r1.outcome) != core.RUNNING) else r2
                for r1, r2 in zip(results, redo)
            ]
        sub = impl([problems[i] for i in stragglers], budget, mesh, 0)
        for i, r in zip(stragglers, sub):
            # Each lane reports the steps of the run that produced its
            # result (stage-1 work on a redone straggler is not added:
            # both redo branches then agree, and a lane can never report
            # steps > budget alongside a decided outcome — same
            # invariant as single-stage).
            results[i] = r
        return results


# ------------------------------------------------------------- mesh serving
#
# ISSUE 6 tentpole: the scheduler's coalesced micro-batches shard their
# lane axis across a device mesh instead of landing on one chip.  The
# shape of the machinery:
#
#   * batched_solve_sharded — the batch-axis sharded dispatch: the
#     single-program batched solve jitted with explicit PartitionSpec
#     shardings on the lane axis, memoized per (mesh, signature) exactly
#     like parallel.clause_shard._sharded_fn.  This is the SPMD
#     spelling: one program, the whole mesh, one fault domain
#     (solve_problems_sharded(spmd=True); the bench scaling row and the
#     multichip dry run measure it against the serving composition);
#   * solve_problems_sharded — the serving entry: slice the batch into
#     per-device shards and drain each device's shards on its own
#     worker thread through the FULL phased pipeline (size-class
#     bucketing → compacted three-phase dispatch → budget escalation —
#     the same composition the single-device path serves with, so the
#     mesh pays no composition tax), with EACH shard under its own
#     fault domain — retry/split/host-fallback via the PR 2 _recovering
#     machinery for that slice only, charging a per-device breaker
#     (deppy_breaker_state{device=...}) so one bad chip degrades one
#     shard of the mesh, not the process.
#
# One program per device rather than one SPMD program over the mesh for
# the *serving* path: problems are independent (zero collectives either
# way — XLA would partition the SPMD program into the same per-device
# work), but separate programs make the fault blast radius one shard,
# which is the entire point of per-shard fault domains — and the
# per-device spelling keeps the phased/compacted composition, where the
# SPMD monolith lane-gates every phase (an UNSAT lane serializes its
# whole dispatch through the deletion loop).


@_functools.lru_cache(maxsize=32)
def batched_solve_sharded(mesh, V: int, NCON: int, NV: int,
                          trace_cap: int = 0, with_core: bool = True):
    """Batch-axis sharded dispatch entry (ISSUE 6): the vmapped
    single-program solve jitted with every ``ProblemTensors`` leaf
    sharded on its leading (lane) axis over the mesh's ``batch`` axis
    (``PartitionSpec``; SNIPPETS.md [1]-[3]), budget replicated, outputs
    lane-sharded.  Memoized per (mesh, space signature); input-shape
    variation within a signature retraces via jit's own cache."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..parallel.mesh import BATCH_AXIS

    s_lane = NamedSharding(mesh, PartitionSpec(BATCH_AXIS))
    s_repl = NamedSharding(mesh, PartitionSpec())
    vfn = jax.vmap(
        _functools.partial(core.solve_full, V=V, NCON=NCON, NV=NV,
                           T=trace_cap, with_core=with_core),
        in_axes=(0, None),
    )
    in_sh = (
        core.ProblemTensors(
            *([s_lane] * len(core.ProblemTensors._fields))),
        s_repl,
    )
    out_sh = core.SolveResult(
        *([s_lane] * len(core.SolveResult._fields)))
    devices = tuple(d.id for d in mesh.devices.flat)
    return jax.jit(
        compileguard.observe(
            "driver.batched_solve_sharded", vfn,
            static=(devices, V, NCON, NV, trace_cap, with_core)),
        in_shardings=in_sh, out_shardings=out_sh)


@_functools.lru_cache(maxsize=64)
def _device_submesh(device):
    """One-device 1-D batch mesh (memoized so the pjit entry's
    per-(mesh, signature) cache hits across dispatches)."""
    from ..parallel.mesh import default_mesh

    return default_mesh([device])


def _shard_slices(n: int, n_dev: int) -> List[List[int]]:
    """Contiguous lane slices for a sharded dispatch: ``ceil(n/n_dev)``
    lanes per shard, capped at MAX_LANES (oversized single programs are
    the documented worker-crash class); shard *i* runs on device
    ``i % n_dev``, so batches past ``n_dev × MAX_LANES`` wrap round-robin
    and every device stays busy."""
    per = min(-(-n // n_dev), MAX_LANES)
    return [list(range(lo, min(lo + per, n)))
            for lo in range(0, n, per)]


def _solve_spmd(problems, budget, mesh, trace_cap) -> List[core.SolveResult]:
    """SPMD spelling of the mesh entry: ONE program over the whole mesh,
    the lane axis partitioned by :func:`batched_solve_sharded`'s explicit
    shardings.  Single fault domain — the bench scaling record and the
    multichip dry run measure it against the per-device serving
    composition (:func:`_solve_sharded_inner`)."""
    return _solve_monolith(problems, budget, mesh, trace_cap,
                           _spmd_entry=True)


def _shard_pipeline(problems, budget, submesh, trace_cap, breaker, point,
                    on_fault) -> List[core.SolveResult]:
    """One shard slice through the FULL single-device composition —
    size-class bucketing, compacted three-phase dispatch, budget
    escalation (the same pipeline :func:`_solve_problems_inner` runs) —
    under the shard's per-device fault domain.  This is why the mesh
    path pays no composition tax over single-device serving: the old
    monolith-per-shard spelling lane-gated every phase, serializing a
    shard's SAT lanes through its UNSAT lanes' deletion loops."""
    n = len(problems)
    impl = _solve_split if n > 1 else _solve_monolith
    buckets = partition_buckets(problems) if n > 1 else [list(range(n))]
    if len(buckets) == 1:
        return _solve_escalating(impl, list(problems), budget, submesh,
                                 trace_cap, breaker=breaker, point=point,
                                 on_fault=on_fault)
    out: List[Optional[core.SolveResult]] = [None] * n
    for idxs in buckets:
        sub = _solve_escalating(impl, [problems[i] for i in idxs], budget,
                                submesh, trace_cap, breaker=breaker,
                                point=point, on_fault=on_fault)
        for i, r in zip(idxs, sub):
            out[i] = r
    return out  # type: ignore[return-value]


def _solve_sharded_inner(problems, budget, mesh,
                         trace_cap: int) -> List[core.SolveResult]:
    n = len(problems)
    devices = list(mesh.devices.flat)
    n_dev = len(devices)
    reg = telemetry.default_registry()
    rep = telemetry.current_report()
    dl = faults.current_deadline()
    if dl is not None and dl.expired():
        faults.note_deadline_exceeded("driver.mesh_dispatch", n)
        return _deadline_results(problems)
    slices = _shard_slices(n, n_dev)
    c_disp = reg.counter(
        "deppy_shard_dispatches_total",
        "Mesh-serving shard dispatches, by device.", labelname="device")
    c_rec = reg.counter(
        "deppy_shard_recoveries_total",
        "Shard slices that entered per-device fault recovery "
        "(retry / split / host fallback).", labelname="device")
    results: List[Optional[core.SolveResult]] = [None] * n
    shard_reports: List[Optional[telemetry.SolveReport]] = \
        [None] * len(slices)
    shard_spans: List[Optional[tuple]] = [None] * len(slices)
    errors: List[BaseException] = []

    def drain_device(di: int) -> None:
        # One worker per device (a device runs one program at a time, so
        # more threads per device buy nothing): drains this device's
        # round-robin share of the slices serially, each through the
        # full phased pipeline under the device's own fault domain.  The
        # report and batch deadline both travel on thread-locals, so the
        # worker re-installs the parent's deadline and fills its own
        # report for the parent to merge after the join — sharing the
        # parent's report would race its unlocked counters.
        dev = devices[di]
        dev_key = str(getattr(dev, "id", di))
        # The device's own breaker gated on the process-wide one: an
        # open accelerator verdict host-routes every shard without an
        # attempt (PR 2's guarantee), while failures charge only this
        # device so one bad chip trips one shard of the mesh.
        br = faults.GatedDeviceBreaker(faults.device_breaker(dev_key),
                                       faults.default_breaker())
        submesh = _device_submesh(dev)
        for si in range(di, len(slices), n_dev):
            idxs = slices[si]
            sub = [problems[i] for i in idxs]
            c_disp.inc(label=dev_key)
            fired = [False]

            def on_fault(fired=fired):
                # Once per slice, however many retries / split halves /
                # breaker-open host routes the recovery walk takes.
                if not fired[0]:
                    fired[0] = True
                    c_rec.inc(label=dev_key)

            srep, owns = telemetry.begin_report(backend="tpu")
            t1 = _time.perf_counter()
            try:
                with faults.deadline_scope(dl):
                    out = _shard_pipeline(
                        sub, budget, submesh, trace_cap, breaker=br,
                        point=f"driver.shard_dispatch.{di}",
                        on_fault=on_fault)
            except BaseException as e:  # re-raised on the parent thread
                errors.append(e)
                return
            finally:
                telemetry.detach_report(srep, owns)
                if owns:
                    shard_reports[si] = srep
            shard_spans[si] = (dev_key, len(idxs),
                               _time.perf_counter() - t1)
            for i, r in zip(idxs, out):
                results[i] = r

    workers = [
        _threading.Thread(target=drain_device, args=(di,),
                          name=f"deppy-shard-{di}", daemon=True)
        for di in range(min(n_dev, len(slices)))
    ]
    with reg.span("driver.mesh_dispatch", problems=n, shards=len(slices),
                  devices=n_dev):
        for t in workers:
            t.start()
        for t in workers:
            t.join()
    # Spans and report merge land on the parent thread: record_span here
    # stamps the submitting request's trace context (workers have none),
    # and the merged report keeps one report event per batch.
    for entry in shard_spans:
        if entry is not None:
            dev_key, lanes, dur = entry
            reg.record_span("driver.shard_solve", dur, device=dev_key,
                            lanes=lanes)
    if rep is not None:
        for srep in shard_reports:
            if srep is not None:
                rep.merge(srep)
    if errors:
        # Semantic outcomes (InternalSolverError et al.) pass through
        # _recovering untouched; surface the first one exactly as the
        # unsharded path would.
        raise errors[0]
    return results  # type: ignore[return-value]


def solve_problems_sharded(
    problems: Sequence[Problem],
    mesh=None,
    max_steps: Optional[int] = None,
    trace_cap: int = 0,
    spmd: bool = False,
) -> List[core.SolveResult]:
    """Mesh-serving batch entry (ISSUE 6): shard one coalesced
    micro-batch's lane axis across ``mesh``'s devices — one worker
    thread per device draining its shards through the full phased
    pipeline, per-shard fault domains (see
    :func:`_solve_sharded_inner`).  Byte-identical results to
    :func:`solve_problems` on the same batch — problems are independent
    and sharding only changes placement — which the shard test suite
    pins.  Falls back to :func:`solve_problems` when the mesh is absent
    or single-device or the batch has a single problem.

    ``spmd=True`` instead dispatches the whole batch as ONE program
    whose lane axis is partitioned over the mesh by explicit
    ``PartitionSpec`` shardings (:func:`batched_solve_sharded`) under a
    single fault domain — same answers, whole-mesh blast radius; the
    bench scaling record measures both spellings."""
    if (mesh is None or getattr(mesh, "size", 1) < 2
            or len(problems) < 2):
        return solve_problems(problems, max_steps=max_steps,
                              trace_cap=trace_cap)
    for p in problems:
        if p.errors:
            raise InternalSolverError(p.errors)
    rep, owns = telemetry.begin_report(backend="tpu",
                                       n_problems=len(problems))
    reg = telemetry.default_registry()
    t0 = _time.perf_counter()
    try:
        with faults.ambient_deadline(), \
                reg.span("driver.solve", problems=len(problems),
                         devices=int(mesh.size)):
            if spmd:
                results = _recovering(_solve_spmd)(
                    list(problems), _budget(max_steps), mesh, trace_cap)
            else:
                results = _solve_sharded_inner(
                    problems, _budget(max_steps), mesh, trace_cap)
        for r in results:
            o = int(r.outcome)
            key = ("sat" if o == core.SAT
                   else "unsat" if o == core.UNSAT else "incomplete")
            rep.count_outcome(key)
            rep.steps += int(r.steps)
            rep.backtracks += int(r.trace_n)
        reg.histogram(
            "deppy_solve_seconds",
            "Wall-clock seconds per driver solve call (pad through "
            "decode).",
        ).observe(_time.perf_counter() - t0)
    finally:
        rep.add_wall("solve", _time.perf_counter() - t0)
        if owns:
            telemetry.end_report(rep, owns)
    return results


def solve_problems(
    problems: Sequence[Problem],
    max_steps: Optional[int] = None,
    mesh=None,
    trace_cap: int = 0,
    split_phases: Optional[bool] = None,
    bucketing: bool = True,
) -> List[core.SolveResult]:
    """Solve lowered problems as device batches; per-problem results with
    host numpy arrays.  With ``mesh`` (a 1-D ``jax.sharding.Mesh`` from
    :mod:`deppy_tpu.parallel`), each dispatch's batch axis is sharded over
    the mesh's devices and XLA partitions the solve — the fleet-scale path.
    ``trace_cap`` > 0 compiles in backtrack tracing with that buffer depth
    (see :class:`core.SolveResult`).

    ``split_phases`` (default: automatic — on for real batches, off for a
    batch of one) dispatches search / minimization / core extraction as
    separate compacted batches; ``bucketing`` partitions heterogeneous
    batches into size classes first.

    Telemetry: the whole call runs under a ``driver.solve`` span, and the
    thread's active :class:`deppy_tpu.telemetry.SolveReport` (created
    here when none is active — nested calls, e.g. checkpoint groups,
    merge into the enclosing one) accumulates padding economics,
    per-stage wall clock, escalation staging, and outcome counters;
    retrieve it afterwards via :func:`deppy_tpu.telemetry.last_report`."""
    for p in problems:
        if p.errors:
            raise InternalSolverError(p.errors)
    rep, owns = telemetry.begin_report(backend="tpu",
                                       n_problems=len(problems))
    reg = telemetry.default_registry()
    t0 = _time.perf_counter()
    try:
        # Ambient batch deadline: the caller's deadline_scope when one is
        # active (service request / CLI --deadline), else
        # DEPPY_TPU_BATCH_DEADLINE_S from the environment.  Expiry never
        # aborts the batch — groups past the deadline decode Incomplete.
        with faults.ambient_deadline(), \
                reg.span("driver.solve", problems=len(problems)):
            results = _solve_problems_inner(
                problems, max_steps, mesh, trace_cap, split_phases,
                bucketing,
            )
        for r in results:
            o = int(r.outcome)
            key = ("sat" if o == core.SAT
                   else "unsat" if o == core.UNSAT else "incomplete")
            rep.count_outcome(key)
            rep.steps += int(r.steps)
            rep.backtracks += int(r.trace_n)
        reg.histogram(
            "deppy_solve_seconds",
            "Wall-clock seconds per driver solve call (pad through "
            "decode).",
        ).observe(_time.perf_counter() - t0)
    finally:
        rep.add_wall("solve", _time.perf_counter() - t0)
        if owns:
            telemetry.end_report(rep, owns)
    return results


def _solve_problems_inner(problems, max_steps, mesh, trace_cap,
                          split_phases, bucketing):
    n = len(problems)
    budget = _budget(max_steps)
    if split_phases is None:
        split_phases = n > 1
    impl = _solve_split if split_phases else _solve_monolith
    buckets = partition_buckets(problems) if (bucketing and n > 1) else [list(range(n))]
    if len(buckets) == 1:
        return _solve_escalating(impl, list(problems), budget, mesh,
                                 trace_cap)
    results: List[Optional[core.SolveResult]] = [None] * n
    for idxs in buckets:
        sub = _solve_escalating(impl, [problems[i] for i in idxs], budget,
                                mesh, trace_cap)
        for i, r in zip(idxs, sub):
            results[i] = r
    return results  # type: ignore[return-value]


def _decode_installed(p: Problem, installed: np.ndarray) -> List[Variable]:
    return [p.variables[i] for i in range(p.n_vars) if installed[i]]


def _decode_core(p: Problem, active: np.ndarray) -> NotSatisfiable:
    return NotSatisfiable([p.applied[j] for j in range(p.n_cons) if active[j]])


# Trace-buffer depth compiled in when a tracer is attached.  Deep enough
# for any realistic catalog search; pass ``trace_cap`` to
# :func:`solve_one` (or ``Solver(trace_cap=...)``) for pathological cases.
# Truncation warns and is visible as stats["backtracks"] > trace calls.
DEFAULT_TRACE_CAP = 256


class _LazyReplayPosition:
    """``SearchPosition`` whose conflict set is reconstructed on demand.

    The assumption stack comes straight off the device trace buffer; the
    conflict list requires a host-engine replay, so it is computed only
    when a tracer actually calls ``conflicts()``.  Stats-only tracers
    (e.g. ``StatsTracer``) therefore cost zero host solves — the tracer
    contract only promises the position, not an eager materialization
    (reference tracer.go:13-15)."""

    def __init__(self, variables, compute_conflicts):
        self._variables = variables
        self._compute = compute_conflicts
        self._conflicts = None

    def variables(self):
        return self._variables

    def conflicts(self):
        if self._conflicts is None:
            self._conflicts = self._compute()
        return self._conflicts


def _replay_trace(problem: Problem, res: core.SolveResult, tracer) -> None:
    """Decode the device trace buffer into host ``Tracer.trace`` calls.

    Each recorded row is the guess-variable stack at one backtrack.  The
    conflict set is reconstructed — lazily, on first ``conflicts()``
    access — by replaying one host-engine Test under those assumptions
    (the host engine is the semantic spec; BCP is confluent, so the
    replayed fixpoint — and its conflict attribution — matches the
    device's).  A backtrack caused by an exhausted leaf DPLL rather than
    a propagation conflict replays without conflict and reports an empty
    conflict list, where the host engine surfaces its DPLL's final
    internal conflict — the assumption stacks agree exactly, the conflict
    annotation is best-effort (reference gini would compute a
    failed-assumption core here, lit_mapping.go:198-207)."""
    total = int(res.trace_n)
    rows = min(total, res.trace_stack.shape[0])
    if rows == 0:
        return
    if total > rows:
        import warnings

        warnings.warn(
            f"search backtracked {total} times but the trace buffer holds "
            f"{rows}; trailing events are dropped — raise trace_cap "
            f"(solve_one) to capture them",
            RuntimeWarning,
            stacklevel=3,
        )
    eng_box: list = []

    def _conflicts_for(gv):
        def compute():
            from ..sat.host import UNSAT as HOST_UNSAT
            from ..sat.host import HostEngine

            if not eng_box:
                eng_box.append(HostEngine(problem))
            eng = eng_box[0]
            outcome, _ = eng._test(guessed=tuple(gv))
            return list(eng.last_conflicts) if outcome == HOST_UNSAT else []

        return compute

    for i in range(rows):
        gv = [int(v) for v in res.trace_stack[i] if v >= 0]
        tracer.trace(
            _LazyReplayPosition(
                [problem.variables[v] for v in gv], _conflicts_for(gv)
            )
        )


def solve_one(
    problem: Problem,
    max_steps: Optional[int] = None,
    stats: Optional[dict] = None,
    tracer=None,
    trace_cap: Optional[int] = None,
) -> List[Variable]:
    """Single-problem entry used by :class:`deppy_tpu.sat.solver.Solver`
    (batch of one).  Same error contract as the host engine.  A ``stats``
    dict, when given, receives ``{"steps": N}`` — the engine iteration count
    (SURVEY.md §5 observability).  A ``tracer`` receives one ``trace`` call
    per search backtrack, like the host engine (reference tracer.go:13-15);
    ``trace_cap`` sizes the device-side event buffer (default
    ``DEFAULT_TRACE_CAP``; a warning fires if the search overflows it)."""
    if trace_cap is None:
        trace_cap = DEFAULT_TRACE_CAP if tracer is not None else 0
    (res,) = solve_problems([problem], max_steps=max_steps,
                            trace_cap=trace_cap)
    if stats is not None:
        stats["steps"] = int(res.steps)
        stats["backtracks"] = int(res.trace_n)
        stats["report"] = telemetry.last_report()
    if tracer is not None:
        _replay_trace(problem, res, tracer)
    if res.outcome == core.SAT:
        return _decode_installed(problem, res.installed)
    if res.outcome == core.UNSAT:
        raise _decode_core(problem, res.core)
    raise Incomplete()


def solve_batch(
    problem_vars: Sequence[Sequence[Variable]],
    max_steps: Optional[int] = None,
    mesh=None,
    stats: Optional[dict] = None,
    checkpoint_dir: Optional[str] = None,
):
    """Batch entry used by :class:`deppy_tpu.resolution.facade.BatchResolver`:
    N independent variable lists → per-problem result: a ``Solution`` dict,
    the problem's :class:`NotSatisfiable` error, or an :class:`Incomplete`
    marker when that problem exhausted the step budget (problems are
    independent, so one straggler never voids its batchmates' answers).  A
    ``stats`` dict, when given, receives ``{"steps": N}`` summed over the
    batch.  ``checkpoint_dir`` enables group-wise resume for fleet-scale
    batches (see :mod:`deppy_tpu.engine.checkpoint`)."""
    problems = [encode(vs) for vs in problem_vars]
    # Own the SolveReport across the whole batch so a checkpointed run's
    # per-group driver calls merge into one report instead of each
    # publishing their own.
    rep, owns = telemetry.begin_report(backend="tpu")
    try:
        if checkpoint_dir is not None:
            from .checkpoint import solve_problems_checkpointed

            results = solve_problems_checkpointed(
                problems, checkpoint_dir, max_steps=max_steps, mesh=mesh
            )
        else:
            results = solve_problems(problems, max_steps=max_steps,
                                     mesh=mesh)
    finally:
        telemetry.end_report(rep, owns)
    if stats is not None:
        stats["steps"] = int(sum(int(r.steps) for r in results))
        stats["report"] = telemetry.last_report()
    return decode_results(problems, results)


def decode_results(
    problems: Sequence[Problem], results: Sequence[core.SolveResult]
) -> List[Union[dict, NotSatisfiable, Incomplete]]:
    """Decode per-problem :class:`core.SolveResult`\\ s back to the
    facade vocabulary: a Solution dict (every entity id → selected?),
    the problem's :class:`NotSatisfiable` core, or an
    :class:`Incomplete` marker.  Shared by :func:`solve_batch` and the
    request scheduler (:mod:`deppy_tpu.sched`), which dispatches
    pre-encoded problems and decodes per lane — the two paths cannot
    drift."""
    out: List[Union[dict, NotSatisfiable, Incomplete]] = []
    # Spanned (ISSUE 4): decode is the last leg of a request's timing
    # breakdown (queue-wait → dispatch → solve → decode), and the trace
    # tree should show it like every other stage.
    with telemetry.default_registry().span("driver.decode",
                                           problems=len(problems)):
        for p, res in zip(problems, results):
            if res.outcome == core.SAT:
                solution = {v.identifier: False for v in p.variables}
                for v in _decode_installed(p, res.installed):
                    solution[v.identifier] = True
                out.append(solution)
            elif res.outcome == core.UNSAT:
                out.append(_decode_core(p, res.core))
            else:
                out.append(Incomplete())
    return out


def warm_screen(problems: Sequence[Problem], models, cones) -> np.ndarray:
    """Batched warm-prefix screen (ISSUE 10): the device lane variant of
    the incremental tier.  Each lane's assignment is initialized from
    its cached ``model`` (bool[n_vars]) with the ``cone`` variables left
    open, and one lockstep :func:`core.batched_warm_check` pass per
    ≤ MAX_LANES chunk (oversized single programs are the documented
    tunneled-worker crash class, and a mesh-sized warm flush can carry
    thousands of lanes) flags lanes whose warm prefix already conflicts
    — those cold-solve without paying a host warm attempt.  Returns
    bool[n].  Router only: results never depend on this screen, so it
    shares no identity obligations with the solve paths."""
    n = len(problems)
    ch_cap = min(max(n, 1), MAX_LANES)
    d = _Dims(problems, ch_cap)
    CH = d.B
    total = max(1, -(-n // CH)) * CH
    pts = pad_stack(problems, d, total, pack=False)
    assign = np.zeros((total, d.NV), np.int32)
    for i, (m, c) in enumerate(zip(models, cones)):
        a = np.where(np.asarray(m, dtype=bool), 1, -1).astype(np.int32)
        a[np.asarray(c, dtype=bool)] = 0
        assign[i, : a.shape[0]] = a
    fn = core.batched_warm_check(d.V, d.NCON, d.NV)
    with telemetry.default_registry().span("driver.warm_screen",
                                           lanes=n):
        outs = [fn(_rows(pts, sl), assign[sl])
                for sl in _chunk_slices(total, CH)]
        ok = np.concatenate([np.asarray(o) for o in jax.device_get(outs)])
    return ok[:n]
