"""Host-side driver: pad, batch, dispatch, decode.

Bridges the symbolic layer (:class:`deppy_tpu.sat.encode.Problem`) and the
tensor engine (:mod:`deppy_tpu.engine.core`):

  * pads each lowered problem's tensors to the batch's common shapes,
    bucketing every dimension up to a power of two so the number of
    distinct compiled programs stays bounded (the padding-economics policy
    from SURVEY.md §7.3);
  * stacks problems along a leading batch axis and dispatches one jitted,
    vmapped solve for the whole batch;
  * decodes outcome masks back to installed variables, and active-constraint
    masks back to :class:`NotSatisfiable` unsat cores, exactly like the
    reference maps lits back through LitMapping
    (/root/reference/pkg/sat/lit_mapping.go:176-207).

Batch entries behind a padded batch dimension are empty problems (zero
variables) which solve trivially and are dropped on decode.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ..sat.constraints import Variable
from ..sat.encode import Problem, encode
from ..sat.errors import Incomplete, InternalSolverError, NotSatisfiable
from . import core

# Default step budget when the caller sets none: generous enough for any
# realistic catalog problem, small enough that a pathological instance
# yields Incomplete rather than an unbounded device loop (the reference
# quirk of unhonored cancellation — SURVEY.md §3.1 — done better).
DEFAULT_MAX_STEPS = 1 << 24


def _bucket(n: int, minimum: int = 1) -> int:
    """Round up to the next power of two (≥ minimum)."""
    n = max(n, minimum)
    out = 1
    while out < n:
        out <<= 1
    return out


def _pad2(a: np.ndarray, rows: int, cols: int, fill: int) -> np.ndarray:
    out = np.full((rows, cols), fill, dtype=np.int32)
    r, c = a.shape
    out[:r, :c] = a
    return out


def _pad1(a: np.ndarray, n: int, fill: int) -> np.ndarray:
    out = np.full((n,), fill, dtype=np.int32)
    out[: a.shape[0]] = a
    return out


class _Dims:
    """Common padded dimensions for a batch of problems."""

    def __init__(self, problems: Sequence[Problem], batch: int, batch_multiple: int = 1):
        self.C = _bucket(max((p.clauses.shape[0] for p in problems), default=1))
        self.K = _bucket(max((p.clauses.shape[1] for p in problems), default=1), 2)
        self.NA = _bucket(max((p.card_ids.shape[0] for p in problems), default=1))
        self.M = _bucket(max((p.card_ids.shape[1] for p in problems), default=1))
        self.A = _bucket(max((p.anchors.shape[0] for p in problems), default=1))
        self.NC = _bucket(max((p.choice_cand.shape[0] for p in problems), default=1))
        self.Kc = _bucket(max((p.choice_cand.shape[1] for p in problems), default=1))
        self.NV = _bucket(max((p.n_vars for p in problems), default=1))
        self.W = _bucket(max((p.var_choices.shape[1] for p in problems), default=1))
        self.NCON = _bucket(max((p.n_cons for p in problems), default=1))
        self.V = self.NV + self.NCON
        self.Wv = -(-self.V // core.WORD)  # bitplane words per variable set
        # Batch padded to a power of two AND a multiple of the mesh size so
        # the batch axis shards evenly.
        b = _bucket(batch)
        if b % batch_multiple:
            b *= batch_multiple // np.gcd(b, batch_multiple)
        self.B = b


def _pack_planes(clauses: np.ndarray, Wv: int) -> tuple:
    """Signed clause matrix → (pos, neg) packed int32 bitplanes."""
    C = clauses.shape[0]
    W = core.WORD
    pos = np.zeros((C, Wv), np.uint32)
    neg = np.zeros((C, Wv), np.uint32)
    for plane, mask in ((pos, clauses > 0), (neg, clauses < 0)):
        r, c = np.nonzero(mask)
        v = np.abs(clauses[r, c]).astype(np.int64) - 1
        np.bitwise_or.at(plane, (r, v // W), np.uint32(1) << np.uint32(v % W))
    return pos.view(np.int32), neg.view(np.int32)


def _pack_index_rows(rows: np.ndarray, Wv: int) -> np.ndarray:
    """0-based index matrix (-1 pad) → packed int32 membership bitplanes."""
    W = core.WORD
    out = np.zeros((rows.shape[0], Wv), np.uint32)
    r, c = np.nonzero(rows >= 0)
    v = rows[r, c].astype(np.int64)
    np.bitwise_or.at(out, (r, v // W), np.uint32(1) << np.uint32(v % W))
    return out.view(np.int32)


def pad_problem(p: Problem, d: _Dims) -> core.ProblemTensors:
    """Pad one lowered problem to the batch dims (numpy, host-side)."""
    clauses = _pad2(p.clauses, d.C, d.K, 0)
    card_ids = _pad2(p.card_ids, d.NA, d.M, -1)
    card_act = _pad1(p.card_act, d.NA, -1)
    pos_bits, neg_bits = _pack_planes(clauses, d.Wv)
    return core.ProblemTensors(
        clauses=clauses,
        card_ids=card_ids,
        card_n=_pad1(p.card_n, d.NA, 0),
        card_act=card_act,
        anchors=_pad1(p.anchors, d.A, -1),
        choice_cand=_pad2(p.choice_cand, d.NC, d.Kc, -1),
        var_choices=_pad2(p.var_choices, d.NV, d.W, -1),
        n_vars=np.int32(p.n_vars),
        n_cons=np.int32(p.n_cons),
        pos_bits=pos_bits,
        neg_bits=neg_bits,
        card_member_bits=_pack_index_rows(card_ids, d.Wv),
        card_act_bits=_pack_index_rows(card_act[:, None], d.Wv),
    )


_EMPTY_PROBLEM: Optional[Problem] = None


def _empty_problem() -> Problem:
    global _EMPTY_PROBLEM
    if _EMPTY_PROBLEM is None:
        _EMPTY_PROBLEM = encode([])
    return _EMPTY_PROBLEM


def _stack(pts: Sequence[core.ProblemTensors]) -> core.ProblemTensors:
    return core.ProblemTensors(
        *[np.stack([getattr(p, f) for p in pts]) for f in core.ProblemTensors._fields]
    )


def solve_problems(
    problems: Sequence[Problem],
    max_steps: Optional[int] = None,
    mesh=None,
    trace_cap: int = 0,
) -> List[core.SolveResult]:
    """Solve lowered problems as one device batch; per-problem results with
    host numpy arrays.  With ``mesh`` (a 1-D ``jax.sharding.Mesh`` from
    :mod:`deppy_tpu.parallel`), the batch axis is sharded over the mesh's
    devices and XLA partitions the solve — the fleet-scale path.
    ``trace_cap`` > 0 compiles in backtrack tracing with that buffer depth
    (see :class:`core.SolveResult`)."""
    for p in problems:
        if p.errors:
            raise InternalSolverError(p.errors)
    n = len(problems)
    d = _Dims(problems, max(n, 1), batch_multiple=mesh.size if mesh is not None else 1)
    padded = list(problems) + [_empty_problem()] * (d.B - n)
    pts = _stack([pad_problem(p, d) for p in padded])
    if mesh is not None:
        from ..parallel.mesh import shard_batch

        pts = shard_batch(mesh, pts)
    budget = np.int32(min(max_steps if max_steps is not None else DEFAULT_MAX_STEPS,
                          np.iinfo(np.int32).max - 1))
    fn = core.batched_solve(d.V, d.NCON, d.NV, trace_cap)
    res = fn(pts, budget)
    outcome = np.asarray(res.outcome)
    installed = np.asarray(res.installed)
    cores = np.asarray(res.core)
    steps = np.asarray(res.steps)
    trace_stack = np.asarray(res.trace_stack)
    trace_n = np.asarray(res.trace_n)
    return [
        core.SolveResult(outcome[i], installed[i], cores[i], steps[i],
                         trace_stack[i], trace_n[i])
        for i in range(n)
    ]


def _decode_installed(p: Problem, installed: np.ndarray) -> List[Variable]:
    return [p.variables[i] for i in range(p.n_vars) if installed[i]]


def _decode_core(p: Problem, active: np.ndarray) -> NotSatisfiable:
    return NotSatisfiable([p.applied[j] for j in range(p.n_cons) if active[j]])


# Trace-buffer depth compiled in when a tracer is attached.  Deep enough
# for any realistic catalog search; pass ``trace_cap`` to
# :func:`solve_one` (or ``Solver(trace_cap=...)``) for pathological cases.
# Truncation warns and is visible as stats["backtracks"] > trace calls.
DEFAULT_TRACE_CAP = 256


class _LazyReplayPosition:
    """``SearchPosition`` whose conflict set is reconstructed on demand.

    The assumption stack comes straight off the device trace buffer; the
    conflict list requires a host-engine replay, so it is computed only
    when a tracer actually calls ``conflicts()``.  Stats-only tracers
    (e.g. ``StatsTracer``) therefore cost zero host solves — the tracer
    contract only promises the position, not an eager materialization
    (reference tracer.go:13-15)."""

    def __init__(self, variables, compute_conflicts):
        self._variables = variables
        self._compute = compute_conflicts
        self._conflicts = None

    def variables(self):
        return self._variables

    def conflicts(self):
        if self._conflicts is None:
            self._conflicts = self._compute()
        return self._conflicts


def _replay_trace(problem: Problem, res: core.SolveResult, tracer) -> None:
    """Decode the device trace buffer into host ``Tracer.trace`` calls.

    Each recorded row is the guess-variable stack at one backtrack.  The
    conflict set is reconstructed — lazily, on first ``conflicts()``
    access — by replaying one host-engine Test under those assumptions
    (the host engine is the semantic spec; BCP is confluent, so the
    replayed fixpoint — and its conflict attribution — matches the
    device's).  A backtrack caused by an exhausted leaf DPLL rather than
    a propagation conflict replays without conflict and reports an empty
    conflict list, where the host engine surfaces its DPLL's final
    internal conflict — the assumption stacks agree exactly, the conflict
    annotation is best-effort (reference gini would compute a
    failed-assumption core here, lit_mapping.go:198-207)."""
    total = int(res.trace_n)
    rows = min(total, res.trace_stack.shape[0])
    if rows == 0:
        return
    if total > rows:
        import warnings

        warnings.warn(
            f"search backtracked {total} times but the trace buffer holds "
            f"{rows}; trailing events are dropped — raise trace_cap "
            f"(solve_one) to capture them",
            RuntimeWarning,
            stacklevel=3,
        )
    eng_box: list = []

    def _conflicts_for(gv):
        def compute():
            from ..sat.host import UNSAT as HOST_UNSAT
            from ..sat.host import HostEngine

            if not eng_box:
                eng_box.append(HostEngine(problem))
            eng = eng_box[0]
            outcome, _ = eng._test(guessed=tuple(gv))
            return list(eng.last_conflicts) if outcome == HOST_UNSAT else []

        return compute

    for i in range(rows):
        gv = [int(v) for v in res.trace_stack[i] if v >= 0]
        tracer.trace(
            _LazyReplayPosition(
                [problem.variables[v] for v in gv], _conflicts_for(gv)
            )
        )


def solve_one(
    problem: Problem,
    max_steps: Optional[int] = None,
    stats: Optional[dict] = None,
    tracer=None,
    trace_cap: Optional[int] = None,
) -> List[Variable]:
    """Single-problem entry used by :class:`deppy_tpu.sat.solver.Solver`
    (batch of one).  Same error contract as the host engine.  A ``stats``
    dict, when given, receives ``{"steps": N}`` — the engine iteration count
    (SURVEY.md §5 observability).  A ``tracer`` receives one ``trace`` call
    per search backtrack, like the host engine (reference tracer.go:13-15);
    ``trace_cap`` sizes the device-side event buffer (default
    ``DEFAULT_TRACE_CAP``; a warning fires if the search overflows it)."""
    if trace_cap is None:
        trace_cap = DEFAULT_TRACE_CAP if tracer is not None else 0
    (res,) = solve_problems([problem], max_steps=max_steps,
                            trace_cap=trace_cap)
    if stats is not None:
        stats["steps"] = int(res.steps)
        stats["backtracks"] = int(res.trace_n)
    if tracer is not None:
        _replay_trace(problem, res, tracer)
    if res.outcome == core.SAT:
        return _decode_installed(problem, res.installed)
    if res.outcome == core.UNSAT:
        raise _decode_core(problem, res.core)
    raise Incomplete()


def solve_batch(
    problem_vars: Sequence[Sequence[Variable]],
    max_steps: Optional[int] = None,
    mesh=None,
    stats: Optional[dict] = None,
):
    """Batch entry used by :class:`deppy_tpu.resolution.facade.BatchResolver`:
    N independent variable lists → per-problem result: a ``Solution`` dict,
    the problem's :class:`NotSatisfiable` error, or an :class:`Incomplete`
    marker when that problem exhausted the step budget (problems are
    independent, so one straggler never voids its batchmates' answers).  A
    ``stats`` dict, when given, receives ``{"steps": N}`` summed over the
    batch."""
    problems = [encode(vs) for vs in problem_vars]
    results = solve_problems(problems, max_steps=max_steps, mesh=mesh)
    if stats is not None:
        stats["steps"] = int(sum(int(r.steps) for r in results))
    out: List[Union[dict, NotSatisfiable, Incomplete]] = []
    for p, res in zip(problems, results):
        if res.outcome == core.SAT:
            solution = {v.identifier: False for v in p.variables}
            for v in _decode_installed(p, res.installed):
                solution[v.identifier] = True
            out.append(solution)
        elif res.outcome == core.UNSAT:
            out.append(_decode_core(p, res.core))
        else:
            out.append(Incomplete())
    return out
