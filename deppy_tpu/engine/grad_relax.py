"""Gradient-guided continuous-relaxation entrant (ISSUE 13).

The TurboSAT bet (PAPERS.md): relax each boolean variable to a
probability, descend a differentiable clause-satisfaction loss, round
the minimum back to an assignment, and let a discrete engine keep the
correctness contract.  This module is that entrant, shaped for the
portfolio racer:

  * :func:`candidate_models` — ONE jitted, vmapped sigmoid-relaxation
    descent over the batch's compact clause tensors (the same
    ``pad_stack(pack=False)`` fields every device dispatch ships).
    Loss per lane: product-form clause unsatisfaction ``Π(1 - s_k)``
    over literal satisfaction probabilities, a squared hinge on each
    AtMost bound, and a pull toward TRUE on anchors.  Deterministic
    (zero-logit init, fixed step count) so race replays and tests
    reproduce bit for bit.
  * :func:`attempt` / :func:`solve_lanes` — the certification leg:
    each rounded candidate goes through
    :meth:`deppy_tpu.sat.host.HostEngine.solve_guided`, which serves an
    answer ONLY when it is provably byte-identical to the canonical
    solve (baseline-SAT fixpoint shortcut, or a verified rounding plus
    a zero-backtrack canonical walk) and raises otherwise.  Unverified
    roundings are therefore NEVER served — the lane comes back None
    and the racing discrete engines own the verdict.

The entrant's niche is the hard-instance class the ROADMAP names:
deep implication chains and adversarial stragglers where lockstep
device DPLL pays whole-batch minimization trips and the serial host
engine pays O(extras) sweep passes, while the certified fast path is
one batched descent plus one BCP fixpoint per lane.
"""

from __future__ import annotations

import functools
import time
from typing import List, Optional, Sequence

import numpy as np

from ..analysis import compileguard
from ..hostpool.worker import HostLaneResult, _degraded_result
from ..sat.errors import Incomplete
from ..sat.host import GuidanceUnverified, HostEngine

# Descent schedule: fixed iteration count and learning rate (no
# stochasticity — restarts/noise would break race reproducibility and
# buy little: the certification leg, not the descent, owns
# correctness).  Module constants, not knobs: the descent is a
# screen whose output is verified, so tuning it can only shift which
# lanes take the fast path, never what is served.
DESCENT_ITERS = 48
DESCENT_LR = 0.8


@functools.lru_cache(maxsize=32)
def _descend_fn(NV: int, C: int, K: int, NA: int, M: int, A: int,
                iters: int):
    """Jitted, vmapped descent for one padded shape signature (the
    driver's power-of-two bucketing bounds the entry count, like every
    other batched_* factory)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def one(clauses, card_ids, card_n, card_valid, anchors, n_vars):
        var = jnp.abs(clauses) - 1                      # [C, K]
        pv = jnp.clip(var, 0, NV - 1)
        is_act = var >= n_vars                          # activation lits
        pad = clauses == 0
        mmask = card_ids >= 0
        mv = jnp.clip(card_ids, 0, NV - 1)
        amask = anchors >= 0
        av = jnp.clip(anchors, 0, NV - 1)
        valid_row = (~pad).any(axis=1)

        def loss(x):
            p = jax.nn.sigmoid(x)
            # Literal satisfaction probability; activation variables
            # read constant TRUE (the solve's base assumption), pad
            # cells contribute nothing to their clause's product.
            p_eff = jnp.where(is_act, 1.0, p[pv])
            s = jnp.where(clauses > 0, p_eff, 1.0 - p_eff)
            un = jnp.where(pad, 1.0, 1.0 - s)
            cl = jnp.prod(un, axis=1)
            total = jnp.where(valid_row, cl, 0.0).sum()
            # AtMost rows: squared hinge over the expected true count.
            mp = jnp.where(mmask, p[mv], 0.0)
            over = jnp.maximum(mp.sum(axis=1) - card_n, 0.0)
            total += jnp.where(card_valid > 0, over * over, 0.0).sum()
            # Anchors are assumed TRUE by every solve — pull them up.
            total += jnp.where(amask, 1.0 - p[av], 0.0).sum()
            return total

        grad = jax.grad(loss)

        def body(_, x):
            return x - DESCENT_LR * grad(x)

        x = lax.fori_loop(0, iters, body, jnp.zeros(NV, jnp.float32))
        live = jnp.arange(NV) < n_vars
        return (jax.nn.sigmoid(x) > 0.5) & live

    return jax.jit(compileguard.observe(
        "grad_relax.descend", jax.vmap(one),
        static=(NV, C, K, NA, M, A, iters)))


def candidate_models(problems: Sequence) -> np.ndarray:
    """Run the batched descent over ``problems``; returns the rounded
    candidates as bool[n, NV] (NV = the batch's padded var width).
    Pure heuristic output — nothing downstream may trust it without
    the certification leg."""
    import jax

    from . import driver

    n = len(problems)
    d = driver._Dims(problems, max(n, 1))
    pts = driver.pad_stack(problems, d, d.B, pack=False)
    fn = _descend_fn(d.NV, d.C, d.K, d.NA, d.M, d.A, DESCENT_ITERS)
    out = jax.device_get(fn(
        pts.clauses, pts.card_ids,
        pts.card_n.astype(np.float32), pts.card_valid,
        pts.anchors, pts.n_vars))
    return np.asarray(out)[:n]


def attempt(problem, model: Optional[np.ndarray],
            max_steps: Optional[int] = None, deadline=None,
            cancel=None) -> Optional[HostLaneResult]:
    """Certify-and-serve one lane.  Returns a
    :class:`~deppy_tpu.hostpool.worker.HostLaneResult` when the guided
    solve certified byte-identity to the canonical engine, None when it
    could not (the caller's discrete engines own the verdict).
    ``cancel`` is the race's cooperative stop flag;
    :class:`~deppy_tpu.sat.host.SolveCancelled` propagates to the
    racer."""
    if deadline is not None and deadline.expired():
        return _degraded_result()
    eng = HostEngine(problem, max_steps=max_steps, cancel=cancel)
    t0 = time.perf_counter()
    try:
        _, installed_idx = eng.solve_guided(model)
    except GuidanceUnverified:
        return None
    except Incomplete:
        # Budget exhausted mid-certification: the discrete engines own
        # the Incomplete call (their step accounting is the canon).
        return None
    return HostLaneResult(
        "sat", installed_idx, (), eng.steps, eng.decisions,
        eng.propagation_rounds, eng.backtracks,
        time.perf_counter() - t0)


def solve_lanes(problems: Sequence,
                max_steps: Optional[int] = None,
                deadlines: Optional[Sequence] = None,
                cancel=None) -> List[Optional[HostLaneResult]]:
    """The racer's entrant entry: one batched descent, then per-lane
    certification.  Lanes come back None when unverified — a partial
    result set, which the racer treats as non-definitive."""
    from ..sat.host import SolveCancelled

    n = len(problems)
    dls = list(deadlines) if deadlines is not None else [None] * n
    per_lane_steps = (list(max_steps)
                      if isinstance(max_steps, (list, tuple))
                      else [max_steps] * n)
    if cancel is not None and cancel.is_set():
        raise SolveCancelled()
    models = candidate_models(problems)
    out: List[Optional[HostLaneResult]] = []
    for p, m, ms, dl in zip(problems, models, per_lane_steps, dls):
        out.append(attempt(p, m[: p.n_vars], max_steps=ms, deadline=dl,
                           cancel=cancel))
    return out
