"""Compressed clause banks + implication-driven BCP (ISSUE 12 tentpole).

Every propagation implementation before this one — the [C, K] gather
round, the jnp bitplane algebra, and both Pallas kernels — is a *scan*:
each round evaluates every clause row against the assignment, so a
fixpoint costs ``rounds x C`` clause evaluations even when a round
derives one literal from one clause.  The PR 10 trip ledger puts a
number on the waste (~175µs per lockstep while-trip for ~10µs of useful
lane work), and SatIn / the FPGA-BCP line (PAPERS.md) converge on the
classic CDCL answer: watched-literal propagation — index clauses by
literal and, when a literal becomes false, visit only the clauses
watching it.

This module is that scheme's lockstep-tensor adaptation:

  * **The bank.**  Each problem lowers to a packed literal-occurrence
    bank: ``occ_pos``/``occ_neg`` are ``i32[V, O]`` adjacency tables
    (clause rows containing +v / -v, -1 padded) and ``card_occ`` is the
    ``i32[NV, Oc]`` member→AtMost-row table.  ``O`` is the per-batch
    max occurrence bucketed to a power of two and capped per size class
    (:data:`deppy_tpu.size_classes.SIZE_CLASSES` ``OCC``): the bank is
    the compressed column-sparse transpose of the clause matrix, padded
    to the size class's block shape instead of a dense C×W grid.
  * **The propagation loop.**  One dense entry round
    (:func:`deppy_tpu.engine.core.round_planes`) evaluates the entry
    state — the engine's fixpoints start from restored snapshots plus a
    handful of new literals, so the entry round is the analog of 2WL's
    watch initialization.  After that, propagation is implication
    driven: a pending-literal bitplane plays the CDCL propagation
    queue, each trip pops the lowest pending literal and visits ONLY
    the adjacency rows of its falsified polarity (O clause rows, not
    C), deriving units/conflicts from a masked recompute of those rows.
  * **What happened to the watch pointers.**  Classic 2WL skips a
    visited clause unless the falsified literal is one of its two
    watches, and moves the watch on visit.  Pointer maintenance is a
    scatter per visit and buys nothing in lockstep execution — a
    masked-out lane costs exactly what an active lane costs under
    ``vmap`` — so the bank keeps the *adjacency* half of the scheme
    (the part that bounds memory traffic) and replaces the *watch-move*
    half with the masked row recompute.  The visited set is a superset
    of what watch pointers would visit; every skipped clause is skipped
    by both schemes.

Identity: BCP is monotone and confluent, so the fixpoint (and the
conflict verdict) is independent of propagation order — the watched
path returns byte-identical ``(conflict, t, f)`` to the dense rounds,
which the fuzz differential pins (tests/test_bcp_watched.py) against
both the dense kernels and the host reference engine.

Cost model: a fixpoint that derives ``L`` literals costs one dense
round plus ``L`` visits of ``O·(K + Wv)`` work, vs ``depth x C·Wv`` for
the dense rounds.  The bet pays where clause sets are large and
implication chains long (the giant-catalog / deep-chain classes); on
small problems the dense rounds win because one round settles many
lanes' literals at once.  Selection is ``DEPPY_TPU_BCP=watched`` /
``set_bcp_impl``; ``auto`` stays on the measured-defaults registry —
this impl becomes a default only behind a measured A/B row
(scripts/tpu_ab.py carries the variant).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import core

WORD = core.WORD


# --------------------------------------------------------------------------
# bank construction — host (numpy) side
#
# The driver's single-problem path (pad_problem(pack=True): tests, the
# graft entry) builds banks on host; batch dispatches derive them on
# device from the already-uploaded compact clause tensors
# (:func:`derive_banks`) so no bank bytes cross host→device.


def max_occurrence(clauses: np.ndarray) -> int:
    """Max clause count any single literal occurs in (0 for an empty
    clause set) — the live width the bank's ``O`` is bucketed from."""
    lits = clauses[clauses != 0]
    if lits.size == 0:
        return 0
    key = 2 * (np.abs(lits).astype(np.int64) - 1) + (lits < 0)
    return int(np.bincount(key).max())


def max_card_membership(card_ids: np.ndarray) -> int:
    """Max AtMost-row count any single member variable occurs in."""
    mem = card_ids[card_ids >= 0]
    if mem.size == 0:
        return 0
    return int(np.bincount(mem.astype(np.int64)).max())


def occ_from_clauses_np(clauses: np.ndarray, V: int, O: int,
                        n_vars: "int | None" = None
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Signed clause matrix [C, K] → (occ_pos, occ_neg) ``i32[V, O]``
    adjacency (-1 pad).  ``n_vars`` drops literals past it (the reduced
    space's constant-true activations, exactly like ``pos_bits_r``)."""
    occ_pos = np.full((V, O), -1, np.int32)
    occ_neg = np.full((V, O), -1, np.int32)
    rows, cols = np.nonzero(clauses)
    lits = clauses[rows, cols]
    if n_vars is not None:
        keep = np.abs(lits) <= n_vars
        rows, lits = rows[keep], lits[keep]
    v = np.abs(lits).astype(np.int64) - 1
    neg = lits < 0
    order = np.lexsort((rows, neg, v))  # group by (v, sign), row-stable
    v, neg, rows = v[order], neg[order], rows[order]
    key = 2 * v + neg
    first = np.searchsorted(key, key, side="left")
    rank = np.arange(key.size) - first
    for plane, m in ((occ_pos, ~neg), (occ_neg, neg)):
        plane[v[m], rank[m]] = rows[m]
    return occ_pos, occ_neg


def card_occ_np(card_ids: np.ndarray, NV: int, Oc: int) -> np.ndarray:
    """Member index matrix [NA, M] (-1 pad) → ``i32[NV, Oc]`` member →
    AtMost-row adjacency (-1 pad)."""
    out = np.full((NV, Oc), -1, np.int32)
    rows, cols = np.nonzero(card_ids >= 0)
    mem = card_ids[rows, cols].astype(np.int64)
    order = np.lexsort((rows, mem))
    mem, rows = mem[order], rows[order]
    first = np.searchsorted(mem, mem, side="left")
    rank = np.arange(mem.size) - first
    out[mem, rank] = rows
    return out


# --------------------------------------------------------------------------
# bank construction — device side


def _grouped_scatter(keys: jax.Array, rows: jax.Array, n_keys: int,
                     O: int) -> jax.Array:
    """Grouped fill: for each key group (ascending), scatter its rows
    into ``out[key, 0..count-1]``.  ``keys == n_keys`` is the invalid
    sentinel (dropped).  The sort is stable, so rows land in clause
    order — identical to the numpy build."""
    order = jnp.argsort(keys)
    ks = keys[order]
    rs = rows[order]
    first = jnp.searchsorted(ks, ks, side="left").astype(jnp.int32)
    rank = jnp.arange(ks.shape[0], dtype=jnp.int32) - first
    out = jnp.full((n_keys + 1, O), -1, jnp.int32)
    # Axis-1 overflow (rank >= O) only ever happens in the sentinel
    # group — the driver sizes O from the batch's measured max
    # occurrence before routing a dispatch here — and mode="drop"
    # discards it along with the sentinel row.
    out = out.at[ks, rank].set(rs, mode="drop")
    return out[:n_keys]


def derive_banks(clauses: jax.Array, card_ids: jax.Array,
                 n_vars: jax.Array, *, V: int, NV: int, Ob: int, Oc: int,
                 red: bool, full: bool = True) -> Tuple[jax.Array, ...]:
    """Batched device bank build from the compact clause tensors.

    Returns (occ_pos, occ_neg, occ_pos_r, occ_neg_r, card_occ); spaces
    not requested come back as 1-row dummies (the same placeholder
    convention as :func:`core.derive_planes` — the watched fixpoint
    detects a dummy bank by its row count and falls back to dense
    rounds).  The driver calls this once per uploaded chunk, jitted and
    cached per shape (:func:`deppy_tpu.engine.driver._bank_fn`)."""
    B, C, K = clauses.shape

    def occ_one(cl, nv, width, drop_acts):
        lit = cl.reshape(-1)
        v = jnp.where(lit != 0, jnp.abs(lit) - 1, 0)
        valid = lit != 0
        if drop_acts:
            valid = valid & (jnp.abs(lit) <= nv)
        key = jnp.where(valid, v * 2 + (lit < 0), 2 * width)
        rows = (jnp.arange(C * K, dtype=jnp.int32) // K)
        occ2 = _grouped_scatter(key.astype(jnp.int32), rows,
                                2 * width, O=Ob)
        return occ2[0::2], occ2[1::2]

    def card_one(ci):
        mem = ci.reshape(-1)
        valid = mem >= 0
        key = jnp.where(valid, mem, NV)
        rows = (jnp.arange(mem.shape[0], dtype=jnp.int32)
                // ci.shape[-1])
        return _grouped_scatter(key.astype(jnp.int32), rows, NV, O=Oc)

    if full:
        occ_pos, occ_neg = jax.vmap(
            lambda cl, nv: occ_one(cl, nv, V, False))(clauses, n_vars)
    else:
        occ_pos = jnp.full((B, 1, 1), -1, jnp.int32)
        occ_neg = jnp.full((B, 1, 1), -1, jnp.int32)
    if red:
        occ_pos_r, occ_neg_r = jax.vmap(
            lambda cl, nv: occ_one(cl, nv, NV, True))(clauses, n_vars)
    else:
        occ_pos_r = jnp.full((B, 1, 1), -1, jnp.int32)
        occ_neg_r = jnp.full((B, 1, 1), -1, jnp.int32)
    card_occ = jax.vmap(card_one)(card_ids)
    return occ_pos, occ_neg, occ_pos_r, occ_neg_r, card_occ


# --------------------------------------------------------------------------
# implication-driven fixpoint


def bank_ready(occ: jax.Array) -> bool:
    """Static check: is this a real adjacency bank (vs the 1-row dummy
    the driver ships when the impl is not 'watched' or the batch's max
    occurrence exceeded its size class's OCC cap)?  Every real bank has
    V >= 2 rows (NV >= 1 and NCON >= 1)."""
    return occ.shape[-2] > 1


def watched_fixpoint(clauses: jax.Array, n_vars: jax.Array,
                     occ_pos: jax.Array, occ_neg: jax.Array,
                     card_occ: jax.Array,
                     pos: jax.Array, neg: jax.Array, mem: jax.Array,
                     card_active: jax.Array, card_n2: jax.Array,
                     min_bits: jax.Array, min_w: jax.Array,
                     t0: jax.Array, f0: jax.Array, enabled: jax.Array,
                     red: bool) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Propagate ``(t0, f0)`` to fixpoint via the clause bank.  Shapes
    as in :func:`core.round_planes` plus the bank tables
    (``occ_pos``/``occ_neg`` ``i32[Vb, O]``, ``card_occ``
    ``i32[NVb, Oc]``) and the compact ``clauses i32[C, K]`` the visits
    recompute from.  Returns (conflict, t, f) — byte-identical to the
    dense rounds by confluence.

    One dense entry round settles every consequence of the entry state;
    after that each loop trip pops the lowest pending literal and
    touches only its adjacency rows.  The pending planes are the
    propagation queue: a bit enters pending in the same update that
    sets it in ``t``/``f``, so every visit's recompute sees ALL
    assignments derived so far (pending ⊆ assigned — the invariant that
    makes one-literal-at-a-time processing reach the same closure as
    whole-round processing).

    ``red`` statically selects the reduced problem-var space: literals
    past ``n_vars`` (constant-true activations) are dropped from the
    visit recompute exactly as they are folded out of ``pos_bits_r``.

    A disabled lane runs the entry round value-gated and zero loop
    trips (the engine's lane-gating idiom: under ``vmap`` the cheap
    entry compute runs for every lane anyway; the *loop* is where
    skipping matters)."""
    Wv = t0.shape[1]
    C, K = clauses.shape
    Vb = occ_pos.shape[0]
    NVb = card_occ.shape[0]
    word_idx = jnp.arange(Wv, dtype=jnp.int32)
    # Host-built banks arrive as numpy; the loop indexes them with
    # traced literals, which needs device arrays.
    clauses = jnp.asarray(clauses)
    occ_pos = jnp.asarray(occ_pos)
    occ_neg = jnp.asarray(occ_neg)
    card_occ = jnp.asarray(card_occ)
    mem = jnp.asarray(mem)
    card_active = jnp.asarray(card_active)
    card_n2 = jnp.asarray(card_n2)

    var_all = jnp.where(clauses != 0, jnp.abs(clauses) - 1, 0)
    sign_all = jnp.sign(clauses)
    live_lit = clauses != 0
    if red:
        live_lit = live_lit & (jnp.abs(clauses) <= n_vars)

    run = jnp.asarray(enabled, bool)
    # Dense entry round: the engine's fixpoints start from a restored
    # snapshot plus a few fresh literals, and their first consequences
    # can hide behind ANY clause — only a full scan finds them all.
    c0, t1, f1, _ = core.round_planes(
        pos, neg, mem, card_active, card_n2, min_bits, min_w, t0, f0,
    )
    conflict0 = run & c0
    t1 = jnp.where(run, t1, t0)
    f1 = jnp.where(run, f1, f0)
    pend_t0 = t1 & ~t0
    pend_f0 = f1 & ~f0
    # Incremental row counters, seeded from the ENTRY assignment: the
    # entry round's fresh literals sit in pending and count when
    # popped.
    trues0 = core.tree_sum(core.popcount32(mem & t0), axis=1,
                           keepdims=True)
    mtrues0 = core.tree_sum(core.popcount32(min_bits & t0))

    def body(st):
        conflict, t, f, pend_t, pend_f, trues, mtrues = st
        p_any = (pend_t | pend_f)[0]
        wi = jnp.argmax(p_any != 0).astype(jnp.int32)
        word = p_any[wi]
        lsb = word & -word
        v = wi * WORD + core.popcount32(lsb - 1)
        vm = jnp.where(word_idx == wi, lsb, 0)[None, :]
        is_true = (pend_t & vm).any()
        pend_t = pend_t & ~vm
        pend_f = pend_f & ~vm

        # Visit the adjacency rows of the falsified polarity: v=TRUE
        # falsifies literal -v (occ_neg), v=FALSE falsifies +v.
        vi = jnp.clip(v, 0, Vb - 1)
        rows = jnp.where(is_true, occ_neg[vi], occ_pos[vi])
        valid = rows >= 0
        c = jnp.where(valid, rows, 0)
        vv = var_all[c]
        ss = sign_all[c]
        lv = live_lit[c]
        w = core._srl(vv, 5)
        b = jnp.int32(1) << (vv & 31)
        tb = (t[0][w] & b) != 0
        fb = (f[0][w] & b) != 0
        val = ss * jnp.where(tb, 1, jnp.where(fb, -1, 0))
        val = jnp.where(lv, val, jnp.int32(core.FALSE))
        sat_c = (val == core.TRUE).any(axis=1)
        n_un = (val == core.UNASSIGNED).sum(axis=1)
        visited = valid & lv.any(axis=1)
        dead = visited & ~sat_c & (n_un == 0)
        unit = visited & ~sat_c & (n_un == 1)
        ucol = jnp.argmax(val == core.UNASSIGNED, axis=1)
        uvar = jnp.take_along_axis(vv, ucol[:, None], 1)[:, 0]
        usign = jnp.take_along_axis(ss, ucol[:, None], 1)[:, 0]
        wsel = (core._srl(uvar, 5)[:, None] == word_idx[None, :])
        ubit = (jnp.int32(1) << (uvar & 31))[:, None]
        add_t = core.or_reduce_rows(
            jnp.where(wsel & (unit & (usign > 0))[:, None], ubit, 0))
        add_f = core.or_reduce_rows(
            jnp.where(wsel & (unit & (usign < 0))[:, None], ubit, 0))

        a_now = t | f

        # AtMost rows: only a TRUE assignment moves a row's count.
        crows = card_occ[jnp.clip(v, 0, NVb - 1)]
        cvalid = (crows >= 0) & is_true & (v < NVb)
        r = jnp.where(cvalid, crows, 0)
        trues = trues.at[r, 0].add(cvalid.astype(jnp.int32))
        tr = trues[r, 0]
        act = card_active[r, 0] & cvalid
        over = act & (tr > card_n2[r, 0])
        full_r = act & (tr == card_n2[r, 0])
        add_f = add_f | core.or_reduce_rows(
            jnp.where(full_r[:, None], mem[r] & ~a_now, 0))

        # Dynamic "at most w of the extras" bound.
        in_min = is_true & ((min_bits & vm) != 0).any()
        mtrues = mtrues + in_min.astype(jnp.int32)
        min_over = in_min & (mtrues > min_w)
        add_f = jnp.where(in_min & (mtrues == min_w),
                          add_f | (min_bits & ~a_now), add_f)

        new_t = add_t & ~a_now
        new_f = add_f & ~a_now
        t = t | new_t
        f = f | new_f
        pend_t = pend_t | new_t
        pend_f = pend_f | new_f
        conflict = (conflict | dead.any() | over.any() | min_over
                    | ((t & f) != 0).any())
        return conflict, t, f, pend_t, pend_f, trues, mtrues

    def cond(st):
        conflict, _, _, pend_t, pend_f, _, _ = st
        return ~conflict & ((pend_t | pend_f) != 0).any()

    st = (conflict0, t1, f1, pend_t0, pend_f0, trues0, mtrues0)
    conflict, t, f, _, _, _, _ = lax.while_loop(cond, body, st)
    return conflict, t, f
