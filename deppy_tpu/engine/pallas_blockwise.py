"""Blockwise clause-partitioned BCP for problems past VMEM capacity.

The fused fixpoint kernel (:mod:`deppy_tpu.engine.pallas_bcp`) wins by
holding ALL clause planes resident in VMEM across propagation rounds —
which caps it at problems whose planes fit (~8 MiB of pos+neg at the
default caps).  Above that, the jnp "bits" path must re-stream every
clause plane from HBM **once per propagation round**, and a deep
implication chain means dozens of rounds, i.e. dozens of full-catalog
HBM sweeps.  This module is SURVEY.md §5's stated translation for the
reference's scaling axis (gini's sparse in-RAM structures,
/root/reference/pkg/sat/bench_test.go:12) on ONE device: partition the
clause rows into VMEM-sized blocks and make the expensive unit of work a
**sweep**, not a round.

Mechanics (Gauss-Seidel over blocks; BCP is monotone and confluent, so
any application order reaches the same unique fixpoint):

* one ``pallas_call`` sweep walks the blocks on a 1-D grid; the
  assignment planes (t, f — a few KiB) live in a VMEM accumulator that
  persists across grid steps, so block k+1 sees block k's forcings
  *within the same sweep*;
* while a block is resident, the kernel runs that block's LOCAL
  fixpoint to convergence (a while loop over
  :func:`core.round_planes`) — intra-block implication chains, however
  deep, cost ONE streaming of that block;
* an outer ``lax.while_loop`` repeats sweeps until a sweep changes
  nothing (or conflicts).  Sweep count tracks CROSS-block chain depth,
  which for locality-correlated encodings (the encoder emits a
  bundle's clauses together) is far below total chain depth — that gap
  is exactly the HBM traffic saved over the bits path.

Cardinality rows ride block 0 (they are few; their activity mask is
gated on ``program_id == 0``), and the dynamic minimization row is
evaluated in every block (idempotent under OR).  Conflict semantics:
the conflict FLAG is order-independent (a dead row is dead in every
completion), and post-conflict plane contents are never read by any
caller (dpll/search gate snapshot use on ¬conflict), so outcome parity
with the bits path holds bit-for-bit — pinned by
tests/test_pallas_blockwise.py's differential suite.

Like every device bet in this tree the impl is opt-in
(``DEPPY_TPU_BCP=blockwise``) until a real-chip measurement lands in
BASELINE.md; ``benchmarks/pallas_case.py --impl blockwise`` builds the
2-4× VMEM case.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import config
from . import core

# Clause rows per block: 2 (pos+neg) x 2 (double-buffered DMA) x
# BLOCK_ROWS x Wv x 4B of streamed VMEM; at the default and Wv = 128
# that is 4 MiB, leaving headroom for the resident accumulators and
# cardinality planes inside the ~16 MiB/core budget.
BLOCK_ROWS = int(config.env_raw("DEPPY_TPU_BLOCK_ROWS", "2048"))


def _kernel(minw_ref, en_ref, pos_ref, neg_ref, mem_ref, act_ref,
            cardn_ref, min_ref, tin_ref, fin_ref,
            conf_ref, t_ref, f_ref):
    b = pl.program_id(0)
    pos = pos_ref[:]
    neg = neg_ref[:]
    mem = mem_ref[:]
    card_n2 = cardn_ref[:]
    min_bits = min_ref[:]
    min_w = minw_ref[0, 0]

    # First block of a sweep: seed the resident accumulators from the
    # sweep's input planes (they persist across the remaining steps).
    @pl.when(b == 0)
    def _():
        conf_ref[0, 0] = jnp.int32(0)
        t_ref[:] = tin_ref[:]
        f_ref[:] = fin_ref[:]

    # Cardinality rows ride block 0 only; other blocks see them all
    # inactive (their member planes are still resident inputs, just
    # masked off).
    card_active = (act_ref[:] != 0) & (b == 0)

    run = (en_ref[0, 0] != 0) & (conf_ref[0, 0] == 0)

    def cond(state):
        conflict, _, _, changed = state
        return changed & ~conflict

    def body(state):
        _, t, f, _ = state
        return core.round_planes(
            pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f
        )

    state = (jnp.bool_(False), t_ref[:], f_ref[:], run)
    conflict, t, f, _ = lax.while_loop(cond, body, state)
    conf_ref[0, 0] = conf_ref[0, 0] | conflict.astype(jnp.int32)
    t_ref[:] = t
    f_ref[:] = f


def _sweep(pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f,
           run, block_rows: int):
    """One full pass over the clause blocks (Gauss-Seidel within the
    sweep).  Returns (conflict, t, f)."""
    C, Wv = pos.shape
    NB = C // block_rows
    NA = mem.shape[0]
    minw2 = jnp.full((1, 1), min_w, jnp.int32)
    en2 = jnp.full((1, 1), run, jnp.int32)
    act = card_active.astype(jnp.int32)

    blk = pl.BlockSpec((block_rows, Wv), lambda b: (b, 0),
                       memory_space=pltpu.VMEM)
    res = lambda *s: pl.BlockSpec(s, lambda b: (0,) * len(s),  # noqa: E731
                                  memory_space=pltpu.VMEM)
    smem = pl.BlockSpec((1, 1), lambda b: (0, 0),
                        memory_space=pltpu.SMEM)
    conf, t, f = pl.pallas_call(
        _kernel,
        grid=(NB,),
        in_specs=[
            smem, smem,
            blk, blk,
            res(NA, Wv), res(NA, 1), res(NA, 1), res(1, Wv),
            res(1, Wv), res(1, Wv),
        ],
        out_specs=(smem, res(1, Wv), res(1, Wv)),
        out_shape=(
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, Wv), jnp.int32),
            jax.ShapeDtypeStruct((1, Wv), jnp.int32),
        ),
        interpret=jax.default_backend() != "tpu",
    )(minw2, en2, pos, neg, mem, act, card_n2, min_bits, t, f)
    return conf[0, 0] != 0, t, f


def bcp_fixpoint(pos, neg, mem, card_active, card_n2, min_bits, min_w,
                 t0, f0, enabled=True, block_rows: int | None = None):
    """Run BCP to fixpoint with clause planes streamed blockwise.
    Signature matches :func:`pallas_bcp.bcp_fixpoint`; returns
    (conflict, t, f).  The outer loop repeats sweeps until one changes
    nothing — its trip count is the cross-block chain depth, normally a
    handful, so while-trip overhead is negligible next to each sweep's
    HBM traffic."""
    C, Wv = pos.shape
    br = block_rows or BLOCK_ROWS
    br = min(br, C)
    # Mosaic requires the block's second-to-minor dim be 8-divisible (or
    # equal to the array's row count); round up to the sublane quantum —
    # the extra rows are zero clause planes, inert under round_planes
    # (first hardware compile 2026-08-01 rejected a 2-row smoke block).
    # Interpret mode has no such constraint and keeps the exact br so
    # the tiny-block differential tests still exercise multi-block
    # sweeps (cross-block conflict/forcing propagation).
    if jax.default_backend() == "tpu":
        br = max(8 * ((br + 7) // 8), 8)
    pad = (-C) % br
    if pad:
        zrow = jnp.zeros((pad, Wv), jnp.int32)
        pos = jnp.concatenate([pos, zrow])
        neg = jnp.concatenate([neg, zrow])

    def cond(s):
        conflict, _, _, changed = s
        return changed & ~conflict

    def body(s):
        _, t, f, _ = s
        conflict, t2, f2 = _sweep(
            pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f,
            jnp.bool_(True), br,
        )
        changed = ((t2 != t) | (f2 != f)).any() & ~conflict
        return conflict, t2, f2, changed

    state = (jnp.bool_(False), t0, f0,
             jnp.asarray(enabled, bool) if not isinstance(enabled, bool)
             else jnp.bool_(enabled))
    conflict, t, f, _ = lax.while_loop(cond, body, state)
    return conflict, t, f
