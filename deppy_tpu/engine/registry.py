"""Engine-backend registry facade (ISSUE 13 tentpole).

The repo grew five engine paths one PR at a time — batched device DPLL
(:mod:`deppy_tpu.engine.driver`), the warm-start device screen
(:mod:`deppy_tpu.incremental`), the inline host engine
(:mod:`deppy_tpu.sat.host`), the hostpool workers
(:mod:`deppy_tpu.hostpool`), and now the gradient-relaxation entrant
(:mod:`deppy_tpu.engine.grad_relax`) — each reachable through its own
ad-hoc call site.  This module is the one declaration point: every
backend registers a :class:`BackendSpec` (capabilities: size-class
range, cardinality support, warm-start support, whether it can decide
ANY instance) plus a per-class cost estimate, and a uniform
``solve_via`` adapter that renders every backend's answers in the one
lane vocabulary (:class:`~deppy_tpu.hostpool.worker.HostLaneResult`)
the scheduler's host drain already decodes.

The portfolio racer (:class:`deppy_tpu.sched.scheduler.PortfolioRacer`)
consumes this surface: :func:`candidates` ranks the backends for a size
class — by the measured-defaults registry's ``portfolio.<class>`` /
``portfolio`` rows when one was learned (``scripts/tpu_ab.py``'s
portfolio variant writes them), else by the static canonical-first
order — and the racer dispatches the top K concurrently.

Answer identity: the host engine is the executable spec and the device
engine is pinned bit-identical to it (models, unsat cores), so any
definitive backend's answers are interchangeable; the grad entrant
serves only what its certification proves identical.  Step counts are
engine-relative — exactly as they already are on the breaker's
host-fallback path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import size_classes as _size_classes
from ..hostpool.worker import HostLaneResult

_CLASS_NAMES = tuple(name for name, _ in _size_classes.ordered_classes())


@dataclass(frozen=True)
class BackendSpec:
    """One registered engine backend.

    ``classes``: ladder classes the backend serves.  ``definitive``:
    whether the backend can decide ANY instance it accepts (the grad
    entrant cannot — unverified lanes come back None and the racer
    treats its result as non-definitive).  ``cost_us``: rough per-lane
    µs-per-solve by class — the ranking fallback when no measured
    ``portfolio`` row exists, and the straggler-triage estimate's
    floor.  The numbers are order-of-magnitude anchors from the
    measured repo artifacts (bcp_rewrite_r12, hostpool_baseline), not
    promises; measured rows override the ordering entirely."""

    name: str
    classes: Tuple[str, ...]
    cardinality: bool
    warm_start: bool
    definitive: bool
    cost_us: Dict[str, float]
    # Signed objective-bound support (ISSUE 18): whether the backend can
    # search under a mixed-sign weighted bound (cost-when-false terms
    # folded to negative signed weights).  All-nonnegative bounds lower
    # to plain AtMost cardinality and need only ``cardinality``.
    bound_weights: bool = False


_SPECS: Dict[str, BackendSpec] = {
    spec.name: spec
    for spec in (
        BackendSpec("device", _CLASS_NAMES, cardinality=True,
                    warm_start=False, definitive=True,
                    cost_us={"xs": 400.0, "s": 700.0, "m": 2000.0,
                             "l": 8000.0, "xl": 16000.0}),
        BackendSpec("host", _CLASS_NAMES, cardinality=True,
                    warm_start=True, definitive=True,
                    cost_us={"xs": 600.0, "s": 2500.0, "m": 12000.0,
                             "l": 60000.0, "xl": 150000.0},
                    bound_weights=True),
        BackendSpec("hostpool", _CLASS_NAMES, cardinality=True,
                    warm_start=False, definitive=True,
                    cost_us={"xs": 300.0, "s": 900.0, "m": 4000.0,
                             "l": 20000.0, "xl": 50000.0}),
        BackendSpec("warm", _CLASS_NAMES, cardinality=True,
                    warm_start=True, definitive=False,
                    cost_us={"xs": 60.0, "s": 120.0, "m": 400.0,
                             "l": 1500.0, "xl": 3000.0}),
        BackendSpec("grad_relax", _CLASS_NAMES, cardinality=True,
                    warm_start=False, definitive=False,
                    cost_us={"xs": 250.0, "s": 500.0, "m": 1500.0,
                             "l": 5000.0, "xl": 10000.0}),
    )
}

# Canonical-first static ranking: without measured evidence the racer
# must keep the canonical winner cheap — the device engine leads (it is
# what racing-off dispatches), the cancellable inline host engine is
# the default second lane, the certified heuristic third, the
# (abandon-only, pool-lock-holding) hostpool last.
_STATIC_ORDER = ("device", "host", "grad_relax", "hostpool")


def specs() -> Dict[str, BackendSpec]:
    """The registered backends (read-only view by convention)."""
    return dict(_SPECS)


def get(name: str) -> BackendSpec:
    return _SPECS[name]


def estimate_us(name: str, class_name: str) -> float:
    """Per-lane cost estimate for one backend in one ladder class."""
    spec = _SPECS[name]
    return spec.cost_us.get(class_name,
                            max(spec.cost_us.values()))


# ISSUE 19: learned-route overlay — in-memory ranking rows adopted by
# the online route registry (:mod:`deppy_tpu.routes.learn`), consulted
# ahead of the measured-defaults file so a serving replica can adopt a
# live-learned row without mutating the package-local registry
# mid-serve.  A learned row can only reorder WHICH definitive backends
# race — the racer's first-definitive-winner rule and sampled
# cross-check still gate every answer, so adoption changes speed,
# never answers.  Empty (the default, and always under
# DEPPY_TPU_ROUTE_LEARN=off) leaves ranked() byte-identical.
_ROUTE_OVERLAY: Dict[str, str] = {}


def set_route_overlay(rows: Optional[Dict[str, str]]) -> None:
    """Replace the learned-route overlay: ``{key: comma-separated
    row}`` under the same keys :func:`ranked` reads
    (``portfolio.<class>`` / ``portfolio``).  None or {} clears it."""
    global _ROUTE_OVERLAY
    _ROUTE_OVERLAY = dict(rows or {})


def update_route_overlay(rows: Dict[str, str]) -> None:
    """Merge rows into the learned-route overlay (atomic swap — the
    racer may be reading concurrently)."""
    global _ROUTE_OVERLAY
    _ROUTE_OVERLAY = {**_ROUTE_OVERLAY, **rows}


def route_overlay() -> Dict[str, str]:
    return dict(_ROUTE_OVERLAY)


def ranked(class_name: str) -> Tuple[List[str], bool]:
    """Candidate backend names for a size class, best first, plus
    whether the order came from a MEASURED ``portfolio`` row (the
    ``auto`` racing mode engages only then).  Rows are comma-separated
    backend names under the measured-defaults keys
    ``portfolio.<class>`` (per class) or ``portfolio`` (global); a
    live-learned overlay row (ISSUE 19) takes precedence and counts as
    measured — it IS a measurement, just a fresher one."""
    from . import core

    for key in (f"portfolio.{class_name}", "portfolio"):
        row = _ROUTE_OVERLAY.get(key) or core.measured_default(key)
        if row:
            names = [n.strip() for n in row.split(",")
                     if n.strip() in _SPECS]
            if len(names) >= 2:
                return names, True
    return list(_STATIC_ORDER), False


def candidates(class_name: str, k: int, device_ok: bool = True,
               pool_ok: Optional[bool] = None,
               cardinality: bool = False) -> Tuple[List[str], bool]:
    """Top-K raceable backends for one flush: the ranked order filtered
    by capability (class served, cardinality when the flush carries
    AtMost rows) and availability (``device_ok`` — the resolved
    backend and breaker verdict; ``pool_ok`` — hostpool spawnability,
    probed lazily when None).  The warm screen never races (warm lanes
    coalesce in their own scheduler class)."""
    names, measured = ranked(class_name)
    out: List[str] = []
    for name in names:
        spec = _SPECS.get(name)
        if spec is None or spec.name == "warm":
            continue
        if class_name not in spec.classes:
            continue
        if cardinality and not spec.cardinality:
            continue
        if name == "device" and not device_ok:
            continue
        if name == "hostpool":
            if pool_ok is None:
                from .. import hostpool

                pool = hostpool.default_pool()
                pool_ok = pool is not None and pool.available
            if not pool_ok:
                continue
        out.append(name)
        if len(out) >= max(int(k), 2):
            break
    return out, measured


def optimize_candidates(class_name: str, k: int = 2,
                        signed: bool = False,
                        device_ok: bool = True,
                        pool_ok: Optional[bool] = None) -> Tuple[List[str], bool]:
    """Raceable backends for one optimize-tier bound probe (ISSUE 18).

    Definitive backends only: a probe's UNSAT at the tightened bound is
    the tier's optimality PROOF, so a backend that can fail to decide an
    instance it accepts (grad_relax) must never answer one.  ``signed``
    probes (mixed-sign weights — upgrade planning's keep-installed
    terms) further require ``bound_weights``; all-nonnegative probes
    lower to plain AtMost cardinality and keep the full definitive
    field."""
    names, measured = candidates(class_name, k=len(_SPECS),
                                 device_ok=device_ok, pool_ok=pool_ok,
                                 cardinality=True)
    out = [n for n in names
           if _SPECS[n].definitive
           and (not signed or _SPECS[n].bound_weights)]
    return out[: max(int(k), 1)], measured


# ------------------------------------------------------------- adapters
#
# One lane vocabulary for every backend: HostLaneResult — the shape the
# hostpool workers already emit and the scheduler's host drain already
# decodes (models via _solution_dict, cores via applied-index lists),
# so racing cannot invent a second decode path to drift.


def _from_solve_result(problem, res) -> HostLaneResult:
    """Render one device :class:`core.SolveResult` in the lane
    vocabulary.  Index lists are in ascending index order — exactly the
    order ``driver.decode_results`` walks, so the decoded answers are
    byte-identical."""
    from . import core

    o = int(res.outcome)
    if o == core.SAT:
        idx = np.nonzero(np.asarray(res.installed)[: problem.n_vars])[0]
        return HostLaneResult("sat", [int(i) for i in idx], [],
                              int(res.steps),
                              backtracks=int(res.trace_n))
    if o == core.UNSAT:
        idx = np.nonzero(np.asarray(res.core)[: problem.n_cons])[0]
        return HostLaneResult("unsat", [], [int(i) for i in idx],
                              int(res.steps),
                              backtracks=int(res.trace_n))
    return HostLaneResult("incomplete", [], [], int(res.steps),
                          backtracks=int(res.trace_n))


def _solve_device(problems, max_steps, deadlines, cancel, mesh=None):
    """Batched device dispatch through the full driver pipeline
    (size-class bucketing, phase compaction, escalation, fault
    domain).  Device programs cannot be cooperatively cancelled — a
    losing race lane runs to completion and its fetch is dropped."""
    from . import driver

    if mesh is not None and getattr(mesh, "size", 1) >= 2:
        results = driver.solve_problems_sharded(problems, mesh=mesh,
                                                max_steps=max_steps)
    else:
        results = driver.solve_problems(problems, max_steps=max_steps)
    return [_from_solve_result(p, r) for p, r in zip(problems, results)]


def _solve_host(problems, max_steps, deadlines, cancel, mesh=None):
    """Inline host-engine lanes — the cancellable spelling (the race's
    cooperative stop flag is checked at every engine step boundary)."""
    from ..hostpool.worker import solve_lane

    n = len(problems)
    dls = list(deadlines) if deadlines is not None else [None] * n
    per = (list(max_steps) if isinstance(max_steps, (list, tuple))
           else [max_steps] * n)
    return [solve_lane(p, max_steps=ms, deadline=dl, cancel=cancel)
            for p, ms, dl in zip(problems, per, dls)]


def _solve_hostpool(problems, max_steps, deadlines, cancel, mesh=None):
    """The shared worker-pool entry.  No cross-process cancel flag —
    a losing pool entrant is abandoned (its results dropped) and its
    dispatch drains in the background."""
    from .. import hostpool

    return hostpool.solve_host_problems(problems, max_steps=max_steps,
                                        deadlines=deadlines)


def _solve_warm(plans, max_steps, deadlines, cancel, mesh=None):
    """Certified warm-start attempts (ISSUE 10) — ``plans`` are
    WarmPlan objects, one per lane; None per lane on fallback.  The
    scheduler's incremental class is the only caller; listed here so
    the registry fronts every engine path."""
    from .. import incremental as inc

    out = []
    for plan in plans:
        res = inc.attempt(plan, max_steps)
        if res is None:
            out.append(None)
            continue
        out.append(HostLaneResult(
            "sat", list(res.installed_idx), [], res.steps,
            decisions=res.decisions,
            propagation_rounds=res.propagation_rounds,
            backtracks=res.backtracks))
    return out


def _solve_grad(problems, max_steps, deadlines, cancel, mesh=None):
    from . import grad_relax

    return grad_relax.solve_lanes(problems, max_steps=max_steps,
                                  deadlines=deadlines, cancel=cancel)


_SOLVERS = {
    "device": _solve_device,
    "host": _solve_host,
    "hostpool": _solve_hostpool,
    "warm": _solve_warm,
    "grad_relax": _solve_grad,
}


def solve_via(name: str, problems: Sequence,
              max_steps=None, deadlines: Optional[Sequence] = None,
              cancel=None, mesh=None):
    """Dispatch one lane set through the named backend.  Returns a list
    of :class:`HostLaneResult` (None per lane a non-definitive backend
    could not certify)."""
    return _SOLVERS[name](problems, max_steps, deadlines, cancel,
                          mesh=mesh)
