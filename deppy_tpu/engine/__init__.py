"""Batched tensor solve engine.

The TPU-native replacement for the reference's gini CDCL engine plus search
driver (/root/reference/pkg/sat/{solve,search}.go): the complete solve
algorithm — baseline propagation, preference-ordered guess search, DPLL leaf
solves, extras-only cardinality minimization, and deletion-based unsat-core
extraction — expressed as fixed-shape tensor programs inside
``lax.while_loop``/``lax.switch``, vmapped over a batch of independent
problems and jit-compiled once per padded shape bucket.

Modules:
  * :mod:`deppy_tpu.engine.core`   — per-problem solve as pure JAX functions;
  * :mod:`deppy_tpu.engine.driver` — padding/bucketing, batching, jit cache,
    and host-side decode back to variables / unsat cores.
"""

from .driver import solve_batch, solve_one

__all__ = ["solve_batch", "solve_one"]
