"""Batched tensor solve engine.

The TPU-native replacement for the reference's gini CDCL engine plus search
driver (/root/reference/pkg/sat/{solve,search}.go): the complete solve
algorithm — baseline propagation, preference-ordered guess search, DPLL leaf
solves, extras-only cardinality minimization, and deletion-based unsat-core
extraction — expressed as fixed-shape tensor programs inside
``lax.while_loop``/``lax.switch``, vmapped over a batch of independent
problems and jit-compiled once per padded shape bucket.

Modules:
  * :mod:`deppy_tpu.engine.core`   — per-problem solve as pure JAX functions;
  * :mod:`deppy_tpu.engine.driver` — padding/bucketing, batching, jit cache,
    and host-side decode back to variables / unsat cores.
"""

from .driver import solve_batch, solve_one


def clear_compile_caches() -> None:
    """Drop every cached compiled program (the batched_* entry-point
    caches, the plane-derivation cache, and JAX's own executable caches).

    A long-lived process that solves problems of many *distinct padded
    shapes* accumulates one executable per shape signature; the driver's
    power-of-two bucketing bounds this for any one workload family, but a
    service fed continually-novel shapes can grow compile memory without
    bound (observed: an LLVM "Cannot allocate memory" after ~600 unique
    single-problem shapes in one process).  Call this at a convenient
    quiesce point to reset; the next solve of each shape recompiles."""
    import jax

    from . import core, driver

    core.clear_batched_caches()
    driver._planes_fn.cache_clear()
    driver._bank_fn.cache_clear()
    jax.clear_caches()


__all__ = ["solve_batch", "solve_one", "clear_compile_caches"]
