"""Per-problem tensor solve: the reference algorithm as pure JAX.

This module re-implements, with dense fixed-shape state, exactly the
algorithm the host reference engine (:mod:`deppy_tpu.sat.host`) specifies —
which in turn mirrors /root/reference/pkg/sat/solve.go:53-119 and
search.go:34-203:

  * :func:`bcp` / :func:`planes_fixpoint` — boolean-constraint propagation
    to fixpoint.  Clauses and assignments live as packed int32 bitplanes;
    one round evaluates every clause and cardinality row simultaneously
    with bitwise algebra (the TPU-native formulation of watched-literal
    propagation; a [C, K] gather variant remains selectable).
  * :func:`dpll` — complete search under assumptions (the analog of gini
    ``Solve()``): chronological DPLL on a fixed-size decision stack,
    deciding the lowest-index unassigned variable false-first.  A trail of
    per-level plane snapshots makes each iteration propagate only its new
    decision literal from the previous fixpoint, and backtracking a pure
    snapshot restore.
  * :func:`search` — the preference-ordered guess search (search.go:34-203):
    the choice deque and guess stack become fixed-capacity circular-buffer /
    stack tensors.  The four reference loop arms run as lane-gated masked
    selects (not ``lax.switch``, which lowers to select under ``vmap`` and
    would execute every arm for every lane), with guess-trail snapshots so
    pops re-Test for free.
  * :func:`solve_full` — the whole pipeline including extras-only
    cardinality minimization (solve.go:86-113) and deletion-based
    unsat-core minimization (the engine-agnostic analog of gini ``Why``,
    lit_mapping.go:198-207).  Phases are lane-gated: each takes an
    ``enabled`` flag that makes its ``while_loop`` trip zero times on lanes
    that don't need it, because under ``vmap`` a ``lax.cond`` would run
    both branches for every lane anyway.

Everything here is shape-static and batchable with ``jax.vmap``; no Python
control flow depends on traced values.  The batch axis and device-mesh
sharding live in :mod:`deppy_tpu.engine.driver` and
:mod:`deppy_tpu.parallel`.
"""

from __future__ import annotations

import functools
import json
import os
import threading
from contextlib import contextmanager
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import config
from ..analysis import compileguard

# Assignment values (same convention as the host engine).
TRUE = 1
FALSE = -1
UNASSIGNED = 0

# Outcomes (reference solve.go:43-47).  RUNNING doubles as UNKNOWN.
SAT = 1
UNSAT = -1
RUNNING = 0

# Bits per bitplane word.  Bitplanes encode clause/assignment sets as packed
# int32 words (logical-shift arithmetic throughout — Mosaic has no unsigned
# reductions), turning BCP's per-literal gather into dense bitwise algebra:
# the TPU-native formulation of watched-literal propagation.
WORD = 32


class ProblemTensors(NamedTuple):
    """One lowered problem, padded to the batch's common shapes.

    Produced by :func:`deppy_tpu.engine.driver.pad_problem` from
    :class:`deppy_tpu.sat.encode.Problem`.  Conventions: clause literals are
    signed 1-based with 0 padding; every other index tensor is 0-based with
    -1 padding.  ``n_vars``/``n_cons`` are the problem's true sizes inside
    the padding.
    """

    clauses: jax.Array      # i32[C, K]
    card_ids: jax.Array     # i32[NA, M]
    card_n: jax.Array       # i32[NA]
    card_act: jax.Array     # i32[NA]  (-1 on padded rows)
    anchors: jax.Array      # i32[A]   (-1 padded)
    choice_cand: jax.Array  # i32[NC, Kc]
    var_choices: jax.Array  # i32[NV, W]
    n_vars: jax.Array       # i32 scalar
    n_cons: jax.Array       # i32 scalar
    # Bitplane mirrors of the clause matrix and cardinality rows (packed
    # int32, Wv = ceil(V/32) words): the "bits"/"pallas" BCP paths evaluate
    # every clause with bitwise algebra instead of a [C, K] gather.
    pos_bits: jax.Array         # i32[C, Wv]  positive-literal membership
    neg_bits: jax.Array         # i32[C, Wv]  negative-literal membership
    card_member_bits: jax.Array  # i32[NA, Wv] AtMost member sets
    card_act_bits: jax.Array    # i32[NA, Wv] one-hot activation var (0 = pad)
    # Reduced-space planes (packed over the problem-var region only,
    # Wr = ceil(NV/32) words): the search and minimization phases never
    # disable constraint activations — every activation variable is
    # constant TRUE there — so each clause's ¬activation literal is
    # constant-false and folds away.  Dropping the activation columns
    # shrinks every propagation round's plane traffic by V/NV (often
    # 2-3×, the activation region usually outnumbering real variables).
    # Only the unsat-core phase, which probes with activation subsets
    # disabled, needs the full-space planes above.
    pos_bits_r: jax.Array       # i32[C, Wr]
    neg_bits_r: jax.Array       # i32[C, Wr]
    card_member_bits_r: jax.Array  # i32[NA, Wr]
    card_valid: jax.Array       # i32[NA]  1 on real AtMost rows, 0 on pads
    # Compressed clause banks (ISSUE 12): literal→clause adjacency for
    # the implication-driven "watched" BCP impl — occ_pos/occ_neg list
    # the clause rows containing +v/-v (i32[V, Ob], -1 padded; _r =
    # the reduced problem-var space), card_occ the AtMost rows each
    # member variable sits in (i32[NV, Oc]).  Every other impl (and a
    # batch whose occurrence width exceeds its size class's OCC cap)
    # ships 1-row dummies; see deppy_tpu.engine.clause_bank.
    occ_pos: jax.Array          # i32[V, Ob]
    occ_neg: jax.Array          # i32[V, Ob]
    occ_pos_r: jax.Array        # i32[NV, Ob]
    occ_neg_r: jax.Array        # i32[NV, Ob]
    card_occ: jax.Array         # i32[NV, Oc]


class SolveResult(NamedTuple):
    outcome: jax.Array     # i32: SAT / UNSAT / RUNNING (= incomplete)
    installed: jax.Array   # bool[NV] (problem-var region, every impl/mode)
    core: jax.Array        # bool[NCON] active applied constraints (UNSAT only)
    steps: jax.Array       # i32 step counter (tests + DPLL iterations)
    # Backtrack trace (tracer.go:13-15): row i = the guess-variable stack
    # (-1 padded) at the i-th search backtrack.  Shape [T, GS]; T is the
    # static trace capacity (0 = tracing off).  ``trace_n`` counts ALL
    # backtracks, so trace_n > T means the buffer truncated.
    trace_stack: jax.Array  # i32[T, GS]
    trace_n: jax.Array      # i32


# --------------------------------------------------------------------------
# assignment construction


def _base_assignment(pt: ProblemTensors, V: int, NCON: int,
                     act_enabled: jax.Array | None = None) -> jax.Array:
    """All problem vars unassigned; activation vars true (the analog of
    ``AssumeConstraints``, reference lit_mapping.go:136-140) unless an
    explicit ``act_enabled: bool[NCON]`` subset is given (unsat-core mode);
    padding slots pinned false so they never read as unassigned."""
    idx = jnp.arange(V, dtype=jnp.int32)
    in_act = (idx >= pt.n_vars) & (idx < pt.n_vars + pt.n_cons)
    if act_enabled is None:
        act_val = jnp.int32(TRUE)
    else:
        j = jnp.clip(idx - pt.n_vars, 0, NCON - 1)
        act_val = jnp.where(act_enabled[j], TRUE, UNASSIGNED).astype(jnp.int32)
    return jnp.where(
        idx < pt.n_vars,
        jnp.int32(UNASSIGNED),
        jnp.where(in_act, act_val, jnp.int32(FALSE)),
    )


def _base_assignment_red(pt: ProblemTensors, NV: int) -> jax.Array:
    """Reduced-space base assignment: no activation region exists (all
    activations are constant TRUE and folded into the reduced planes);
    padding slots beyond ``n_vars`` are pinned false."""
    idx = jnp.arange(NV, dtype=jnp.int32)
    return jnp.where(idx < pt.n_vars, jnp.int32(UNASSIGNED), jnp.int32(FALSE))


def _apply_anchors(pt: ProblemTensors, assign: jax.Array, V: int) -> jax.Array:
    """Assume every anchor (Mandatory variable) true (solve.go:67-75)."""
    tgt = jnp.where(pt.anchors >= 0, pt.anchors, V)
    return assign.at[tgt].set(TRUE, mode="drop")


def _anchor_mask(pt: ProblemTensors, V: int) -> jax.Array:
    tgt = jnp.where(pt.anchors >= 0, pt.anchors, V)
    return jnp.zeros(V, bool).at[tgt].set(True, mode="drop")


# --------------------------------------------------------------------------
# bitplane algebra (shared by the jnp "bits" path and the Pallas kernel)


def _srl(x: jax.Array, n) -> jax.Array:
    """Logical right shift on int32 (sign bit is data, not sign)."""
    return lax.shift_right_logical(x, n)


def popcount32(v: jax.Array) -> jax.Array:
    """Per-word SWAR popcount on int32 bitplanes (no unsigned types:
    Mosaic cannot reduce unsigned ints; logical shifts keep this exact)."""
    v = v - (_srl(v, 1) & 0x55555555)
    v = (v & 0x33333333) + (_srl(v, 2) & 0x33333333)
    v = (v + _srl(v, 4)) & 0x0F0F0F0F
    return (v + _srl(v, 8) + _srl(v, 16) + _srl(v, 24)) & 0x3F


def or_reduce_rows(x: jax.Array) -> jax.Array:
    """Bitwise-OR reduce over axis 0 → shape [1, ...].  Static halving tree
    (works inside Pallas kernels, where ufunc or-reductions don't lower);
    rows are zero-padded to a power of two first."""
    n = x.shape[0]
    p = 1
    while p < n:
        p <<= 1
    if p != n:
        x = jnp.concatenate(
            [x, jnp.zeros((p - n,) + x.shape[1:], x.dtype)], axis=0
        )
    while p > 1:
        x = x[: p // 2] | x[p // 2 :]
        p //= 2
    return x


def _pow2_pad(x: jax.Array, axis: int, fill) -> tuple:
    """Pad ``axis`` with ``fill`` up to the next power of two; returns
    (padded, padded length)."""
    n = x.shape[axis]
    p = 1
    while p < n:
        p <<= 1
    if p != n:
        shape = list(x.shape)
        shape[axis] = p - n
        x = jnp.concatenate(
            [x, jnp.full(shape, fill, x.dtype)], axis=axis
        )
    return x, p


def _tree_fold(x: jax.Array, axis: int, combine, fill) -> jax.Array:
    """Static halving-tree reduction along ``axis`` (keepdims).  The
    installed Mosaic lowering rejects every *integer* ``reduce_*``
    primitive ("Reductions over integers not implemented", jax 0.4.x),
    while adds/mins and slices always lower — so the kernels reduce by
    tree instead.  Bit-exact vs the reduction primitives: int32 add and
    min are associative."""
    x, p = _pow2_pad(x, axis, fill)
    while p > 1:
        h = p // 2
        x = combine(lax.slice_in_dim(x, 0, h, axis=axis),
                    lax.slice_in_dim(x, h, p, axis=axis))
        p = h
    return x


def tree_sum(x: jax.Array, axis: "int | None" = None,
             keepdims: bool = False) -> jax.Array:
    """Mosaic-safe integer sum (see :func:`_tree_fold`).  ``axis=None``
    reduces every axis to a scalar.  Bools count as int32."""
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.int32)
    if axis is None:
        for ax in range(x.ndim):
            x = _tree_fold(x, ax, lax.add, 0)
        return jnp.squeeze(x)
    axis = axis % x.ndim
    x = _tree_fold(x, axis, lax.add, 0)
    return x if keepdims else jnp.squeeze(x, axis=axis)


def tree_min(x: jax.Array, axis: "int | None" = None,
             keepdims: bool = False) -> jax.Array:
    """Mosaic-safe integer min (see :func:`_tree_fold`)."""
    fill = jnp.iinfo(x.dtype).max
    if axis is None:
        for ax in range(x.ndim):
            x = _tree_fold(x, ax, lax.min, fill)
        return jnp.squeeze(x)
    axis = axis % x.ndim
    x = _tree_fold(x, axis, lax.min, fill)
    return x if keepdims else jnp.squeeze(x, axis=axis)


def tree_max(x: jax.Array, axis: "int | None" = None,
             keepdims: bool = False) -> jax.Array:
    """Mosaic-safe integer max (see :func:`_tree_fold`)."""
    fill = jnp.iinfo(x.dtype).min
    if axis is None:
        for ax in range(x.ndim):
            x = _tree_fold(x, ax, lax.max, fill)
        return jnp.squeeze(x)
    axis = axis % x.ndim
    x = _tree_fold(x, axis, lax.max, fill)
    return x if keepdims else jnp.squeeze(x, axis=axis)


def pack_mask(mask: jax.Array, Wv: int) -> jax.Array:
    """bool[V] → packed i32[1, Wv] bitplane.  Distinct bit positions make the
    int32 sum carry-free, i.e. an OR."""
    V = mask.shape[0]
    pad = Wv * WORD - V
    m = mask
    if pad:
        m = jnp.concatenate([m, jnp.zeros(pad, bool)])
    m = m.reshape(Wv, WORD).astype(jnp.int32)
    shifts = jnp.arange(WORD, dtype=jnp.int32)[None, :]
    return tree_sum(m << shifts, axis=1)[None, :]


def unpack_mask(words: jax.Array, V: int) -> jax.Array:
    """packed i32[1, Wv] → bool[V]."""
    shifts = jnp.arange(WORD, dtype=jnp.int32)[None, :]
    bits = (_srl(words.reshape(-1, 1), shifts) & 1).astype(bool)
    return bits.reshape(-1)[:V]


# Mesh axis for clause-sharded propagation (intra-problem parallelism,
# SURVEY.md §2.7 axis 3 / §5's beyond-one-core scaling): when set, each
# device holds a row shard of the clause/cardinality planes and every
# propagation round combines the per-shard unit/conflict partials with an
# OR collective.  Trace-time state (like _BCP_IMPL) so the whole solve
# stack runs unmodified inside ``shard_map`` — control flow is replicated,
# only the clause row axis is distributed.  Thread-local: a retrace of an
# unsharded program on another thread while one thread holds the context
# must not capture the collectives (an unbound axis name outside
# shard_map is a trace error).
_AXIS_STATE = threading.local()


def _clause_axis_name() -> "str | None":
    return getattr(_AXIS_STATE, "name", None)


class clause_axis:
    """Context manager: trace the enclosed programs with clause-row
    collectives over ``name`` (a mesh axis inside ``shard_map``)."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._prev = _clause_axis_name()
        _AXIS_STATE.name = self.name
        return self

    def __exit__(self, *exc):
        _AXIS_STATE.name = self._prev
        return False


def _axis_or_fused(wpos: jax.Array, wneg: jax.Array, conflict: jax.Array,
                   axis_name: str) -> tuple:
    """Combine a round's shard partials in ONE collective: the forced
    masks and the conflict flag concatenate into a single [1, 2Wv+1]
    buffer, one all-gather crosses ICI, and the OR-fold splits back out
    (conflict OR == any)."""
    Wv = wpos.shape[1]
    buf = jnp.concatenate(
        [wpos, wneg, conflict.astype(jnp.int32).reshape(1, 1)], axis=1
    )
    g = lax.all_gather(buf, axis_name)  # [D, 1, 2Wv+1]
    out = g[0]
    for i in range(1, g.shape[0]):
        out = out | g[i]
    return out[:, :Wv], out[:, Wv: 2 * Wv], out[0, 2 * Wv] != 0


def round_planes(pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f):
    """One propagation round on bitplanes — the exact bitwise translation of
    :func:`bcp_round` (itself the dense analog of gini's watched-literal BCP).
    Shapes: pos/neg i32[C, Wv]; mem i32[NA, Wv]; card_active bool[NA, 1];
    card_n2 i32[NA, 1]; min_bits/t/f i32[1, Wv]; min_w i32 scalar.  Returns
    (conflict, new_t, new_f, changed).  Runs unchanged under jit and inside
    the Pallas kernel (:mod:`deppy_tpu.engine.pallas_bcp`).

    ``card_active`` is precomputed by the caller: activation variables are
    assumptions — propagation never flips one (a clause forcing ¬act on a
    true act is a conflict, not a flip) — so row activity is invariant
    across a fixpoint and need not be re-derived every round.

    Under :class:`clause_axis`, ``pos``/``neg``/``mem`` rows are one mesh
    shard of the problem's clause set and ``t``/``f``/``min_bits`` are
    replicated: the per-shard forced-literal masks and conflict flags
    combine with one fused OR all-gather per round — the only cross-device
    traffic of a clause-sharded solve, a few dozen words per round over
    ICI."""
    a = t | f
    sat = (((pos & t) | (neg & f)) != 0).any(axis=1, keepdims=True)   # [C,1]
    upos = pos & ~a
    uneg = neg & ~a
    n_un = tree_sum(popcount32(upos), axis=1, keepdims=True) + tree_sum(
        popcount32(uneg), axis=1, keepdims=True
    )                                                                  # [C,1]
    valid = ((pos | neg) != 0).any(axis=1, keepdims=True)
    dead = valid & ~sat & (n_un == 0)
    unit = valid & ~sat & (n_un == 1)
    wpos = or_reduce_rows(jnp.where(unit, upos, 0))                    # [1,Wv]
    wneg = or_reduce_rows(jnp.where(unit, uneg, 0))

    # AtMost rows: count true / unassigned members; > n conflicts, == n
    # forces the rest false.
    active = card_active                                               # [NA,1]
    trues = tree_sum(popcount32(mem & t), axis=1, keepdims=True)
    unk = tree_sum(popcount32(mem & ~a), axis=1, keepdims=True)
    over = active & (trues > card_n2)
    full = active & (trues == card_n2) & (unk > 0)
    wneg = wneg | or_reduce_rows(jnp.where(full, mem & ~a, 0))

    # Dynamic "at most w of the extras" bound for the minimization loop.
    # (min_bits/t are replicated under clause sharding — no collective.)
    mtrues = tree_sum(popcount32(min_bits & t))
    min_over = mtrues > min_w
    wneg = jnp.where(mtrues == min_w, wneg | (min_bits & ~a), wneg)

    row_conflict = dead.any() | over.any()
    axis = _clause_axis_name()
    if axis is not None:
        # Combine shard partials: forced-literal masks OR together (the
        # replicated min-bound contribution is idempotent under OR), row
        # conflicts any-reduce — all in one fused all-gather.
        wpos, wneg, row_conflict = _axis_or_fused(
            wpos, wneg, row_conflict, axis
        )
    conflict = row_conflict | min_over | ((wpos & wneg) != 0).any()
    new_t = t | (wpos & ~a)
    new_f = f | (wneg & ~a)
    changed = ((new_t != t) | (new_f != f)).any() & ~conflict
    return conflict, new_t, new_f, changed


# --------------------------------------------------------------------------
# BCP


def bcp_round(pt: ProblemTensors, assign: jax.Array,
              min_mask: jax.Array, min_w: jax.Array
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One propagation round: evaluate every clause and cardinality row,
    derive implied literals, detect conflicts.  Returns
    (conflict, new_assign, changed).  This is the hot op the Pallas kernel
    (:mod:`deppy_tpu.engine.pallas_bcp`) specializes."""
    V = assign.shape[0]
    cls_mask = pt.clauses != 0
    cls_var = jnp.where(cls_mask, jnp.abs(pt.clauses) - 1, 0)
    cls_sign = jnp.sign(pt.clauses)
    cls_valid = cls_mask.any(axis=1)

    vals = assign[cls_var] * cls_sign
    vals = jnp.where(cls_mask, vals, jnp.int32(FALSE))
    satc = (vals == TRUE).any(axis=1)
    n_un = (vals == UNASSIGNED).sum(axis=1)
    dead = cls_valid & ~satc & (n_un == 0)
    unit = cls_valid & ~satc & (n_un == 1)
    ucol = jnp.argmax(vals == UNASSIGNED, axis=1)
    uvar = jnp.take_along_axis(cls_var, ucol[:, None], axis=1)[:, 0]
    usign = jnp.take_along_axis(cls_sign, ucol[:, None], axis=1)[:, 0]
    wpos = jnp.zeros(V, jnp.int32).at[uvar].max((unit & (usign > 0)).astype(jnp.int32))
    wneg = jnp.zeros(V, jnp.int32).at[uvar].max((unit & (usign < 0)).astype(jnp.int32))

    # Native cardinality rows (AtMost): count true members; > n is a
    # conflict, == n forces every unassigned member false — the
    # arc-consistency equivalent of gini's CardSort network.
    card_mask = pt.card_ids >= 0
    card_var = jnp.where(card_mask, pt.card_ids, 0)
    card_valid = pt.card_act >= 0
    act_idx = jnp.where(card_valid, pt.card_act, 0)
    mvals = assign[card_var]
    trues = ((mvals == TRUE) & card_mask).sum(axis=1)
    unk = ((mvals == UNASSIGNED) & card_mask).sum(axis=1)
    active = card_valid & (assign[act_idx] == TRUE)
    over = active & (trues > pt.card_n)
    full = active & (trues == pt.card_n) & (unk > 0)
    force = full[:, None] & card_mask & (mvals == UNASSIGNED)
    wneg = wneg.at[card_var].max(force.astype(jnp.int32))

    # Dynamic "at most w of the extras" side-constraint used by the
    # minimization loop (the native replacement for CardinalityConstrainer
    # + Leq(w), solve.go:100-110).
    mtrues = ((assign == TRUE) & min_mask).sum()
    min_over = mtrues > min_w
    min_force = (mtrues == min_w) & (assign == UNASSIGNED) & min_mask
    wneg = jnp.maximum(wneg, min_force.astype(jnp.int32))

    conflict = dead.any() | over.any() | min_over | ((wpos == 1) & (wneg == 1)).any()
    unas = assign == UNASSIGNED
    new = jnp.where(
        unas & (wpos == 1),
        jnp.int32(TRUE),
        jnp.where(unas & (wneg == 1), jnp.int32(FALSE), assign),
    )
    changed = (new != assign).any() & ~conflict
    return conflict, new, changed


# BCP implementation selection: "gather" = the [C, K] literal-gather round
# above; "bits" = jnp bitplane algebra; "pallas" = the fused fixpoint kernel
# holding the planes in VMEM across rounds; "watched" = the compressed
# clause-bank implication-driven path (engine/clause_bank.py — visits
# only the clauses adjacent to a newly-falsified literal instead of
# scanning every row per round).  "auto" = the measured-defaults
# registry's "bcp" row for this backend when one exists, else "bits":
# measured on a real v5-lite chip (256-problem random-catalog batch),
# bits is 18.7× faster than gather (368/s vs 19.7/s) and 1.8× faster
# than the Pallas kernel — under vmap, XLA vectorizes the batch axis of
# the bitplane algebra across VPU lanes, while a vmapped pallas_call
# serializes problems into grid steps.  The kernel pays off only for
# single very large problems (clause planes near VMEM capacity), so it
# stays opt-in; "watched" likewise defaults off until a measured A/B
# row lands (scripts/tpu_ab.py carries the variant).  Measured on this
# box (CPU XLA, r12): watched wins 7x on deep-implication-chain batches
# (1855/s vs 260/s, 96 lanes x depths 48-192) and loses ~10% on the
# mixed random-catalog fleet — benchmarks/results/bcp_rewrite_r12.json.
_BCP_IMPL = config.env_raw("DEPPY_TPU_BCP", "auto")

_BCP_IMPLS = ("auto", "gather", "bits", "pallas", "blockwise", "watched")

# Propagation rounds applied per fixpoint while_loop trip (the "bits"
# path only).  >1 trades redundant work on converged lanes for fewer
# loop trips — a bet on per-trip scheduling overhead, i.e. a TPU knob;
# exit states are bit-identical at any setting (see planes_fixpoint).
# Measured on CPU XLA it LOSES outright (deep-chain config: 7552/s at
# 1 vs 6563/s at 2 vs 2631/s at 3; random catalog the same shape) —
# per-trip overhead is negligible there and the redundant gated round
# dominates.  Default 1; A/B on a real TPU before ever raising it.
_BCP_UNROLL = max(1, int(config.env_raw("DEPPY_TPU_BCP_UNROLL", "1")))

# Decision steps applied per dpll while_loop trip — the decision-level
# twin of _BCP_UNROLL, one level up the trip hierarchy (search trips =
# episodes × decisions × propagation rounds; this attacks the middle
# factor).  The dpll body is fully lane-gated on a ``live`` predicate
# (status RUNNING and in budget), so K-fold body repetition inside one
# trip is exit-state- and step-count-identical at any K: a finished or
# budget-exhausted lane's extra applications are no-ops.  Same bet
# shape as _BCP_UNROLL — redundant gated work for fewer ~175µs trips —
# and same policy: default 1 everywhere until a real-chip A/B row
# exists (scripts/tpu_ab.py carries dpll-unroll variants).
_DPLL_UNROLL = max(1, int(config.env_raw("DEPPY_TPU_DPLL_UNROLL", "1")))

# Episode-control steps (guess-stack pushes/pops) applied per control
# while_loop trip — the outermost factor of the trip product.  Same
# gated-repeat construction and same identity contract as _DPLL_UNROLL
# (the control body's arms are selected under a ``live`` predicate);
# default 1 until an on-chip A/B row exists.
_CTL_UNROLL = max(1, int(config.env_raw("DEPPY_TPU_CTL_UNROLL", "1")))


def _batch_planes(clauses: jax.Array, W: int) -> Tuple[jax.Array, jax.Array]:
    """Batched signed clause matrices [B, C, K] → (pos, neg) packed int32
    bitplanes [B, C, W].  The device-side equivalent of the driver's numpy
    packing.  O(K) emitted ops (K is small and static): each literal
    column scatters into its word via a one-hot compare over the word
    axis, OR-folded into the accumulators — compile size stays flat as W
    grows (the near-VMEM single-problem case has W in the hundreds)."""
    B, C, K = clauses.shape
    w_idx = jnp.arange(W, dtype=jnp.int32)
    acc_p = jnp.zeros((B, C, W), jnp.int32)
    acc_n = jnp.zeros((B, C, W), jnp.int32)
    for k in range(K):
        lit = clauses[..., k]
        v = jnp.where(lit != 0, jnp.abs(lit) - 1, 0)
        onehot = _srl(v, 5)[..., None] == w_idx
        bit = jnp.left_shift(jnp.int32(1), v & 31)[..., None]
        acc_p = acc_p | jnp.where(onehot & (lit > 0)[..., None], bit, 0)
        acc_n = acc_n | jnp.where(onehot & (lit < 0)[..., None], bit, 0)
    return acc_p, acc_n


def _batch_index_planes(rows: jax.Array, W: int) -> jax.Array:
    """Batched 0-based index matrices [B, R, M] (-1 pad) → packed int32
    membership bitplanes [B, R, W].  Same O(M)-op structure as
    :func:`_batch_planes`."""
    B, R, M = rows.shape
    w_idx = jnp.arange(W, dtype=jnp.int32)
    acc = jnp.zeros((B, R, W), jnp.int32)
    for m in range(M):
        v0 = rows[..., m]
        valid = v0 >= 0
        v = jnp.where(valid, v0, 0)
        onehot = _srl(v, 5)[..., None] == w_idx
        bit = jnp.left_shift(jnp.int32(1), v & 31)[..., None]
        acc = acc | jnp.where(onehot & valid[..., None], bit, 0)
    return acc


def derive_planes(clauses: jax.Array, card_ids: jax.Array,
                  card_act: jax.Array, n_vars: jax.Array,
                  *, Wv: int, Wr: int, red: bool, full: bool = True
                  ) -> Tuple[jax.Array, ...]:
    """Compute packed-bitplane fields of :class:`ProblemTensors` from the
    compact clause/cardinality tensors, on device and batched.

    Returns (pos_bits, neg_bits, card_member_bits, card_act_bits,
    pos_bits_r, neg_bits_r, card_member_bits_r).  The driver calls this
    once per uploaded chunk (jitted, cached per shape): dispatches ship
    only the compact [B, C, K] literal matrices and the device builds the
    plane variants in a few fused passes instead of a host numpy loop.

    ``red``/``full`` select which spaces materialize (the other side comes
    back as 1-word dummies): the bits impl's search/minimization phases
    read only the reduced problem-var space, so SAT-dominated batches
    never hold full-space planes resident — only a dispatch that will run
    the unsat-core phase (which probes with activations disabled) asks for
    ``full=True``."""
    B, C, _ = clauses.shape
    NA = card_ids.shape[1]
    if full:
        pos, neg = _batch_planes(clauses, Wv)
        member = _batch_index_planes(card_ids, Wv)
        act_bits = _batch_index_planes(card_act[:, :, None], Wv)
    else:
        pos = jnp.zeros((B, C, 1), jnp.int32)
        neg = jnp.zeros((B, C, 1), jnp.int32)
        member = jnp.zeros((B, NA, 1), jnp.int32)
        act_bits = jnp.zeros((B, NA, 1), jnp.int32)
    if red:
        cl_r = jnp.where(jnp.abs(clauses) <= n_vars[:, None, None], clauses, 0)
        pos_r, neg_r = _batch_planes(cl_r, Wr)
        mem_r = _batch_index_planes(card_ids, Wr)
    else:
        pos_r = jnp.zeros((B, C, 1), jnp.int32)
        neg_r = jnp.zeros((B, C, 1), jnp.int32)
        mem_r = jnp.zeros((B, NA, 1), jnp.int32)
    return pos, neg, member, act_bits, pos_r, neg_r, mem_r


def clear_batched_caches() -> None:
    """Drop every cached batched_* entry-point wrapper (and with them
    their compiled executables).  Shared by :func:`set_bcp_impl` and
    :func:`deppy_tpu.engine.clear_compile_caches` — add new cached entry
    points here so both invalidation paths stay complete."""
    batched_solve.cache_clear()
    batched_search.cache_clear()
    batched_core.cache_clear()
    batched_minimize_gated.cache_clear()
    batched_core_gated.cache_clear()
    # A deliberate drop means the recompiles that follow are expected:
    # zero the compile-guard ledger so they don't read as a storm.
    compileguard.reset_counts()


def set_bcp_impl(name: str) -> None:
    """Select the BCP implementation ('auto'|'gather'|'bits'|'pallas'|
    'blockwise'|'watched') and invalidate compiled solves."""
    global _BCP_IMPL
    if name not in _BCP_IMPLS:
        raise ValueError(f"unknown BCP impl {name!r}")
    _BCP_IMPL = name
    clear_batched_caches()


# Phase-1 search substrate: "xla" = the vmapped lockstep program in this
# module; "fused" = the whole phase in ONE Pallas kernel per problem
# (engine/pallas_search.py) — the escalation against the tunneled chip's
# ~175µs-per-while-trip overhead (BASELINE.md; round-3 verdict #1).
# "auto" = "xla" unless a MEASURED default exists for the current
# backend (measured_defaults.json — written by the revalidation
# ladder's stage F3 only after a same-run Mosaic smoke pass + paired
# A/B win + full headline bench under the knob; every device bet in
# this tree defaults off until such a measured row exists).  The env
# knob and set_search_impl always override.
_SEARCH_IMPL = config.env_raw("DEPPY_TPU_SEARCH", "auto")

# Measured-default registry: {backend: {"search": "fused"|"xla", ...}}.
# Package-local so an installed wheel carries its measured defaults;
# DEPPY_TPU_MEASURED_DEFAULTS overrides the path (tests, the ladder).
_MEASURED_DEFAULTS_PATH = config.env_raw(
    "DEPPY_TPU_MEASURED_DEFAULTS",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "measured_defaults.json"))
_MEASURED_DEFAULTS: Optional[dict] = None


def measured_default(key: str) -> Optional[str]:
    """The measured default recorded for ``key`` on the current backend
    (None when no measured row exists).  Keys in use: ``search``
    (phase-substrate: 'fused'|'xla'), ``spec_core`` ('on'|'off'), and
    ``bcp`` (propagation impl, e.g. 'watched'|'bits')."""
    global _MEASURED_DEFAULTS
    # Reachable at trace time via _resolved_impl (the auto impl route):
    # the registry read is memoized into module state whose only write
    # path (reload_measured_defaults) drops every compiled program, so
    # a traced program can never go stale against it — the exact
    # contract the compile-surface/trace-purity rules exist to enforce.
    # deppy: lint-ok[compile-surface] memoized; reload_measured_defaults invalidates the jit caches
    if _MEASURED_DEFAULTS is None:
        try:
            # deppy: lint-ok[trace-purity] one memoized registry read; re-traces reuse the cached dict
            with open(_MEASURED_DEFAULTS_PATH) as f:
                loaded = json.load(f)
            _MEASURED_DEFAULTS = loaded if isinstance(loaded, dict) else {}
        except (OSError, ValueError):
            _MEASURED_DEFAULTS = {}
    # deppy: lint-ok[compile-surface] memoized; reload_measured_defaults invalidates the jit caches
    entry = _MEASURED_DEFAULTS.get(jax.default_backend())
    val = entry.get(key) if isinstance(entry, dict) else None
    return val if isinstance(val, str) else None


def _measured_default_search() -> Optional[str]:
    impl = measured_default("search")
    return impl if impl in ("fused", "xla") else None


def reload_measured_defaults() -> None:
    """Drop the cached measured-default registry (tests; the ladder
    after writing a new row) and invalidate compiled solves."""
    global _MEASURED_DEFAULTS
    _MEASURED_DEFAULTS = None
    clear_batched_caches()


def set_search_impl(name: str) -> None:
    """Select the phase-1 search substrate ('auto'|'xla'|'fused') and
    invalidate compiled solves."""
    global _SEARCH_IMPL
    if name not in ("auto", "xla", "fused"):
        raise ValueError(f"unknown search impl {name!r}")
    _SEARCH_IMPL = name
    clear_batched_caches()


def _resolved_search_impl() -> str:
    if _SEARCH_IMPL == "auto":
        return _measured_default_search() or "xla"
    return _SEARCH_IMPL


def _fused_routable(pts, arr) -> bool:
    """The one dispatch rule shared by every fused-kernel factory
    (batched_search / batched_minimize_gated / batched_core /
    batched_core_gated): mesh-sharded batches stay on the XLA programs
    (a pallas_call over a multi-device batch would need shard_map
    plumbing the fused path doesn't have), and the batch's static shapes
    must fit the kernel's unroll caps.  ``arr`` is the tensor whose
    sharding decides (the planes the phase actually reads)."""
    from . import pallas_search

    sharding = getattr(arr, "sharding", None)
    multi = sharding is not None and len(sharding.device_set) > 1
    return not multi and pallas_search.fused_supported(pts)


def _has_full_planes(pts, V: int) -> bool:
    """Whether this batch carries REAL full-space bit planes.  Under the
    gather impl (``phases_reduced()`` False and no bits planes anywhere)
    the driver ships 1-row placeholders — the XLA core phase walks
    ``pt.clauses`` directly and never reads them, but the fused deletion
    kernel inlines bits algebra and MUST see the real planes (caught by
    the gather+fused knob-combination test: a placeholder makes every
    probe misbehave and the core comes back unminimized).  Checks BOTH
    placeholder conventions: the 1-row gather dummy (row count) and the
    1-word pack=False dummy (word width vs the V the planes must
    cover)."""
    rows_ok = pts.pos_bits.shape[-2] == pts.clauses.shape[-2]
    width_ok = pts.pos_bits.shape[-1] == -(-V // WORD)
    return rows_ok and width_ok


# Per-size-class impl override (ISSUE 13 satellite): the driver scopes
# each dispatch to its ladder class's measured `bcp.<class>` row so
# deep-chain classes can run `watched` while the mixed fleet keeps
# `bits` — closing PR 12's "~10% loss on the mixed fleet" compromise.
# Thread-local (mesh shard workers dispatch concurrently).  Safe
# against stale compiled programs because the driver classifies each
# dispatch by its PADDED batch dims (driver.padded_class: cost over
# the bucketed C/NV/NCON maxima — a function of exactly the dims that
# key jit's shape cache), so two dispatches reaching the same
# compiled program always resolve the same class, hence the same
# impl.  Only
# the reduced-space impls (bits/watched) are honored per class —
# a per-class `gather` row would flip ``phases_reduced()`` under a
# factory wrapper whose ``red`` was baked at a shape key that does not
# include C.
_IMPL_TLS = threading.local()
_CLASS_ROUTABLE = ("bits", "watched")


@contextmanager
def impl_scope(impl: "Optional[str]"):
    """Scope the resolved BCP impl for one dispatch (driver use only).
    ``None`` is a no-op scope — the global resolution applies."""
    prev = getattr(_IMPL_TLS, "impl", None)
    _IMPL_TLS.impl = impl
    try:
        yield
    finally:
        _IMPL_TLS.impl = prev


def resolved_impl_for(class_name: "Optional[str]") -> str:
    """The BCP impl a dispatch of ladder class ``class_name`` should
    run: the explicit global knob when set, else the measured
    ``bcp.<class>`` row, else the global ``bcp`` row, else bits."""
    if _BCP_IMPL != "auto":
        return _BCP_IMPL
    if class_name is not None:
        measured = measured_default(f"bcp.{class_name}")
        if measured in _CLASS_ROUTABLE:
            return measured
    measured = measured_default("bcp")
    if measured in _BCP_IMPLS and measured != "auto":
        return measured
    return "bits"


def _resolved_impl() -> str:
    # deppy: lint-ok[compile-surface] trace-time impl dispatch by design: set_bcp_impl's write invalidates every compiled program via clear_batched_caches
    impl = _BCP_IMPL
    if impl != "auto":
        return impl
    # Per-dispatch class scope (impl_scope) wins over the global row —
    # the driver only installs one when the global knob is "auto", and
    # the class↔shape argument above keeps traced programs consistent.
    override = getattr(_IMPL_TLS, "impl", None)
    if override is not None:
        return override
    # Measured-defaults route (ISSUE 12 policy: engine bets become
    # defaults only behind a same-backend A/B row, never by fiat).
    measured = measured_default("bcp")
    if measured in _BCP_IMPLS and measured != "auto":
        return measured
    return "bits"


def _bcp_gather(pt: ProblemTensors, assign: jax.Array,
                min_mask: jax.Array, min_w: jax.Array, enabled: jax.Array
                ) -> Tuple[jax.Array, jax.Array]:
    def cond(state):
        conflict, _, changed = state
        return ~conflict & changed

    def body(state):
        _, a, _ = state
        return bcp_round(pt, a, min_mask, min_w)

    state = (jnp.bool_(False), assign, enabled)
    conflict, assign, _ = lax.while_loop(cond, body, state)
    return conflict, assign


def bcp(pt: ProblemTensors, assign: jax.Array,
        min_mask: jax.Array, min_w: jax.Array,
        enabled: "jax.Array | bool" = True) -> Tuple[jax.Array, jax.Array]:
    """Propagate to fixpoint (the analog of gini ``Test`` propagation;
    host reference: HostEngine._bcp).  Returns (conflict, assignment).
    Dispatches to the implementation chosen by :func:`set_bcp_impl` /
    ``DEPPY_TPU_BCP``.

    ``enabled`` seeds the fixpoint loop's ``changed`` flag: a disabled lane
    runs **zero** rounds.  This is the lane-gating idiom used throughout
    the engine — under ``vmap``, ``lax.cond``/``lax.switch`` lower to
    select (every branch executes for every lane), so skipping work must be
    expressed as a ``while_loop`` whose condition is immediately false for
    inactive lanes."""
    impl = _resolved_impl()
    if impl == "gather":
        return _bcp_gather(pt, assign, min_mask, min_w, enabled)
    V = assign.shape[0]
    Wv = pt.pos_bits.shape[1]
    t = pack_mask(assign == TRUE, Wv)
    f = pack_mask(assign == FALSE, Wv)
    conflict, t, f = planes_fixpoint(
        pt, t, f, pack_mask(min_mask, Wv), min_w, enabled, V
    )
    return conflict, planes_to_assign(t, f, V)


def planes_fixpoint(pt: ProblemTensors, t: jax.Array, f: jax.Array,
                    min_bits: jax.Array, min_w: jax.Array,
                    enabled: jax.Array, V: int, red: bool = False
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fixpoint directly on packed (t, f) planes — the incremental engine
    primitive: starting from a previous fixpoint plus newly set literals,
    propagation converges in the few rounds the *new* implications need
    (BCP is monotone and confluent, so the result equals a from-scratch
    run).  Returns (conflict, t, f).  Dispatches on the selected impl; the
    gather path unpacks to assignment form and back.

    ``red`` (static) selects the reduced problem-var-only plane space (see
    ProblemTensors.pos_bits_r): activations are constant TRUE there, so row
    activity is just row validity.  Only the "bits" impl supports it."""
    impl = _resolved_impl()
    card_n2 = pt.card_n[:, None]
    # Incremental starts can assert a literal whose negation is already
    # set (e.g. guessing a candidate that propagation forced false): that
    # t∧f overlap IS the conflict, and it must be caught here — a clause
    # containing the overlapped variable reads as satisfied to the round
    # kernel, masking it.  From-scratch starts never overlap.
    pre_conflict = enabled & ((t & f) != 0).any()
    run = enabled & ~pre_conflict
    if impl == "gather":
        assert not red, "reduced planes are a bits-impl path"
        assign = planes_to_assign(t, f, V)
        conflict, assign = _bcp_gather(
            pt, assign, unpack_mask(min_bits, V), min_w, run
        )
        Wv = t.shape[1]
        return (conflict | pre_conflict,
                pack_mask(assign == TRUE, Wv), pack_mask(assign == FALSE, Wv))
    if red:
        assert impl in ("bits", "watched"), \
            "reduced planes are a bits/watched-impl path"
        pos, neg, mem = pt.pos_bits_r, pt.neg_bits_r, pt.card_member_bits_r
        card_active = (pt.card_valid != 0)[:, None]
    else:
        pos, neg, mem = pt.pos_bits, pt.neg_bits, pt.card_member_bits
        # Activation bits never flip inside a fixpoint (see round_planes),
        # so row activity is computed once from the entry state.
        card_active = ((pt.card_act_bits & t) != 0).any(axis=1, keepdims=True)
    if impl == "watched" and _clause_axis_name() is None:
        # Implication-driven propagation over the compressed clause
        # bank (ISSUE 12).  A dummy bank — the driver ships one when
        # the batch's occurrence width exceeds its size class's OCC cap
        # — statically falls through to the dense rounds below.  Under
        # clause sharding the bank rows would straddle shards, so the
        # sharded program stays on the dense rounds (which carry the
        # per-round collective).
        from . import clause_bank

        occ_p = pt.occ_pos_r if red else pt.occ_pos
        occ_n = pt.occ_neg_r if red else pt.occ_neg
        if clause_bank.bank_ready(occ_p):
            conflict, t, f = clause_bank.watched_fixpoint(
                pt.clauses, pt.n_vars, occ_p, occ_n, pt.card_occ,
                pos, neg, mem, card_active, card_n2, min_bits,
                min_w, t, f, run, red,
            )
            return conflict | pre_conflict, t, f
    if impl == "pallas":
        from . import pallas_bcp

        conflict, t, f = pallas_bcp.bcp_fixpoint(
            pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f, run,
        )
        return conflict | pre_conflict, t, f
    if impl == "blockwise":
        from . import pallas_blockwise

        conflict, t, f = pallas_blockwise.bcp_fixpoint(
            pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f, run,
        )
        return conflict | pre_conflict, t, f

    def cond(state):
        conflict, _, _, changed = state
        return ~conflict & changed

    def body(state):
        _, t, f, _ = state
        c, t, f, ch = round_planes(
            pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f,
        )
        # Optional unroll: more propagation rounds per loop trip (deep
        # implication chains advance one link per round, and each
        # while_loop trip has fixed scheduling overhead — a TPU lever).
        # Exit state stays bit-identical to the 1-round loop: extra
        # applications are gated on the trip's flags so a conflicted or
        # converged state passes through unchanged (confluence would
        # make any interleaving equivalent anyway; gating keeps even the
        # intermediate states aligned).
        for _ in range(_BCP_UNROLL - 1):
            c2, t2, f2, ch2 = round_planes(
                pos, neg, mem, card_active, card_n2, min_bits, min_w, t, f,
            )
            keep = ~c & ch
            t = jnp.where(keep, t2, t)
            f = jnp.where(keep, f2, f)
            ch = jnp.where(keep, ch2, ch)
            c = c | (keep & c2)
        return c, t, f, ch

    conflict, t, f, _ = lax.while_loop(cond, body, (jnp.bool_(False), t, f, run))
    return conflict | pre_conflict, t, f


def planes_to_assign(t: jax.Array, f: jax.Array, V: int) -> jax.Array:
    """(t, f) planes → int32 assignment vector."""
    tb = unpack_mask(t, V)
    fb = unpack_mask(f, V)
    return jnp.where(
        tb, jnp.int32(TRUE), jnp.where(fb, jnp.int32(FALSE), jnp.int32(UNASSIGNED))
    )


def set_plane_bit(plane: jax.Array, var: jax.Array, on: jax.Array) -> jax.Array:
    """Set bit ``var`` in a packed [1, Wv] plane when ``on`` (no-op
    otherwise).  ``var`` is a traced index."""
    word = var // WORD
    bit = jnp.int32(1) << (var % WORD)
    cur = plane[0, word]
    return plane.at[0, word].set(jnp.where(on, cur | bit, cur))


# --------------------------------------------------------------------------
# Test


def test_outcome(conflict: jax.Array, t: jax.Array, f: jax.Array,
                 pvb: jax.Array) -> jax.Array:
    """Outcome of a propagated plane state — the analog of gini ``Test``'s
    result (solve.go:79, search.go:76): UNSAT on conflict, SAT only when
    propagation alone totalizes the problem-var region (``pvb`` = packed
    problem-var mask), else RUNNING.  The single definition shared by the
    baseline Test, the search's push Test, and dpll's totality check."""
    all_assigned = ((pvb & ~(t | f)) == 0).all()
    return jnp.where(
        conflict, jnp.int32(UNSAT),
        jnp.where(all_assigned, jnp.int32(SAT), jnp.int32(RUNNING)),
    )


# --------------------------------------------------------------------------
# DPLL


def dpll(pt: ProblemTensors, t_init: jax.Array, f_init: jax.Array,
         min_bits: jax.Array, min_w: jax.Array, budget: jax.Array,
         steps: jax.Array, NV: int, V: int,
         enabled: "jax.Array | bool" = True, red: bool = False
         ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Complete search under the fixed partial assignment given as packed
    ``(t_init, f_init)`` planes — the analog of gini ``Solve()``
    (search.go:168, solve.go:107) and of HostEngine._dpll: false-first
    decisions on the lowest-index unassigned problem variable,
    chronological backtracking that flips the deepest unflipped decision.

    Trail-style snapshots: ``snap[k]`` holds the packed-plane fixpoint
    after ``k`` decisions, so each iteration propagates only the *new*
    decision literal from the previous fixpoint (BCP is monotone and
    confluent — the incremental fixpoint equals the from-scratch one), and
    backtracking restores a snapshot instead of re-propagating the whole
    stack.  The decision order, phases, and discovered model are identical
    to the rebuild-from-scratch formulation.  All inputs and the returned
    model stay in packed plane form — no [V]-length unpack anywhere on the
    iteration path.  Returns (status, model_t, model_f, steps).

    A disabled lane runs zero iterations and returns status RUNNING; the
    caller must discard it (see :func:`bcp` for the lane-gating idiom)."""
    Wv = (pt.pos_bits_r if red else pt.pos_bits).shape[1]
    lvl = jnp.arange(NV, dtype=jnp.int32)
    pvb = pack_mask(jnp.arange(V, dtype=jnp.int32) < pt.n_vars, Wv)

    conflict0, t0, f0 = planes_fixpoint(
        pt, t_init, f_init, min_bits, min_w, enabled, V, red
    )
    status0 = jnp.where(conflict0, jnp.int32(UNSAT), jnp.int32(RUNNING))
    snap_t0 = jnp.zeros((NV + 1, Wv), jnp.int32).at[0].set(t0[0])
    snap_f0 = jnp.zeros((NV + 1, Wv), jnp.int32).at[0].set(f0[0])

    def body(st):
        (dec_var, dec_phase, sp, flip, status, m_t, m_f,
         snap_t, snap_f, steps) = st
        t = snap_t[jnp.clip(sp, 0, NV)][None, :]
        f = snap_f[jnp.clip(sp, 0, NV)][None, :]

        # SAT when the problem-var region is totalized at the current level
        # (a pending flip always has its own variable unassigned, so this
        # can only fire on the decide path).  First-unassigned comes from
        # packed bit algebra: lowest set bit of the first nonzero word.
        un_words = (pvb & ~(t | f))[0]
        nz = un_words != 0
        has_un = nz.any()
        wi = jnp.argmax(nz).astype(jnp.int32)
        word = un_words[wi]
        lsb = word & -word
        first_un = wi * WORD + popcount32(lsb - 1)
        # ``live`` restates the while cond inside the body: under
        # _DPLL_UNROLL > 1 repeated applications run WITHOUT a cond
        # check between them, and a lane that finished or exhausted its
        # budget mid-trip must be inert — including for the SAT check,
        # which would otherwise overwrite a budget-exhausted RUNNING
        # verdict.  At unroll 1 this is exactly what cond guaranteed.
        live = (status == RUNNING) & (steps <= budget)
        sat_now = live & ~flip & ~has_un
        status = jnp.where(sat_now, jnp.int32(SAT), status)
        m_t = jnp.where(sat_now, t, m_t)
        m_f = jnp.where(sat_now, f, m_f)

        do_step = live & (status == RUNNING)
        # The decision applied this iteration: a pending flip re-tries the
        # level's variable true, otherwise decide first-unassigned false.
        var = jnp.where(flip, dec_var[jnp.clip(sp, 0, NV - 1)], first_un)
        neg_phase = ~flip  # fresh decisions are false-first
        dv_idx = jnp.where(do_step & ~flip, jnp.clip(sp, 0, NV - 1), NV)
        dec_var = dec_var.at[dv_idx].set(var, mode="drop")
        dec_phase = dec_phase.at[dv_idx].set(FALSE, mode="drop")
        # A flip consumes the level's second phase.
        fl_idx = jnp.where(do_step & flip, jnp.clip(sp, 0, NV - 1), NV)
        dec_phase = dec_phase.at[fl_idx].set(TRUE, mode="drop")

        t2 = set_plane_bit(t, var, do_step & ~neg_phase)
        f2 = set_plane_bit(f, var, do_step & neg_phase)
        conflict, t3, f3 = planes_fixpoint(
            pt, t2, f2, min_bits, min_w, do_step, V, red
        )

        ok = do_step & ~conflict
        sidx = jnp.where(ok, jnp.clip(sp + 1, 0, NV), NV + 1)
        snap_t = snap_t.at[sidx].set(t3[0], mode="drop")
        snap_f = snap_f.at[sidx].set(f3[0], mode="drop")

        # SAT the moment a propagation totalizes the problem vars — in the
        # same iteration, so a solve on the last in-budget step still
        # reports its model.
        tot = ok & (((pvb & ~(t3 | f3)) == 0).all())
        status = jnp.where(tot, jnp.int32(SAT), status)
        m_t = jnp.where(tot, t3, m_t)
        m_f = jnp.where(tot, f3, m_f)

        # Chronological backtrack: deepest level still on its false phase.
        cand = (lvl <= sp) & (dec_phase == FALSE)
        l = jnp.max(jnp.where(cand, lvl, -1))
        no_bt = l < 0
        bt = do_step & conflict & ~no_bt
        status = jnp.where(do_step & conflict & no_bt, jnp.int32(UNSAT), status)
        sp = jnp.where(ok, sp + 1, jnp.where(bt, l, sp))
        flip = jnp.where(ok, jnp.bool_(False), jnp.where(bt, jnp.bool_(True), flip))
        steps = steps + do_step.astype(jnp.int32)
        return (dec_var, dec_phase, sp, flip, status, m_t, m_f,
                snap_t, snap_f, steps)

    def cond(st):
        _, _, _, _, status, _, _, _, _, steps = st
        return enabled & (status == RUNNING) & (steps <= budget)

    def trip(st):
        st = body(st)
        for _ in range(_DPLL_UNROLL - 1):
            st = body(st)  # gated repeats: no-ops on finished lanes
        return st

    st = (
        jnp.zeros(NV, jnp.int32),
        jnp.zeros(NV, jnp.int32),
        jnp.int32(0),
        jnp.bool_(False),
        status0,
        t0, f0,
        snap_t0, snap_f0,
        steps,
    )
    (_, _, _, _, status, m_t, m_f, _, _, steps) = lax.while_loop(cond, trip, st)
    return status, m_t, m_f, steps


# --------------------------------------------------------------------------
# preference-ordered guess search


def search(pt: ProblemTensors, t0: jax.Array, f0: jax.Array,
           outcome0: jax.Array, budget: jax.Array, steps: jax.Array,
           V: int, NCON: int, NV: int, T: int = 0,
           enabled: "jax.Array | bool" = True, red: bool = False
           ) -> Tuple[jax.Array, ...]:
    """The reference guess search (search.go:158-203; host: _search).

    Fixed-shape translation: the choice deque is a circular buffer of
    (choice row, candidate index) pairs with capacity NC+1 (each choice row
    lives in at most one place at a time — deque or guess stack); the guess
    stack holds (choice, index, var, children).  One loop iteration executes
    exactly one arm of the reference loop, in the reference's precedence
    order:

      0. deque empty, outcome unknown  → full DPLL solve  (search.go:167-169)
      1. outcome unsat                 → backtrack / give up (:172-179)
      2. deque empty, outcome sat      → done              (:182-184)
      3. otherwise                     → push next guess   (:187, :34-77)

    Two engine-level optimizations over a literal translation, both
    outcome-preserving:

    * **No branch dispatch** — under ``vmap``, ``lax.switch`` lowers to
      select and would execute a full DPLL plus propagation on every
      iteration of every lane; instead all four arms' bookkeeping runs as
      masked selects with exactly one lane-gated DPLL and at most one
      lane-gated propagation fixpoint per iteration.
    * **Guess-trail snapshots** — the packed-plane fixpoint and Test
      outcome after each guess are stacked; a push propagates only its new
      literal from the previous fixpoint (incremental BCP — monotone, so
      identical to from-scratch), and a pop is a pure snapshot restore with
      **zero** propagation, where the reference re-runs ``Test``
      (search.go:84) and the naive translation re-propagated everything.

    ``t0``/``f0``/``outcome0`` are the baseline fixpoint planes and Test
    outcome under anchors + activations alone (solve.go:74-79).

    ``T`` is the static trace capacity: when positive, every backtrack
    entry (the moment the reference calls ``Tracer.Trace``,
    search.go:172-173) appends the current guess-variable stack to a
    [T, GS] buffer; events past T are counted but not stored.  ``T = 0``
    keeps tracing fully out of the compiled program.

    Returns (result, guessed_mask, model, steps, trace_stack, trace_n)."""
    NC, Kc = pt.choice_cand.shape
    DQ = NC + 1
    GS = NC + 1
    Wv = (pt.pos_bits_r if red else pt.pos_bits).shape[1]
    dq_pos = jnp.arange(DQ, dtype=jnp.int32)
    pvb = pack_mask(jnp.arange(V, dtype=jnp.int32) < pt.n_vars, Wv)
    no_min_bits = jnp.zeros((1, Wv), jnp.int32)

    na = (pt.anchors >= 0).sum().astype(jnp.int32)
    # Anchor choice rows are rows 0..na-1 of the choice table, seeded in
    # input order (search.go:159-161).
    dq_c0 = jnp.where(dq_pos < na, dq_pos, 0)
    dq_i0 = jnp.zeros(DQ, jnp.int32)
    # Guess-trail snapshots: level k = fixpoint + outcome after k guesses.
    snap_t0 = jnp.zeros((GS + 1, Wv), jnp.int32).at[0].set(t0[0])
    snap_f0 = jnp.zeros((GS + 1, Wv), jnp.int32).at[0].set(f0[0])
    out_st0 = jnp.zeros(GS + 1, jnp.int32).at[0].set(outcome0)

    def body(st):
        (dq_c, dq_i, head, cnt, g_c, g_i, g_v, g_ch, gsp,
         snap_t, snap_f, out_st, result, m_t, m_f, assumed, done, need_leaf,
         steps, tr_stack, tr_n) = st

        # Arm selection (mutually exclusive; reference precedence order).
        # ``live`` restates ctl_cond inside the body: under
        # _CTL_UNROLL > 1 repeated applications run without a cond check
        # between them, and a parked (need_leaf), done, or
        # budget-exhausted lane must take NO arm — every write below is
        # gated through an arm flag, so a non-live application is inert.
        # At unroll 1 this is exactly what ctl_cond guaranteed.
        live = ~done & ~need_leaf & (steps <= budget)
        is_leaf = live & (cnt == 0) & (result == RUNNING)
        is_bt = live & ~is_leaf & (result == UNSAT)
        is_done = live & ~is_leaf & ~is_bt & (cnt == 0)
        is_push = live & ~is_leaf & ~is_bt & ~is_done

        # Trace: the reference fires Tracer.Trace at every backtrack entry
        # (search.go:172-173) with the pre-pop guess stack.
        if T > 0:
            row = jnp.where(
                jnp.arange(GS, dtype=jnp.int32) < gsp, g_v, jnp.int32(-1)
            )
            tidx = jnp.where(is_bt & (tr_n < T), jnp.clip(tr_n, 0, T - 1), T)
            tr_stack = tr_stack.at[tidx].set(row, mode="drop")
        tr_n = tr_n + is_bt.astype(jnp.int32)

        cur_t = snap_t[jnp.clip(gsp, 0, GS)][None, :]
        cur_f = snap_f[jnp.clip(gsp, 0, GS)][None, :]

        # --- arm 0: leaf DPLL request (search.go:167-169) ---------------
        # The full solve is NOT embedded here: the lane freezes (the
        # control loop's cond excludes need_leaf lanes) and one lane-gated
        # dpll per episode runs after the control loop drains — so control
        # iterations don't pay the dpll prologue/snapshot machinery, and
        # concurrent leaf lanes share a single dpll invocation.
        need_leaf = need_leaf | is_leaf

        # --- arm 1: backtrack bookkeeping (PopGuess, search.go:79-98) ---
        give_up = is_bt & (gsp == 0)
        bt = is_bt & ~give_up
        gsp2 = gsp - 1
        gc = g_c[jnp.clip(gsp2, 0)]
        gi = g_i[jnp.clip(gsp2, 0)]
        gv = g_v[jnp.clip(gsp2, 0)]
        gch = g_ch[jnp.clip(gsp2, 0)]
        head_bt = jnp.mod(head - 1, DQ)  # requeue popped choice at the front

        # --- arm 3: push bookkeeping (PushGuess, search.go:34-77) -------
        cid = dq_c[jnp.clip(head, 0, DQ - 1)]
        idx = dq_i[jnp.clip(head, 0, DQ - 1)]
        head_push = jnp.mod(head + 1, DQ)
        cands = pt.choice_cand[jnp.clip(cid, 0, NC - 1)]   # i32[Kc]
        ncand = (cands >= 0).sum()
        cand_var = cands[jnp.clip(idx, 0, Kc - 1)]
        var = jnp.where(idx < ncand, cand_var, -1)
        already = ((cands >= 0) & assumed[jnp.clip(cands, 0)]).any()
        var = jnp.where(already, jnp.int32(-1), var)
        ch_row = pt.var_choices[jnp.clip(var, 0)]          # i32[W]
        valid_ch = is_push & (var >= 0) & (ch_row >= 0)
        nch = valid_ch.sum().astype(jnp.int32)
        offs = jnp.cumsum(valid_ch.astype(jnp.int32)) - valid_ch.astype(jnp.int32)
        pos = jnp.mod(head_push + (cnt - 1) + offs, DQ)

        # --- merged state updates (each write gated by its arm) ---------
        head = jnp.where(bt, head_bt, jnp.where(is_push, head_push, head))
        cnt = jnp.where(bt, cnt - gch + 1,
                        jnp.where(is_push, cnt - 1 + nch, cnt))
        # Backtrack: requeue the popped choice, its candidate index
        # advanced past a real guess (children died with the pop — the
        # cnt shrink above removes them from the live window).
        dq_c = dq_c.at[jnp.where(bt, head_bt, DQ)].set(gc, mode="drop")
        dq_i = dq_i.at[jnp.where(bt, head_bt, DQ)].set(
            gi + (gv >= 0).astype(jnp.int32), mode="drop")
        # Push: enqueue the guessed variable's dependency choices.
        tgt = jnp.where(valid_ch, pos, DQ)
        dq_c = dq_c.at[tgt].set(ch_row, mode="drop")
        dq_i = dq_i.at[tgt].set(0, mode="drop")
        # Push always records a guess entry, null (var == -1) or not.
        g_idx = jnp.where(is_push, jnp.clip(gsp, 0, GS - 1), GS)
        g_c = g_c.at[g_idx].set(cid, mode="drop")
        g_i = g_i.at[g_idx].set(idx, mode="drop")
        g_v = g_v.at[g_idx].set(var, mode="drop")
        g_ch = g_ch.at[g_idx].set(nch, mode="drop")

        assumed = assumed.at[jnp.where(bt & (gv >= 0), jnp.clip(gv, 0), V)
                             ].set(False, mode="drop")
        assumed = assumed.at[jnp.where(is_push & (var >= 0), jnp.clip(var, 0), V)
                             ].set(True, mode="drop")

        # Push with a real variable: propagate just the new literal from
        # the current fixpoint (lane-gated).  A null push copies the level.
        push_test = is_push & (var >= 0)
        t2 = set_plane_bit(cur_t, jnp.clip(var, 0), push_test)
        conflict, t3, f3 = planes_fixpoint(
            pt, t2, cur_f, no_min_bits, jnp.int32(0), push_test, V, red
        )
        push_out = test_outcome(conflict, t3, f3, pvb)
        sidx = jnp.where(is_push, jnp.clip(gsp + 1, 0, GS), GS + 1)
        snap_t = snap_t.at[sidx].set(
            jnp.where(push_test, t3[0], cur_t[0]), mode="drop")
        snap_f = snap_f.at[sidx].set(
            jnp.where(push_test, f3[0], cur_f[0]), mode="drop")
        out_st = out_st.at[sidx].set(
            jnp.where(push_test, push_out, out_st[jnp.clip(gsp, 0, GS)]),
            mode="drop")
        gsp = jnp.where(bt, gsp2, jnp.where(is_push, gsp + 1, gsp))

        # Pop of a real guess re-Tests (search.go:84) — with snapshots the
        # outcome was already recorded at the restored level: zero
        # propagation.  Popping or pushing a null guess leaves the prior
        # outcome standing (search.go:55-60; a standing UNSAT keeps the pop
        # loop going).
        pop_restore = bt & (gv >= 0)
        pop_out = out_st[jnp.clip(gsp2, 0, GS)]
        result = jnp.where(pop_restore, pop_out,
                           jnp.where(push_test, push_out, result))
        pop_sat = pop_restore & (pop_out == SAT)
        m_t = jnp.where(pop_sat, snap_t[jnp.clip(gsp2, 0, GS)][None, :], m_t)
        m_f = jnp.where(pop_sat, snap_f[jnp.clip(gsp2, 0, GS)][None, :], m_f)
        push_sat = push_test & (push_out == SAT)
        m_t = jnp.where(push_sat, t3, m_t)
        m_f = jnp.where(push_sat, f3, m_f)

        done = done | give_up | is_done
        steps = steps + (bt | is_push).astype(jnp.int32)
        return (dq_c, dq_i, head, cnt, g_c, g_i, g_v, g_ch, gsp,
                snap_t, snap_f, out_st, result, m_t, m_f, assumed, done,
                need_leaf, steps, tr_stack, tr_n)

    def ctl_cond(st):
        done = st[16]
        need_leaf = st[17]
        steps = st[18]
        return enabled & ~done & ~need_leaf & (steps <= budget)

    def episode_body(st):
        # Drain control arms until every live lane is done or parked at a
        # leaf, then run one lane-gated dpll for all parked lanes.
        def ctl_trip(s):
            s = body(s)
            for _ in range(_CTL_UNROLL - 1):
                s = body(s)  # gated repeats: no-ops on non-live lanes
            return s

        st = lax.while_loop(ctl_cond, ctl_trip, st)
        (dq_c, dq_i, head, cnt, g_c, g_i, g_v, g_ch, gsp,
         snap_t, snap_f, out_st, result, m_t, m_f, assumed, done, need_leaf,
         steps, tr_stack, tr_n) = st
        cur_t = snap_t[jnp.clip(gsp, 0, GS)][None, :]
        cur_f = snap_f[jnp.clip(gsp, 0, GS)][None, :]
        leaf_status, leaf_t, leaf_f, steps = dpll(
            pt, cur_t, cur_f, no_min_bits, jnp.int32(0), budget, steps,
            NV, V, enabled=need_leaf, red=red,
        )
        result = jnp.where(need_leaf, leaf_status, result)
        leaf_sat = need_leaf & (leaf_status == SAT)
        m_t = jnp.where(leaf_sat, leaf_t, m_t)
        m_f = jnp.where(leaf_sat, leaf_f, m_f)
        # Budget exhaustion leaves status RUNNING; the episode cond exits.
        need_leaf = jnp.bool_(False)
        return (dq_c, dq_i, head, cnt, g_c, g_i, g_v, g_ch, gsp,
                snap_t, snap_f, out_st, result, m_t, m_f, assumed, done,
                need_leaf, steps, tr_stack, tr_n)

    def episode_cond(st):
        done = st[16]
        steps = st[18]
        return enabled & ~done & (steps <= budget)

    st = (
        dq_c0, dq_i0, jnp.int32(0), na,
        jnp.zeros(GS, jnp.int32), jnp.zeros(GS, jnp.int32),
        jnp.zeros(GS, jnp.int32), jnp.zeros(GS, jnp.int32), jnp.int32(0),
        snap_t0, snap_f0, out_st0,
        jnp.int32(RUNNING), jnp.zeros((1, Wv), jnp.int32),
        jnp.zeros((1, Wv), jnp.int32), jnp.zeros(V, bool),
        jnp.bool_(False), jnp.bool_(False), steps,
        jnp.full((T, GS), -1, jnp.int32), jnp.int32(0),
    )
    st = lax.while_loop(episode_cond, episode_body, st)
    (_, _, _, _, _, _, _, _, _, _, _, _,
     result, m_t, m_f, assumed, done, _, steps, tr_stack, tr_n) = st
    result = jnp.where(done, result, jnp.int32(RUNNING))
    model = planes_to_assign(m_t, m_f, V)
    return result, assumed, model, steps, tr_stack, tr_n


# --------------------------------------------------------------------------
# full pipeline


def search_phase(pt: ProblemTensors, budget: jax.Array,
                 en: "jax.Array | bool" = True,
                 *, V: int, NCON: int, NV: int, T: int = 0, red: bool = False
                 ) -> Tuple[jax.Array, ...]:
    """Phase 1: baseline Test + preference-ordered guess search
    (solve.go:53-85).  Returns (result, guessed, model, steps, tr_stack,
    tr_n).  ``en`` gates the whole phase (padding lanes of a compacted
    batch run zero propagation rounds and report RUNNING).

    With ``red`` (static), ``V`` is the reduced problem-var space width
    (== NV) and all planes/outputs live in that space — activations are
    constant TRUE during search, so their columns are folded away."""
    idxV = jnp.arange(V, dtype=jnp.int32)
    pv_mask = idxV < pt.n_vars
    steps0 = jnp.int32(1)
    Wv = (pt.pos_bits_r if red else pt.pos_bits).shape[1]
    pvb = pack_mask(pv_mask, Wv)
    no_min_bits = jnp.zeros((1, Wv), jnp.int32)

    # Baseline Test under anchors + activations (solve.go:74-79), computed
    # as planes so the search can snapshot from it.
    if red:
        base = _base_assignment_red(pt, V)
    else:
        base = _base_assignment(pt, V, NCON)
    base = _apply_anchors(pt, base, V)
    t0 = pack_mask(base == TRUE, Wv)
    f0 = pack_mask(base == FALSE, Wv)
    conflict0, t0, f0 = planes_fixpoint(
        pt, t0, f0, no_min_bits, jnp.int32(0), en, V, red,
    )
    outcome0 = test_outcome(conflict0, t0, f0, pvb)
    a0 = planes_to_assign(t0, f0, V)

    # ---- guess search when the baseline Test is undetermined ----
    need_search = en & (outcome0 == RUNNING)
    s_result, s_guessed, s_model, steps, tr_stack, tr_n = search(
        pt, t0, f0, outcome0, budget, steps0, V, NCON, NV, T,
        enabled=need_search, red=red,
    )
    result = jnp.where(need_search, s_result, outcome0)
    # Baseline already decided: the anchors play the guess-set role for
    # minimization (solve.go:77-83).
    guessed = jnp.where(need_search, s_guessed, _anchor_mask(pt, V))
    model = jnp.where(need_search, s_model, a0)
    result = jnp.where(en, result, jnp.int32(RUNNING))
    return result, guessed, model, steps, tr_stack, tr_n


def minimize_phase(pt: ProblemTensors, model: jax.Array, guessed: jax.Array,
                   budget: jax.Array, steps: jax.Array,
                   en: "jax.Array | bool" = True,
                   *, V: int, NCON: int, NV: int, red: bool = False
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Phase 2 (SAT lanes): extras-only cardinality minimization
    (solve.go:86-113).  Returns (installed [NV], min_found, steps).

    The reference probes w = 0, 1, 2, … and stops at the first SAT
    (solve.go:105-110).  Satisfiability is monotone in w, so binary
    search over [0, n_extras] finds the same minimal w in O(log) solves.
    Caveat: the probe sequence (and so the steps consumed) differs from
    the host engine's linear scan — under a tight ``max_steps`` budget
    the two backends can disagree on complete-vs-incomplete for the same
    problem.  Outcome parity is only guaranteed with sufficient budget
    (pinned by tests/test_differential.py::test_minimization_budget_parity).

    ``red``/``V`` as in :func:`search_phase`; ``model``/``guessed`` are in
    the same space as that phase's outputs."""
    idxV = jnp.arange(V, dtype=jnp.int32)
    pv_mask = idxV < pt.n_vars
    Wv = (pt.pos_bits_r if red else pt.pos_bits).shape[1]
    extras = (model == TRUE) & ~guessed & pv_mask
    excluded = (model != TRUE) & ~guessed & pv_mask
    if red:
        m_init = _base_assignment_red(pt, V)
    else:
        m_init = _base_assignment(pt, V, NCON)
    m_init = _apply_anchors(pt, m_init, V)
    m_init = jnp.where(guessed, jnp.int32(TRUE), m_init)
    m_init = jnp.where(excluded, jnp.int32(FALSE), m_init)
    n_extras = jnp.where(en, extras.sum(), 0)
    # Pack the probe's fixed partial assignment and the extras set once —
    # every minimization probe starts from the same planes.
    m_init_t = pack_mask(m_init == TRUE, Wv)
    m_init_f = pack_mask(m_init == FALSE, Wv)
    extras_bits = pack_mask(extras, Wv)

    def mcond(c):
        lo, hi, _, _, _, steps = c
        return en & (lo < hi) & (steps <= budget)

    def mbody(c):
        lo, hi, best_w, m2_t, found, steps = c
        w = (lo + hi) // 2
        status, mt, _, steps = dpll(
            pt, m_init_t, m_init_f, extras_bits, w, budget, steps, NV, V,
            enabled=en, red=red,
        )
        sat_w = status == SAT
        # SAT at w: the minimum is ≤ w — keep this probe's model and shrink
        # hi.  UNSAT at w: the minimum is > w.  Budget exhaustion (RUNNING)
        # changes nothing; the steps guard exits.
        best_w = jnp.where(sat_w, w, best_w)
        m2_t = jnp.where(sat_w, mt, m2_t)
        found = found | sat_w
        lo = jnp.where(sat_w, lo, jnp.where(status == UNSAT, w + 1, hi))
        hi = jnp.where(sat_w, w, hi)
        return lo, hi, best_w, m2_t, found, steps

    # Invariant: UNSAT strictly below lo, SAT at hi (the search/baseline
    # model witnesses w = n_extras).  At exit lo == hi == minimal w.
    _, m_hi, best_w, m2_t, m_found, steps = lax.while_loop(
        mcond, mbody,
        (jnp.int32(0), n_extras, jnp.int32(-1), pack_mask(model == TRUE, Wv),
         jnp.bool_(False), steps),
    )
    # The reported model must come from a probe at the minimal w itself —
    # the reference returns the w-bounded dpll model, which can differ from
    # the search witness even at equal cardinality (solve.go:108).  Probe
    # once more if the last SAT probe wasn't at the final bound.  With zero
    # extras the probe is skipped entirely: every variable is fixed by the
    # guess/excluded partition, so propagation could only rederive the
    # search model itself (the reference's single w=0 probe returns exactly
    # that model; skipping it changes the step count but never the answer).
    need_final = en & (best_w != m_hi) & (n_extras > 0)
    f_status, f_t, _, steps = dpll(
        pt, m_init_t, m_init_f, extras_bits, m_hi, budget, steps, NV, V,
        enabled=need_final, red=red,
    )
    m2_t = jnp.where(need_final & (f_status == SAT), f_t, m2_t)
    min_found = (
        jnp.where(need_final, f_status == SAT, m_found)
        | (en & (n_extras == 0))
    )
    # Uniform [NV] output in both spaces (full space's activation/padding
    # tail can never be "installed").
    installed = (unpack_mask(m2_t, V) & pv_mask & min_found & en)[:NV]
    return installed, min_found, steps


# Deletion probes are batched into chunks of this width: one probe tries
# removing a whole chunk, and only a chunk that cannot be dropped wholesale
# is probed member by member.  Cores are small in practice (the reference
# tests pin 2-4 constraints), so most chunks drop in a single probe —
# ~n/G + k·(G+1) DPLLs instead of n.  8 is the measured optimum on the
# UNSAT-heavy pinned-tenant fleet (CPU XLA, 512 problems): 4 is -9%,
# 16 is -13%.
CORE_CHUNK = 8


def core_phase(pt: ProblemTensors, budget: jax.Array, steps: jax.Array,
               en: "jax.Array | bool" = True,
               *, V: int, NCON: int, NV: int
               ) -> Tuple[jax.Array, jax.Array]:
    """Phase 3 (UNSAT lanes): deletion-based unsat-core minimization.
    Returns (core, steps).

    Start from all applied constraints active and drop any whose removal
    keeps the remainder unsatisfiable (host: _unsat_core; the analog of
    gini's failed-assumption Why, lit_mapping.go:198-207).  Probes run
    chunk-first: satisfiability is monotone in the active set — if the
    remainder without a whole chunk is still UNSAT, sequential deletion
    would have dropped every chunk member too — so a chunk-level UNSAT
    probe replaces ``CORE_CHUNK`` member probes while provably producing
    the *identical* core as the host spec's one-at-a-time loop; only a
    chunk whose removal makes the remainder satisfiable falls back to
    member-by-member probing in the host's order.  (Step *counts* differ:
    a core spread across many chunks pays the extra chunk probes, so a
    budget tuned to the wire of the sequential sweep can exhaust here —
    the usual generous budgets are orders of magnitude away from this.)

    Negative result, measured round 3: a second chunk level (64-wide
    superblocks over these 8-chunks) is a net LOSS on every workload tried
    (giant 1.7k-cons catalog: 9.0s vs 7.7s on CPU XLA; UNSAT-heavy fleet:
    1920/s vs 2009/s on TPU).  The sweep's cost is dominated by the
    kept-member probes — full SAT searches — and every hierarchy level
    whose block contains a core member adds one more of those; the cheap
    UNSAT block drops it saves were never the cost.  Don't re-try deeper
    hierarchies; cut SAT-probe cost instead (or route to the host spec
    engine for giant singles, driver.HOST_CORE_NCONS)."""
    Wv = pt.pos_bits.shape[1]
    no_min_bits = jnp.zeros((1, Wv), jnp.int32)
    active0 = (jnp.arange(NCON, dtype=jnp.int32) < pt.n_cons) & en
    G = min(CORE_CHUNK, max(NCON, 1))
    idx = jnp.arange(NCON, dtype=jnp.int32)

    def ccond(c):
        j, _, _, _, steps = c
        return en & (j < pt.n_cons) & (steps <= budget)

    def cbody(c):
        j, k, chunk_mode, active, steps = c
        in_chunk = (idx >= j) & (idx < j + G)
        trial_chunk = active & ~in_chunk
        member = jnp.where(~chunk_mode & (j + k < pt.n_cons), j + k, NCON)
        trial_member = active.at[member].set(False, mode="drop")
        trial = jnp.where(chunk_mode, trial_chunk, trial_member)
        init = _base_assignment(pt, V, NCON, act_enabled=trial)
        status, _, _, steps = dpll(
            pt, pack_mask(init == TRUE, Wv), pack_mask(init == FALSE, Wv),
            no_min_bits, jnp.int32(0), budget, steps, NV, V,
            enabled=en,
        )
        unsat = status == UNSAT
        active = jnp.where(unsat, trial, active)
        # Chunk probe UNSAT → whole chunk dropped, advance to next chunk.
        # Chunk probe SAT → re-probe this chunk member by member.  Member
        # mode advances within the chunk, then on to the next chunk.
        k2 = jnp.where(chunk_mode, jnp.int32(0), k + 1)
        chunk_done = chunk_mode & unsat
        member_done = ~chunk_mode & ((k2 >= G) | (j + k2 >= pt.n_cons))
        advance = chunk_done | member_done
        j = jnp.where(advance, j + G, j)
        k2 = jnp.where(advance, jnp.int32(0), k2)
        # Next mode is chunk-probe exactly when advancing to a fresh chunk;
        # a SAT chunk probe (or an unfinished member sweep) stays/drops
        # into member mode.
        return j, k2, advance, active, steps

    _, _, _, core, steps = lax.while_loop(
        ccond, cbody,
        (jnp.int32(0), jnp.int32(0), jnp.bool_(True), active0, steps),
    )
    return core, steps


def solve_full(pt: ProblemTensors, budget: jax.Array,
               *, V: int, NCON: int, NV: int, T: int = 0,
               with_core: bool = True) -> SolveResult:
    """One problem end to end (host: HostEngine.solve; reference
    solve.go:53-119): baseline Test, guess search if undetermined,
    extras-only minimization on SAT, deletion-based core on UNSAT.

    Every phase runs unconditionally but lane-gated: under ``vmap`` a
    ``lax.cond`` would execute both branches for every lane anyway (select
    semantics), so the phases instead take an ``enabled`` flag that makes
    their loops trip zero times on lanes that don't need them — a SAT lane
    pays nothing for core extraction, an UNSAT lane nothing for
    minimization.

    This single-program composition is kept for single-dispatch users (the
    mesh dry run, the graft entry); the driver's default path dispatches
    the three phases as separate compacted batches
    (:func:`deppy_tpu.engine.driver.solve_problems`), which removes the
    vmap max-over-lanes coupling between phases — a batch's few UNSAT
    lanes no longer serialize every SAT lane through the deletion loop."""
    red = phases_reduced()
    Vs = NV if red else V
    result, guessed, model, steps, tr_stack, tr_n = search_phase(
        pt, budget, V=Vs, NCON=NCON, NV=NV, T=T, red=red,
    )
    sat_en = result == SAT
    installed, min_found, steps = minimize_phase(
        pt, model, guessed, budget, steps, sat_en,
        V=Vs, NCON=NCON, NV=NV, red=red,
    )
    if with_core:
        unsat_en = result == UNSAT
        core, steps = core_phase(
            pt, budget, steps, unsat_en, V=V, NCON=NCON, NV=NV,
        )
    else:
        # Core extraction delegated to the caller (the driver routes giant
        # single problems to the host spec engine — driver.HOST_CORE_NCONS);
        # compiling the deletion arm out keeps the program short.
        core = jnp.zeros(NCON, bool)
    incomplete = (steps > budget) | (result == RUNNING) | (
        sat_en & ~min_found
    )
    outcome = jnp.where(incomplete, jnp.int32(RUNNING), result)
    return SolveResult(outcome=outcome, installed=installed, core=core,
                       steps=steps, trace_stack=tr_stack, trace_n=tr_n)


def phases_reduced() -> bool:
    """Whether the search/minimization phases run in the reduced
    problem-var plane space (bits/watched impls; see ProblemTensors)."""
    return _resolved_impl() in ("bits", "watched")


@functools.lru_cache(maxsize=128)
def batched_solve(V: int, NCON: int, NV: int, T: int = 0,
                  with_core: bool = True):
    """Jitted, vmapped single-program solve for one padded shape signature.
    Cached so each shape bucket compiles exactly once per process (the
    driver buckets padded dims to powers of two to bound the number of
    entries).  ``T`` is the static trace capacity (0 = tracing compiled
    out); ``with_core=False`` compiles the deletion arm out (the driver
    host-routes core extraction for giant single problems)."""
    fn = functools.partial(solve_full, V=V, NCON=NCON, NV=NV, T=T,
                           with_core=with_core)
    return jax.jit(compileguard.observe(
        "core.batched_solve", jax.vmap(fn, in_axes=(0, None)),
        static=(V, NCON, NV, T, with_core)))


@functools.lru_cache(maxsize=128)
def batched_search(V: int, NCON: int, NV: int, T: int = 0):
    """Jitted, vmapped phase-1 program (baseline + search); per-lane
    ``en`` mask gates padding lanes.  Under ``DEPPY_TPU_SEARCH=fused``
    (reduced planes, no trace buffer) the returned callable routes
    supported shapes to the fused Pallas kernel instead, falling back to
    the XLA program for shapes past the kernel's static-unroll caps."""
    red = phases_reduced()
    fn = functools.partial(search_phase, V=NV if red else V,
                           NCON=NCON, NV=NV, T=T, red=red)
    xla_fn = jax.jit(compileguard.observe(
        "core.batched_search", jax.vmap(fn, in_axes=(0, None, 0)),
        static=(V, NCON, NV, T, red)))
    if T == 0 and red and _resolved_search_impl() == "fused":
        from . import pallas_search

        def dispatch(pts, budget, en):
            if _fused_routable(pts, pts.pos_bits_r):
                return pallas_search.batched_search_fused(pts, budget, en)
            return xla_fn(pts, budget, en)

        return dispatch
    return xla_fn


@functools.lru_cache(maxsize=128)
def batched_core(V: int, NCON: int, NV: int):
    """Jitted, vmapped phase-3 program over a compacted UNSAT batch.
    Under ``DEPPY_TPU_SEARCH=fused`` supported shapes route to the fused
    deletion-sweep kernel (same dispatch rules as
    :func:`batched_search`)."""
    fn = functools.partial(core_phase, V=V, NCON=NCON, NV=NV)
    xla_fn = jax.jit(compileguard.observe(
        "core.batched_core", jax.vmap(fn, in_axes=(0, None, 0, 0)),
        static=(V, NCON, NV)))
    if _resolved_search_impl() == "fused":
        from . import pallas_search

        def dispatch(pts, budget, steps, en):
            if _has_full_planes(pts, V) and _fused_routable(pts, pts.pos_bits):
                return pallas_search.batched_core_fused(
                    pts, budget, steps, en, V=V, NCON=NCON, NV=NV)
            return xla_fn(pts, budget, steps, en)

        return dispatch
    return xla_fn


# --------------------------------------------------------------------------
# speculative deletion probes (driver._speculative_core_mask)
#
# One GIANT problem's deletion sweep turned inside out: instead of one lane
# probing its n_cons activation subsets sequentially (core_phase), ALL
# single-drop probes of one shared problem run as vmap lanes of one
# program — the problem planes broadcast (in_axes=None), only the [NCON]
# activation masks are per-lane.  Stage 1 settles most probes with a
# search-free propagation fixpoint; stage 2 finishes the stragglers with
# full DPLL lanes.


def probe_fixpoint_phase(pt: ProblemTensors, drop_j: jax.Array,
                         *, V: int, NCON: int) -> jax.Array:
    """Stage-1 probe: propagate the single-drop probe's base assignment
    (all applied constraints active except ``drop_j``, anchors NOT
    assumed — host unsat_core_mask's probe convention) to fixpoint.
    Returns the conflict flag: True proves the probe UNSAT outright; False
    means undetermined (finish with :func:`probe_phase`).  Uses the
    full-space planes (activations are live variables here, exactly like
    core_phase's probes).  Lanes carry only an int32 index — the driver
    ships [P] indices, not [P, NCON] masks."""
    Wv = pt.pos_bits.shape[1]
    idx = jnp.arange(NCON, dtype=jnp.int32)
    act_enabled = (idx < pt.n_cons) & (idx != drop_j)
    init = _base_assignment(pt, V, NCON, act_enabled=act_enabled)
    no_min = jnp.zeros((1, Wv), jnp.int32)
    conflict, _, _ = planes_fixpoint(
        pt, pack_mask(init == TRUE, Wv), pack_mask(init == FALSE, Wv),
        no_min, jnp.int32(0), jnp.bool_(True), V,
    )
    return conflict


@functools.lru_cache(maxsize=128)
def batched_probe_fixpoint(V: int, NCON: int):
    """Jitted stage-1 probe batch: problem broadcast, drop indices
    vmapped."""
    fn = functools.partial(probe_fixpoint_phase, V=V, NCON=NCON)
    return jax.jit(compileguard.observe(
        "core.batched_probe_fixpoint", jax.vmap(fn, in_axes=(None, 0)),
        static=(V, NCON)))


def probe_phase(pt: ProblemTensors, act_enabled: jax.Array,
                budget: jax.Array, *, V: int, NCON: int, NV: int
                ) -> Tuple[jax.Array, jax.Array]:
    """Stage-2 probe: complete DPLL under the activation subset — the
    exact probe core_phase runs per trial, one vmap lane per subset.
    Returns (status, steps)."""
    Wv = pt.pos_bits.shape[1]
    init = _base_assignment(pt, V, NCON, act_enabled=act_enabled)
    no_min = jnp.zeros((1, Wv), jnp.int32)
    status, _, _, steps = dpll(
        pt, pack_mask(init == TRUE, Wv), pack_mask(init == FALSE, Wv),
        no_min, jnp.int32(0), budget, jnp.int32(0), NV, V,
    )
    return status, steps


@functools.lru_cache(maxsize=128)
def batched_probe(V: int, NCON: int, NV: int):
    """Jitted stage-2 probe batch: problem broadcast, act masks vmapped."""
    fn = functools.partial(probe_phase, V=V, NCON=NCON, NV=NV)
    return jax.jit(compileguard.observe(
        "core.batched_probe", jax.vmap(fn, in_axes=(None, 0, None)),
        static=(V, NCON, NV)))


def _minimize_gated(pt, result, model, guessed, budget, steps, en_lanes,
                    *, V, NCON, NV, red):
    return minimize_phase(
        pt, model, guessed, budget, steps,
        en_lanes & (result == SAT), V=V, NCON=NCON, NV=NV, red=red,
    )


@functools.lru_cache(maxsize=128)
def batched_minimize_gated(V: int, NCON: int, NV: int):
    """Phase-2 program gated by the phase-1 ``result`` on device: runs over
    the SAME chunks (and device-resident tensors) as phase 1, so no
    host-side compaction round trip and no re-upload of problem tensors.
    Non-SAT lanes trip zero loop iterations.  Under
    ``DEPPY_TPU_SEARCH=fused`` supported shapes route to the fused
    Pallas minimize kernel (same dispatch rules as
    :func:`batched_search`)."""
    red = phases_reduced()
    fn = functools.partial(_minimize_gated, V=NV if red else V,
                           NCON=NCON, NV=NV, red=red)
    xla_fn = jax.jit(compileguard.observe(
        "core.batched_minimize_gated",
        jax.vmap(fn, in_axes=(0, 0, 0, 0, None, 0, 0)),
        static=(V, NCON, NV, red)))
    if red and _resolved_search_impl() == "fused":
        from . import pallas_search

        def dispatch(pts, result, model, guessed, budget, steps, en):
            if _fused_routable(pts, pts.pos_bits_r):
                return pallas_search.batched_minimize_fused(
                    pts, result, model, guessed, budget, steps, en)
            return xla_fn(pts, result, model, guessed, budget, steps, en)

        return dispatch
    return xla_fn


def warm_check_phase(pt: ProblemTensors, assign: jax.Array,
                     *, V: int, NCON: int, NV: int) -> jax.Array:
    """Warm-prefix screen for one lane (ISSUE 10): the assignment is
    initialized from the lane's cached model (+1 true / -1 false over
    the off-cone variables, 0 for the cone left open to the re-solve),
    activation variables constant TRUE, and every clause and cardinality
    row is evaluated in one pass.  Returns the per-lane OK flag: False
    means the warm prefix already conflicts (a dead clause or a violated
    bound with no open member) and the lane should cold-solve without
    paying a host warm attempt.  One elementwise pass, no loop — the
    lockstep DPLL equivalent of starting at a deep, model-seeded node
    instead of the root."""
    a = assign.astype(jnp.int32)
    lit = pt.clauses
    var = jnp.abs(lit) - 1
    # Activation (and any padded) variable indices read as constant
    # TRUE: the solve assumes every applied constraint active, exactly
    # like the host engine's base assignment.
    is_act = var >= pt.n_vars
    pv = jnp.clip(jnp.where(is_act, 0, var), 0, NV - 1)
    val = jnp.where(
        lit == 0,
        jnp.int32(-1),  # pad cell: falsified, like the host's _FALSE
        jnp.where(is_act, jnp.sign(lit), jnp.sign(lit) * a[pv]),
    )
    valid_row = (lit != 0).any(axis=1)
    sat_c = (val == 1).any(axis=1)
    open_c = (val == 0).any(axis=1)
    dead = valid_row & ~sat_c & ~open_c
    members = pt.card_ids
    mvals = a[jnp.clip(members, 0, NV - 1)]
    mmask = members >= 0
    trues = ((mvals == 1) & mmask).sum(axis=1)
    over = (pt.card_valid > 0) & (trues > pt.card_n)
    return ~(dead.any() | over.any())


@functools.lru_cache(maxsize=128)
def batched_warm_check(V: int, NCON: int, NV: int):
    """Jitted, vmapped warm-prefix screen: assignment planes initialized
    from the cached models, one lockstep pass per coalesced warm lane
    class (driver.warm_screen is the padding/stacking entry)."""
    fn = functools.partial(warm_check_phase, V=V, NCON=NCON, NV=NV)
    return jax.jit(compileguard.observe(
        "core.batched_warm_check", jax.vmap(fn, in_axes=(0, 0)),
        static=(V, NCON, NV)))


def _core_gated(pt, result, budget, steps, en_lanes, *, V, NCON, NV):
    return core_phase(
        pt, budget, steps, en_lanes & (result == UNSAT),
        V=V, NCON=NCON, NV=NV,
    )


@functools.lru_cache(maxsize=128)
def batched_core_gated(V: int, NCON: int, NV: int):
    """Phase-3 program gated by the phase-1 ``result`` on device — used
    when most of a batch is UNSAT, where compaction would re-upload nearly
    everything for no lane savings.  Routes to the fused kernel under
    ``DEPPY_TPU_SEARCH=fused`` like :func:`batched_core`."""
    fn = functools.partial(_core_gated, V=V, NCON=NCON, NV=NV)
    xla_fn = jax.jit(compileguard.observe(
        "core.batched_core_gated",
        jax.vmap(fn, in_axes=(0, 0, None, 0, 0)),
        static=(V, NCON, NV)))
    if _resolved_search_impl() == "fused":
        from . import pallas_search

        def dispatch(pts, result, budget, steps, en):
            if _has_full_planes(pts, V) and _fused_routable(pts, pts.pos_bits):
                return pallas_search.batched_core_fused(
                    pts, budget, steps, en & (result == UNSAT),
                    V=V, NCON=NCON, NV=NV)
            return xla_fn(pts, result, budget, steps, en)

        return dispatch
    return xla_fn
