"""The fault-domain metric families — single source of truth.

Every counter the fault layer increments is declared here once (name,
help, optional label) and accessed through :func:`fault_counter`, so the
help text can never drift between the incrementing site and the
service's ``/metrics`` mirror (``faults.render_metric_lines``), and
docs/observability.md's table has exactly one thing to stay in sync
with.

This module sits below ``breaker``/``policy``/``inject`` in the import
order (they all use it), and imports telemetry lazily so the package
stays cycle-free.
"""

from __future__ import annotations

from typing import Optional, Tuple

# name -> (help text, labelname or None), in exposition order.
FAMILIES: "dict[str, Tuple[str, Optional[str]]]" = {
    "deppy_breaker_transitions_total":
        ("Circuit-breaker state transitions.", "state"),
    "deppy_fault_failures_total":
        ("Device dispatch attempts that raised.", None),
    "deppy_fault_retries":
        ("Device dispatch attempts retried by the fault policy.", None),
    "deppy_fault_host_routed_total":
        ("Problems solved by the host engine because device dispatch "
         "failed or the breaker was open.", None),
    "deppy_deadline_exceeded":
        ("Dispatches and requests that ran past their deadline.", None),
    "deppy_faults_injected_total":
        ("Scripted faults fired by the injection harness.", "point"),
}

BREAKER_STATE_HELP = ("Accelerator circuit breaker: 0 closed, "
                      "1 half-open, 2 open (host-only).")


def fault_counter(name: str):
    """The named fault-domain counter on the default telemetry registry,
    registered from the :data:`FAMILIES` declaration on first use."""
    from .. import telemetry

    help_text, labelname = FAMILIES[name]
    return telemetry.default_registry().counter(name, help_text,
                                                labelname=labelname)
