"""deppy_tpu.faults — the fault-domain layer (ISSUE 2).

PR 1 gave the pipeline eyes (telemetry); this package gives it reflexes.
Three pieces, consumed by the engine driver, the resolution facade, and
the service:

  * **policy** — :class:`RetryPolicy` (exponential backoff + jitter for
    failed device dispatches) and :class:`Deadline` (wall-clock budgets,
    per batch and per chunk) carried on a thread-local scope so the
    driver's pinned internal signatures stay untouched;
  * **breaker** — the accelerator :class:`CircuitBreaker`: N consecutive
    device failures trip the whole process to host-only solving, a
    cooldown later one half-open probe dispatch decides whether to
    close it again;
  * **inject** — the deterministic fault-injection harness
    (``DEPPY_TPU_FAULT_PLAN`` / ``--fault-plan``): named fault points in
    the driver, checkpoint writer, and service raise or stall on a
    scripted schedule so every recovery path runs in CI on CPU.

Metric families (ISSUE 2 acceptance): ``deppy_fault_retries``,
``deppy_breaker_state``, ``deppy_deadline_exceeded`` — registered on
:func:`deppy_tpu.telemetry.default_registry`, mirrored into the
service's ``/metrics`` scrape via :func:`render_metric_lines`, and
emitted as ``fault`` / ``breaker`` events on the JSONL sink.  See
docs/robustness.md for the fault matrix.
"""

from .breaker import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    GatedDeviceBreaker,
    default_breaker,
    device_breaker,
    device_breakers,
    reset_device_breakers,
    set_default_breaker,
)
from .metrics import FAMILIES, fault_counter
from .inject import (
    FaultPlan,
    FaultRule,
    InjectedFault,
    configure_plan,
    current_plan,
    inject,
    plan_from_env,
    plan_from_spec,
)
from .policy import (
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    ambient_deadline,
    current_deadline,
    deadline_scope,
    env_float,
    note_deadline_exceeded,
)

__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
    "FaultPlan",
    "FaultRule",
    "InjectedFault",
    "RetryPolicy",
    "ambient_deadline",
    "configure_plan",
    "current_deadline",
    "current_plan",
    "deadline_scope",
    "default_breaker",
    "device_breaker",
    "device_breakers",
    "GatedDeviceBreaker",
    "reset_device_breakers",
    "env_float",
    "fault_counter",
    "FAMILIES",
    "inject",
    "note_deadline_exceeded",
    "plan_from_env",
    "plan_from_spec",
    "render_metric_lines",
    "set_default_breaker",
]


def render_metric_lines() -> list:
    """Prometheus exposition lines for every fault-domain family
    (docs/observability.md's table), for a service ``Metrics.render`` to
    append — the same injection pattern as ``deppy_auto_engine_usable``.
    Reads the pipeline-global state, so every server in the process
    reports the one real breaker.  The breaker gauge is synthesized from
    the live breaker (always present, cooldown edge included); the
    counters render from their ``default_registry`` families — declared
    once in :mod:`deppy_tpu.faults.metrics`, registered here at zero
    when nothing has incremented them yet."""
    from .. import telemetry
    from .metrics import BREAKER_STATE_HELP, FAMILIES, fault_counter

    lines = [
        f"# HELP deppy_breaker_state {BREAKER_STATE_HELP}",
        "# TYPE deppy_breaker_state gauge",
        f"deppy_breaker_state {default_breaker().state_code()}",
    ]
    # Per-device breaker fleet (ISSUE 6): one labeled line per mesh
    # device that has ever dispatched a shard, synthesized live like the
    # process-wide gauge (cooldown edge included).
    for key, br in sorted(device_breakers().items()):
        lines.append(f'deppy_breaker_state{{device="{key}"}} '
                     f"{br.state_code()}")
    for name in FAMILIES:
        fault_counter(name)  # ensure registered (zero) before rendering
    return lines + telemetry.default_registry().render_families(
        list(FAMILIES))
