"""Accelerator circuit breaker: trip to host-only solving under repeated
device failures, half-open on a probe dispatch after a cooldown.

The failure mode this guards is documented all over the driver: a
tunneled TPU worker that starts crashing (oversized programs,
minutes-long executions) takes *minutes to hours* to come back, and
every dispatch against it during that window burns its full retry
budget before falling back.  The breaker converts that per-dispatch
penalty into a process-wide verdict:

  * **closed** — normal operation; every device failure recorded by the
    driver's recovery wrapper counts toward ``failure_threshold``;
  * **open** — ``failure_threshold`` consecutive failures seen.  Device
    dispatch is denied outright (``allow()`` is False), the driver
    routes groups straight to the host engine, and ``auto`` backend
    resolution (:func:`deppy_tpu.sat.solver.resolve_backend`) degrades
    to host without paying the probe;
  * **half-open** — ``reset_after_s`` after tripping, exactly one probe
    dispatch is let through.  Success closes the breaker; failure
    re-opens it for another cooldown.

State changes are exported on the PR-1 telemetry registry
(``deppy_breaker_state`` gauge, ``deppy_breaker_transitions_total``
counter) and emitted as ``breaker`` events on the JSONL sink; the
service mirrors the gauge into ``/metrics`` and flags the degraded mode
on ``/readyz``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

# Gauge codes, chosen so "bigger = less healthy" for dashboards.
BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2

_STATE_NAMES = {
    BREAKER_CLOSED: "closed",
    BREAKER_HALF_OPEN: "half_open",
    BREAKER_OPEN: "open",
}


class CircuitBreaker:
    """Thread-safe three-state breaker (closed → open → half-open).

    ``device`` labels a per-device breaker (ISSUE 6: one breaker per
    mesh device, so a single bad chip trips only its shard of the
    serving mesh to host).  ``None`` is the historical process-wide
    accelerator breaker; labeled breakers publish their transitions
    with a ``device`` field and leave the process-wide
    ``deppy_breaker_state`` gauge alone (the service's ``/metrics``
    synthesizes ``deppy_breaker_state{device=...}`` lines from the
    registry — see :func:`deppy_tpu.faults.render_metric_lines`)."""

    def __init__(self, failure_threshold: int = 3,
                 reset_after_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 device: Optional[str] = None):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.reset_after_s = float(reset_after_s)
        self.device = device
        self._clock = clock
        from ..analysis import lockdep

        self._lock = lockdep.make_lock("faults.breaker")
        self._state = BREAKER_CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False

    # ------------------------------------------------------------- queries

    def state(self) -> str:
        """Current state name; evaluates the cooldown (an open breaker
        whose cooldown elapsed reads ``half_open``)."""
        with self._lock:
            return _STATE_NAMES[self._state_locked()]

    def state_code(self) -> int:
        """Gauge value: 0 closed, 1 half-open, 2 open."""
        with self._lock:
            return self._state_locked()

    def blocks_device(self) -> bool:
        """True while device dispatch is denied (open, cooldown not yet
        elapsed).  Non-consuming — safe for routing decisions; the
        half-open probe slot is only claimed by :meth:`allow`."""
        with self._lock:
            return self._state_locked() == BREAKER_OPEN

    def remaining_s(self) -> float:
        """Cooldown seconds left before a half-open probe (0 when not
        open) — the service's ``Retry-After`` hint."""
        with self._lock:
            if self._state != BREAKER_OPEN:
                return 0.0
            return max(self._opened_at + self.reset_after_s - self._clock(),
                       0.0)

    # ------------------------------------------------------------ verdicts

    def allow(self) -> bool:
        """May a device dispatch proceed?  In half-open state exactly one
        caller gets True (the probe); everyone else is denied until the
        probe resolves via record_success/record_failure."""
        with self._lock:
            state = self._state_locked()
            if state == BREAKER_CLOSED:
                return True
            if state == BREAKER_OPEN:
                return False
            # Half-open: claim the single probe slot.
            if self._probe_in_flight:
                return False
            ev = self._transition_locked(BREAKER_HALF_OPEN)
            self._probe_in_flight = True
        self._publish(ev)
        return True

    def record_success(self) -> None:
        """A device dispatch completed: reset the failure streak and
        close the breaker (a half-open probe succeeding is the recovery
        signal)."""
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            ev = self._transition_locked(BREAKER_CLOSED)
        self._publish(ev)

    def record_failure(self) -> bool:
        """A device dispatch failed; returns True when this failure trips
        (or re-trips) the breaker open."""
        ev = None
        tripped = False
        with self._lock:
            self._consecutive_failures += 1
            state = self._state_locked()
            if state == BREAKER_HALF_OPEN or self._probe_in_flight:
                # The probe failed: back to a fresh cooldown.
                self._probe_in_flight = False
                ev = self._open_locked()
                tripped = True
            elif (state == BREAKER_CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                ev = self._open_locked()
                tripped = True
        self._publish(ev)
        return tripped

    def abandon_probe(self) -> None:
        """Release a claimed half-open probe slot without a verdict —
        the dispatch exited for a non-device reason (semantic outcome,
        admission error) before proving anything about the accelerator.
        The next ``allow()`` may probe again; without this, a leaked
        slot would deny device dispatch forever.  No-op when no probe
        is in flight."""
        with self._lock:
            self._probe_in_flight = False

    def reset(self) -> None:
        """Force-close (tests; also the solver's successful re-probe —
        independent evidence the accelerator recovered)."""
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            ev = self._transition_locked(BREAKER_CLOSED)
        self._publish(ev)

    # ------------------------------------------------------------ internal

    def _state_locked(self) -> int:
        """Current state with the open→half-open cooldown edge applied
        lazily (no background timer thread)."""
        if (self._state == BREAKER_OPEN
                and self._clock() - self._opened_at >= self.reset_after_s):
            return BREAKER_HALF_OPEN
        return self._state

    def _open_locked(self) -> "Optional[dict]":
        """Caller holds the breaker lock (the ``_locked`` convention the
        concurrency-discipline checker keys on)."""
        prev = self._state
        self._opened_at = self._clock()
        ev = self._transition_locked(BREAKER_OPEN)
        if ev is not None:
            ev["from"] = _STATE_NAMES[prev]
        return ev

    def _transition_locked(self, new_state: int) -> "Optional[dict]":
        """Mutate state only (caller holds the lock) and return the
        transition record for :meth:`_publish`, or None on no change.
        Telemetry — gauge, counter, JSONL sink write — happens OUTSIDE
        the breaker lock so slow sink I/O can never stall concurrent
        allow()/blocks_device()/scrape calls on the solve hot path."""
        if new_state == self._state:
            return None
        self._state = new_state
        return {"state": _STATE_NAMES[new_state], "code": new_state,
                "consecutive_failures": self._consecutive_failures}

    def _publish(self, ev: "Optional[dict]") -> None:
        """Export one transition (outside the lock).  Under a rare race
        of two back-to-back transitions the gauge may briefly publish
        out of order — last-write-wins and the next transition corrects
        it; the counter and sink events are order-independent."""
        if ev is None:
            return
        from .. import telemetry
        from .metrics import BREAKER_STATE_HELP, fault_counter

        reg = telemetry.default_registry()
        if self.device is None:
            reg.gauge("deppy_breaker_state", BREAKER_STATE_HELP).set(
                ev["code"])
            reg.event("breaker", state=ev["state"],
                      consecutive_failures=ev["consecutive_failures"])
        else:
            # Per-device breaker (ISSUE 6): the process-wide gauge stays
            # the whole-accelerator verdict; this shard's state rides the
            # event stream (and the /metrics mirror's labeled lines).
            reg.event("breaker", state=ev["state"], device=self.device,
                      consecutive_failures=ev["consecutive_failures"])
        if self.device is None:
            # Process transitions only: this counter predates the device
            # fleet and alerts on it read "the accelerator is cycling".
            # One flapping device must not fire that page — per-device
            # churn is visible in the labeled state gauge lines and the
            # device-tagged breaker events above.
            fault_counter("deppy_breaker_transitions_total").inc(
                1, label=ev["state"])
        if (self.device is None and ev["state"] == "open"
                and ev.get("from") == "closed"):
            # A FRESH trip (closed → open) is the incident moment: dump
            # the flight recorder to the JSONL sink NOW (ISSUE 4) — the
            # healthy context leading up to the trip.  Half-open probe
            # failures re-open without re-dumping: a hard-down
            # accelerator re-trips every cooldown, and re-dumping the
            # whole ring each cycle would grow the sink without bound.
            # The tripping requests themselves are still in flight
            # here; their traces reach the sink when they complete
            # (FlightRecorder.record writes every errored trace
            # through).  Never raises.
            from ..telemetry.trace import notify_breaker_open

            notify_breaker_open()


_DEFAULT: Optional[CircuitBreaker] = None
_DEFAULT_LOCK = threading.Lock()


def _breaker_from_env() -> CircuitBreaker:
    from .policy import env_float

    return CircuitBreaker(
        failure_threshold=int(env_float("DEPPY_TPU_BREAKER_THRESHOLD", 3)),
        reset_after_s=env_float("DEPPY_TPU_BREAKER_RESET_S", 30.0),
    )


def default_breaker() -> CircuitBreaker:
    """The process-wide accelerator breaker (one accelerator, one
    breaker).  Configured from ``DEPPY_TPU_BREAKER_THRESHOLD`` /
    ``DEPPY_TPU_BREAKER_RESET_S`` at first use."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = _breaker_from_env()
    return _DEFAULT


def set_default_breaker(
        breaker: Optional[CircuitBreaker]) -> Optional[CircuitBreaker]:
    """Swap the process breaker (tests); returns the previous one.
    ``None`` re-creates from the environment at next use."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        prev, _DEFAULT = _DEFAULT, breaker
    return prev


# --------------------------------------------------------- per-device fleet
#
# ISSUE 6: the mesh-sharded dispatch path charges failures to the breaker
# of the DEVICE whose shard failed, so one bad chip degrades only its
# slice of the serving mesh — batchmates on healthy devices keep
# dispatching.  The process-wide breaker above stays the whole-
# accelerator verdict (it still trips when every device is failing,
# because the driver's non-sharded paths keep charging it).

_DEVICE_BREAKERS: "dict[str, CircuitBreaker]" = {}
_DEVICE_LOCK = threading.Lock()


def device_breaker(device: object) -> CircuitBreaker:
    """The breaker for one mesh device, keyed by its stable id (an int
    device index or a ``jax.Device.id``); created from the same
    ``DEPPY_TPU_BREAKER_*`` environment knobs as the process breaker on
    first use."""
    key = str(device)
    with _DEVICE_LOCK:
        br = _DEVICE_BREAKERS.get(key)
        if br is None:
            br = _breaker_from_env()
            br.device = key
            _DEVICE_BREAKERS[key] = br
    return br


def device_breakers() -> "dict[str, CircuitBreaker]":
    """Snapshot of the per-device breaker fleet (metrics rendering)."""
    with _DEVICE_LOCK:
        return dict(_DEVICE_BREAKERS)


def reset_device_breakers() -> None:
    """Drop every per-device breaker (tests; also after a mesh
    reconfiguration, where stale device keys would render forever)."""
    with _DEVICE_LOCK:
        _DEVICE_BREAKERS.clear()


class GatedDeviceBreaker:
    """A per-device breaker view that ALSO honors the process-wide
    accelerator breaker: the mesh path must keep PR 2's guarantee that
    an OPEN process breaker host-routes every dispatch group without
    paying an attempt — a fleet-wide outage verdict applies to every
    shard, not just the non-sharded paths.  Verdicts still charge only
    the device breaker: one shard's failure must not trip the process
    to host-only, and a shard success must not close (or consume the
    half-open probe slot of) the process breaker — that slot belongs to
    the driver's non-sharded probe dispatch."""

    def __init__(self, device: CircuitBreaker, process: CircuitBreaker):
        self._device = device
        self._process = process

    def allow(self) -> bool:
        # blocks_device() is the non-consuming check: an open process
        # breaker denies the shard without claiming its probe slot.
        if self._process.blocks_device():
            return False
        return self._device.allow()

    def blocks_device(self) -> bool:
        return (self._process.blocks_device()
                or self._device.blocks_device())

    def state(self) -> int:
        """The effective (most-degraded) state for fault events."""
        return max(self._process.state(), self._device.state())

    def record_success(self) -> None:
        self._device.record_success()

    def record_failure(self) -> bool:
        return self._device.record_failure()

    def abandon_probe(self) -> None:
        self._device.abandon_probe()
