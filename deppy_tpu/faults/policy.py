"""Retry/backoff policy and wall-clock deadlines for the solve path.

The driver's failure class is documented in its own comments: a dispatch
against the tunneled TPU worker can die (oversized programs,
minutes-long single executions) or stall.  The policy layer decides what
happens next:

  * :class:`RetryPolicy` — how many times a failed dispatch group is
    re-attempted, with exponential backoff + jitter between attempts,
    and whether a group that keeps failing is split in half (isolating a
    poison chunk) before falling back to the host engine;
  * :class:`Deadline` — a monotonic wall-clock budget.  The **batch**
    deadline rides a thread-local scope (:func:`deadline_scope`) from
    the service request / CLI flag down through the driver without
    touching the pinned internal signatures; the **chunk** deadline
    (``RetryPolicy.chunk_deadline_s``) bounds one dispatch attempt —
    an attempt that runs past it counts ``deppy_deadline_exceeded`` and
    charges the circuit breaker, because a minutes-long single execution
    is exactly the class that crashes the tunneled worker.

Nothing here sleeps or loops on its own; the driver's recovery wrapper
(:func:`deppy_tpu.engine.driver._recovering`) consumes both.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Iterator, Optional, Union


def env_float(name: str, default: Optional[float],
              warn: bool = False) -> Optional[float]:
    """Shared defensive float-env parsing for every fault-domain knob
    (and the service's): a typo'd value degrades to the default — the
    fault layer must never be the thing that crashes a solve — with an
    optional stderr warning for operator-facing knobs.  ``DEPPY_TPU_*``
    names resolve through the typed registry (ISSUE 7): an undeclared
    knob raises at the read site instead of silently existing."""
    from .. import config

    config.require(name)
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        if warn:
            print(f"[deppy] ignoring non-numeric {name}={raw!r}",
                  file=sys.stderr, flush=True)
        return default


class DeadlineExceeded(Exception):
    """A request/batch deadline could not be met.

    Raised only at admission time (service: the request's deadline is
    already unmeetable → 503 + Retry-After).  Inside the driver an
    expired deadline *degrades* — remaining problems come back
    ``Incomplete`` — rather than raising, so completed batchmates keep
    their answers."""


class Deadline:
    """Monotonic wall-clock budget.  Cheap value object: two floats."""

    __slots__ = ("seconds", "_expires", "_clock")

    def __init__(self, seconds: float,
                 clock: Callable[[], float] = time.monotonic):
        self.seconds = float(seconds)
        self._clock = clock
        self._expires = clock() + self.seconds

    def remaining(self) -> float:
        """Seconds left; negative once expired."""
        return self._expires - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


@dataclass
class RetryPolicy:
    """How a failed device dispatch is retried before degrading.

    ``max_attempts`` counts total tries of one dispatch group (2 = one
    retry).  Backoff for attempt *k* (1-based failures) is
    ``base * multiplier**(k-1)`` clamped to ``max_backoff_s``, plus up
    to ``jitter`` of itself at random so a fleet of workers retrying
    against a shared accelerator doesn't synchronize its hammering.
    ``split_failed_groups`` halves a group that exhausted its attempts
    (recursively, so a single poison problem isolates in log2 steps)
    before the host-engine fallback.  ``chunk_deadline_s`` > 0 bounds
    one attempt's wall clock (see module docstring); 0 disables.
    """

    max_attempts: int = 2
    base_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    split_failed_groups: bool = True
    chunk_deadline_s: float = 0.0

    def backoff_s(self, attempt: int,
                  rng: Callable[[], float] = random.random) -> float:
        """Sleep before retry number ``attempt`` (1-based)."""
        base = min(self.base_backoff_s * self.multiplier ** max(attempt - 1, 0),
                   self.max_backoff_s)
        return base * (1.0 + self.jitter * rng())

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Build the driver's policy from the environment (malformed
        values degrade to defaults, see :func:`env_float`)."""
        return cls(
            max_attempts=max(int(env_float(
                "DEPPY_TPU_FAULT_RETRIES", cls.max_attempts)), 1),
            base_backoff_s=max(env_float(
                "DEPPY_TPU_FAULT_BACKOFF_S", cls.base_backoff_s), 0.0),
            max_backoff_s=max(env_float(
                "DEPPY_TPU_FAULT_BACKOFF_MAX_S", cls.max_backoff_s), 0.0),
            chunk_deadline_s=max(env_float(
                "DEPPY_TPU_CHUNK_DEADLINE_S", 0.0), 0.0),
        )


# ------------------------------------------------------------- deadline scope
#
# The active batch deadline travels on a thread-local, like the active
# SolveReport (telemetry.report): the driver's internal phase functions
# are monkeypatched by tests and their signatures are pinned, so the
# deadline cannot ride a parameter.

_TLS = threading.local()


def current_deadline() -> Optional[Deadline]:
    """The batch deadline active on this thread, if any."""
    return getattr(_TLS, "deadline", None)


@contextmanager
def deadline_scope(
    seconds: Optional[Union[float, Deadline]],
) -> Iterator[Optional[Deadline]]:
    """Make a batch deadline active for the enclosed solve.  ``None`` is
    a no-op scope.  Nested scopes keep whichever deadline expires first
    (an inner, looser deadline must not extend the request's).

    Accepts either seconds (a fresh :class:`Deadline` starts now) or an
    existing :class:`Deadline` — the request scheduler captures each
    request's deadline on its submitting thread and re-installs the SAME
    clock on the dispatch-loop thread, so coalescing never restarts a
    request's budget."""
    prev = current_deadline()
    if seconds is None:
        yield prev
        return
    dl = seconds if isinstance(seconds, Deadline) else Deadline(seconds)
    if prev is not None and prev.remaining() < dl.remaining():
        dl = prev
    _TLS.deadline = dl
    try:
        yield dl
    finally:
        _TLS.deadline = prev


@contextmanager
def ambient_deadline() -> Iterator[Optional[Deadline]]:
    """The driver's entry-point scope: when no caller installed a batch
    deadline, apply ``DEPPY_TPU_BATCH_DEADLINE_S`` from the environment
    (unset/invalid/<=0 → no deadline)."""
    if current_deadline() is not None:
        yield current_deadline()
        return
    seconds = env_float("DEPPY_TPU_BATCH_DEADLINE_S", None, warn=True)
    if seconds is not None and seconds <= 0:
        seconds = None
    with deadline_scope(seconds) as dl:
        yield dl


def note_deadline_exceeded(where: str, n_problems: int = 0,
                           tenant: Optional[str] = None) -> None:
    """Count one deadline expiry (``deppy_deadline_exceeded``) and emit a
    ``fault`` event to the telemetry sink.  Under an active trace
    context (ISSUE 4) the event is also stamped onto the request's span
    tree and marks the trace errored, so the flight recorder retains
    every deadline-degraded request in its error ring.  ``tenant``
    (ISSUE 11: the scheduler's triage knows whose lane expired) rides
    the event so deadline misses are attributable per tenant offline;
    callers without tenant context emit the historical event shape."""
    from .. import telemetry
    from .metrics import fault_counter

    fault_counter("deppy_deadline_exceeded").inc()
    fields = {"where": where, "problems": n_problems}
    if tenant is not None:
        fields["tenant"] = tenant
    telemetry.default_registry().event(
        "fault", fault="deadline_exceeded", **fields)
