"""Deterministic fault injection at named pipeline points.

Every recovery path in the fault-domain layer (retry, group split, host
fallback, breaker trip, checkpoint resume, request drain) must be
exercisable in CI on CPU, where the accelerator never actually fails.
This harness scripts the failures: a **fault plan** — JSON from
``DEPPY_TPU_FAULT_PLAN`` or ``--fault-plan`` — lists rules matched
against named **fault points** the pipeline calls :func:`inject` at.

Fault points wired in this PR:

  ==========================  ================================================
  point                       where
  ==========================  ================================================
  ``driver.dispatch``         entry of every device dispatch attempt (the
                              recovery wrapper, so retries re-hit it)
  ``driver.device_put``       host→device upload of a dispatch group
  ``driver.host_fallback``    entry of the host-engine fallback (latency
                              injection; an error here propagates — the host
                              engine is the last line of defense, faults
                              there must fail loud)
  ``checkpoint.save_group``   before a completed group's npz write
  ``service.resolve``         entry of one ``/v1/resolve`` request body
  ``sched.dispatch``          entry of one coalesced scheduler dispatch
                              (ISSUE 3; before backend resolution, so an
                              error here fails every coalesced request
                              and latency stalls the whole flush)
  ``hostpool.dispatch``       entry of one host-worker-pool dispatch
                              (ISSUE 5; an error degrades the batch to
                              the inline engine byte-identically —
                              counted ``deppy_hostpool_inline_fallback_
                              total``)
  ``hostpool.worker_crash``   per chunk assignment in the pool parent
                              (ISSUE 5; an error hard-kills the assigned
                              worker mid-task — the crash-retry path
                              runs exactly as for a real worker death)
  ==========================  ================================================

Plan format — an object ``{"faults": [...]}`` or a bare list of rules::

    [{"point": "driver.device_put", "kind": "error", "times": 1},
     {"point": "driver.dispatch", "kind": "latency", "latency_s": 0.02,
      "times": -1},
     {"point": "driver.dispatch", "kind": "error", "period": 2, "times": 1}]

Rule fields: ``point`` (exact name or fnmatch glob, e.g. ``driver.*``),
``kind`` (``error`` | ``latency``, default ``error``), ``times`` (total
firings, -1 = unlimited, default 1), ``after`` (skip the first K hits),
``period`` (when > 0, fire on the first ``times`` hits of every
``period``-hit cycle — "every first chunk attempt" is
``{"period": 2, "times": 1}`` under a 2-attempt retry policy), and
``latency_s`` / ``message``.  Hit counting is per rule, under one lock —
deterministic for a given call sequence.

Errors raise :class:`InjectedFault` (a ``RuntimeError``), which the
recovery wrapper treats exactly like a real device failure.  Injections
count ``deppy_faults_injected_total{point=}`` and emit ``fault`` events
to the telemetry sink.
"""

from __future__ import annotations

import json
import os
import threading
import time
from fnmatch import fnmatch
from typing import List, Optional, Union


# The registered fault-point vocabulary (ISSUE 7 registry-sync): every
# literal ``inject("point")`` site must name one of these (pinned by
# `deppy lint`), and the operator plan paths (env / --fault-plan) warn
# on rules that match none of them — a chaos plan written against a
# renamed point would otherwise inject nothing and report green.
# Entries ending ``.*`` are prefixes for dynamically-suffixed points
# (one per mesh device).
KNOWN_POINTS = (
    "driver.dispatch",
    "driver.device_put",
    "driver.host_fallback",
    "driver.shard_dispatch.*",
    "checkpoint.save_group",
    "service.resolve",
    "sched.dispatch",
    "sched.race.*",
    "hostpool.dispatch",
    "hostpool.worker_crash",
    "fleet.forward",
    "fleet.join_stream",
    "fleet.arc_flip",
    "router.peer_sync",
    "sessions.op",
)


def unmatched_points(plan: "FaultPlan") -> List[str]:
    """Rule points that match no registered fault point (exact, or
    either side globbing).  The operator plan paths warn on these; the
    unit-test path (``FaultPlan.from_doc`` with synthetic points) stays
    silent."""
    out = []
    for rule in plan.rules:
        matched = any(
            rule.point == known
            or fnmatch(known, rule.point)
            or fnmatch(rule.point, known)
            for known in KNOWN_POINTS)
        if not matched:
            out.append(rule.point)
    return out


class InjectedFault(RuntimeError):
    """The scripted failure raised at an ``error`` fault point."""


class FaultRule:
    """One scripted fault: where, what, and on which hits."""

    __slots__ = ("point", "kind", "times", "after", "period", "latency_s",
                 "message", "hits", "fired")

    def __init__(self, point: str, kind: str = "error", times: int = 1,
                 after: int = 0, period: int = 0, latency_s: float = 0.0,
                 message: str = ""):
        if kind not in ("error", "latency"):
            raise ValueError(f"fault rule kind must be 'error' or "
                             f"'latency', got {kind!r}")
        self.point = str(point)
        self.kind = kind
        self.times = int(times)
        self.after = max(int(after), 0)
        self.period = max(int(period), 0)
        self.latency_s = float(latency_s)
        self.message = message or f"injected fault at {point}"
        self.hits = 0       # matching inject() calls seen
        self.fired = 0      # times this rule actually fired

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        if not isinstance(d, dict) or "point" not in d:
            raise ValueError(f"fault rule must be an object with a "
                             f"'point' key, got {d!r}")
        unknown = set(d) - {"point", "kind", "times", "after", "period",
                            "latency_s", "message"}
        if unknown:
            raise ValueError(
                f"unknown fault rule keys {sorted(unknown)} in {d!r}")
        return cls(
            point=d["point"], kind=d.get("kind", "error"),
            times=d.get("times", 1), after=d.get("after", 0),
            period=d.get("period", 0), latency_s=d.get("latency_s", 0.0),
            message=d.get("message", ""),
        )

    def should_fire(self, consume: bool = True) -> bool:
        """Advance this rule's hit counter and decide; caller holds the
        plan lock.  ``consume=False`` still advances the schedule but
        leaves the ``times`` budget untouched — used for an error rule
        shadowed by an earlier one on the same hit, so its scripted
        firing isn't silently spent without ever raising."""
        self.hits += 1
        idx = self.hits - 1  # 0-based hit index
        if idx < self.after:
            return False
        idx -= self.after
        if self.period > 0:
            fire = (idx % self.period) < max(self.times, 0) or self.times < 0
        else:
            fire = self.times < 0 or self.fired < self.times
        if fire and consume:
            self.fired += 1
        return fire and consume


class FaultPlan:
    """A parsed, hit-counting set of fault rules."""

    def __init__(self, rules: List[FaultRule]):
        from ..analysis import lockdep

        self.rules = rules
        self._lock = lockdep.make_lock("faults.fault_plan")

    @classmethod
    def from_doc(cls, doc: Union[dict, list]) -> "FaultPlan":
        if isinstance(doc, dict):
            doc = doc.get("faults", [])
        if not isinstance(doc, list):
            raise ValueError(
                "fault plan must be a list of rules or "
                '{"faults": [...]}')
        return cls([FaultRule.from_dict(r) for r in doc])

    def check(self, point: str) -> None:
        """Match ``point`` against every rule; sleep for latency rules,
        raise :class:`InjectedFault` for the first error rule that
        fires.  Latency rules evaluated before the error raise, so a
        slow-then-dead fault composes in one plan."""
        sleep_s = 0.0
        error: Optional[FaultRule] = None
        with self._lock:
            for rule in self.rules:
                if rule.point != point and not fnmatch(point, rule.point):
                    continue
                consume = rule.kind == "latency" or error is None
                if not rule.should_fire(consume=consume):
                    continue
                if rule.kind == "latency":
                    sleep_s += rule.latency_s
                else:
                    error = rule
        if sleep_s > 0.0:
            _record(point, "latency", sleep_s=sleep_s)
            time.sleep(sleep_s)
        if error is not None:
            _record(point, "error")
            raise InjectedFault(error.message)


def _record(point: str, kind: str, **attrs) -> None:
    from .. import telemetry
    from .metrics import fault_counter

    fault_counter("deppy_faults_injected_total").inc(1, label=point)
    telemetry.default_registry().event(
        "fault", fault="injected", point=point, fault_kind=kind, **attrs)


# ------------------------------------------------------------ plan plumbing

_PLAN: Optional[FaultPlan] = None
_PLAN_LOCK = threading.Lock()
_ENV_LOADED = False


def plan_from_spec(spec: str) -> FaultPlan:
    """Parse a plan from inline JSON, ``@file``, or a plain file path
    (anything not starting with ``[`` / ``{`` is treated as a path)."""
    spec = spec.strip()
    if spec.startswith("@"):
        spec = spec[1:]
    if spec and spec[0] not in "[{":
        with open(spec, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    else:
        doc = json.loads(spec)
    return FaultPlan.from_doc(doc)


def plan_from_env() -> Optional[FaultPlan]:
    """Parse ``DEPPY_TPU_FAULT_PLAN`` (inline JSON or a file path);
    unset/empty → None.  A malformed plan raises — a chaos run that
    silently injects nothing would report green without testing
    anything."""
    from .. import config

    raw = (config.env_raw("DEPPY_TPU_FAULT_PLAN", "") or "").strip()
    if not raw:
        return None
    plan = plan_from_spec(raw)
    _warn_unmatched(plan)
    return plan


def _warn_unmatched(plan: FaultPlan) -> None:
    import sys

    for point in unmatched_points(plan):
        print(f"[deppy] fault-plan rule point {point!r} matches no "
              f"registered fault point ({', '.join(KNOWN_POINTS)}); "
              f"it will never fire", file=sys.stderr, flush=True)


def configure_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install the active plan (None disarms); returns the previous."""
    global _PLAN, _ENV_LOADED
    with _PLAN_LOCK:
        prev, _PLAN = _PLAN, plan
        _ENV_LOADED = True  # explicit configuration overrides the env
        return prev


def current_plan() -> Optional[FaultPlan]:
    """The active plan, loading ``DEPPY_TPU_FAULT_PLAN`` on first call."""
    global _PLAN, _ENV_LOADED
    if not _ENV_LOADED:
        with _PLAN_LOCK:
            if not _ENV_LOADED:
                _PLAN = plan_from_env()
                _ENV_LOADED = True
    return _PLAN


def inject(point: str) -> None:
    """The pipeline's fault hook.  No active plan → one global read and
    return; the hot paths never pay more than that."""
    plan = current_plan()
    if plan is not None:
        plan.check(point)
