"""``deppy top`` — terminal fleet dashboard (ISSUE 16).

A refresh loop over the router's two fleet surfaces:

  * ``GET /fleet/status`` — replica liveness/drain states, routing
    policy, telemetry-ingest counts per replica;
  * ``GET /fleet/metrics`` — the federated scrape: per-replica
    families under the ``replica`` label plus the fleet rollups.

Rendered as one screen per refresh: a fleet header line (live
replicas, fleet warm-hit ratio, fleet queue depth), one row per
replica (state, warm-hit, queue depth, worst cost-model drift ratio,
ingested event count), and the per-tenant fleet burn-rate line.  Pure
functions end to end (fetch -> snapshot dict -> text) so tests can pin
the rendering without a live fleet.
"""

from __future__ import annotations

import http.client
import json
import time
from typing import Dict, Optional

from .federate import parse_samples

FETCH_TIMEOUT_S = 10.0


def fetch(router: str) -> dict:
    """One dashboard snapshot from a live router (raises OSError-family
    on transport failure)."""
    host, _, port = router.rpartition(":")
    conn = http.client.HTTPConnection(host or "127.0.0.1", int(port),
                                      timeout=FETCH_TIMEOUT_S)
    try:
        conn.request("GET", "/fleet/status")
        status = json.loads(conn.getresponse().read().decode("utf-8"))
        conn.request("GET", "/fleet/metrics")
        metrics = conn.getresponse().read().decode("utf-8",
                                                   errors="replace")
    finally:
        conn.close()
    return snapshot(router, status, metrics)


def snapshot(router: str, status: dict, metrics_text: str) -> dict:
    """Fold the two fleet surfaces into one renderable dict."""
    samples = parse_samples(metrics_text)

    def _fleet(family: str) -> Optional[float]:
        vals = [v for n, labels, v in samples
                if n == family and "replica" not in labels]
        return vals[0] if vals else None

    def _per_replica(family: str, agg="sum") -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n, labels, v in samples:
            if n != family or "replica" not in labels:
                continue
            rep = labels["replica"]
            if agg == "max":
                out[rep] = max(out.get(rep, v), v)
            else:
                out[rep] = out.get(rep, 0.0) + v
        return out

    hits = _per_replica("deppy_cache_hits_total")
    incr = _per_replica("deppy_incremental_hits_total")
    misses = _per_replica("deppy_cache_misses_total")
    warm: Dict[str, Optional[float]] = {}
    for rep in set(hits) | set(misses):
        asks = hits.get(rep, 0.0) + misses.get(rep, 0.0)
        warm[rep] = (round((hits.get(rep, 0.0) + incr.get(rep, 0.0))
                           / asks, 3) if asks else None)
    burn = {labels.get("tenant", "?"): v
            for n, labels, v in samples
            if n == "deppy_fleet_tenant_burn_rate"}
    ingest = (status.get("telemetry") or {}).get("ingested") or {}
    rows = []
    for state in status.get("replicas", []):
        addr = state.get("replica", "?")
        rows.append({
            "replica": addr,
            "state": ("dead" if state.get("dead")
                      else "drained" if state.get("drained") else "up"),
            "warm_hit_ratio": warm.get(addr),
            "queue_depth": _per_replica("deppy_sched_queue_depth")
            .get(addr),
            "drift_ratio": _per_replica(
                "deppy_costmodel_drift_ratio", agg="max").get(addr),
            "regret_s": _per_replica(
                "deppy_route_regret_seconds_total").get(addr),
            "stale_classes": _per_replica(
                "deppy_route_stale_classes").get(addr),
            "events": ingest.get(addr),
        })
    return {
        "router": router,
        "policy": status.get("policy"),
        "replicas": rows,
        "fleet": {
            "warm_hit_ratio": _fleet("deppy_fleet_warm_hit_ratio"),
            "queue_depth": _fleet("deppy_fleet_queue_depth"),
            "tenant_burn_rate": burn,
            "route_regret_s": _fleet("deppy_fleet_route_regret_seconds"),
            "route_stale_classes":
                _fleet("deppy_fleet_route_stale_classes"),
        },
    }


def _num(v, fmt="{:.3f}") -> str:
    return "-" if v is None else fmt.format(v)


def render_text(snap: dict) -> str:
    fleet = snap.get("fleet", {})
    rows = snap.get("replicas", [])
    live = sum(1 for r in rows if r["state"] == "up")
    lines = [
        f"deppy fleet @ {snap.get('router', '?')}   "
        f"policy={snap.get('policy', '?')}   "
        f"{live}/{len(rows)} live   "
        f"warm={_num(fleet.get('warm_hit_ratio'))}   "
        f"queue={_num(fleet.get('queue_depth'), '{:.0f}')}   "
        f"regret={_num(fleet.get('route_regret_s'))}s   "
        f"stale={_num(fleet.get('route_stale_classes'), '{:.0f}')}",
        "",
        f"  {'REPLICA':<22}  {'STATE':<8}  {'WARM':>6}  {'QUEUE':>6}  "
        f"{'DRIFT':>6}  {'REGRET':>7}  {'STALE':>5}  {'EVENTS':>8}",
    ]
    for r in rows:
        lines.append(
            f"  {r['replica']:<22}  {r['state']:<8}  "
            f"{_num(r['warm_hit_ratio']):>6}  "
            f"{_num(r['queue_depth'], '{:.0f}'):>6}  "
            f"{_num(r['drift_ratio'], '{:.2f}'):>6}  "
            f"{_num(r.get('regret_s'), '{:.2f}'):>7}  "
            f"{_num(r.get('stale_classes'), '{:.0f}'):>5}  "
            f"{_num(r['events'], '{:.0f}'):>8}")
    burn = fleet.get("tenant_burn_rate") or {}
    if burn:
        lines.append("")
        lines.append("  tenant burn (fleet): " + "  ".join(
            f"{t}={burn[t]:.3f}" for t in sorted(burn)))
    return "\n".join(lines)


def run(router: str, interval_s: float = 2.0, once: bool = False,
        out=None) -> int:
    """The ``deppy top`` loop.  Returns a process exit code."""
    import sys

    out = out or sys.stdout
    while True:
        try:
            snap = fetch(router)
        except (OSError, ValueError, http.client.HTTPException) as exc:
            print(f"deppy top: cannot reach router at {router}: {exc}",
                  file=sys.stderr)
            return 1
        if not once:
            out.write("\x1b[2J\x1b[H")  # clear + home
        out.write(render_text(snap) + "\n")
        out.flush()
        if once:
            return 0
        try:
            time.sleep(interval_s)
        except KeyboardInterrupt:
            return 0
