"""Router-side telemetry aggregation: one merged, replica-stamped sink.

The :class:`Aggregator` is the receiving end of the streaming layer
(:mod:`deppy_tpu.obs.stream`): the router's ``POST /fleet/telemetry``
hands it each ``{"replica": ..., "events": [...]}`` batch, and it
appends every event — stamped ``"replica": <source>`` — to ONE merged
JSONL sink (``DEPPY_TPU_OBS_SINK`` / ``--obs-sink``).  The merged sink
uses the exact per-event schema of the per-process sink
(docs/observability.md), plus the ``replica`` stamp, so every existing
sink consumer (``deppy stats`` / ``trace`` / ``profile``) reads it
unchanged — and ``deppy trace --fleet`` can reconstruct a routed
request's cross-replica span tree from it alone.

The router's OWN events (its ``router.forward`` hop spans) are
ingested locally via :meth:`ingest_event` stamped ``replica="router"``.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

ROUTER_REPLICA = "router"


class Aggregator:
    """Append replica-stamped telemetry events to the merged sink."""

    def __init__(self, sink_path: str, registry=None):
        from ..analysis import lockdep

        self.sink_path = sink_path
        self._lock = lockdep.make_lock("obs.aggregate")
        self._file = None
        self._counts: Dict[str, int] = {}
        self._c_events = self._c_batches = self._c_rejects = None
        if registry is not None:
            self._c_events = registry.counter(
                "deppy_obs_ingest_events_total",
                "Telemetry events ingested into the merged fleet sink, "
                "by source replica.", labelname="replica")
            self._c_batches = registry.counter(
                "deppy_obs_ingest_batches_total",
                "Telemetry batches accepted by POST /fleet/telemetry.")
            self._c_rejects = registry.counter(
                "deppy_obs_ingest_rejects_total",
                "Malformed telemetry batches rejected (bad JSON shape).")

    def ingest(self, doc) -> Tuple[int, Optional[str]]:
        """One ``POST /fleet/telemetry`` body.  Returns
        ``(accepted_count, error)`` — error is a client-facing reason
        string for a 400, None on success."""
        from ..profile import sanitize_replica

        if not isinstance(doc, dict):
            return self._reject("body must be a JSON object")
        events = doc.get("events")
        if not isinstance(events, list):
            return self._reject("'events' must be a list")
        replica = sanitize_replica(doc.get("replica")) or "unknown"
        accepted = 0
        for ev in events:
            if isinstance(ev, dict):
                self.ingest_event(replica, ev, flush=False)
                accepted += 1
        # One flush per accepted batch, not per event: the sink stays
        # tail-readable at the streamers' flush cadence while the
        # aggregator's syscall rate is bounded by batches, not events.
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                except OSError:
                    self._file = None
        if self._c_batches is not None:
            self._c_batches.inc()
        return accepted, None

    def _reject(self, reason: str) -> Tuple[int, str]:
        if self._c_rejects is not None:
            self._c_rejects.inc()
        return 0, reason

    def ingest_event(self, replica: str, event: dict,
                     flush: bool = True) -> None:
        """Stamp + append one event.  The aggregator is authoritative
        for the ``replica`` field: a forged in-event stamp is
        overwritten by the transport-level source."""
        stamped = dict(event)
        stamped["replica"] = replica
        line = json.dumps(stamped) + "\n"
        with self._lock:
            self._counts[replica] = self._counts.get(replica, 0) + 1
            try:
                if self._file is None:
                    self._file = open(self.sink_path, "a",
                                      encoding="utf-8")
                self._file.write(line)
                if flush:
                    self._file.flush()
            except OSError:
                self._file = None
        if self._c_events is not None:
            self._c_events.inc(label=replica)

    def counts(self) -> Dict[str, int]:
        """Events ingested per source replica (for /fleet/status)."""
        with self._lock:
            return dict(self._counts)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
