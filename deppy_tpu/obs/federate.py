"""Metrics federation: one fleet scrape from N replica scrapes.

Router ``GET /fleet/metrics`` = :func:`collect` (scrape every live
replica's ``/metrics`` concurrently, one thread per replica, mirroring
the split-forward path) + :func:`render_fleet_metrics`:

  * **rollups first** — the fleet-level gauges the ROADMAP-item-2
    autoscaler policy consumes, computed from the per-replica samples:
    ``deppy_fleet_warm_hit_ratio`` (fleet warm hits / fleet asks — the
    request-weighted average of the per-replica ratios),
    ``deppy_fleet_tenant_burn_rate{tenant}`` (request-weighted),
    ``deppy_fleet_queue_depth`` (sum), and
    ``deppy_fleet_race_win_share{backend}`` (fraction of fleet race
    wins per backend);
  * **merged families** — every per-replica family re-labeled with
    ``replica="<addr>"`` (first replica's HELP/TYPE wins), samples
    grouped per family so the output stays valid exposition format;
  * the router's own ``deppy_fleet_*`` registry last.

A replica that fails to scrape is skipped (and charges the router's
transport breaker via ``forward``); the fleet scrape degrades instead
of failing.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Tuple

_SAMPLE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")
_LABEL = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="([^"]*)"')

SCRAPE_TIMEOUT_S = 10.0


def parse_samples(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """``(name, labels, value)`` per sample line of an exposition page
    (comments and non-numeric samples skipped)."""
    out: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if m is None:
            continue
        name, rawlabels, rawval = m.groups()
        try:
            value = float(rawval)
        except ValueError:
            continue
        labels = dict(_LABEL.findall(rawlabels)) if rawlabels else {}
        out.append((name, labels, value))
    return out


def _sum(samples, family: str) -> float:
    return sum(v for n, _, v in samples if n == family)


def _by_label(samples, family: str, label: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for n, labels, v in samples:
        if n == family and label in labels:
            out[labels[label]] = out.get(labels[label], 0.0) + v
    return out


# ------------------------------------------------------------- rollups


def fleet_rollups(scrapes: List[Tuple[str, str]]) -> dict:
    """Fleet-level aggregates from ``[(replica, exposition_text)]``."""
    warm_hits = warm_asks = queue_depth = 0.0
    route_regret = route_stale = route_shadow = route_learned = 0.0
    burn_num: Dict[str, float] = {}
    burn_den: Dict[str, float] = {}
    wins: Dict[str, float] = {}
    per_replica: Dict[str, dict] = {}
    for replica, text in scrapes:
        samples = parse_samples(text)
        hits = _sum(samples, "deppy_cache_hits_total") \
            + _sum(samples, "deppy_incremental_hits_total")
        asks = _sum(samples, "deppy_cache_hits_total") \
            + _sum(samples, "deppy_cache_misses_total")
        depth = _sum(samples, "deppy_sched_queue_depth")
        regret = _sum(samples, "deppy_route_regret_seconds_total")
        stale = _sum(samples, "deppy_route_stale_classes")
        warm_hits += hits
        warm_asks += asks
        queue_depth += depth
        route_regret += regret
        route_stale += stale
        route_shadow += _sum(samples,
                             "deppy_route_shadow_dispatches_total")
        route_learned += _sum(samples, "deppy_route_learned_rows")
        per_replica[replica] = {
            "warm_hit_ratio": (round(hits / asks, 6) if asks else None),
            "queue_depth": depth,
            "route_regret_s": round(regret, 6),
            "route_stale_classes": stale,
        }
        burn = _by_label(samples, "deppy_tenant_burn_rate", "tenant")
        reqs = _by_label(samples, "deppy_tenant_requests_total",
                         "tenant")
        for tenant, rate in burn.items():
            weight = reqs.get(tenant, 1.0) or 1.0
            burn_num[tenant] = burn_num.get(tenant, 0.0) + rate * weight
            burn_den[tenant] = burn_den.get(tenant, 0.0) + weight
        for backend, n in _by_label(samples, "deppy_race_wins_total",
                                    "backend").items():
            wins[backend] = wins.get(backend, 0.0) + n
    total_wins = sum(wins.values())
    return {
        "replicas": len(scrapes),
        "warm_hit_ratio": (round(warm_hits / warm_asks, 6)
                           if warm_asks else None),
        "warm_hits": warm_hits,
        "warm_asks": warm_asks,
        "queue_depth": queue_depth,
        "tenant_burn_rate": {
            t: round(burn_num[t] / burn_den[t], 6)
            for t in sorted(burn_num) if burn_den.get(t)},
        "race_win_share": {
            b: round(wins[b] / total_wins, 6)
            for b in sorted(wins)} if total_wins else {},
        "route_regret_s": round(route_regret, 6),
        "route_stale_classes": route_stale,
        "route_shadow_dispatches": route_shadow,
        "route_learned_rows": route_learned,
        "per_replica": per_replica,
    }


def render_rollup_lines(rollups: dict) -> List[str]:
    lines: List[str] = []
    if rollups.get("warm_hit_ratio") is not None:
        lines += [
            "# HELP deppy_fleet_warm_hit_ratio Fleet warm-hit ratio: "
            "(cache + incremental hits) / (cache hits + misses) summed "
            "over live replicas.",
            "# TYPE deppy_fleet_warm_hit_ratio gauge",
            f"deppy_fleet_warm_hit_ratio {rollups['warm_hit_ratio']}",
        ]
    lines += [
        "# HELP deppy_fleet_queue_depth Problems queued for coalesced "
        "dispatch right now, summed over live replicas.",
        "# TYPE deppy_fleet_queue_depth gauge",
        f"deppy_fleet_queue_depth {_fmt_num(rollups.get('queue_depth', 0))}",
    ]
    burn = rollups.get("tenant_burn_rate") or {}
    if burn:
        lines += [
            "# HELP deppy_fleet_tenant_burn_rate Request-weighted fleet "
            "error-budget burn rate per tenant.",
            "# TYPE deppy_fleet_tenant_burn_rate gauge",
        ]
        for tenant in sorted(burn):
            lines.append(
                f'deppy_fleet_tenant_burn_rate{{tenant="{tenant}"}} '
                f"{burn[tenant]}")
    share = rollups.get("race_win_share") or {}
    if share:
        lines += [
            "# HELP deppy_fleet_race_win_share Fraction of fleet "
            "portfolio-race wins per backend.",
            "# TYPE deppy_fleet_race_win_share gauge",
        ]
        for backend in sorted(share):
            lines.append(
                f'deppy_fleet_race_win_share{{backend="{backend}"}} '
                f"{share[backend]}")
    # Route health (ISSUE 19): fleet totals render only once some
    # replica exposes the families — a learn=off fleet's scrape stays
    # byte-identical to pre-plane.
    if (rollups.get("route_regret_s") or rollups.get("route_stale_classes")
            or rollups.get("route_shadow_dispatches")
            or rollups.get("route_learned_rows")):
        lines += [
            "# HELP deppy_fleet_route_regret_seconds Wall-clock seconds "
            "frozen routing defaults burned beyond observed race "
            "winners, summed over live replicas.",
            "# TYPE deppy_fleet_route_regret_seconds gauge",
            f"deppy_fleet_route_regret_seconds "
            f"{rollups.get('route_regret_s', 0.0)}",
            "# HELP deppy_fleet_route_stale_classes Live size classes "
            "with stale/missing routing rows, summed over live "
            "replicas.",
            "# TYPE deppy_fleet_route_stale_classes gauge",
            f"deppy_fleet_route_stale_classes "
            f"{_fmt_num(rollups.get('route_stale_classes', 0))}",
            "# HELP deppy_fleet_route_learned_rows Live-learned routing "
            "rows adopted across live replicas.",
            "# TYPE deppy_fleet_route_learned_rows gauge",
            f"deppy_fleet_route_learned_rows "
            f"{_fmt_num(rollups.get('route_learned_rows', 0))}",
        ]
    return lines


def _fmt_num(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else str(v)


# -------------------------------------------------------------- merge


def merge_scrapes(scrapes: List[Tuple[str, str]]) -> List[str]:
    """Merge N exposition pages into one, every sample re-labeled with
    its source ``replica``.  Families are grouped (samples contiguous
    under one HELP/TYPE, first replica's header wins) so the merged
    page stays valid exposition format."""
    order: List[str] = []
    headers: Dict[str, List[str]] = {}
    samples: Dict[str, List[str]] = {}
    known: set = set()

    def _family_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in known:
                return name[: -len(suffix)]
        return name

    for replica, text in scrapes:
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                    fam = parts[2]
                    known.add(fam)
                    if fam not in headers:
                        headers[fam] = []
                        samples[fam] = []
                        order.append(fam)
                    if len(headers[fam]) < 2:
                        headers[fam].append(line)
                continue
            m = _SAMPLE.match(line)
            if m is None:
                continue
            name, rawlabels, rawval = m.groups()
            fam = _family_of(name)
            if fam not in headers:
                headers[fam] = []
                samples[fam] = []
                order.append(fam)
            labels = f'replica="{replica}"'
            if rawlabels:
                labels += f",{rawlabels}"
            samples[fam].append(f"{name}{{{labels}}} {rawval}")
    lines: List[str] = []
    for fam in order:
        lines.extend(headers[fam])
        lines.extend(samples[fam])
    return lines


# ------------------------------------------------------------- collect


def collect(router) -> List[Tuple[str, str]]:
    """Scrape every live replica's ``/metrics`` concurrently through
    the router's forward path (so failures charge the transport
    breaker).  Returns ``[(replica_addr, text)]`` for the replicas that
    answered, in address order."""
    replicas = router.live_replicas()
    results: List[Optional[str]] = [None] * len(replicas)

    def _scrape(i: int, addr: str) -> None:
        try:
            status, data, _ = router.forward(
                addr, "GET", "/metrics", None,
                timeout=SCRAPE_TIMEOUT_S)
        except OSError:
            return
        if status == 200:
            results[i] = data.decode("utf-8", errors="replace")

    threads = [threading.Thread(target=_scrape, args=(i, addr),
                                name=f"fleet-scrape-{i}", daemon=True)
               for i, addr in enumerate(replicas)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(SCRAPE_TIMEOUT_S + 1.0)
    return [(addr, text)
            for addr, text in zip(replicas, results) if text is not None]


def render_fleet_metrics(router) -> str:
    """The ``GET /fleet/metrics`` body: rollups, merged replica
    families, then the router's own registry."""
    scrapes = collect(router)
    lines = render_rollup_lines(fleet_rollups(scrapes))
    lines += merge_scrapes(scrapes)
    return "\n".join(lines) + "\n" + router.render_metrics()
