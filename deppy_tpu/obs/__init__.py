"""deppy_tpu.obs — fleet-wide observability plane (ISSUE 16 tentpole).

PR 15 made N replicas behave like one warm process; this package makes
them *observable* as one process.  Four layers:

  * **stream** — :class:`~deppy_tpu.obs.stream.TelemetryStreamer`: a
    registry event forwarder that batch-pushes every sink event
    (profile, race, fault, lockdep, compileguard, speculate, spans) to
    an aggregator endpoint (``POST /fleet/telemetry`` on the router).
    Bounded queue with counted drops — a slow aggregator can never
    stall serving.  Armed by ``DEPPY_TPU_OBS_STREAM`` / ``--obs-stream``;
    disarmed is byte-identical to the local-sink-only pipeline.
  * **aggregate** — :class:`~deppy_tpu.obs.aggregate.Aggregator`: the
    router-side ingest that stamps each event with its source replica
    and appends to ONE merged JSONL sink (``DEPPY_TPU_OBS_SINK`` /
    ``--obs-sink``), the file ``deppy trace --fleet`` reconstructs
    cross-replica span trees from.
  * **federate** — router ``GET /fleet/metrics``: scrape every live
    replica concurrently, merge families under a ``replica`` label, and
    compute the fleet rollups (warm-hit ratio, per-tenant burn rate,
    queue depth, race win share) the ROADMAP-item-2 autoscaler policy
    consumes.
  * **drift** — :class:`~deppy_tpu.obs.drift.CostModelWatchdog`: fits
    the effective µs/trip per size class from live ``profile`` ledger
    samples and compares it against the committed bench baseline
    (``DEPPY_TPU_OBS_BASELINE``, e.g. BENCH_r16.json); drift past the
    band emits a ``costmodel_drift`` event and pushes the
    ``deppy_costmodel_drift_ratio`` gauge past it.

Capped by ``deppy top`` (:mod:`deppy_tpu.obs.top`): a terminal fleet
dashboard over ``/fleet/metrics`` + ``/fleet/status``.

See docs/observability.md ("Fleet observability") for schemas and
semantics.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from .aggregate import Aggregator
from .drift import CostModelWatchdog, load_baseline
from .federate import fleet_rollups, merge_scrapes
from .stream import STREAM_FAMILIES, TelemetryStreamer

# Process-wide active components (one serving process = one replica):
# Metrics.render() injects their exposition lines the same way the
# profiler and SLO accountant inject theirs.
_LOCK = threading.Lock()
_STREAMER: Optional[TelemetryStreamer] = None
_WATCHDOG: Optional[CostModelWatchdog] = None


def start_streamer(target: str, replica: Optional[str] = None,
                   flush_ms: Optional[float] = None) -> TelemetryStreamer:
    """Build, register (as a default-registry event forwarder), and
    start the process streamer.  Replaces any previous one."""
    global _STREAMER
    streamer = TelemetryStreamer(target, replica=replica,
                                 flush_ms=flush_ms)
    with _LOCK:
        prev, _STREAMER = _STREAMER, streamer
    if prev is not None:
        prev.close()
    streamer.start()
    return streamer


def start_watchdog(baseline: str,
                   replica: Optional[str] = None
                   ) -> Optional[CostModelWatchdog]:
    """Build and register the process cost-model drift watchdog.
    Returns None (disarmed) when the baseline artifact is unreadable —
    observability must never fail serving."""
    global _WATCHDOG
    watchdog = CostModelWatchdog.from_baseline(baseline, replica=replica)
    if watchdog is None:
        return None
    with _LOCK:
        prev, _WATCHDOG = _WATCHDOG, watchdog
    if prev is not None:
        prev.close()
    watchdog.install()
    return watchdog


def stop_all() -> None:
    """Detach and stop the process streamer + watchdog (server drain)."""
    global _STREAMER, _WATCHDOG
    with _LOCK:
        streamer, _STREAMER = _STREAMER, None
        watchdog, _WATCHDOG = _WATCHDOG, None
    if streamer is not None:
        streamer.close()
    if watchdog is not None:
        watchdog.close()


def render_metric_lines() -> List[str]:
    """Exposition lines for the armed obs components — appended to the
    service ``/metrics`` like the profiler/SLO injections.  Disarmed
    (no streamer, no watchdog) this is exactly []."""
    from .. import telemetry

    with _LOCK:
        streamer, watchdog = _STREAMER, _WATCHDOG
    lines: List[str] = []
    if streamer is not None:
        lines += telemetry.default_registry().render_families(
            STREAM_FAMILIES)
    if watchdog is not None:
        lines += watchdog.render_metric_lines()
    return lines


__all__ = [
    "Aggregator",
    "CostModelWatchdog",
    "STREAM_FAMILIES",
    "TelemetryStreamer",
    "fleet_rollups",
    "load_baseline",
    "merge_scrapes",
    "render_metric_lines",
    "start_streamer",
    "start_watchdog",
    "stop_all",
]
