"""Cost-model drift watchdog (ISSUE 16): live µs/trip vs the baseline.

The profiler's trip ledger (ISSUE 11) emits one ``profile`` event per
sampled device dispatch carrying ``trips`` and ``solve_s``.  The
:class:`CostModelWatchdog` registers as a registry event forwarder and
folds those samples into a bounded window per size class, computing
the **effective µs/trip** — ``1e6 * Σ solve_s / Σ trips`` over the
window.  The ratio-of-sums is deliberately used instead of the OLS
slope ``deppy profile`` fits: a *constant* per-dispatch overhead
regression (the classic deploy bug — extra sync, extra host hop) moves
only the regression intercept and would be invisible to the slope,
while it inflates the effective per-trip cost exactly in proportion to
the damage done.

The live figure is compared against the committed baseline artifact
(``DEPPY_TPU_OBS_BASELINE`` — a ``BENCH_rNN.json`` with an embedded
``costmodel`` section, or a ``deppy profile --json`` report).  Past
the relative band (``DEPPY_TPU_OBS_DRIFT_BAND``) the watchdog emits
one ``costmodel_drift`` event per crossing and the
``deppy_costmodel_drift_ratio{size_class,replica}`` gauge sits past
the band until the window recovers — the permanent regression tripwire
the ROADMAP-item-1 megakernel rewrite runs against.

Unset baseline = no watchdog object = byte-identical pipeline.
"""

from __future__ import annotations

import json
from collections import deque
from typing import Dict, Optional

WINDOW = 64  # samples retained per size class
# A size class's first dispatches pay the jit compile inside their
# measured wall clock (driver.py: ``fn(pts, budget)`` compiles on first
# call, inside the ``dispatch_t0`` window) — seconds against a
# sub-millisecond steady state.  One such sample would dominate the
# ratio-of-sums for a full window and read as drift on a perfectly
# healthy replica, so the watchdog discards each class's first samples
# as warm-up before windowing begins.
WARMUP_SAMPLES = 2


def load_baseline(path: str) -> Optional[Dict[str, float]]:
    """Per-size-class baseline µs/trip from a committed artifact.

    Accepted shapes (first match wins per field):

      * ``BENCH_rNN.json`` — ``{"costmodel": {"us_per_trip": g,
        "size_classes": {cls: {"us_per_trip": x}}}}``;
      * a bare costmodel object of the same shape;
      * a ``deppy profile --json`` report — per-class µs/trip derived
        from each class's ``solve_s``/``trips``, global fallback from
        ``trip_overhead.us_per_trip``.

    Returns ``{size_class: us_per_trip}`` with the global fallback
    under ``"*"``; None when the file is unreadable or carries no
    usable figure (the watchdog then stays disarmed)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    cm = doc.get("costmodel")
    if isinstance(cm, dict):
        doc = cm
    out: Dict[str, float] = {}
    classes = doc.get("size_classes")
    if isinstance(classes, dict):
        for cls, row in classes.items():
            if not isinstance(row, dict):
                continue
            us = row.get("us_per_trip")
            if us is None and row.get("trips") and row.get("solve_s"):
                us = float(row["solve_s"]) * 1e6 / float(row["trips"])
            if isinstance(us, (int, float)) and us > 0:
                out[str(cls)] = float(us)
    glob = doc.get("us_per_trip")
    if glob is None and isinstance(doc.get("trip_overhead"), dict):
        glob = doc["trip_overhead"].get("us_per_trip")
    if isinstance(glob, (int, float)) and glob > 0:
        out["*"] = float(glob)
    return out or None


class CostModelWatchdog:
    """Registry forwarder comparing live effective µs/trip per size
    class against a committed baseline."""

    def __init__(self, baseline: Dict[str, float],
                 band: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 replica: Optional[str] = None,
                 registry=None):
        from .. import config, telemetry
        from ..analysis import lockdep
        from ..profile import sanitize_replica

        if band is None:
            band = config.env_float("DEPPY_TPU_OBS_DRIFT_BAND", 0.5,
                                    strict=False)
        if min_samples is None:
            min_samples = config.env_int("DEPPY_TPU_OBS_DRIFT_MIN", 8,
                                         strict=False)
        self.baseline = dict(baseline)
        self.band = float(band)
        self.min_samples = max(int(min_samples), 2)
        self.replica = sanitize_replica(replica)
        self._registry = (registry if registry is not None
                          else telemetry.default_registry())
        self._lock = lockdep.make_lock("obs.drift")
        self._windows: Dict[str, deque] = {}
        self._warmup: Dict[str, int] = {}
        self._ratios: Dict[str, dict] = {}
        self._alerted: set = set()

    @classmethod
    def from_baseline(cls, path: str, replica: Optional[str] = None,
                      **kw) -> Optional["CostModelWatchdog"]:
        baseline = load_baseline(path)
        if baseline is None:
            return None
        return cls(baseline, replica=replica, **kw)

    def install(self) -> None:
        self._registry.add_forwarder(self)

    def close(self) -> None:
        self._registry.remove_forwarder(self)

    # --------------------------------------------------------- event side

    def __call__(self, event: dict) -> None:
        if event.get("kind") != "profile":
            return
        trips = event.get("trips")
        solve_s = event.get("solve_s")
        if not trips or not solve_s:
            return
        cls = str(event.get("size_class_name")
                  or event.get("size_class") or "?")
        base = self.baseline.get(cls, self.baseline.get("*"))
        if base is None:
            return
        alert = None
        with self._lock:
            seen = self._warmup.get(cls, 0)
            if seen < WARMUP_SAMPLES:
                self._warmup[cls] = seen + 1
                return
            window = self._windows.get(cls)
            if window is None:
                window = self._windows[cls] = deque(maxlen=WINDOW)
            window.append((float(trips), float(solve_s)))
            if len(window) < self.min_samples:
                return
            sum_trips = sum(t for t, _ in window)
            if sum_trips <= 0:
                return
            live = 1e6 * sum(s for _, s in window) / sum_trips
            ratio = live / base
            drifted = abs(ratio - 1.0) > self.band
            self._ratios[cls] = {
                "live_us_per_trip": round(live, 3),
                "baseline_us_per_trip": round(base, 3),
                "ratio": round(ratio, 4),
                "samples": len(window),
                "drift": drifted,
            }
            if drifted and cls not in self._alerted:
                self._alerted.add(cls)
                alert = self._ratios[cls]
            elif not drifted:
                self._alerted.discard(cls)
        if alert is not None:
            fields = dict(alert, size_class=cls, band=self.band)
            fields.pop("drift", None)
            if self.replica:
                fields["replica"] = self.replica
            self._registry.event("costmodel_drift", **fields)

    # ------------------------------------------------------------- render

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {cls: dict(row)
                    for cls, row in self._ratios.items()}

    def render_metric_lines(self) -> list:
        with self._lock:
            rows = sorted(self._ratios.items())
        if not rows:
            return []
        rep = (f',replica="{self.replica}"' if self.replica else "")
        lines = [
            "# HELP deppy_costmodel_drift_ratio Live effective us/trip "
            "over the committed baseline per size class (1.0 = "
            "on-model; past the band = drift).",
            "# TYPE deppy_costmodel_drift_ratio gauge",
        ]
        for cls, row in rows:
            lines.append(
                f'deppy_costmodel_drift_ratio{{size_class="{cls}"{rep}}} '
                f"{row['ratio']}")
        lines += [
            "# HELP deppy_costmodel_us_per_trip Live effective us/trip "
            "per size class (windowed ratio of sums from sampled "
            "profile events).",
            "# TYPE deppy_costmodel_us_per_trip gauge",
        ]
        for cls, row in rows:
            lines.append(
                f'deppy_costmodel_us_per_trip{{size_class="{cls}"{rep}}} '
                f"{row['live_us_per_trip']}")
        return lines
