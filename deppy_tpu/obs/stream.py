"""Telemetry streaming: replica -> aggregator event push (ISSUE 16).

A :class:`TelemetryStreamer` registers as an event forwarder on the
process telemetry registry (:meth:`Registry.add_forwarder`), so every
JSONL sink event — profile, race, fault, lockdep, compileguard,
speculate, spans, flight-recorder dumps — is also enqueued for the
fleet aggregator, sink file or not.

Backpressure contract (the load-bearing part): ``enqueue`` NEVER
blocks and NEVER raises.  The queue is a bounded list under a named
lock; when a slow (or dead) aggregator lets it fill, further events
are dropped and counted (``deppy_obs_stream_dropped_total``) — serving
latency is unperturbed by observability.  A daemon thread drains the
queue in batches of ``DEPPY_TPU_OBS_BATCH`` at most every
``DEPPY_TPU_OBS_FLUSH_MS`` milliseconds, POSTing
``{"replica": ..., "events": [...]}`` to ``/fleet/telemetry`` on the
aggregator; a failed POST drops that batch (counted) rather than
requeueing it, so the queue bound is real.

After a failed POST the streamer additionally holds off for a bounded,
exponentially growing interval (ISSUE 17: doubling from the flush
period up to ``DEPPY_TPU_OBS_BACKOFF_MAX_S``) instead of re-hammering
a restarting aggregator at full flush cadence; the first successful
POST after a down streak resets the hold-off and is counted on
``deppy_obs_stream_reconnects_total``.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import List, Optional, Tuple

# The streamer's own families (registered on the process registry only
# while a streamer is armed; `obs.render_metric_lines` mirrors them
# onto the service /metrics).
STREAM_FAMILIES = (
    "deppy_obs_stream_events_total",
    "deppy_obs_stream_dropped_total",
    "deppy_obs_stream_batches_total",
    "deppy_obs_stream_errors_total",
    "deppy_obs_stream_reconnects_total",
)

POST_TIMEOUT_S = 5.0


def _parse_target(target: str) -> Tuple[str, int]:
    host, _, port = target.rpartition(":")
    return (host or "127.0.0.1"), int(port)


class TelemetryStreamer:
    """Bounded, non-blocking event pusher to the fleet aggregator."""

    def __init__(self, target: str, replica: Optional[str] = None,
                 queue_cap: Optional[int] = None,
                 batch: Optional[int] = None,
                 flush_ms: Optional[float] = None,
                 registry=None):
        from .. import config, telemetry
        from ..analysis import lockdep
        from ..profile import sanitize_replica

        self.target = target
        self._host, self._port = _parse_target(target)
        self.replica = sanitize_replica(replica) or "unknown"
        if queue_cap is None:
            queue_cap = config.env_int("DEPPY_TPU_OBS_QUEUE", 4096,
                                       strict=False)
        if batch is None:
            batch = config.env_int("DEPPY_TPU_OBS_BATCH", 256,
                                   strict=False)
        if flush_ms is None:
            flush_ms = config.env_float("DEPPY_TPU_OBS_FLUSH_MS", 200.0,
                                        strict=False)
        self._cap = max(int(queue_cap), 1)
        self._batch = max(int(batch), 1)
        self._flush_s = max(float(flush_ms), 1.0) / 1000.0
        backoff_max = config.env_float("DEPPY_TPU_OBS_BACKOFF_MAX_S",
                                       5.0, strict=False)
        self._backoff_max_s = max(float(backoff_max), 0.0)
        self._backoff_s = 0.0     # current hold-off (0 = healthy)
        self._retry_at = 0.0      # monotonic deadline of the hold-off
        self._down = False        # last POST failed
        self._lock = lockdep.make_lock("obs.stream")
        self._queue: List[dict] = []
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registry = (registry if registry is not None
                          else telemetry.default_registry())
        reg = self._registry
        self._c_events = reg.counter(
            "deppy_obs_stream_events_total",
            "Telemetry events enqueued for fleet streaming.")
        self._c_dropped = reg.counter(
            "deppy_obs_stream_dropped_total",
            "Telemetry events dropped on a full streamer queue (slow "
            "or dead aggregator) — the backpressure valve; serving "
            "never blocks on observability.")
        self._c_batches = reg.counter(
            "deppy_obs_stream_batches_total",
            "Telemetry batches delivered to the fleet aggregator.")
        self._c_errors = reg.counter(
            "deppy_obs_stream_errors_total",
            "Telemetry batch POSTs that failed (batch dropped, not "
            "requeued).")
        self._c_reconnects = reg.counter(
            "deppy_obs_stream_reconnects_total",
            "Successful POSTs that ended a failed-POST streak: the "
            "streamer resumed after its bounded exponential hold-off "
            "(ISSUE 17).")

    # --------------------------------------------------------- event side

    def __call__(self, event: dict) -> None:
        """The registry-forwarder entry point."""
        self.enqueue(event)

    def enqueue(self, event: dict) -> None:
        with self._lock:
            if len(self._queue) >= self._cap:
                dropped = True
                depth = len(self._queue)
            else:
                self._queue.append(event)
                dropped = False
                depth = len(self._queue)
        if dropped:
            self._c_dropped.inc()
            return
        self._c_events.inc()
        if depth >= self._batch:
            self._wake.set()

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # --------------------------------------------------------- drain side

    def start(self) -> None:
        if self._thread is not None:
            return
        self._registry.add_forwarder(self)
        self._thread = threading.Thread(
            target=self._run, name="obs-stream", daemon=True)
        self._thread.start()

    def close(self, drain_s: float = 2.0) -> None:
        """Detach from the registry, flush what's queued, stop."""
        self._registry.remove_forwarder(self)
        self._stop.set()
        self._wake.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=drain_s)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self._flush_s)
            self._wake.clear()
            self.flush()
        self.flush()

    def flush(self) -> None:
        """Drain the queue in batches; called from the drain thread and
        from tests.  While a failed-POST hold-off is pending, flush is
        a no-op (events keep queueing, bounded as ever) — except the
        final ``close()`` flush, which bypasses the hold-off for one
        last delivery attempt."""
        while True:
            if self._down and not self._stop.is_set() \
                    and time.monotonic() < self._retry_at:
                return
            with self._lock:
                batch = self._queue[: self._batch]
                del self._queue[: len(batch)]
            if not batch:
                return
            if self._post(batch):
                self._c_batches.inc()
                if self._down:
                    self._down = False
                    self._backoff_s = 0.0
                    self._c_reconnects.inc()
            else:
                self._c_errors.inc()
                base = max(self._flush_s, 0.05)
                grown = self._backoff_s * 2.0 if self._backoff_s \
                    else base
                self._backoff_s = min(grown, self._backoff_max_s) \
                    if self._backoff_max_s else base
                self._retry_at = time.monotonic() + self._backoff_s
                self._down = True
                # This batch is dropped (the queue bound stays real);
                # the REST of the queue waits out the hold-off rather
                # than feeding a dead aggregator batch after batch.
                return
            if len(batch) < self._batch:
                return

    def _post(self, batch: List[dict]) -> bool:
        body = json.dumps({"replica": self.replica,
                           "events": batch}).encode("utf-8")
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=POST_TIMEOUT_S)
        try:
            conn.request("POST", "/fleet/telemetry", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            return 200 <= resp.status < 300
        except (OSError, http.client.HTTPException):
            return False
        finally:
            conn.close()
