"""Catalog-shaped benchmark families from BASELINE.json.

Five workload generators modeling the operator-catalog resolution patterns
the reference framework was built for (OLM bundles, package version pins,
GVK uniqueness), sized per /root/repo/BASELINE.json configs:

1. :func:`operatorhub_catalog` — ~200 bundles across packages/channels,
   Mandatory roots + preference-ordered Dependency edges.
2. :func:`version_pinned_chains` — deep transitive chains with AtMost-1 per
   package (version pinning).
3. :func:`gvk_conflict_catalog` — Conflict-heavy GVK-uniqueness problems.
4. :func:`pinned_tenant_catalog` — UNSAT-heavy version-pin collisions
   (tenants pinning incompatible providers of a shared GVK).
5. :func:`fleet_states` — N independent cluster states over a shared
   catalog: the fleet-scale batched workload.
6. :func:`giant_pinned_conflict` — ONE giant unsatisfiable catalog (a
   3-constraint core buried in ~1.7k constraints): the host-routed
   core-extraction workload.
"""

from __future__ import annotations

import random
from typing import List

from ..sat.constraints import (
    Variable,
    at_most,
    conflict,
    dependency,
    mandatory,
)


def operatorhub_catalog(
    n_packages: int = 40,
    versions_per_package: int = 5,
    seed: int = 0,
) -> List[Variable]:
    """Mandatory+Dependency catalog: each package ships several versions
    (newest preferred), one root package per problem is mandatory, and each
    version depends on a random other package (any of its versions, newest
    preferred).  ~``n_packages * versions_per_package`` bundles."""
    rng = random.Random(seed)
    out: List[Variable] = []
    for p in range(n_packages):
        vids = [f"p{p}.v{v}" for v in range(versions_per_package)]
        # Version pinning: at most one installed version per package.
        out.append(
            Variable(
                f"p{p}",
                (mandatory(), dependency(*vids), at_most(1, *vids))
                if p == 0
                else (dependency(*vids), at_most(1, *vids)),
            )
        )
        for v, vid in enumerate(vids):
            cons = []
            if p + 1 < n_packages and rng.random() < 0.6:
                dep = rng.randrange(p + 1, n_packages)
                cons.append(dependency(f"p{dep}"))
            out.append(Variable(vid, tuple(cons)))
    return out


def version_pinned_chains(
    depth: int = 20,
    width: int = 3,
    seed: int = 0,
) -> List[Variable]:
    """Deep transitive dependency chains with AtMost-1 version pins: package
    i at each chain level offers ``width`` versions, the mandatory root
    pulls level 0, and each version depends on some version of the next
    level (preference order = newest first)."""
    rng = random.Random(seed)
    out: List[Variable] = [
        Variable("root", (mandatory(), dependency(*[f"l0.v{w}" for w in range(width)])))
    ]
    for level in range(depth):
        vids = [f"l{level}.v{w}" for w in range(width)]
        out.append(Variable(f"l{level}", (at_most(1, *vids),)))
        for vid in vids:
            cons = []
            if level + 1 < depth:
                nxt = [f"l{level + 1}.v{w}" for w in range(width)]
                rng.shuffle(nxt)
                cons.append(dependency(*nxt))
            out.append(Variable(vid, tuple(cons)))
    return out


def gvk_conflict_catalog(
    n_groups: int = 20,
    providers_per_group: int = 4,
    n_required: int = 10,
    seed: int = 0,
) -> List[Variable]:
    """GVK-uniqueness style: each API group has several providers that all
    conflict pairwise (only one provider of a GVK may be installed
    cluster-wide); ``n_required`` groups must be satisfied."""
    rng = random.Random(seed)
    out: List[Variable] = []
    for g in range(n_groups):
        provs = [f"g{g}.op{i}" for i in range(providers_per_group)]
        required = g < n_required
        out.append(
            Variable(
                f"gvk{g}",
                (mandatory(), dependency(*provs)) if required else (dependency(*provs),),
            )
        )
        for i, pid in enumerate(provs):
            cons = [conflict(other) for other in provs[:i]]
            if rng.random() < 0.3:
                peer = rng.randrange(n_groups)
                if peer != g:
                    cons.append(dependency(f"gvk{peer}"))
            out.append(Variable(pid, tuple(cons)))
    return out


def pinned_tenant_catalog(
    n_groups: int = 8,
    providers_per_group: int = 3,
    n_tenants: int = 4,
    pin_pool: int = 2,
    seed: int = 0,
) -> List[Variable]:
    """Version-pin collision workload: the UNSAT-heavy fleet shape.

    A GVK catalog (providers of a group conflict pairwise) plus
    ``n_tenants`` mandatory tenants, each *pinning* one exact provider
    drawn from the first ``pin_pool`` groups.  Two tenants pinning
    different providers of the same group make the cluster state
    unsatisfiable with a small, human-readable core (tenant A is
    mandatory, requires pA; tenant B is mandatory, requires pB; pA
    conflicts with pB) — the "two operators demand incompatible
    dependencies" failure the reference's README walks through
    (README.md:77-107).  With the defaults ~90% of seeds are UNSAT
    (P(SAT) ≈ 0.10 by direct enumeration; measured 1823/2048), so a
    fleet of these exercises the unsat-core phase at scale (the
    gated/compacted core strategies in the driver)."""
    rng = random.Random(seed)
    out: List[Variable] = []
    for g in range(n_groups):
        provs = [f"g{g}.op{i}" for i in range(providers_per_group)]
        out.append(Variable(f"gvk{g}", (dependency(*provs),)))
        for i, pid in enumerate(provs):
            out.append(Variable(pid, tuple(conflict(o) for o in provs[:i])))
    for t in range(n_tenants):
        g = rng.randrange(min(pin_pool, n_groups))
        p = rng.randrange(providers_per_group)
        out.append(
            Variable(f"tenant{t}", (mandatory(), dependency(f"g{g}.op{p}")))
        )
    return out


def giant_pinned_conflict(
    n_packages: int = 250,
    versions_per_package: int = 8,
    seed: int = 0,
) -> List[Variable]:
    """ONE giant unsatisfiable catalog: an :func:`operatorhub_catalog`
    (~``n_packages * versions_per_package`` bundles, ~1.7k applied
    constraints at the defaults) plus two mandatory pins that conflict —
    the cluster-wide "two mandatory operators are incompatible" failure
    at full catalog scale.  The answer is a 3-constraint core buried in
    thousands of irrelevant constraints: the workload that exercises
    host-routed core extraction (engine.driver.HOST_CORE_NCONS) and,
    historically, the long-device-program worker crash it guards against
    (BASELINE.md round-3 notes)."""
    out = list(operatorhub_catalog(n_packages, versions_per_package, seed))
    out.append(Variable("pin-a", (mandatory(), conflict("pin-b"))))
    out.append(Variable("pin-b", (mandatory(),)))
    return out


def fleet_states(
    n_states: int,
    base_seed: int = 0,
    generator=gvk_conflict_catalog,
    **kwargs,
) -> List[List[Variable]]:
    """``n_states`` independent problems over the same catalog family —
    the fleet-scale batched workload (BASELINE.json config 5)."""
    return [generator(seed=base_seed + i, **kwargs) for i in range(n_states)]
