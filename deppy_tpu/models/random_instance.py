"""Random-instance generator.

Re-creation of the reference benchmark's seeded random problem
(/root/reference/pkg/sat/bench_test.go:10-64): ``length`` variables named by
their index, each independently given a Mandatory constraint with
probability ``p_mandatory``, a Dependency on 1..n_dependency-1 random other
variables with probability ``p_dependency``, and 1..n_conflict-1 Conflict
constraints with probability ``p_conflict``.  Python's ``random`` replaces
Go's ``math/rand`` so literal streams differ, but the distribution matches.
"""

from __future__ import annotations

import random
from typing import List

from ..sat.constraints import Constraint, Variable, conflict, dependency, mandatory


def random_instance(
    length: int = 256,
    seed: int = 9,
    p_mandatory: float = 0.1,
    p_dependency: float = 0.15,
    n_dependency: int = 6,
    p_conflict: float = 0.05,
    n_conflict: int = 3,
) -> List[Variable]:
    rng = random.Random(seed)

    def other(i: int) -> int:
        if length < 2:
            return i
        y = i
        while y == i:
            y = rng.randrange(length)
        return y

    out: List[Variable] = []
    for i in range(length):
        cons: List[Constraint] = []
        if rng.random() < p_mandatory:
            cons.append(mandatory())
        if rng.random() < p_dependency:
            n = rng.randrange(1, n_dependency)
            cons.append(dependency(*[str(other(i)) for _ in range(n)]))
        if rng.random() < p_conflict:
            n = rng.randrange(1, n_conflict)
            for _ in range(n):
                cons.append(conflict(str(other(i))))
        out.append(Variable(str(i), tuple(cons)))
    return out
