"""Benchmark problem families.

Generators for the workload configs recorded in BASELINE.json plus the
reference's random-instance benchmark generator
(/root/reference/pkg/sat/bench_test.go:10-64).  These are the "model zoo"
of a constraint-resolution framework: realistic catalog shapes used for
conformance fuzzing, differential testing, and performance measurement.
"""

from .random_instance import random_instance
from .catalog import (
    fleet_states,
    giant_pinned_conflict,
    gvk_conflict_catalog,
    operatorhub_catalog,
    pinned_tenant_catalog,
    version_pinned_chains,
)

__all__ = [
    "fleet_states",
    "giant_pinned_conflict",
    "gvk_conflict_catalog",
    "operatorhub_catalog",
    "pinned_tenant_catalog",
    "random_instance",
    "version_pinned_chains",
]
