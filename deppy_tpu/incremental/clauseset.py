"""Clause-set fingerprinting and delta classification (ISSUE 10, piece 1).

PR 3's canonical fingerprint is all-or-nothing: one changed bundle in a
catalog flips the digest and the whole cache misses.  This module
fingerprints each lowered problem at CLAUSE granularity — a multiset of
per-row keys over the problem-variable literals (activation literals are
dropped: they are positional bookkeeping that shifts when the applied
list shifts, while the solve treats them as constant TRUE) plus the
decode-vocabulary key — so a delta request can be matched against the
NEAREST cached solve and classified instead of rejected:

  * ``identical``  — same clause/cardinality multiset (the exact digest
    may still differ: constraint strings are not solve-relevant);
  * ``additive``   — rows added only;
  * ``retractive`` — rows removed only;
  * ``mixed``      — both.

For a classified delta the **touched cone** is the variable set
reachable from the changed rows through shared literals, closed over
the union of both problems' structural rows — by construction no clause
or cardinality row spans the cone boundary, which is exactly the
decomposition :meth:`deppy_tpu.sat.host.HostEngine.solve_warm` certifies
against.  The warm plan gates (cached solve was SAT with zero search
backtracks, cone fraction under the ``DEPPY_TPU_INCREMENTAL_MAX_DELTA``
cutoff, generous step budget) keep every served warm start inside the
regime where warm output provably equals cold output; anything outside
falls back to a cold solve.
"""

from __future__ import annotations

import time
from collections import Counter, OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry
from ..sat.encode import Problem

DELTA_IDENTICAL = "identical"
DELTA_ADDITIVE = "additive"
DELTA_RETRACTIVE = "retractive"
DELTA_MIXED = "mixed"
# ISSUE 20: a stateful session's scoped solve, planned from the delta
# the session DECLARED (its assumption-stack diff) instead of from
# per-row classification — the O(delta) fast path of plan_for_scope().
DELTA_SCOPED = "scoped"

# Nearest-entry search is a multiset intersection per candidate; bound
# the scan to the most recent entries of the vocabulary bucket so a huge
# index cannot turn every lookup into a linear walk, and stop early at
# an entry within ACCEPT_DELTA changed rows — a single-row delta cannot
# meaningfully be beaten (a 0-row twin would classify identical, but
# both serve from the same cached model).  Anything looser was measured
# to pick a 2-row neighbor spanning TWO bundles over a 1-row neighbor
# spanning one, inflating the cone past the serve cutoff.
SCAN_CAP = 32
ACCEPT_DELTA = 1

# Warm serving is certified for models/cores, but a warm solve does less
# WORK than a cold solve — under a pathologically tight step budget the
# cold run could exhaust (Incomplete) where the warm run finishes.  The
# tier therefore engages only under budgets generously above the cached
# solve's measured cost; tighter budgets take the cold path unchanged.
MIN_WARM_BUDGET = 1 << 16
WARM_BUDGET_FACTOR = 16


def problem_rows(problem: Problem) -> "Counter[tuple]":
    """The problem's structural-row multiset: one key per clause and one
    per cardinality row.  Activation literals are dropped — see the
    module docstring.  Two deliberate asymmetries:

      * Clause literals keep their EMITTED order, and each clause key
        carries its ordinal among its subject variable's clauses.  Both
        are preference-relevant: a dependency's candidate order decides
        which candidate the search guesses first, and a variable's
        constraint order decides the order its choices spawn — sorting
        either away once served a cached model for a problem whose cold
        solve prefers a different candidate (byte-identity break, caught
        in review).
      * Cardinality members ARE sorted: counting true members is
        order-invariant and spawns no choices.

    Memoized on the problem object: classification and store both need
    it, and rows never change after encode()."""
    memo = problem.__dict__.get("_inc_rows")
    if memo is not None:
        return memo
    n = problem.n_vars
    rows: "Counter[tuple]" = Counter()
    c = problem.clauses
    per_subject: Dict[int, int] = {}
    if c.size:
        kept = np.where(np.abs(c) <= n, c, 0)
        for row in kept:
            lits = tuple(row[row != 0].tolist())
            subj = abs(lits[0]) - 1 if lits else -1
            ordinal = per_subject.get(subj, 0)
            per_subject[subj] = ordinal + 1
            rows[("c", ordinal) + lits] += 1
    for ids_row, bound in zip(problem.card_ids, problem.card_n):
        members = ids_row[ids_row >= 0]
        rows[("k", int(bound)) + tuple(sorted(members.tolist()))] += 1
    problem.__dict__["_inc_rows"] = rows
    return rows


def vocab_key(problem: Problem) -> Tuple[int, tuple]:
    """Decode-vocabulary identity: variable identifiers in input order.
    Warm starts require index-aligned models, so only same-vocabulary
    problems are comparable.  (Applied-constraint strings are NOT part
    of this key — they are exactly what churn changes.)"""
    memo = problem.__dict__.get("_inc_vocab")
    if memo is not None:
        return memo
    key = (problem.n_vars,
           tuple(str(v.identifier) for v in problem.variables))
    problem.__dict__["_inc_vocab"] = key
    return key


def _row_vars(key: tuple) -> List[int]:
    """0-based problem-var indices of one row key (clause keys are
    ``("c", ordinal, *lits)``, cardinality keys ``("k", bound,
    *members)``)."""
    if key[0] == "c":
        return [abs(lit) - 1 for lit in key[2:]]
    return list(key[2:])


def touched_cone(problem: Problem, seed_vars, extra_rows) -> np.ndarray:
    """Close ``seed_vars`` over shared-literal adjacency: any structural
    row (of the NEW problem, plus ``extra_rows`` — the removed rows of
    the old one) sharing a variable with the cone pulls all its
    variables in.  At the fixpoint every row is wholly inside or wholly
    outside the cone, so the problem decomposes across the boundary."""
    n = problem.n_vars
    cone = np.zeros(n, dtype=bool)
    seed = [v for v in seed_vars if 0 <= v < n]
    if not seed:
        return cone
    cone[seed] = True
    # Vectorized edges: clause rows (act literals masked off) and
    # cardinality member rows, padded with sentinel index ``n``.
    edges = []
    c = problem.clauses
    if c.size:
        kept = np.where(np.abs(c) <= n, np.abs(c), 0)
        edges.append(np.where(kept > 0, kept - 1, n))
    if problem.card_ids.size:
        m = problem.card_ids
        edges.append(np.where(m >= 0, m, n).astype(np.int64))
    extra = [np.asarray(_row_vars(k), dtype=np.int64)
             for k in extra_rows if _row_vars(k)]
    ext = np.zeros(n + 1, dtype=bool)
    while True:
        ext[:n] = cone
        grew = False
        for vm in edges:
            touched = ext[vm].any(axis=1)
            if touched.any():
                hit = vm[touched]
                hit = hit[hit < n]
                if not cone[hit].all():
                    cone[hit] = True
                    grew = True
        for row in extra:
            if cone[row].any() and not cone[row].all():
                cone[row] = True
                grew = True
        if not grew:
            return cone


class _Entry:
    __slots__ = ("key", "_rows", "vocab", "model", "steps", "backtracks",
                 "_problem")

    def __init__(self, key: str, rows: "Optional[Counter[tuple]]", vocab,
                 model: np.ndarray, steps: int, backtracks: int,
                 problem: Optional[Problem] = None):
        self.key = key
        # ``rows=None`` defers the per-row multiset to first use (ISSUE
        # 20: a session's private-index store happens per interactive
        # step, and the scoped planner never reads rows — only the
        # generic-classifier fallback and the snapshot export do, so
        # eager O(problem) hashing there is latency for nothing).
        self._rows = rows
        self._problem = problem if rows is None else None
        self.vocab = vocab
        self.model = model            # bool[n_vars], the final installed set
        self.steps = int(steps)
        self.backtracks = int(backtracks)

    @property
    def rows(self) -> "Counter[tuple]":
        rows = self._rows
        if rows is None:
            prob = self._problem
            if prob is None:
                # Another thread materialized between our None check
                # and the problem read — its assignment is ordered
                # before the clear.
                return self._rows
            rows = problem_rows(prob)
            self._rows = rows
            self._problem = None
        return rows


class WarmPlan:
    """Everything one lane needs to attempt a warm-started solve."""

    __slots__ = ("problem", "key", "warm_assign", "cone", "klass",
                 "cone_fraction", "entry_key", "entry_steps")

    def __init__(self, problem: Problem, key: str, warm_assign: np.ndarray,
                 cone: np.ndarray, klass: str, cone_fraction: float,
                 entry_key: str, entry_steps: int):
        self.problem = problem
        self.key = key
        self.warm_assign = warm_assign  # int8[n_vars], cached model
        self.cone = cone                # bool[n_vars], to re-solve
        self.klass = klass
        self.cone_fraction = cone_fraction
        self.entry_key = entry_key
        self.entry_steps = entry_steps


class ClauseSetIndex:
    """Thread-safe LRU of solved clause-set fingerprints — the
    delta-aware tier in front of the exact-fingerprint result cache.

    ``plan()`` classifies an exact-miss problem against the nearest
    same-vocabulary entry and returns a :class:`WarmPlan` when every
    warm-identity gate passes; ``store()`` records SAT solves that are
    warm-start seeds (zero search backtracks).  Counters and the cone
    histogram land on the registry the scheduler was built with."""

    def __init__(self, capacity: int = 512,
                 max_delta_ratio: float = 0.25,
                 registry: Optional[telemetry.Registry] = None):
        from ..analysis import lockdep

        self.capacity = max(int(capacity), 0)
        self.max_delta_ratio = float(max_delta_ratio)
        self._lock = lockdep.make_lock("incremental.index")
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._by_vocab: Dict[tuple, "OrderedDict[str, None]"] = {}
        reg = registry if registry is not None \
            else telemetry.default_registry()
        self._registry = reg
        self._c_hits = reg.counter(
            "deppy_incremental_hits_total",
            "Warm-started solves served from the incremental tier.")
        self._c_fallbacks = reg.counter(
            "deppy_incremental_warm_fallbacks_total",
            "Warm-start attempts that fell back to a cold solve "
            "(prefix conflict, cone backtrack, budget).")
        self._c_delta = reg.counter(
            "deppy_incremental_delta_total",
            "Delta classifications against the clause-set index, by "
            "class (identical / additive / retractive / mixed / "
            "scoped / none).",
            labelname="class")
        self._h_cone = reg.histogram(
            "deppy_incremental_cone_fraction",
            "Touched-cone size as a fraction of problem variables, per "
            "planned warm start.",
            buckets=telemetry.RATIO_BUCKETS)
        self._n_lookups = 0
        self._n_hits = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # ------------------------------------------------------------ store

    def store(self, key: str, problem: Problem, model: np.ndarray,
              steps: int, backtracks: int,
              lazy_rows: bool = False) -> None:
        """Record one SAT solve.  Only zero-backtrack solves are
        warm-start seeds (the certification precondition), so anything
        else is dropped here rather than filtered on every lookup.
        ``lazy_rows=True`` (the scoped session store) defers the
        O(problem) per-row hashing to first use — the scoped planner
        never reads it."""
        if self.capacity == 0 or int(backtracks) != 0:
            return
        rows = None if lazy_rows else problem_rows(problem)
        vocab = vocab_key(problem)
        model = np.asarray(model, dtype=bool).copy()
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self._entries[key] = _Entry(key, rows, vocab, model,
                                            steps, backtracks,
                                            problem=problem)
                # Refresh bucket recency too: the nearest-entry scan is
                # bounded to the most recent bucket keys, and a cycling
                # catalog re-stores old fingerprints — without the touch
                # the scan window drifts away from the live neighbors.
                bucket = self._by_vocab.get(vocab)
                if bucket is not None and key in bucket:
                    bucket.move_to_end(key)
                return
            self._admit_locked(_Entry(key, rows, vocab, model,
                                      steps, backtracks, problem=problem))

    def _admit_locked(self, entry: _Entry) -> None:
        """Insert a NEW entry (caller holds the lock; ``entry.key``
        not resident) and evict past capacity, keeping ``_entries``
        and ``_by_vocab`` in sync — the one copy of the eviction
        invariant, shared by ``store`` and ``import_entry``."""
        self._entries[entry.key] = entry
        bucket = self._by_vocab.setdefault(entry.vocab, OrderedDict())
        bucket[entry.key] = None
        while len(self._entries) > self.capacity:
            old_key, old = self._entries.popitem(last=False)
            ob = self._by_vocab.get(old.vocab)
            if ob is not None:
                ob.pop(old_key, None)
                if not ob:
                    del self._by_vocab[old.vocab]

    def export_entries(self) -> List[_Entry]:
        """Every resident entry, least recently used first (so an
        importer replaying the list reproduces this index's recency
        order) — the fleet snapshot/handoff surface (ISSUE 15)."""
        with self._lock:
            return list(self._entries.values())

    def import_entry(self, key: str, rows: "Counter[tuple]", vocab,
                     model: np.ndarray, steps: int,
                     backtracks: int) -> bool:
        """Admit one deserialized entry (the snapshot handoff path).
        Returns False without touching anything when ``key`` is already
        resident — the live entry is at least as fresh as the handed-off
        copy — or when the entry is not a certified warm seed (the
        store() gate: only zero-backtrack SAT models may seed warm
        starts, and a tampered snapshot must not widen that).  Raises
        ``ValueError`` when the model is not index-aligned with the
        entry's vocabulary: admitting a misaligned entry would plant a
        crash on the live warm path for that family's next delta."""
        if self.capacity == 0 or int(backtracks) != 0:
            return False
        model = np.asarray(model, dtype=bool).copy()
        if model.shape != (int(vocab[0]),):
            raise ValueError(
                f"model length {model.size} does not match the entry "
                f"vocabulary ({vocab[0]} variables)")
        with self._lock:
            if key in self._entries:
                return False
            self._admit_locked(_Entry(key, rows, vocab, model,
                                      steps, backtracks))
        return True

    def touch(self, key: str) -> None:
        """Refresh ``key``'s LRU and bucket recency without re-storing.
        Called on EXACT result-cache hits: those bypass the solve (and
        therefore the store-side recency refresh), and a cycling
        catalog would otherwise drift the bounded nearest-entry scan
        window away from the states traffic is actually revisiting."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return
            self._entries.move_to_end(key)
            bucket = self._by_vocab.get(entry.vocab)
            if bucket is not None and key in bucket:
                bucket.move_to_end(key)

    # ------------------------------------------------------------- plan

    def plan(self, problem: Problem, key: str, budget: int,
             account: bool = True) -> Optional[WarmPlan]:
        """Classify ``problem`` against the nearest cached entry and
        return a warm plan when certifiable, else None.  Spanned as
        ``incremental.delta`` with the class and cone size.

        ``account=False`` (ISSUE 14: the read-only preview tier) skips
        the lookup/delta/cone accounting AND the span: a what-if
        consultation that never serves must not deflate the serving
        tier's hit ratio or inflate its delta counters."""
        if self.capacity == 0:
            return None
        t0 = time.perf_counter()
        plan = self._plan_inner(problem, key, budget, account)
        if account:
            self._registry.record_span(
                "incremental.delta", time.perf_counter() - t0,
                klass=plan.klass if plan is not None else "none",
                cone=int(plan.cone.sum()) if plan is not None else 0)
        return plan

    def _plan_inner(self, problem: Problem, key: str, budget: int,
                    account: bool = True) -> Optional[WarmPlan]:
        vocab = vocab_key(problem)
        with self._lock:
            if account:
                self._n_lookups += 1
            empty = not self._by_vocab.get(vocab)
        if empty:
            # No comparable entry: skip the per-row hashing entirely —
            # a cold fleet's first pass must not pay the delta tier.
            if account:
                self._c_delta.inc(label="none")
            return None
        rows = problem_rows(problem)
        with self._lock:
            entry = self._nearest_locked(vocab, rows)
        if entry is None:
            if account:
                self._c_delta.inc(label="none")
            return None
        added = rows - entry.rows
        removed = entry.rows - rows
        if not added and not removed:
            klass = DELTA_IDENTICAL
        elif not removed:
            klass = DELTA_ADDITIVE
        elif not added:
            klass = DELTA_RETRACTIVE
        else:
            klass = DELTA_MIXED
        if account:
            self._c_delta.inc(label=klass)
        seed: List[int] = []
        for k in list(added) + list(removed):
            seed.extend(_row_vars(k))
        cone = touched_cone(problem, seed, removed.keys())
        fraction = float(cone.sum()) / max(problem.n_vars, 1)
        if fraction > self.max_delta_ratio:
            return None
        if int(budget) < max(MIN_WARM_BUDGET,
                             WARM_BUDGET_FACTOR * (entry.steps + 1)):
            return None
        warm_assign = np.where(entry.model, 1, -1).astype(np.int8)
        if account:
            self._h_cone.observe(fraction)
        return WarmPlan(problem, key, warm_assign, cone, klass, fraction,
                        entry.key, entry.steps)

    def _nearest_locked(self, vocab, rows) -> Optional[_Entry]:
        bucket = self._by_vocab.get(vocab)
        if not bucket:
            return None
        best = None
        best_delta = None
        n_rows = sum(rows.values())
        # Most recent entries first (churn clusters in time); nearest =
        # SMALLEST symmetric difference, not largest intersection — two
        # ancestors can share equally many rows while one carries extra
        # baggage that would all land in the cone.
        for k in list(reversed(bucket))[:SCAN_CAP]:
            entry = self._entries.get(k)
            if entry is None:
                continue
            shared = sum((rows & entry.rows).values())
            delta = (n_rows - shared) + (sum(entry.rows.values()) - shared)
            if best_delta is None or delta < best_delta:
                best, best_delta = entry, delta
            if best_delta <= ACCEPT_DELTA:
                break
        return best

    # ------------------------------------------- scoped planning (ISSUE 20)

    def plan_for_scope(self, problem: Problem, key: str, budget: int,
                       entry_key: str, seed_vars) -> Optional[WarmPlan]:
        """O(delta) warm planning for a stateful session's scoped solve.

        A session KNOWS its delta: successive scoped solves differ from
        each other only in the assumption-derived unit constraints on
        the variables whose assumptions changed — ``seed_vars``, the
        symmetric difference of the two assumption stacks.  That makes
        the generic :meth:`plan` pipeline's per-row multiset hashing and
        nearest-entry scan (both O(problem), paid per step) pure
        overhead here: this path looks the declared predecessor up by
        ``entry_key`` directly and closes the declared seed over
        shared-literal adjacency, so the per-step planning cost scales
        with the CHANGE, not the catalog.

        Identity is preserved by construction plus certification: every
        added/removed row is a unit constraint whose subject variable is
        in ``seed_vars`` (per-subject clause ordinals shift only for
        those same subjects), so the fixpoint cone contains every
        differing row and off-cone rows are byte-identical between the
        entry's problem and this one — the same decomposition invariant
        :meth:`plan` establishes, with
        :meth:`deppy_tpu.sat.host.HostEngine.solve_warm` still the
        authoritative certifier (any imperfect plan falls back to a
        cold solve, answers unchanged).  The serve gates — entry is a
        zero-backtrack seed (enforced at :meth:`store`), cone fraction
        under ``max_delta_ratio``, generous budget — are the generic
        path's gates, unweakened."""
        if self.capacity == 0:
            return None
        t0 = time.perf_counter()
        with self._lock:
            self._n_lookups += 1
            entry = self._entries.get(entry_key)
        plan = None
        if entry is not None and entry.vocab == vocab_key(problem):
            cone = touched_cone(problem, seed_vars, ())
            fraction = float(cone.sum()) / max(problem.n_vars, 1)
            if (fraction <= self.max_delta_ratio
                    and int(budget) >= max(
                        MIN_WARM_BUDGET,
                        WARM_BUDGET_FACTOR * (entry.steps + 1))):
                warm_assign = np.where(entry.model, 1, -1).astype(np.int8)
                self._h_cone.observe(fraction)
                plan = WarmPlan(problem, key, warm_assign, cone,
                                DELTA_SCOPED, fraction, entry.key,
                                entry.steps)
        self._c_delta.inc(
            label=DELTA_SCOPED if plan is not None else "none")
        self._registry.record_span(
            "incremental.delta", time.perf_counter() - t0,
            klass=plan.klass if plan is not None else "none",
            cone=int(plan.cone.sum()) if plan is not None else 0)
        return plan

    # ------------------------------------------------- affected (ISSUE 14)

    def affected_keys(self, identifiers) -> List[str]:
        """Fingerprints of indexed solves a catalog publish touches,
        most recently stored first: an entry is affected when some
        structural row (clause or cardinality) mentions a changed
        identifier — the per-row keys store literals as vocab indices,
        so membership is a vocab-index lookup plus a row scan.  A
        changed identifier absent from an entry's vocabulary cannot
        affect it (no row can reference an unknown variable)."""
        wanted = frozenset(identifiers)
        if not wanted:
            return []
        out: List[str] = []
        with self._lock:
            entries = list(reversed(self._entries.values()))
        for entry in entries:
            idx = {i for i, ident in enumerate(entry.vocab[1])
                   if ident in wanted}
            if not idx:
                continue
            for row_key in entry.rows:
                if any(v in idx for v in _row_vars(row_key)):
                    out.append(entry.key)
                    break
        return out

    # -------------------------------------------------------- accounting

    def note_served(self) -> None:
        with self._lock:
            self._n_hits += 1
        self._c_hits.inc()

    def note_fallback(self) -> None:
        self._c_fallbacks.inc()

    def hit_ratio(self) -> float:
        """Warm starts served / incremental lookups (exact-cache misses
        that consulted this tier)."""
        with self._lock:
            if self._n_lookups == 0:
                return 0.0
            return round(self._n_hits / self._n_lookups, 4)
