"""Delta-aware incremental resolution (ISSUE 10).

Catalog churn re-asks 99%-identical problems; this subsystem turns those
re-solves from full searches into near-lookups:

  * :mod:`.clauseset` — clause-level fingerprinting: a
    :class:`ClauseSetIndex` of solved problems keyed by per-row hashes
    plus the decode vocabulary, with a delta extractor classifying new
    requests as {identical, additive, retractive, mixed} and computing
    the touched cone (variables reachable from changed rows through
    shared literals);
  * :mod:`.warm` — warm-start execution: seed the assignment from the
    cached model outside the cone, re-solve the cone only
    (``HostEngine.solve_warm``), fall back to a cold solve whenever
    byte-identity cannot be certified; plus the batched device
    prefix screen.

The scheduler (:mod:`deppy_tpu.sched`) wires the index in front of its
exact-fingerprint result cache and drains warm lanes as their own
"incremental" size class; ``DEPPY_TPU_INCREMENTAL=off`` removes the tier
entirely and restores the pre-change dispatch byte for byte.
"""

from .clauseset import (  # noqa: F401
    DELTA_ADDITIVE,
    DELTA_IDENTICAL,
    DELTA_MIXED,
    DELTA_RETRACTIVE,
    DELTA_SCOPED,
    ClauseSetIndex,
    WarmPlan,
    problem_rows,
    touched_cone,
    vocab_key,
)
from .warm import attempt, screen  # noqa: F401
