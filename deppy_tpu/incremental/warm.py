"""Warm-start execution (ISSUE 10, piece 2).

One function, :func:`attempt`, runs a planned warm start on the host
spec engine and reports either the served lane result or None — the
caller (the scheduler's incremental lane class, or any library user)
answers None with a cold solve through its normal backend path, so the
fault domain, deadline triage, and breaker semantics of the cold path
apply unchanged to every fallback.

Results are shaped as :class:`deppy_tpu.hostpool.worker.HostLaneResult`
— the same value object every other host-path consumer decodes — so the
scheduler's decode code is shared, not parallel-maintained.

:func:`screen` is the batched DEVICE variant: assignment planes are
initialized from each lane's cached model (off-cone values pinned, cone
left open, activations true) and one lockstep pass flags lanes whose
warm prefix already conflicts — those lanes skip the host warm attempt
entirely and cold-solve with their batchmates.  The screen is a router:
the authoritative certification stays in ``HostEngine.solve_warm``.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from ..hostpool.worker import HostLaneResult
from .clauseset import WarmPlan


def attempt(plan: WarmPlan,
            max_steps: Optional[int] = None) -> Optional[HostLaneResult]:
    """Run one warm-started solve.  Returns the lane result on a served
    warm start, or None when the attempt fell back (warm prefix
    conflict, cone backtrack, budget exhaustion mid-warm) — the caller
    cold-solves.  ``InternalSolverError`` propagates: a malformed
    problem is an error either way."""
    from ..sat.errors import Incomplete
    from ..sat.host import HostEngine, WarmStartConflict

    eng = HostEngine(plan.problem, max_steps=max_steps)
    t0 = time.perf_counter()
    try:
        _, installed_idx = eng.solve_warm(plan.warm_assign, plan.cone)
    except (WarmStartConflict, Incomplete):
        # Fallback is control flow, not failure: the cold path answers.
        return None
    return HostLaneResult(
        "sat", list(installed_idx), [], eng.steps, eng.decisions,
        eng.propagation_rounds, eng.backtracks,
        time.perf_counter() - t0,
    )


def screen(plans: Sequence[WarmPlan]) -> List[bool]:
    """Batched device warm-prefix screen over one warm lane class.
    ``True`` means the prefix survived the lockstep check and the host
    warm attempt is worth paying; ``False`` routes the lane straight to
    the cold path.  Any screen failure (device fault) degrades to
    all-True — the host attempt re-checks authoritatively."""
    from .. import telemetry
    from ..engine import driver

    try:
        ok = driver.warm_screen(
            [p.problem for p in plans],
            [p.warm_assign > 0 for p in plans],
            [p.cone for p in plans])
        return [bool(v) for v in ok]
    except Exception as e:  # noqa: BLE001 — router only; host re-checks
        telemetry.default_registry().event(
            "fault", fault="incremental_screen_failed",
            error=type(e).__name__, lanes=len(plans))
        return [True] * len(plans)
