"""Offline route-health reconstruction — the ``deppy routes`` CLI.

The live plane never needs to be scraped to audit routing: every input
it folds — ``race`` events with censored-aware ``losers``, shadow
``route`` probes, ``route_stale`` crossings, ``route_learned``
adoptions — is already on the JSONL sink.  :func:`build_report` replays
a sink (or several, merged with cross-replica dedupe) through the SAME
:class:`~deppy_tpu.routes.ledger.RegretLedger` the live forwarder
drives, then joins the defaults store's provenance stamps, so the CLI
table is the live table recomputed from first principles.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from .ledger import RegretLedger


def build_report(events: Iterable[Optional[dict]],
                 rows_doc: Optional[dict] = None,
                 platform: Optional[str] = None,
                 decay: Optional[float] = None) -> dict:
    """Fold sink events into the `deppy routes` document.  ``rows_doc``
    (a defaults-store read) joins provenance; ``platform`` selects its
    backend section, defaulting to the platform the events themselves
    were stamped with."""
    ledger = RegretLedger(decay=decay)
    stale: Dict[str, dict] = {}
    learned: Dict[str, dict] = {}
    platforms: Dict[str, int] = {}
    n_events = 0
    for ev in events:
        if not isinstance(ev, dict):
            continue
        n_events += 1
        kind = ev.get("kind")
        if kind in ("race", "route"):
            ledger.fold(ev)
        elif kind == "route_stale":
            cls = ev.get("size_class_name")
            if cls:
                # Latest crossing wins — the sink is append-ordered, so
                # the last verdict per class is the current one.
                stale[str(cls)] = {
                    k: ev[k] for k in
                    ("reason", "key", "row", "age_s", "box", "replica")
                    if k in ev}
        elif kind == "route_learned":
            key = ev.get("key")
            if isinstance(key, str):
                learned[key] = {
                    k: ev[k] for k in
                    ("row", "source", "origin", "replica",
                     "est_us_per_lane", "size_class_name")
                    if k in ev}
                cls = ev.get("size_class_name")
                if cls:
                    # An adoption supersedes any earlier stale verdict
                    # for its class, exactly like the live watcher's
                    # mark_fresh().
                    stale.pop(str(cls), None)
        p = ev.get("platform")
        if isinstance(p, str) and p:
            platforms[p] = platforms.get(p, 0) + 1
    if platform is None and platforms:
        platform = max(sorted(platforms), key=platforms.get)

    snapshot = ledger.snapshot()
    estimates = ledger.estimates()
    provenance: Dict[str, dict] = {}
    if isinstance(rows_doc, dict) and platform:
        entry = rows_doc.get(platform)
        if isinstance(entry, dict):
            ev_map = entry.get("evidence")
            ev_map = ev_map if isinstance(ev_map, dict) else {}
            for key, row in entry.items():
                if key.startswith("portfolio") and isinstance(row, str):
                    provenance[key] = {"row": row,
                                       "evidence": ev_map.get(key)}

    classes: Dict[str, dict] = {}
    for cls in sorted(set(snapshot) | set(estimates) | set(stale)):
        doc = dict(snapshot.get(cls) or {})
        doc["estimates"] = estimates.get(cls, {})
        doc["stale"] = stale.get(cls)
        doc["learned"] = learned.get(f"portfolio.{cls}")
        prov = (provenance.get(f"portfolio.{cls}")
                or provenance.get("portfolio"))
        doc["registry"] = prov
        classes[cls] = doc

    total_regret = sum(
        s for c in classes.values()
        for s in (c.get("regret_s") or {}).values())
    return {
        "platform": platform,
        "events": n_events,
        "classes": classes,
        "shadow": ledger.shadow_counts(),
        "learned": learned,
        "totals": {
            "races": sum(c.get("races", 0) for c in classes.values()),
            "regret_s": round(total_regret, 6),
            "stale_classes": len(stale),
            "learned_rows": len(learned),
        },
    }


def render_text(report: dict) -> str:
    """The human table: one row per size class — races, default, win
    leader, regret charged to the default, staleness verdict, learned
    row."""
    lines: List[str] = []
    classes = report.get("classes") or {}
    totals = report.get("totals") or {}
    lines.append(
        f"route health — platform={report.get('platform') or '?'} "
        f"events={report.get('events', 0)} "
        f"races={totals.get('races', 0)} "
        f"regret={totals.get('regret_s', 0.0):.3f}s "
        f"stale={totals.get('stale_classes', 0)} "
        f"learned={totals.get('learned_rows', 0)}")
    if not classes:
        lines.append("  (no race/route events on the sink)")
        return "\n".join(lines)

    hdr = (f"  {'class':<10} {'races':>6} {'default':<10} "
           f"{'leader':<16} {'regret_s':>9} {'status':<22} learned")
    lines.append(hdr)
    lines.append("  " + "-" * (len(hdr) - 2))
    for cls, doc in classes.items():
        shares = doc.get("win_share") or {}
        if shares:
            top = max(sorted(shares), key=shares.get)
            leader = f"{top} {shares[top] * 100:.0f}%"
        else:
            leader = "-"
        regret = sum((doc.get("regret_s") or {}).values())
        stale = doc.get("stale")
        if stale:
            status = stale.get("reason", "?")
            if stale.get("age_s") is not None:
                status += f" ({stale['age_s'] / 86400.0:.1f}d)"
            elif stale.get("box"):
                status += f" ({stale['box']})"
        elif doc.get("learned"):
            status = "fresh (learned)"
        elif doc.get("registry"):
            status = "fresh"
        else:
            status = "-"
        learned = doc.get("learned") or {}
        lrow = learned.get("row", "-")
        if learned.get("source") == "gossip":
            lrow += f" (gossip:{learned.get('origin') or '?'})"
        lines.append(
            f"  {cls:<10} {doc.get('races', 0):>6} "
            f"{doc.get('default') or '-':<10} {leader:<16} "
            f"{regret:>9.3f} {status:<22} {lrow}")

    # Per-class backend estimates: the decayed µs-per-lane table the
    # online registry ranks by, censored counts alongside so a cancel-
    # heavy backend's missing estimate is explainable.
    lines.append("")
    lines.append(f"  {'class':<10} {'backend':<12} {'us/lane':>10} "
                 f"{'samples':>8} {'censored':>9}")
    for cls, doc in classes.items():
        for backend in sorted(doc.get("estimates") or {}):
            row = doc["estimates"][backend]
            us = row.get("us_per_lane")
            us_s = "-" if us is None else f"{us:.1f}"
            lines.append(
                f"  {cls:<10} {backend:<12} {us_s:>10} "
                f"{row.get('samples', 0):>8} {row.get('censored', 0):>9}")

    shadow = report.get("shadow") or {}
    if shadow:
        lines.append("")
        lines.append("  shadow probes: " + "  ".join(
            f"{b}={v['dispatches']}"
            + (f" (failed {v['failed']})" if v.get("failed") else "")
            for b, v in shadow.items()))
    return "\n".join(lines)
