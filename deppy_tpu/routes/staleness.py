"""Measured-defaults staleness watcher (ISSUE 19, piece 2).

A frozen ``portfolio`` row is only as good as the box, platform, and
traffic mix it was measured on.  The shared defaults store
(:mod:`deppy_tpu.engine.defaults_store`) now stamps every written row
with provenance — ``ts``, ``box``, optional ``platform`` / ``samples``
— and this watcher grades each size class *actually observed in live
traffic* against it:

  * ``missing``        — no ``portfolio.<class>`` / ``portfolio`` row
    exists for the serving platform at all (the static order serves);
  * ``no_provenance``  — a row exists but predates evidence stamping
    (unageable: treat as stale);
  * ``stale``          — the row's ``ts`` is older than
    ``DEPPY_TPU_ROUTE_MAX_AGE_S``;
  * ``foreign_box``    — the row was measured on a different host.

One ``route_stale`` event fires per crossing (the PR 16 drift-watchdog
discipline — a flapping class does not spam the sink), and the set of
currently-flagged live classes backs the
``deppy_route_stale_classes`` gauge.  A learned-row adoption marks the
class fresh: the adopted row IS a measurement from this box, now.
"""

from __future__ import annotations

import socket
import time
from typing import Dict, Optional

DEFAULT_MAX_AGE_S = 7 * 24 * 3600.0


class StalenessWatcher:
    def __init__(self, max_age_s: Optional[float] = None,
                 platform: Optional[str] = None,
                 replica: Optional[str] = None,
                 registry=None, rows_doc: Optional[dict] = None,
                 box: Optional[str] = None):
        from .. import config, telemetry
        from ..analysis import lockdep
        from ..engine import defaults_store

        if max_age_s is None:
            max_age_s = config.env_float("DEPPY_TPU_ROUTE_MAX_AGE_S",
                                         DEFAULT_MAX_AGE_S, strict=False)
        self.max_age_s = float(max_age_s)
        if platform is None:
            import jax

            platform = jax.default_backend()
        self.platform = platform
        self.box = box if box is not None else socket.gethostname()
        self.replica = replica
        self._registry = (registry if registry is not None
                          else telemetry.default_registry())
        self._doc = (rows_doc if rows_doc is not None
                     else defaults_store.read_rows())
        self._lock = lockdep.make_lock("routes.staleness")
        self._live: set = set()
        self._flagged: Dict[str, dict] = {}  # class -> verdict fields
        self._fresh: set = set()  # learned-row adoptions override

    # ------------------------------------------------------------ grade

    def _grade(self, cls: str) -> Optional[dict]:
        """The staleness verdict for one class (None = fresh)."""
        entry = self._doc.get(self.platform)
        entry = entry if isinstance(entry, dict) else {}
        key = f"portfolio.{cls}"
        if not entry.get(key):
            key = "portfolio"
        row = entry.get(key)
        if not isinstance(row, str) or not row:
            return {"reason": "missing", "key": f"portfolio.{cls}"}
        ev = entry.get("evidence")
        stamp = ev.get(key) if isinstance(ev, dict) else None
        ts = stamp.get("ts") if isinstance(stamp, dict) else None
        if not isinstance(ts, (int, float)):
            return {"reason": "no_provenance", "key": key, "row": row}
        age = time.time() - float(ts)
        if age > self.max_age_s:
            return {"reason": "stale", "key": key, "row": row,
                    "age_s": round(age, 1)}
        box = stamp.get("box")
        if isinstance(box, str) and box and self.box and box != self.box:
            return {"reason": "foreign_box", "key": key, "row": row,
                    "box": box}
        return None

    def observe(self, cls: str) -> Optional[str]:
        """Note one live flush of ``cls``; returns the current
        staleness reason (None = fresh — no shadow probing needed)."""
        alert = None
        with self._lock:
            self._live.add(cls)
            if cls in self._fresh:
                self._flagged.pop(cls, None)
                return None
            verdict = self._grade(cls)
            if verdict is None:
                self._flagged.pop(cls, None)
                return None
            already = self._flagged.get(cls)
            self._flagged[cls] = verdict
            if already is None or already.get("reason") != \
                    verdict.get("reason"):
                alert = dict(verdict)
            reason = verdict["reason"]
        if alert is not None:
            fields = dict(alert, size_class_name=cls,
                          platform=self.platform)
            if self.replica:
                fields["replica"] = self.replica
            self._registry.event("route_stale", **fields)
        return reason

    def mark_fresh(self, cls: str) -> None:
        """A learned row was adopted for ``cls`` — it is measured, on
        this box, now."""
        with self._lock:
            self._fresh.add(cls)
            self._flagged.pop(cls, None)

    def reload(self, rows_doc: Optional[dict] = None) -> None:
        """Re-read the defaults registry (tests; post-persist)."""
        from ..engine import defaults_store

        doc = (rows_doc if rows_doc is not None
               else defaults_store.read_rows())
        with self._lock:
            self._doc = doc

    # --------------------------------------------------------- snapshot

    def status(self) -> Dict[str, dict]:
        with self._lock:
            return {cls: dict(v) for cls, v in self._flagged.items()}

    def stale_count(self) -> int:
        with self._lock:
            return len(self._flagged)

    def render_metric_lines(self, replica: Optional[str] = None) -> list:
        rep = f'{{replica="{replica}"}}' if replica else ""
        return [
            "# HELP deppy_route_stale_classes Live-observed size "
            "classes whose measured routing row is currently flagged "
            "stale, missing, unprovenanced, or foreign.",
            "# TYPE deppy_route_stale_classes gauge",
            f"deppy_route_stale_classes{rep} {self.stale_count()}",
        ]
