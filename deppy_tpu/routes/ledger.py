"""Regret ledger (ISSUE 19, piece 1): wall-time lost to the frozen
routing default, measured from the racer's own exhaust.

Every ``race`` sink event already carries the counterfactual the
ledger needs: the winner's wall clock, plus — since this PR — one
``losers`` entry per non-winning entrant with its wall clock and a
``censored`` flag (a cancelled loser's partial wall measures when the
cancel landed, not how fast the backend solves, so it must never feed
a speed estimate).  The ledger folds those, plus ``route`` events from
shadow probes, into:

  * decayed per-(size-class, backend) **wall estimates** — EWMA of
    µs-per-lane over uncensored observations, the figure the online
    route registry ranks by;
  * per-class **win shares** (``deppy_route_win_share``);
  * a per-class **regret total** (``deppy_route_regret_seconds_total``)
    attributed to the frozen default backend: each race where the
    ranked head (the event's ``default``) did not win adds the wall
    the default burned (observed uncensored, else its decayed
    estimate) minus the winner's wall.  Regret is the live price of a
    wrong frozen row, in seconds, straight off the sink.

The same :meth:`fold` drives both the live forwarder path and the
offline ``deppy routes`` reconstruction — the CLI table IS the live
table, recomputed from the JSONL sink alone.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

DEFAULT_DECAY = 0.2


class RegretLedger:
    """Fold ``race``/``route`` events into per-class route-health
    state.  Thread-safe (the live path calls :meth:`fold` from racer
    and dispatch-loop threads concurrently)."""

    def __init__(self, decay: Optional[float] = None):
        from ..analysis import lockdep

        self.decay = DEFAULT_DECAY if decay is None else float(decay)
        self.decay = min(max(self.decay, 0.01), 1.0)
        self._lock = lockdep.make_lock("routes.ledger")
        # (class, backend) -> {"us_per_lane": ewma, "samples": n}
        self._est: Dict[Tuple[str, str], dict] = {}
        # (class, backend) -> censored-loser observations (cancels).
        self._censored: Dict[Tuple[str, str], int] = {}
        self._wins: Dict[str, Dict[str, int]] = {}
        self._races: Dict[str, int] = {}
        self._no_winner: Dict[str, int] = {}
        # (class, backend) -> accumulated regret seconds charged to the
        # frozen default backend.
        self._regret: Dict[Tuple[str, str], float] = {}
        self._default: Dict[str, str] = {}  # latest default per class
        self._shadow: Dict[str, int] = {}  # backend -> shadow dispatches
        self._shadow_failed: Dict[str, int] = {}

    # ------------------------------------------------------------- fold

    def _observe(self, cls: str, backend: str, wall_s: float,
                 lanes: int) -> None:
        us = 1e6 * float(wall_s) / max(int(lanes or 1), 1)
        row = self._est.get((cls, backend))
        if row is None:
            self._est[(cls, backend)] = {"us_per_lane": us, "samples": 1}
            return
        a = self.decay
        row["us_per_lane"] = (1.0 - a) * row["us_per_lane"] + a * us
        row["samples"] += 1

    def fold(self, event: dict) -> None:
        kind = event.get("kind")
        if kind == "race":
            self._fold_race(event)
        elif kind == "route" and event.get("phase") == "shadow":
            self._fold_shadow(event)

    def _fold_race(self, ev: dict) -> None:
        cls = ev.get("size_class_name")
        if not cls:
            return
        cls = str(cls)
        winner = ev.get("winner")
        default = ev.get("default")
        lanes = ev.get("lanes") or 1
        wall = ev.get("wall_s")
        losers = ev.get("losers")
        with self._lock:
            if winner is None:
                # No definitive finisher (or a straggler-triage marker
                # event): nothing raced to a usable wall clock.
                if ev.get("entrants"):
                    self._no_winner[cls] = self._no_winner.get(cls, 0) + 1
                return
            self._races[cls] = self._races.get(cls, 0) + 1
            wins = self._wins.setdefault(cls, {})
            wins[winner] = wins.get(winner, 0) + 1
            if isinstance(default, str):
                self._default[cls] = default
            if isinstance(wall, (int, float)):
                self._observe(cls, str(winner), wall, lanes)
            default_wall = None
            if isinstance(losers, list):
                for loser in losers:
                    if not isinstance(loser, dict):
                        continue
                    b = loser.get("backend")
                    lw = loser.get("wall_s")
                    if not isinstance(b, str):
                        continue
                    if loser.get("censored") or not isinstance(
                            lw, (int, float)):
                        self._censored[(cls, b)] = self._censored.get(
                            (cls, b), 0) + 1
                        continue
                    self._observe(cls, b, lw, lanes)
                    if b == default:
                        default_wall = float(lw)
            # Regret: the default backend did not win this race — charge
            # it the wall it burned beyond the winner's.  Censored
            # defaults fall back to the decayed estimate (a cancel means
            # "at least this slow"; the estimate is the unbiased floor).
            if (isinstance(default, str) and default != winner
                    and isinstance(wall, (int, float))):
                if default_wall is None:
                    est = self._est.get((cls, default))
                    if est is not None:
                        default_wall = (est["us_per_lane"]
                                        * max(int(lanes or 1), 1) / 1e6)
                if default_wall is not None:
                    inc = max(default_wall - float(wall), 0.0)
                    key = (cls, default)
                    self._regret[key] = self._regret.get(key, 0.0) + inc

    def _fold_shadow(self, ev: dict) -> None:
        cls = ev.get("size_class_name")
        backend = ev.get("backend")
        if not cls or not isinstance(backend, str):
            return
        cls = str(cls)
        with self._lock:
            self._shadow[backend] = self._shadow.get(backend, 0) + 1
            if not ev.get("ok"):
                self._shadow_failed[backend] = \
                    self._shadow_failed.get(backend, 0) + 1
                return
            wall = ev.get("wall_s")
            if isinstance(wall, (int, float)):
                self._observe(cls, backend, wall, ev.get("lanes") or 1)

    # ---------------------------------------------------------- snapshot

    def estimates(self) -> Dict[str, Dict[str, dict]]:
        """{class: {backend: {"us_per_lane", "samples", "censored"}}}"""
        with self._lock:
            out: Dict[str, Dict[str, dict]] = {}
            for (cls, backend), row in self._est.items():
                out.setdefault(cls, {})[backend] = {
                    "us_per_lane": round(row["us_per_lane"], 3),
                    "samples": row["samples"],
                    "censored": self._censored.get((cls, backend), 0),
                }
            for (cls, backend), n in self._censored.items():
                out.setdefault(cls, {}).setdefault(backend, {
                    "us_per_lane": None, "samples": 0, "censored": n})
            return out

    def snapshot(self) -> Dict[str, dict]:
        """Per-class route-health rows (the `deppy routes` table's live
        twin)."""
        with self._lock:
            classes = (set(self._races) | set(self._no_winner)
                       | {c for c, _ in self._est})
            out: Dict[str, dict] = {}
            for cls in sorted(classes):
                races = self._races.get(cls, 0)
                wins = dict(self._wins.get(cls, {}))
                regret = {b: round(s, 6)
                          for (c, b), s in self._regret.items()
                          if c == cls}
                out[cls] = {
                    "races": races,
                    "no_winner": self._no_winner.get(cls, 0),
                    "default": self._default.get(cls),
                    "wins": wins,
                    "win_share": {b: round(n / races, 4)
                                  for b, n in sorted(wins.items())}
                    if races else {},
                    "regret_s": regret,
                    "censored": {b: n
                                 for (c, b), n in self._censored.items()
                                 if c == cls},
                }
            return out

    def shadow_counts(self) -> Dict[str, dict]:
        with self._lock:
            return {b: {"dispatches": n,
                        "failed": self._shadow_failed.get(b, 0)}
                    for b, n in sorted(self._shadow.items())}

    # ------------------------------------------------------------- render

    def render_metric_lines(self, replica: Optional[str] = None) -> List[str]:
        rep = f',replica="{replica}"' if replica else ""
        with self._lock:
            regret = sorted(self._regret.items())
            shares: List[Tuple[str, str, float]] = []
            for cls in sorted(self._races):
                races = self._races[cls]
                if not races:
                    continue
                for b, n in sorted(self._wins.get(cls, {}).items()):
                    shares.append((cls, b, round(n / races, 6)))
            shadow = sorted(self._shadow.items())
        lines: List[str] = []
        if regret:
            lines += [
                "# HELP deppy_route_regret_seconds_total Wall-clock "
                "seconds the frozen default backend burned beyond the "
                "observed race winner, per size class (censored "
                "cancels fall back to the decayed estimate).",
                "# TYPE deppy_route_regret_seconds_total counter",
            ]
            for (cls, b), s in regret:
                lines.append(
                    f'deppy_route_regret_seconds_total{{'
                    f'size_class="{cls}",backend="{b}"{rep}}} '
                    f"{round(s, 6)}")
        if shares:
            lines += [
                "# HELP deppy_route_win_share Fraction of this size "
                "class's portfolio races won per backend.",
                "# TYPE deppy_route_win_share gauge",
            ]
            for cls, b, share in shares:
                lines.append(
                    f'deppy_route_win_share{{size_class="{cls}",'
                    f'backend="{b}"{rep}}} {share}')
        if shadow:
            lines += [
                "# HELP deppy_route_shadow_dispatches_total Shadow "
                "route probes dispatched at idle priority, by "
                "candidate backend.",
                "# TYPE deppy_route_shadow_dispatches_total counter",
            ]
            for b, n in shadow:
                lines.append(
                    f'deppy_route_shadow_dispatches_total{{'
                    f'backend="{b}"{rep}}} {n}')
        return lines
