"""deppy_tpu.routes — route-health observability plane (ISSUE 19
tentpole).

Every routing decision in the stack is learned offline and frozen;
this package makes routing-decision QUALITY a live, fleet-readable
metric and closes the measured-defaults loop.  Four pieces:

  * **ledger** — :class:`~deppy_tpu.routes.ledger.RegretLedger`: folds
    the racer's ``race`` events (winner wall + censored-aware loser
    walls) and shadow ``route`` events into decayed per-(class,
    backend) wall estimates, per-class win shares, and a running
    regret total charged to the frozen default
    (``deppy_route_regret_seconds_total`` /
    ``deppy_route_win_share``).
  * **staleness** — :class:`~deppy_tpu.routes.staleness.
    StalenessWatcher`: grades live-observed classes against the
    defaults store's provenance stamps (``route_stale`` events, one
    per crossing; ``deppy_route_stale_classes`` gauge).
  * **shadow** — :class:`~deppy_tpu.routes.shadow.ShadowSampler`: for
    flagged classes only, a deterministic 1-in-N sampler duplicates an
    already-coalesced flush to one non-serving candidate on the
    scheduler's idle-priority queue (live traffic preempts; results
    feed the ledger, never a response).
  * **learn** — :class:`~deppy_tpu.routes.learn.OnlineRouteRegistry`:
    re-ranks classes from live estimates and adopts
    ``portfolio.<class>`` rows onto the engine registry's in-memory
    overlay, gated by the racer's definitive-winner rule + sampled
    cross-check so a learned route changes speed, never answers.
    Learned rows gossip fleet-wide through the PR 16 obs streamer →
    router → ``POST /v1/routes/learned`` on every peer.

Armed by ``DEPPY_TPU_ROUTE_LEARN`` / ``--route-learn``: ``off`` (the
default) constructs nothing — no forwarder, no scheduler hook, no
metric families, responses byte-identical; ``observe`` runs ledger +
staleness + shadow probing without adoption; ``on`` adds the online
registry.  ``deppy routes`` (:mod:`deppy_tpu.routes.report`)
reconstructs the whole table offline from the JSONL sink alone.

See docs/observability.md ("Route health") for schemas and metrics.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .ledger import RegretLedger
from .learn import OnlineRouteRegistry
from .shadow import ShadowSampler
from .staleness import StalenessWatcher

MODES = ("off", "observe", "on")


def resolve_mode(mode: Optional[str] = None) -> str:
    from .. import config

    if mode is None:
        mode = config.env_raw("DEPPY_TPU_ROUTE_LEARN", "off")
    mode = str(mode).strip().lower()
    if mode in ("off", "0", "false", "no", ""):
        return "off"
    if mode in ("on", "1", "true", "yes", "learn"):
        return "on"
    return "observe"


class RoutePlane:
    """The per-replica route-health plane: a default-registry event
    forwarder (ledger + learner) plus the scheduler's flush-observation
    hook (staleness + shadow sampling)."""

    def __init__(self, scheduler=None, mode: str = "observe",
                 shadow_rate: Optional[float] = None,
                 max_age_s: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 decay: Optional[float] = None,
                 registry_path: Optional[str] = None,
                 replica: Optional[str] = None,
                 registry=None):
        from .. import config, telemetry
        from ..profile import sanitize_replica

        self.mode = mode
        self.replica = sanitize_replica(replica)
        self._registry = (registry if registry is not None
                          else telemetry.default_registry())
        self._scheduler = scheduler
        if decay is None:
            decay = config.env_float("DEPPY_TPU_ROUTE_DECAY", None,
                                     strict=False)
        self.ledger = RegretLedger(decay=decay)
        self.watcher = StalenessWatcher(max_age_s=max_age_s,
                                        replica=self.replica,
                                        registry=self._registry)
        self.sampler = ShadowSampler(rate=shadow_rate)
        self.learner: Optional[OnlineRouteRegistry] = None
        if mode == "on":
            self.learner = OnlineRouteRegistry(
                self.ledger, min_samples=min_samples,
                platform=self.watcher.platform, replica=self.replica,
                registry=self._registry, registry_path=registry_path,
                watcher=self.watcher)

    # -------------------------------------------------------- lifecycle

    def install(self) -> None:
        self._registry.add_forwarder(self)
        if self._scheduler is not None:
            self._scheduler.set_route_plane(self)

    def close(self, clear_overlay: bool = True) -> None:
        from ..engine import registry as engine_registry

        self._registry.remove_forwarder(self)
        if self._scheduler is not None:
            self._scheduler.set_route_plane(None)
        if clear_overlay and self.learner is not None:
            adopted = self.learner.adopted()
            if adopted:
                overlay = engine_registry.route_overlay()
                for key in adopted:
                    overlay.pop(key, None)
                engine_registry.set_route_overlay(overlay)

    # ------------------------------------------------------- event side

    def __call__(self, event: dict) -> None:
        """Registry event forwarder — must never raise."""
        try:
            kind = event.get("kind")
            if kind not in ("race", "route"):
                return
            self.ledger.fold(event)
            if self.learner is not None:
                cls = event.get("size_class_name")
                if cls:
                    self.learner.consider(str(cls))
        # deppy: lint-ok[exception-hygiene] a broken route-health fold must never fail the race that emitted the event
        except Exception:
            pass

    # ------------------------------------------------------- flush side

    def observe_flush(self, scheduler, live) -> None:
        """Called by the scheduler after each cold live flush: grade
        the class's routing row and, when flagged, maybe queue one
        shadow probe at idle priority."""
        from .. import faults
        from ..engine.driver import padded_class

        cls = padded_class([lane.problem for lane in live])
        reason = self.watcher.observe(cls)
        if reason is None or self.sampler.interval == 0:
            return
        from ..engine import registry as engine_registry

        racer = getattr(scheduler, "_racer", None)
        k = racer.k if racer is not None else 1
        need_card = any(
            lane.problem.card_act.shape[0] > 0
            and (lane.problem.card_act >= 0).any() for lane in live)
        device_ok = not faults.default_breaker().blocks_device()
        # The exclusion set is exactly the entrant set the racer's
        # plan() would launch for this flush — a shadow probe must
        # measure a backend the live race does NOT already measure.
        serving, _ = engine_registry.candidates(
            cls, k=k, device_ok=device_ok, cardinality=need_card)
        exclude = list(serving)
        if serving:
            head = (self.ledger.estimates().get(cls) or {}).get(
                serving[0])
            if head is None or head.get("us_per_lane") is None:
                # The serving head (the frozen default) is cancelled the
                # moment another entrant wins, so the race can never
                # observe its full wall — yet that counterfactual IS the
                # regret signal.  Keep it probeable until one uncensored
                # wall lands in the ledger.
                exclude = serving[1:]
        backend = self.sampler.pick(cls, exclude=exclude,
                                    cardinality=need_card,
                                    device_ok=device_ok)
        if backend is None:
            return
        scheduler.submit_shadow(backend, cls,
                                [lane.problem for lane in live],
                                max_steps=live[0].max_steps)

    # ----------------------------------------------------------- render

    def snapshot(self) -> dict:
        doc = {
            "mode": self.mode,
            "classes": self.ledger.snapshot(),
            "stale": self.watcher.status(),
            "shadow": self.ledger.shadow_counts(),
        }
        if self.learner is not None:
            doc["learned"] = self.learner.adopted()
        return doc

    def render_metric_lines(self) -> List[str]:
        lines = self.ledger.render_metric_lines(replica=self.replica)
        lines += self.watcher.render_metric_lines(replica=self.replica)
        if self.learner is not None:
            lines += self.learner.render_metric_lines(
                replica=self.replica)
        return lines


# Process-wide active plane (one serving process = one replica), the
# obs-plane lifecycle pattern: Metrics.render() injects its exposition
# lines; disarmed is exactly [].
_LOCK = threading.Lock()
_PLANE: Optional[RoutePlane] = None


def start_plane(scheduler=None, mode: Optional[str] = None,
                **kw) -> Optional[RoutePlane]:
    """Build, install, and register the process route plane; replaces
    any previous one.  Returns None (nothing armed, nothing changed)
    when the resolved mode is ``off``."""
    global _PLANE
    resolved = resolve_mode(mode)
    if resolved == "off":
        return None
    plane = RoutePlane(scheduler, mode=resolved, **kw)
    with _LOCK:
        prev, _PLANE = _PLANE, plane
    if prev is not None:
        prev.close()
    plane.install()
    return plane


def stop_plane() -> None:
    global _PLANE
    with _LOCK:
        plane, _PLANE = _PLANE, None
    if plane is not None:
        plane.close()


def active_plane() -> Optional[RoutePlane]:
    return _PLANE


def adopt_remote(rows: Dict[str, str],
                 origin: Optional[str] = None) -> Dict[str, str]:
    """Gossip ingress (``POST /v1/routes/learned``): adopt peer-learned
    rows onto this replica's overlay.  No plane, or a plane without
    learning, ignores the push — a replica that did not opt into
    learned routing never changes behavior on a peer's say-so."""
    with _LOCK:
        plane = _PLANE
    if plane is None or plane.learner is None:
        return {}
    return plane.learner.adopt(rows, source="gossip", origin=origin)


def render_metric_lines() -> List[str]:
    with _LOCK:
        plane = _PLANE
    return plane.render_metric_lines() if plane is not None else []


__all__ = [
    "OnlineRouteRegistry",
    "RegretLedger",
    "RoutePlane",
    "ShadowSampler",
    "StalenessWatcher",
    "active_plane",
    "adopt_remote",
    "render_metric_lines",
    "resolve_mode",
    "start_plane",
    "stop_plane",
]
