"""Per-replica online route registry (ISSUE 19, piece 4).

Decayed live measurements (the regret ledger's per-(class, backend)
µs-per-lane estimates, fed by race winners, uncensored losers, and
shadow probes) are re-ranked into candidate ``portfolio.<class>``
rows.  Once at least two backends carry ``DEPPY_TPU_ROUTE_MIN_SAMPLES``
uncensored observations for a class and the measured-best differs from
the currently-served head, the row is ADOPTED:

  * the in-memory overlay (:func:`deppy_tpu.engine.registry.
    set_route_overlay`) flips ``ranked()`` for this process — the
    package-local registry file is never mutated mid-serve;
  * a ``route_learned`` sink event records the row, the estimates it
    was ranked from, and its provenance (replica, box, source) — the
    fleet gossip leg and ``deppy routes`` both read this trail;
  * optionally (``DEPPY_TPU_ROUTE_REGISTRY``) the row persists through
    the shared flock-guarded defaults store, provenance-stamped, so a
    restart keeps the discovery.

Safety is structural, not behavioral: adoption only reorders which
DEFINITIVE backends the racer launches.  The first-definitive-winner
rule and the sampled cross-check still gate every answer, so an
adversarially-wrong learned row (the worst backend promoted
everywhere) costs speed, never answers — the fuzz-differential pin in
tests/test_routes.py holds exactly that.
"""

from __future__ import annotations

from typing import Dict, Optional

DEFAULT_MIN_SAMPLES = 8


class OnlineRouteRegistry:
    def __init__(self, ledger, min_samples: Optional[int] = None,
                 platform: Optional[str] = None,
                 replica: Optional[str] = None,
                 registry=None, registry_path: Optional[str] = None,
                 watcher=None):
        from .. import config, telemetry
        from ..analysis import lockdep

        if min_samples is None:
            min_samples = config.env_int("DEPPY_TPU_ROUTE_MIN_SAMPLES",
                                         DEFAULT_MIN_SAMPLES,
                                         strict=False)
        self.min_samples = max(int(min_samples), 1)
        if platform is None:
            import jax

            platform = jax.default_backend()
        self.platform = platform
        self.replica = replica
        self._registry = (registry if registry is not None
                          else telemetry.default_registry())
        if registry_path is None:
            registry_path = config.env_str("DEPPY_TPU_ROUTE_REGISTRY")
        self.registry_path = registry_path or None
        self.watcher = watcher
        self._ledger = ledger
        self._lock = lockdep.make_lock("routes.learn")
        self._adopted: Dict[str, str] = {}  # "portfolio.<cls>" -> row

    # ---------------------------------------------------------- propose

    def consider(self, cls: str) -> Optional[str]:
        """Re-rank one class from the ledger's live estimates; adopt a
        new row when the measurement disagrees with what is served.
        Returns the adopted row (None = no change)."""
        from ..engine import registry as engine_registry

        est = self._ledger_estimates().get(cls) or {}
        eligible = {
            b: row for b, row in est.items()
            if row.get("us_per_lane") is not None
            and row.get("samples", 0) >= self.min_samples
            and b in engine_registry.specs()}
        if len(eligible) < 2:
            return None
        order = sorted(eligible,
                       key=lambda b: eligible[b]["us_per_lane"])
        row = ",".join(order)
        key = f"portfolio.{cls}"
        with self._lock:
            if self._adopted.get(key) == row:
                return None
            served, _ = engine_registry.ranked(cls)
            if key not in self._adopted and served \
                    and served[0] == order[0]:
                # The frozen row already leads with the measured best —
                # adopting would churn the tail for no regret win.
                return None
        self.adopt({key: row}, source="live",
                   estimates={b: eligible[b]["us_per_lane"]
                              for b in order})
        return row

    def _ledger_estimates(self) -> dict:
        return self._ledger.estimates() if self._ledger is not None \
            else {}

    # ------------------------------------------------------------ adopt

    def adopt(self, rows: Dict[str, str], source: str,
              origin: Optional[str] = None,
              estimates: Optional[dict] = None) -> Dict[str, str]:
        """Install learned rows on the overlay (idempotent — already-
        adopted identical rows are skipped, which also terminates the
        gossip echo).  Returns the rows actually applied."""
        from ..engine import registry as engine_registry

        specs = engine_registry.specs()
        applied: Dict[str, str] = {}
        with self._lock:
            for key, row in rows.items():
                if not (isinstance(key, str)
                        and key.startswith("portfolio")
                        and isinstance(row, str)):
                    continue
                names = [n.strip() for n in row.split(",")
                         if n.strip() in specs]
                if len(names) < 2:
                    continue
                canon = ",".join(names)
                if self._adopted.get(key) == canon:
                    continue
                self._adopted[key] = canon
                applied[key] = canon
            if applied:
                engine_registry.update_route_overlay(applied)
        if not applied:
            return applied
        for key, row in applied.items():
            cls = key.split(".", 1)[1] if "." in key else None
            if cls and self.watcher is not None:
                self.watcher.mark_fresh(cls)
            fields = {"key": key, "row": row, "source": source,
                      "platform": self.platform}
            if cls:
                fields["size_class_name"] = cls
            if self.replica:
                fields["replica"] = self.replica
            if origin:
                fields["origin"] = origin
            if estimates:
                fields["est_us_per_lane"] = {
                    b: round(v, 3) for b, v in estimates.items()}
            self._registry.event("route_learned", **fields)
        if self.registry_path and source == "live":
            # Persist through the shared flock-guarded store so a
            # restart keeps the discovery — provenance-stamped like
            # every other measured row.  Never the package-local file
            # unless the operator pointed the knob at it.
            from ..engine import defaults_store

            try:
                defaults_store.merge_rows(
                    self.platform, dict(applied),
                    evidence={"platform": self.platform,
                              "source": "route_learn",
                              "replica": self.replica or ""},
                    path=self.registry_path)
            except OSError:
                pass  # persistence is best-effort; serving never fails
        return applied

    # ---------------------------------------------------------- snapshot

    def adopted(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._adopted)

    def render_metric_lines(self, replica: Optional[str] = None) -> list:
        rep = f'{{replica="{replica}"}}' if replica else ""
        with self._lock:
            n = len(self._adopted)
        return [
            "# HELP deppy_route_learned_rows Live-learned routing rows "
            "currently adopted on this replica's overlay.",
            "# TYPE deppy_route_learned_rows gauge",
            f"deppy_route_learned_rows{rep} {n}",
        ]
