"""Shadow-race sampler (ISSUE 19, piece 3).

For size classes whose measured routing row is stale or missing, a
deterministic 1-in-N sampler duplicates an already-coalesced live
flush to ONE candidate backend that is *not* serving it, via the
scheduler's idle-priority queue — live traffic preempts every shadow
dispatch at the flush boundary, and the probe's answers are discarded
(its wall clock feeds the regret ledger / online registry through a
``route`` sink event, never a response).

Determinism matters the same way it does for the racer's sampled
cross-check: a per-class flush counter (not a RNG) decides which
flushes probe, so replaying a workload replays its shadow schedule —
and the candidate rotates per class, so repeated probes sweep the
whole non-serving field instead of hammering one backend.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

DEFAULT_SHADOW_RATE = 0.0625  # 1-in-16 flushes of a flagged class


class ShadowSampler:
    def __init__(self, rate: Optional[float] = None):
        from .. import config
        from ..analysis import lockdep

        if rate is None:
            rate = config.env_float("DEPPY_TPU_ROUTE_SHADOW_RATE",
                                    DEFAULT_SHADOW_RATE, strict=False)
        rate = max(float(rate), 0.0)
        self.interval = (int(round(1.0 / min(rate, 1.0)))
                         if rate > 0 else 0)
        self._lock = lockdep.make_lock("routes.shadow")
        self._count: Dict[str, int] = {}
        self._rotate: Dict[str, int] = {}

    def candidates(self, cls: str, exclude: Sequence[str],
                   cardinality: bool = False,
                   device_ok: bool = True) -> List[str]:
        """Non-serving raceable backends for one class, in ranked
        order (the registry's capability/availability filter minus the
        backends the live race already measures)."""
        from ..engine import registry as engine_registry

        names, _ = engine_registry.candidates(
            cls, k=len(engine_registry.specs()), device_ok=device_ok,
            cardinality=cardinality)
        drop = set(exclude)
        return [n for n in names if n not in drop]

    def pick(self, cls: str, exclude: Sequence[str],
             cardinality: bool = False,
             device_ok: bool = True) -> Optional[str]:
        """The backend to shadow-probe for THIS flush of a flagged
        class, or None (off-sample, rate 0, or nothing to probe)."""
        if self.interval == 0:
            return None
        with self._lock:
            c = self._count.get(cls, 0)
            self._count[cls] = c + 1
            if c % self.interval:
                return None
            cands = self.candidates(cls, exclude,
                                    cardinality=cardinality,
                                    device_ok=device_ok)
            if not cands:
                return None
            i = self._rotate.get(cls, 0)
            self._rotate[cls] = i + 1
            return cands[i % len(cands)]
