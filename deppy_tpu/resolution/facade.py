"""Resolution facade: entities + generators → Solution.

Rebuild of /root/reference/pkg/solver/solver.go.  ``Resolver`` runs the
pipeline for one problem: aggregate variables from constraint generators,
solve, and report a ``Solution`` mapping every variable's entity id to
selected/not-selected (solver.go:36-64 initializes all to False and flips
the installed ones to True).

``BatchResolver`` is the batch-native extension with no reference
counterpart: N independent problems (e.g. 10k cluster states over a shared
catalog) encoded once and dispatched to the TPU engine together.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

from .. import faults, telemetry
from ..entity.entity import EntityID
from ..entity.source import EntityQuerier
from ..sat.constraints import Variable
from ..sat.errors import Incomplete, InternalSolverError, NotSatisfiable
from ..sat.solver import Solver
from ..sat.tracer import Tracer
from .generator import ConstraintAggregator, GeneratorLike

# Solution maps every input entity id to whether it was selected
# (reference solver.go:12-16).
Solution = Dict[EntityID, bool]


def _to_solution(variables: Sequence[Variable], installed: Sequence[Variable]) -> Solution:
    """Every input variable appears, installed ones True
    (reference solver.go:52-62)."""
    solution: Solution = {v.identifier: False for v in variables}
    for v in installed:
        solution[v.identifier] = True
    return solution


class Resolver:
    """Single-problem resolution facade (reference DeppySolver,
    solver.go:24-64)."""

    def __init__(
        self,
        source: EntityQuerier,
        *generators: GeneratorLike,
        backend: str = "auto",
        tracer: Optional[Tracer] = None,
        max_steps: Optional[int] = None,
        parallel_generators: bool = False,
    ):
        self.source = source
        self.aggregator = ConstraintAggregator(
            *generators, parallel=parallel_generators
        )
        self.backend = backend
        self.tracer = tracer
        self.max_steps = max_steps

    def solve(self) -> Solution:
        """Aggregate variables, solve, and build the Solution map.  Raises
        :class:`NotSatisfiable` (with its minimal constraint core) when
        resolution is impossible."""
        variables = self.aggregator.get_variables(self.source)
        installed = Solver(
            variables,
            backend=self.backend,
            tracer=self.tracer,
            max_steps=self.max_steps,
        ).solve()
        return _to_solution(variables, installed)


class BatchResolver:
    """Resolve many independent problems in one device dispatch.

    Each problem is its own variable list (typically: one per cluster state,
    sharing a catalog's entity source).  Results come back per problem as a
    ``Solution``, the ``NotSatisfiable`` error carrying that problem's
    minimal constraint core, or an ``Incomplete`` marker when that problem
    exhausted the step budget (stragglers never void their batchmates).
    """

    def __init__(
        self,
        backend: str = "auto",
        max_steps: Optional[int] = None,
        mesh=None,
        checkpoint_dir: Optional[str] = None,
        deadline_s: Optional[float] = None,
        scheduler=None,
    ):
        self.backend = backend
        self.max_steps = max_steps
        self.mesh = mesh  # jax.sharding.Mesh from deppy_tpu.parallel
        # Cross-request continuous batching (ISSUE 3): when a
        # deppy_tpu.sched.Scheduler is given, solve() routes through its
        # shared queue + result cache instead of dispatching privately —
        # concurrent resolvers coalesce into shared device dispatches.
        # The scheduler owns backend routing then (it was built with its
        # own backend); mesh/checkpoint_dir stay private-dispatch-only
        # features and are ignored on the scheduled path.
        self.scheduler = scheduler
        # Wall-clock budget for one solve call (ISSUE 2): problems not
        # dispatched before it expires come back Incomplete instead of
        # the batch aborting; the service threads each request's
        # deadline through here.
        self.deadline_s = deadline_s
        # Group-wise resume for fleet-scale batches: completed groups of a
        # crashed run are loaded instead of re-solved (tensor backend only;
        # see deppy_tpu.engine.checkpoint).
        self.checkpoint_dir = checkpoint_dir
        # Engine iterations consumed by the last solve, summed over the
        # batch (SURVEY.md §5 observability; exported by the service).
        self.last_steps: int = 0
        # Structured per-batch telemetry for the last solve (ISSUE 1):
        # outcomes, engine counters, padding economics, escalation
        # stage, host-fallback rows.  The service feeds its /metrics
        # histograms from this.
        self.last_report: Optional[telemetry.SolveReport] = None

    def solve(
        self, problems: Sequence[Sequence[Variable]]
    ) -> List[Union[Solution, NotSatisfiable, Incomplete]]:
        if self.scheduler is not None:
            # Scheduled path: the shared queue coalesces this batch with
            # concurrent callers' problems and serves cache hits without
            # dispatching; submit() applies the same deadline scoping
            # (explicit + ambient) the private path does below.
            stats: dict = {}
            try:
                return self.scheduler.submit(
                    problems, deadline_s=self.deadline_s,
                    max_steps=self.max_steps, stats=stats)
            finally:
                self.last_steps = stats.get("steps", 0)
                self.last_report = stats.get("report")
        # ambient_deadline picks up DEPPY_TPU_BATCH_DEADLINE_S when no
        # explicit deadline is active — here rather than only in the
        # tensor driver, so the env knob also bounds the host-backend
        # serial loop (including auto degraded to host by the breaker).
        with faults.deadline_scope(self.deadline_s), \
                faults.ambient_deadline():
            return self._solve_inner(problems)

    def _solve_inner(
        self, problems: Sequence[Sequence[Variable]]
    ) -> List[Union[Solution, NotSatisfiable, Incomplete]]:
        from ..sat.solver import resolve_backend

        backend = resolve_backend(self.backend)
        self.last_steps = 0
        self.last_report = None
        if backend == "host":
            if self.checkpoint_dir is not None:
                import sys

                print(
                    "warning: checkpoint_dir is a tensor-backend feature; "
                    "the host engine solves without persisting groups — "
                    "a crashed run will restart from scratch",
                    file=sys.stderr,
                )
            return self._solve_host_batch(problems)
        from ..engine.driver import solve_batch

        stats: dict = {}
        try:
            return solve_batch(
                problems, max_steps=self.max_steps, mesh=self.mesh,
                stats=stats, checkpoint_dir=self.checkpoint_dir,
            )
        finally:
            self.last_steps = stats.get("steps", 0)
            self.last_report = stats.get("report")

    def _solve_host_batch(
        self, problems: Sequence[Sequence[Variable]]
    ) -> List[Union[Solution, NotSatisfiable, Incomplete]]:
        """Host-backend batch solve through the shared hostpool entry
        (ISSUE 5): lanes run concurrently across the worker pool when
        one is available (``DEPPY_TPU_HOST_WORKERS``), inline otherwise
        — bit-identical either way.  Deadline semantics mirror the
        historical serial loop: problems not started before the batch
        deadline expires come back ``Incomplete``, counted as ONE
        deadline event for the whole degraded remainder (the driver's
        per-group accounting)."""
        from .. import hostpool

        # begin/end (not a bare SolveReport) so host-backend batches
        # honor the same telemetry contract as device batches: the
        # report reaches telemetry.last_report() and the JSONL sink,
        # and the batch shows up as a span.
        batch_rep, owns_rep = telemetry.begin_report(
            backend="host", n_problems=len(problems)
        )
        reg = telemetry.default_registry()
        try:
            with reg.span("facade.host_solve", problems=len(problems)):
                dl = faults.current_deadline()
                # Deadline triage BEFORE each encode, like the serial
                # loop checked before each Solver construction: an
                # already-expired batch must not pay unbounded encode
                # work (and a malformed problem past the expiry point
                # degrades like any other remainder instead of raising).
                # Encoding errors (DuplicateIdentifier) for problems
                # reached in time surface exactly as before.
                encoded = []
                for vs in problems:
                    if dl is not None and dl.expired():
                        break
                    encoded.append(Solver(vs, backend="host",
                                          max_steps=self.max_steps).problem)
                lanes = hostpool.solve_host_problems(
                    encoded, max_steps=self.max_steps,
                    deadlines=[dl] * len(encoded)) if encoded else []
                lanes += [hostpool.HostLaneResult("incomplete",
                                                  degraded=True)
                          for _ in range(len(problems) - len(encoded))]
                n_degraded = sum(1 for r in lanes if r.degraded)
                if n_degraded:
                    faults.note_deadline_exceeded("facade.host_solve",
                                                  n_degraded)
                out: List[Union[Solution, NotSatisfiable, Incomplete]] = []
                for variables, p, lane in zip(problems,
                                              encoded + [None] * (
                                                  len(problems)
                                                  - len(encoded)),
                                              lanes):
                    batch_rep.count_outcome(lane.outcome)
                    batch_rep.steps += lane.steps
                    batch_rep.decisions += lane.decisions
                    batch_rep.propagation_rounds += lane.propagation_rounds
                    batch_rep.backtracks += lane.backtracks
                    batch_rep.add_wall("solve", lane.wall_s)
                    self.last_steps += lane.steps
                    if lane.outcome == "sat":
                        out.append(_to_solution(
                            variables,
                            [p.variables[i] for i in lane.installed_idx]))
                    elif lane.outcome == "unsat":
                        out.append(NotSatisfiable(
                            [p.applied[j] for j in lane.core_idx]))
                    else:
                        out.append(Incomplete())
        finally:
            telemetry.end_report(batch_rep, owns_rep)
        self.last_report = batch_rep
        return out
