"""Constraint generators: the domain-logic plugin point.

Rebuild of /root/reference/pkg/constraints/constraint_generator.go.  A
``ConstraintGenerator`` inspects an entity store and emits constrained
variables (e.g. "every required API group must have exactly one provider").
The ``ConstraintAggregator`` fans over registered generators and
concatenates their variables (constraint_generator.go:29-40) — here over a
thread pool rather than serially, realizing the reference's own
scatter-gather TODO (constraint_generator.go:30).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Protocol, Sequence, Union, runtime_checkable

from ..entity.source import EntityQuerier
from ..sat.constraints import Variable


@runtime_checkable
class ConstraintGenerator(Protocol):
    """Generates solver variables from an entity store
    (constraint_generator.go:11-13)."""

    def get_variables(self, querier: EntityQuerier) -> Sequence[Variable]: ...


# Plain functions are accepted wherever a generator is expected.
GeneratorLike = Union[ConstraintGenerator, Callable[[EntityQuerier], Sequence[Variable]]]


def _call(gen: GeneratorLike, querier: EntityQuerier) -> Sequence[Variable]:
    if hasattr(gen, "get_variables"):
        return gen.get_variables(querier)
    return gen(querier)


class ConstraintAggregator:
    """Aggregates several generators, concatenating their variables in
    registration order (constraint_generator.go:19-40).  With
    ``parallel=True`` generators run over a thread pool — the reference's
    own scatter-gather TODO (constraint_generator.go:30) — joined in
    registration order so output stays deterministic; the default is the
    reference's serial behavior, safe for queriers that aren't thread-safe.
    """

    def __init__(self, *generators: GeneratorLike, parallel: bool = False):
        self._generators: List[GeneratorLike] = list(generators)
        self._parallel = parallel

    def get_variables(self, querier: EntityQuerier) -> List[Variable]:
        if not self._generators:
            return []
        if not self._parallel or len(self._generators) == 1:
            out: List[Variable] = []
            for gen in self._generators:
                out.extend(_call(gen, querier))
            return out
        with ThreadPoolExecutor(max_workers=len(self._generators)) as pool:
            results = list(pool.map(lambda g: _call(g, querier), self._generators))
        out = []
        for r in results:
            out.extend(r)
        return out
