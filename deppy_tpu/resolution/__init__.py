"""Constraint-generation API and the resolution facade.

Rebuild of the reference's ``pkg/constraints`` (the plugin point where
domain logic turns entities into constrained variables,
constraint_generator.go:11-40) and ``pkg/solver`` (the ``DeppySolver``
facade producing a ``Solution``, solver.go:16-64) — plus the batch-native
``BatchResolver`` that resolves many independent problems in one TPU
dispatch, which is this framework's reason to exist.
"""

from .generator import ConstraintAggregator, ConstraintGenerator
from .facade import BatchResolver, Resolver, Solution

__all__ = [
    "BatchResolver",
    "ConstraintAggregator",
    "ConstraintGenerator",
    "Resolver",
    "Solution",
]
